"""Fault injection: the chaos hooks the gated smoke drives.

Every guard mechanism needs a way to make its failure happen on demand:

- :func:`flip_byte` corrupts a checkpoint file in place (exercises the
  digest check + ``load_latest`` walk-back),
- :func:`inject_nan` poisons live device state (exercises the health
  sentinel lanes and the quarantine/rollback policies),
- :func:`inject_dispatch_failures` makes the next N step dispatches
  raise a transient error (exercises bounded retry-with-backoff),
- :func:`desync_cell_map`, :func:`inject_dead_residue`, and
  :func:`corrupt_params_row` seed the three semantic corruptions the
  graftcheck deep audit (``check.audit_world``) must each reject with a
  typed violation,
- :func:`poison_world_mm` and :func:`corrupt_world_params` are the
  FLEET-targeted twins: they poison ONE world slot of a running
  :class:`~magicsoup_tpu.fleet.FleetScheduler` (writing into the
  group's stacked device arrays when the lane is resident), so the
  warden's per-world quarantine/heal policies and the fleet-chaos
  smoke have a single-tenant fault to isolate,
- process-level chaos (SIGKILL mid-megastep, SIGTERM graceful drain)
  lives in ``performance/smoke.py --chaos``, which orchestrates child
  processes around these hooks.

Import cost is deliberately tiny; nothing here runs unless called.
"""
from __future__ import annotations

import os
from pathlib import Path

from magicsoup_tpu.guard.errors import TransientDispatchError


def flip_byte(path, offset: int | None = None, *, rng=None) -> int:
    """Flip one byte of ``path`` in place; returns the offset flipped.

    Default offset targets the payload region (past the magic + header
    line) so the corruption exercises the DIGEST check rather than the
    cheaper header parse.  Pass ``rng`` (``random.Random``) to pick a
    random payload offset reproducibly.
    """
    path = Path(path)
    data = bytearray(path.read_bytes())
    if len(data) == 0:
        raise ValueError(f"cannot flip a byte of empty file {path}")
    if offset is None:
        start = data.find(b"\n", data.find(b"\n") + 1) + 1
        if start <= 0 or start >= len(data):
            start = 0
        if rng is not None:
            offset = rng.randrange(start, len(data))
        else:
            offset = start
    offset = int(offset) % len(data)
    data[offset] ^= 0xFF
    # deliberately NON-atomic: this simulates on-disk corruption of an
    # already-complete file, not a torn write
    with open(path, "wb") as fh:  # graftlint: disable=GL010,GL018 fault injector corrupts files on purpose
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    return offset


def inject_nan(target, *, row: int = 0, mol: int = 0) -> None:
    """Poison one concentration with NaN.

    ``target`` is a ``PipelinedStepper`` (poisons the live device carry,
    so the NEXT fused step's sentinel lanes see it) or a ``World``
    (poisons the cell-molecule buffer the classic driver integrates).
    """
    import jax.numpy as jnp

    if hasattr(target, "_state"):  # stepper: poison the device carry
        st = target
        st._state = st._state._replace(
            cm=st._state.cm.at[row, mol].set(jnp.nan)
        )
    else:  # world
        w = target
        w._cell_molecules = w._cell_molecules.at[row, mol].set(jnp.nan)
        w._cm_cache = None


def desync_cell_map(world) -> tuple:
    """Clear one occupied pixel in the host occupancy map WITHOUT
    removing the cell — the occupancy/position desync
    ``check.audit_world`` reports as ``cell_map_desync`` (and the device
    invariant lanes catch as ``occ_alive_mismatch`` once the map is
    re-uploaded).  Returns the ``(row, col)`` pixel cleared so a test
    can restore it."""
    import numpy as np

    hits = np.argwhere(world._np_cell_map)
    if len(hits) == 0:
        raise ValueError("world has no occupied pixels to desync")
    r, c = (int(x) for x in hits[0])
    world._np_cell_map[r, c] = False
    return r, c


def inject_dead_residue(world, *, mol: int = 0, value: float = 1.0) -> int:
    """Write a nonzero concentration into a DEAD cell row (the first row
    past the live prefix) — the dead-row residue ``check.audit_world``
    reports as ``dead_cm_residue`` and the device lanes flag as bit 3.
    Returns the corrupted row index."""
    row = int(world.n_cells)
    if row >= world._cell_molecules.shape[0]:
        raise ValueError("world is at capacity: no dead rows to corrupt")
    world._cell_molecules = world._cell_molecules.at[row, mol].set(value)
    world._cm_cache = None
    return row


def _pick_translated_row(world) -> int:
    """First audited (sampled) live row whose genome translates to at
    least one protein — the row ``check.audit_world``'s sampled
    re-translation cross-check will actually look at."""
    from magicsoup_tpu.check.audit import _sample_rows

    n = int(world.n_cells)
    counts, _, _ = world.genetics.translate_genomes_flat(
        list(world.cell_genomes)
    )
    row = next(
        (i for i in _sample_rows(n, 8) if int(counts[i]) > 0), None
    )
    if row is None:
        raise ValueError(
            "no sampled cell translates to any protein; nothing for "
            "the cross-check to catch"
        )
    return row


def corrupt_params_row(world, *, row: int | None = None) -> int:
    """Overwrite a live cell's resident Vmax column WITHOUT touching its
    genome — the params/genome desync ``check.audit_world``'s sampled
    re-translation cross-check reports as ``params_genome_mismatch``.
    Picks the first audited (sampled) row whose genome translates to at
    least one protein unless ``row`` is given; returns the row."""
    if row is None:
        row = _pick_translated_row(world)
    kin = world.kinetics
    kin.params = kin.params._replace(
        Vmax=kin.params.Vmax.at[row, 0].add(7.0)
    )
    return row


def poison_world_mm(scheduler, slot: int, *, mol: int = 0, pixel=(0, 0)):
    """Poison ONE fleet world's molecule map with NaN — the
    single-tenant fault the warden must isolate.

    ``slot`` indexes ``scheduler.lanes`` (admission order).  While the
    lane is RESIDENT its device truth lives in the group's stacked
    arrays, so the NaN is written into that world's slice of
    ``group.fstate`` — the other worlds' slices are untouched, which is
    exactly the isolation the det-mode bit-identity test pins.  The
    next fleet dispatch's health lanes flag ``mm_nonfinite`` for that
    slot only.
    """
    import jax.numpy as jnp

    lane = scheduler.lanes[slot]
    r, c = pixel
    if lane._fleet_resident:
        group, gslot = lane._fleet_slot
        group.fstate = group.fstate._replace(
            mm=group.fstate.mm.at[gslot, mol, r, c].set(jnp.nan)
        )
    else:
        lane._state = lane._state._replace(
            mm=lane._state.mm.at[mol, r, c].set(jnp.nan)
        )


def corrupt_world_params(scheduler, slot: int, *, row: int | None = None) -> int:
    """Fleet twin of :func:`corrupt_params_row`: desync ONE world's
    resident kinetics params from its genomes (Vmax bump on an audited
    row) inside the group's stacked params when resident — the
    corruption ``restore_world(..., audit=True)`` must reject after a
    fleet save.  Returns the corrupted row."""
    lane = scheduler.lanes[slot]
    if row is None:
        row = _pick_translated_row(lane.world)
    if lane._fleet_resident:
        group, gslot = lane._fleet_slot
        group.fparams = group.fparams._replace(
            Vmax=group.fparams.Vmax.at[gslot, row, 0].add(7.0)
        )
    else:
        lane.kin.params = lane.kin.params._replace(
            Vmax=lane.kin.params.Vmax.at[row, 0].add(7.0)
        )
    return row


def inject_dispatch_failures(stepper, n: int = 1) -> None:
    """Arm the stepper so its next ``n`` step dispatches raise
    :class:`TransientDispatchError` BEFORE touching device buffers.

    The error carries a transient marker, so a stepper constructed with
    ``dispatch_retries >= n`` absorbs the faults through its bounded
    backoff and the trajectory is unchanged (retries fire before any
    donated input is consumed).
    """
    if not hasattr(stepper, "_fault_dispatch"):
        raise TypeError(
            f"{type(stepper).__name__} has no dispatch fault hook"
        )
    stepper._fault_dispatch = int(n)


def consume_dispatch_fault(stepper) -> None:
    """Stepper-side check (called at the top of the dispatch wrapper):
    raise one armed fault, decrementing the countdown."""
    count = getattr(stepper, "_fault_dispatch", 0)
    if count > 0:
        stepper._fault_dispatch = count - 1
        raise TransientDispatchError()
