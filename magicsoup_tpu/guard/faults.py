"""Fault injection: the chaos hooks the gated smoke drives.

Every guard mechanism needs a way to make its failure happen on demand:

- :func:`flip_byte` corrupts a checkpoint file in place (exercises the
  digest check + ``load_latest`` walk-back),
- :func:`inject_nan` poisons live device state (exercises the health
  sentinel lanes and the quarantine/rollback policies),
- :func:`inject_dispatch_failures` makes the next N step dispatches
  raise a transient error (exercises bounded retry-with-backoff),
- process-level chaos (SIGKILL mid-megastep, SIGTERM graceful drain)
  lives in ``performance/smoke.py --chaos``, which orchestrates child
  processes around these hooks.

Import cost is deliberately tiny; nothing here runs unless called.
"""
from __future__ import annotations

import os
from pathlib import Path

from magicsoup_tpu.guard.errors import TransientDispatchError


def flip_byte(path, offset: int | None = None, *, rng=None) -> int:
    """Flip one byte of ``path`` in place; returns the offset flipped.

    Default offset targets the payload region (past the magic + header
    line) so the corruption exercises the DIGEST check rather than the
    cheaper header parse.  Pass ``rng`` (``random.Random``) to pick a
    random payload offset reproducibly.
    """
    path = Path(path)
    data = bytearray(path.read_bytes())
    if len(data) == 0:
        raise ValueError(f"cannot flip a byte of empty file {path}")
    if offset is None:
        start = data.find(b"\n", data.find(b"\n") + 1) + 1
        if start <= 0 or start >= len(data):
            start = 0
        if rng is not None:
            offset = rng.randrange(start, len(data))
        else:
            offset = start
    offset = int(offset) % len(data)
    data[offset] ^= 0xFF
    # deliberately NON-atomic: this simulates on-disk corruption of an
    # already-complete file, not a torn write
    with open(path, "wb") as fh:  # graftlint: disable=GL010 fault injector corrupts files on purpose
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    return offset


def inject_nan(target, *, row: int = 0, mol: int = 0) -> None:
    """Poison one concentration with NaN.

    ``target`` is a ``PipelinedStepper`` (poisons the live device carry,
    so the NEXT fused step's sentinel lanes see it) or a ``World``
    (poisons the cell-molecule buffer the classic driver integrates).
    """
    import jax.numpy as jnp

    if hasattr(target, "_state"):  # stepper: poison the device carry
        st = target
        st._state = st._state._replace(
            cm=st._state.cm.at[row, mol].set(jnp.nan)
        )
    else:  # world
        w = target
        w._cell_molecules = w._cell_molecules.at[row, mol].set(jnp.nan)
        w._cm_cache = None


def inject_dispatch_failures(stepper, n: int = 1) -> None:
    """Arm the stepper so its next ``n`` step dispatches raise
    :class:`TransientDispatchError` BEFORE touching device buffers.

    The error carries a transient marker, so a stepper constructed with
    ``dispatch_retries >= n`` absorbs the faults through its bounded
    backoff and the trajectory is unchanged (retries fire before any
    donated input is consumed).
    """
    if not hasattr(stepper, "_fault_dispatch"):
        raise TypeError(
            f"{type(stepper).__name__} has no dispatch fault hook"
        )
    stepper._fault_dispatch = int(n)


def consume_dispatch_fault(stepper) -> None:
    """Stepper-side check (called at the top of the dispatch wrapper):
    raise one armed fault, decrementing the countdown."""
    count = getattr(stepper, "_fault_dispatch", 0)
    if count > 0:
        stepper._fault_dispatch = count - 1
        raise TransientDispatchError()
