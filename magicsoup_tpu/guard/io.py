"""Crash-safe file writes: temp file -> fsync -> ``os.replace``.

The naive ``open(path, "wb"); write`` truncates the previous snapshot
the moment the file opens — a crash (SIGKILL, OOM, power) between the
truncate and the final flush destroys BOTH the old state and the new.
The atomic protocol here guarantees a reader sees either the complete
old bytes or the complete new bytes, never a prefix:

1. write the full payload to a uniquely-named temp file IN THE SAME
   DIRECTORY (``os.replace`` is only atomic within a filesystem),
2. flush + ``os.fsync`` the temp file (data durable before the rename
   makes it visible),
3. ``os.replace`` onto the target (atomic on POSIX and Windows),
4. fsync the directory so the rename itself survives a power cut.

This module is stdlib-only on purpose: ``scripts/summarize_capture.py``
and other no-jax consumers must be able to import it.

Chaos instrumentation: every atomic write is a fault point of the
graftchaos plane (``guard.chaos``).  To keep this file loadable as a
STANDALONE file (the stdlib-pure contract above), the probe is handed
over by registration — ``guard.chaos`` imports this module and sets
``_chaos_probe``; nothing here imports the package.  Unarmed (or
standalone) the probe is ``None`` and a write pays one attribute read.
"""
from __future__ import annotations

import os
from pathlib import Path

# set to guard.chaos.site by guard.chaos at import; None = disarmed
_chaos_probe = None


def _fsync_dir(dirpath: Path) -> None:
    # directory fsync is POSIX-only; on platforms that refuse to open a
    # directory the rename is still atomic, just not power-cut durable
    try:
        fd = os.open(dirpath, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write_bytes(path, data: bytes, *, chaos_site: str = "io.write") -> None:
    """Atomically replace ``path`` with ``data`` (see module docstring).

    The temp file carries the target's name plus a pid/random suffix so
    concurrent writers never collide; on any failure the temp file is
    removed and the previous ``path`` contents are untouched.

    ``chaos_site`` names this write's fault point in the graftchaos
    plane (callers with a more specific identity pass their own —
    ``checkpoint.write``, ``registry.write``); an armed ``enospc``/
    ``eio`` fault raises the errno-carrying ``OSError`` before any byte
    lands, and a ``torn`` fault simulates on-disk corruption of the
    target (a truncated prefix) — the scenario the verified-checkpoint
    walk-back exists to absorb.
    """
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    if _chaos_probe is not None:
        fault = _chaos_probe(chaos_site)
        if fault is not None:
            if fault.kind == "torn":
                # deliberately NON-atomic truncated write: stands in for
                # the corruption a non-atomic filesystem (or a flipped
                # sector) leaves behind; readers must REFUSE these bytes
                with open(path, "wb") as fh:  # graftlint: disable=GL018 chaos fault injector tears the target on purpose
                    fh.write(data[: max(1, len(data) // 2)])
                return
            raise fault.as_oserror()
    tmp = path.parent / (
        f".{path.name}.tmp.{os.getpid()}.{os.urandom(4).hex()}"  # graftlint: disable=GL004 temp-file name uniqueness, not simulation state
    )
    try:
        with open(tmp, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(path.parent)


def atomic_write_text(
    path, text: str, encoding: str = "utf-8", *, chaos_site: str = "io.write"
) -> None:
    """:func:`atomic_write_bytes` for text payloads."""
    atomic_write_bytes(path, text.encode(encoding), chaos_site=chaos_site)
