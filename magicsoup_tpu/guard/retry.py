"""Bounded retry-with-backoff for transient backend failures.

A long run over a preemptible TPU pod sees occasional transient RPC
errors (tunnel drop, brief UNAVAILABLE) that a blind crash turns into a
lost trajectory.  The policy here is deliberately narrow:

- only errors whose text carries a known transient marker are retried
  (a shape error or OOM retried forever is a hang, not resilience),
- the retry budget is bounded and the delay exponential with a cap,
- every retry is observable via the ``on_retry`` callback (the stepper
  wires it to a stats counter + telemetry note).

Determinism note: retries happen at the DISPATCH boundary, before any
result is consumed — a successfully retried dispatch produces the same
bytes as a first-try success, so the bit-identity contract survives.
"""
from __future__ import annotations

import errno
import time
from typing import Callable

from magicsoup_tpu.guard.backoff import BackoffPolicy

# substrings that mark an error as plausibly transient; mirrors the
# classification performance/bench.py uses for probe failures
_TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "RESOURCE_EXHAUSTED: Attempting to reserve",
    "Socket closed",
    "Connection reset",
    "transport is closing",
)

# errnos that mean "the storage itself is unusable" — retrying a full
# disk or a read-only filesystem is a hang with extra steps.  Checked
# BEFORE the marker scan so a message that happens to contain a marker
# substring cannot win retries for a dead disk.
_NON_TRANSIENT_ERRNOS = frozenset(
    {errno.ENOSPC, errno.EROFS, errno.EDQUOT}
)


def is_transient_error(exc: BaseException) -> bool:
    """True when ``exc`` looks like a transient backend/RPC failure
    worth retrying (vs. a deterministic bug that never will succeed).

    Errno-carrying ``OSError`` with ENOSPC / EROFS / EDQUOT is
    explicitly NON-transient: disk-full does not heal inside a retry
    window, and the graceful-degradation layer (skip + retry next
    cadence) owns that failure mode instead.
    """
    if isinstance(exc, OSError) and exc.errno in _NON_TRANSIENT_ERRNOS:
        return False
    text = f"{type(exc).__name__}: {exc}"
    return any(marker in text for marker in _TRANSIENT_MARKERS)


def retry_call(
    fn: Callable,
    *,
    retries: int,
    base_delay: float = 0.5,
    max_delay: float = 8.0,
    retry_if: Callable[[BaseException], bool] = is_transient_error,
    on_retry: Callable[[int, BaseException], None] | None = None,
    sleep: Callable[[float], None] = time.sleep,
):
    """Call ``fn()`` with up to ``retries`` retries on transient errors.

    Delay doubles each attempt from ``base_delay`` up to ``max_delay``
    (the shared :class:`~magicsoup_tpu.guard.backoff.BackoffPolicy`
    ladder — same schedule the warden and serve edge use).
    Non-transient errors (per ``retry_if``) and the final transient
    failure propagate unchanged.  ``on_retry(attempt, exc)`` fires
    before each sleep; ``sleep`` is injectable so tests stay instant.
    """
    policy = BackoffPolicy(base=base_delay, factor=2.0, max_delay=max_delay)
    attempt = 0
    while True:
        try:
            return fn()
        except BaseException as exc:  # noqa: BLE001 - reraised unless retried
            if attempt >= retries or not retry_if(exc):
                raise
            attempt += 1
            if on_retry is not None:
                on_retry(attempt, exc)
            policy.sleep(attempt, sleep=sleep)
