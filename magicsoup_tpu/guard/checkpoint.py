"""Verified checkpoint files + rolling retention.

File format (``*.msck``)::

    b"MSCK\\n"                                   magic, 5 bytes
    {"schema": 2, "payload_len": N,
     "sha256": "...", "meta": {...}}\\n           one JSON header line
    <N payload bytes>                            pickle of the object

Every field exists to make loading REFUSE bad bytes instead of
unpickling garbage into a live world:

- the magic line rejects arbitrary files handed to the loader,
- ``schema`` rejects checkpoints from an incompatible writer; schemas
  older than the current one but listed in ``SUPPORTED_SCHEMAS`` load
  through a typed migration chain instead (schema 1 wrote host-string
  genome worlds — see :func:`_migrate_v1`),
- ``payload_len`` catches truncation (a crash mid-copy, a partial
  download) before hashing,
- ``sha256`` over the payload catches bit flips (the fault-injection
  smoke literally flips one byte and asserts the typed rejection),
- only after ALL checks pass does ``pickle.loads`` run.

Failures raise :class:`~magicsoup_tpu.guard.errors.CheckpointError`
whose ``check`` attribute names the first verification that failed.

:class:`CheckpointManager` adds step-indexed filenames, rolling
retention of the last ``keep`` snapshots, and a ``load_latest`` that
walks BACKWARD over corrupt/unreadable snapshots — a half-written or
flipped newest file costs one checkpoint interval, not the run.

Writes go through :func:`magicsoup_tpu.guard.io.atomic_write_bytes`, so
a crash mid-save never destroys an existing snapshot.
"""
from __future__ import annotations

import hashlib
import json
import pickle
import re
import warnings
from pathlib import Path

from magicsoup_tpu.guard import chaos as _chaos
from magicsoup_tpu.guard.errors import CheckpointError
from magicsoup_tpu.guard.io import atomic_write_bytes

_MAGIC = b"MSCK\n"
#: schema the writer stamps.  2 = device-resident genome era: World
#: pickles carry ``genome_backend`` plus either the packed token store
#: or the host string list.
SCHEMA_VERSION = 2
#: schemas the reader accepts; anything older than SCHEMA_VERSION
#: passes through the typed migration chain in ``_MIGRATIONS``.
SUPPORTED_SCHEMAS = frozenset({1, 2})


def _payload_worlds(obj):
    """Yield every World carried by a checkpoint payload: a bare World,
    a ``guard.resume`` run payload (``{"world": ...}``), or a
    ``fleet.persist`` payload nesting one run per world."""
    if isinstance(obj, dict):
        if "world" in obj:
            yield obj["world"]
        runs = obj.get("runs")
        if isinstance(runs, (list, tuple)):
            for run in runs:
                if isinstance(run, dict) and "world" in run:
                    yield run["world"]
    elif hasattr(obj, "cell_genomes") and hasattr(obj, "n_cells"):
        yield obj


def _migrate_v1(obj, path):
    """Schema 1 -> 2: v1 writers predate the device-resident genome
    store — their worlds pickle genomes as a host ``cell_genomes``
    string list with no ``genome_backend`` marker.
    ``World.__setstate__`` adopts that legacy layout on unpickle
    (string backend); the migration verifies each world actually landed
    in a coherent v2 genome layout, so a damaged or foreign v1 payload
    fails the typed ``migrate`` check HERE instead of deep inside a
    resume.  Pass ``genome_backend="token"`` to the resume entry points
    to continue a migrated run on the device-token path."""
    for world in _payload_worlds(obj):
        backend = getattr(world, "genome_backend", None)
        if backend not in ("string", "token"):
            raise CheckpointError(
                f"checkpoint {path} failed the migrate check: schema 1 "
                f"world did not normalize to a v2 genome layout "
                f"(genome_backend={backend!r})",
                check="migrate",
                path=path,
            )
        try:
            n = int(world.n_cells)
            n_genomes = len(world.cell_genomes)
        except Exception as exc:  # noqa: BLE001 - typed below
            raise CheckpointError(
                f"checkpoint {path} failed the migrate check: schema 1 "
                f"world's genome state is unreadable: {exc}",
                check="migrate",
                path=path,
            ) from exc
        if n_genomes != n:
            raise CheckpointError(
                f"checkpoint {path} failed the migrate check: schema 1 "
                f"world carries {n_genomes} genomes for n_cells={n}",
                check="migrate",
                path=path,
            )
    return obj


#: schema N -> the migration that lifts a payload to schema N+1
_MIGRATIONS = {1: _migrate_v1}


def _pack(obj, meta: dict | None) -> bytes:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    header = {
        "schema": SCHEMA_VERSION,
        "payload_len": len(payload),
        "sha256": hashlib.sha256(payload).hexdigest(),
        "meta": dict(meta or {}),
    }
    head = json.dumps(header, separators=(",", ":"), sort_keys=True)
    return _MAGIC + head.encode("utf-8") + b"\n" + payload


def write_checkpoint(path, obj, *, meta: dict | None = None) -> Path:
    """Atomically write ``obj`` as a verified checkpoint file."""
    path = Path(path)
    atomic_write_bytes(path, _pack(obj, meta), chaos_site="checkpoint.write")
    return path


def _read_header(path: Path) -> tuple[dict, bytes]:
    try:
        fault = _chaos.site("checkpoint.read")
        if fault is not None:
            raise fault.as_oserror()
        raw = path.read_bytes()
    except FileNotFoundError:
        raise CheckpointError(
            f"checkpoint {path} does not exist", check="truncated", path=path
        ) from None
    except OSError as exc:
        # an EIO/EACCES on the read path is not corruption — surface it
        # as its own typed check so load_latest's walk-back can count it
        # separately from bad bytes
        raise CheckpointError(
            f"checkpoint {path} failed the io check: could not read the "
            f"file: {exc}",
            check="io",
            path=path,
        ) from exc
    if not raw.startswith(_MAGIC):
        raise CheckpointError(
            f"checkpoint {path} failed the magic check: not an MSCK file",
            check="magic",
            path=path,
        )
    body = raw[len(_MAGIC) :]
    nl = body.find(b"\n")
    if nl < 0:
        raise CheckpointError(
            f"checkpoint {path} failed the header check: truncated before "
            "the header line ended",
            check="header",
            path=path,
        )
    try:
        header = json.loads(body[:nl].decode("utf-8"))
        schema = int(header["schema"])
        payload_len = int(header["payload_len"])
        digest = str(header["sha256"])
    except (ValueError, KeyError, TypeError, UnicodeDecodeError) as exc:
        raise CheckpointError(
            f"checkpoint {path} failed the header check: {exc}",
            check="header",
            path=path,
        ) from exc
    header["schema"] = schema
    header["payload_len"] = payload_len
    header["sha256"] = digest
    return header, body[nl + 1 :]


def inspect_checkpoint(path) -> dict:
    """Verified header (schema/meta/digest) WITHOUT unpickling the
    payload — safe on untrusted files; listing tools use this."""
    header, _payload = _read_header(Path(path))
    return header


def read_checkpoint(path) -> tuple[object, dict]:
    """Load a checkpoint, verifying magic -> schema -> length -> digest
    BEFORE unpickling.  Returns ``(obj, meta)``."""
    path = Path(path)
    header, payload = _read_header(path)
    schema = header["schema"]
    if schema not in SUPPORTED_SCHEMAS:
        raise CheckpointError(
            f"checkpoint {path} failed the version check: schema "
            f"{schema} not in supported {sorted(SUPPORTED_SCHEMAS)}",
            check="version",
            path=path,
        )
    if len(payload) != header["payload_len"]:
        raise CheckpointError(
            f"checkpoint {path} failed the truncation check: payload is "
            f"{len(payload)} bytes, header promises {header['payload_len']}",
            check="truncated",
            path=path,
        )
    actual = hashlib.sha256(payload).hexdigest()
    if actual != header["sha256"]:
        raise CheckpointError(
            f"checkpoint {path} failed the digest check: payload sha256 "
            f"{actual[:16]}... != header {header['sha256'][:16]}...",
            check="digest",
            path=path,
        )
    try:
        obj = pickle.loads(payload)
    except Exception as exc:  # noqa: BLE001 - surfaced as the typed error
        raise CheckpointError(
            f"checkpoint {path} failed to unpickle after all byte checks "
            f"passed: {exc}",
            check="unpickle",
            path=path,
        ) from exc
    meta = header.get("meta", {})
    if schema != SCHEMA_VERSION:
        for v in range(schema, SCHEMA_VERSION):
            obj = _MIGRATIONS[v](obj, path)
        meta = {**meta, "migrated_from": schema}
    return obj, meta


class CheckpointManager:
    """Step-indexed checkpoint directory with rolling retention.

    Parameters:
        directory: Where the ``<prefix>-<step>.msck`` files live
            (created on first save).
        keep: How many newest snapshots to retain; older ones are
            pruned after each successful save.  ``keep >= 2`` is the
            sane minimum — it is what makes ``load_latest``'s
            walk-backward fallback useful when the newest file is
            corrupt.
        prefix: Filename prefix (several managers can share a dir).
    """

    def __init__(self, directory, *, keep: int = 3, prefix: str = "ckpt"):
        if keep < 1:
            raise ValueError("keep must be >= 1")
        if not re.fullmatch(r"[A-Za-z0-9_.-]+", prefix):
            raise ValueError(f"prefix {prefix!r} must be filename-safe")
        self.directory = Path(directory)
        self.keep = int(keep)
        self.prefix = prefix
        self._pat = re.compile(rf"^{re.escape(prefix)}-(\d+)\.msck$")
        # failure accounting — the graceful-degradation contract needs a
        # manager-level view of "saves have been failing" that wardens
        # and statuses() can read without string-matching exceptions
        self.save_failures = 0
        self.consecutive_save_failures = 0
        self.delete_failures = 0
        self.last_save_error: str | None = None
        self._warned_delete = False

    def path_for(self, step: int) -> Path:
        return self.directory / f"{self.prefix}-{int(step):010d}.msck"

    def checkpoints(self) -> list[tuple[int, Path]]:
        """``(step, path)`` pairs, ascending by step."""
        if not self.directory.is_dir():
            return []
        out = []
        for p in self.directory.iterdir():
            m = self._pat.match(p.name)
            if m:
                out.append((int(m.group(1)), p))
        out.sort()
        return out

    def latest(self) -> Path | None:
        cks = self.checkpoints()
        return cks[-1][1] if cks else None

    def save(self, obj, *, step: int, meta: dict | None = None) -> Path:
        """Write ``obj`` at ``step`` and prune beyond ``keep``.

        An ``OSError`` (ENOSPC, EIO, ...) propagates to the caller — the
        atomic-write protocol guarantees no torn file was left behind —
        but is COUNTED first (``save_failures`` /
        ``consecutive_save_failures``), so degradation policies can
        decide "warn and retry next cadence" vs "give up" without
        re-deriving history from exceptions.
        """
        meta = dict(meta or {})
        meta.setdefault("step", int(step))
        try:
            path = write_checkpoint(self.path_for(step), obj, meta=meta)
        except OSError as exc:
            self.save_failures += 1
            self.consecutive_save_failures += 1
            self.last_save_error = f"{type(exc).__name__}: {exc}"
            _chaos.note_counter("checkpoint_save_failures")
            raise
        self.consecutive_save_failures = 0
        self.last_save_error = None
        self.prune()
        return path

    def prune(self) -> list[Path]:
        """Delete all but the newest ``keep`` snapshots; returns the
        removed paths.  Delete failures no longer vanish: each one bumps
        ``delete_failures`` and the shared chaos counter (one warning
        per manager, not per file — retention retries the same victims
        every save)."""
        removed = []
        for _step, p in self.checkpoints()[: -self.keep or None]:
            try:
                fault = _chaos.site("checkpoint.delete")
                if fault is not None:
                    raise fault.as_oserror()
                p.unlink()
            except OSError as exc:
                self.delete_failures += 1
                _chaos.note_counter("checkpoint_delete_failures")
                if not self._warned_delete:
                    self._warned_delete = True
                    warnings.warn(
                        f"checkpoint retention could not delete {p.name}: "
                        f"{exc} (counted; retried next save)"
                    )
                continue
            removed.append(p)
        return removed

    def failure_counters(self) -> dict[str, int]:
        """The manager's failure accounting as one flat dict (surfaced
        by warden ``statuses()`` and the serve health snapshot)."""
        return {
            "save_failures": self.save_failures,
            "consecutive_save_failures": self.consecutive_save_failures,
            "delete_failures": self.delete_failures,
        }

    def load(self, path) -> tuple[object, dict]:
        return read_checkpoint(path)

    def load_latest(
        self, *, fallback: bool = True
    ) -> tuple[object, dict, Path]:
        """Load the newest verifiable checkpoint.

        With ``fallback`` (default) a corrupt/truncated/mismatched
        newest file is SKIPPED with a warning and the walk continues
        backward — the retention window is exactly the budget for this.
        Raises :class:`CheckpointError` (``check="none"``) when nothing
        in the directory loads.
        """
        cks = self.checkpoints()
        errors: list[CheckpointError] = []
        for _step, path in reversed(cks):
            try:
                obj, meta = read_checkpoint(path)
            except CheckpointError as exc:
                if not fallback:
                    raise
                errors.append(exc)
                import warnings

                warnings.warn(
                    f"skipping unloadable checkpoint {path.name} "
                    f"(failed check: {exc.check}); falling back to the "
                    "previous snapshot"
                )
                continue
            return obj, meta, path
        detail = "; ".join(f"{e.path}: {e.check}" for e in errors)
        raise CheckpointError(
            f"no loadable checkpoint under {self.directory}"
            + (f" (rejected: {detail})" if detail else " (directory empty)"),
            check="none",
            path=self.directory,
        )
