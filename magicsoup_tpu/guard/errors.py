"""Typed errors for the graftguard fault-tolerance layer.

Every failure a driver might want to CATCH AND HANDLE differently gets
its own class with structured fields — a restore loop that falls back to
the previous snapshot needs to distinguish "file is corrupt" from "file
is from a future schema" without parsing message strings.
"""
from __future__ import annotations


class GuardError(RuntimeError):
    """Base class for all graftguard errors."""


class CheckpointError(GuardError):
    """A checkpoint could not be written, verified, or loaded.

    Attributes:
        check: Which verification failed — one of ``"magic"``,
            ``"header"``, ``"version"``, ``"truncated"``, ``"digest"``,
            ``"unpickle"``, ``"config"``, ``"format"``, ``"io"`` (the
            file could not be read at the OS level), ``"degraded"``
            (too many consecutive save failures under a graceful-
            degradation policy), or ``"none"`` (no loadable checkpoint
            found).
        path: The offending file, when there is one.
    """

    def __init__(self, message: str, *, check: str, path=None):
        super().__init__(message)
        self.check = check
        self.path = None if path is None else str(path)


class SentinelTripped(GuardError):
    """A health sentinel fired under the ``rollback`` policy.

    Attributes:
        flags: The raw health flag word from the step record (see
            :func:`magicsoup_tpu.guard.sentinel.decode_health`).
        step: The replayed step index at which the flags were observed.
        n_bad_cells: How many live cells carried a bad concentration.
    """

    def __init__(self, message: str, *, flags: int, step: int, n_bad_cells: int):
        super().__init__(message)
        self.flags = int(flags)
        self.step = int(step)
        self.n_bad_cells = int(n_bad_cells)


class InvariantTripped(SentinelTripped):
    """A graftcheck state invariant fired under the ``rollback`` policy.

    A subclass of :class:`SentinelTripped` so existing rollback handlers
    catch both; ``flags`` here is the INVARIANT flag word (see
    :func:`magicsoup_tpu.check.invariants.decode_invariants`), not the
    health word.
    """

    def __init__(self, message: str, *, flags: int, step: int):
        super().__init__(message, flags=flags, step=step, n_bad_cells=0)


class GuardConfigError(GuardError):
    """A guard environment knob holds an unusable value.

    Raised at PARSE time (when the knob is first read) instead of
    letting a garbage value propagate into a confusing ``float()``
    traceback deep inside the watchdog.

    Attributes:
        variable: The environment variable name.
        value: The raw string that failed to parse.
    """

    def __init__(self, message: str, *, variable: str, value: str):
        super().__init__(message)
        self.variable = variable
        self.value = value


class WatchdogTimeout(GuardError):
    """A dispatch/fetch exceeded its wall-clock budget.

    Raised (fetch) or reported via diagnostics dump (dispatch — a stuck
    C call cannot be interrupted from Python) instead of hanging the
    process, the capture-probe failure mode.

    Attributes:
        phase: ``"fetch"`` or ``"dispatch"``.
        seconds: The budget that was exceeded.
    """

    def __init__(self, message: str, *, phase: str, seconds: float):
        super().__init__(message)
        self.phase = phase
        self.seconds = float(seconds)


class TransientDispatchError(GuardError):
    """Fault-injection stand-in for a transient backend error.

    The message deliberately carries a transient marker
    (``UNAVAILABLE``) so :func:`magicsoup_tpu.guard.retry.is_transient_error`
    classifies it exactly like a real tunnel drop.
    """

    def __init__(self, message: str = "injected fault: UNAVAILABLE: backend lost"):
        super().__init__(message)
