"""
graftguard: the fault-tolerance layer.

The paper's workload is millions of evolution steps; the ROADMAP north
star is a long-lived multi-tenant simulation service.  Both die on the
same four failure modes, and this package owns one answer to each:

- **Crash-safe persistence** (:mod:`.io`, :mod:`.checkpoint`): every
  state write goes temp-file -> fsync -> ``os.replace``, so a crash
  mid-write never destroys the previous snapshot; checkpoint files
  carry a schema version and a content digest that are verified BEFORE
  any byte is unpickled, and corruption raises a typed
  :class:`CheckpointError` naming the failed check.
- **Deterministic resume** (:mod:`.resume`): a checkpoint captures the
  full trajectory state — world tensors, host bookkeeping, every PRNG
  stream, and the stepper's schedule state — so in det mode a run
  killed at a checkpoint boundary and restored in a fresh process
  continues BIT-identically (the same byte-equality contract the
  megastep and mesh layers pin, extended across process death).
- **Health sentinels** (:mod:`.sentinel`, :mod:`.watchdog`): the fused
  step packs non-finite/negative-concentration flags into the record it
  already fetches (zero extra D2H, device program identical guard-on vs
  guard-off); policies escalate from a telemetry note to quarantining
  poisoned cells to rolling back to the last good checkpoint.  The
  watchdog turns a wedged dispatch/fetch into diagnostics + a typed
  error instead of a silent hang.
- **Fault injection** (:mod:`.faults`): the chaos hooks the gated smoke
  in ``performance/smoke.py`` drives — SIGKILL mid-flight, SIGTERM
  graceful drain, checkpoint byte flips, NaN injection, transient
  dispatch failures against :mod:`.retry`.

Quickstart::

    from magicsoup_tpu import guard

    mgr = guard.CheckpointManager("run/checkpoints", keep=3)
    ...
    guard.save_run(mgr, world, stepper)        # at any flush boundary
    # -- process dies --
    world, aux, meta = guard.restore_run(mgr)
    stepper = PipelinedStepper(world, **same_kwargs)
    guard.restore_stepper(stepper, aux)        # bit-identical continue
"""
from magicsoup_tpu.guard.errors import (
    CheckpointError,
    GuardConfigError,
    GuardError,
    InvariantTripped,
    SentinelTripped,
    TransientDispatchError,
    WatchdogTimeout,
)
from magicsoup_tpu.guard.faults import (
    corrupt_params_row,
    corrupt_world_params,
    desync_cell_map,
    flip_byte,
    inject_dead_residue,
    inject_dispatch_failures,
    inject_nan,
    poison_world_mm,
)
from magicsoup_tpu.guard.io import atomic_write_bytes
from magicsoup_tpu.guard.checkpoint import (
    SCHEMA_VERSION,
    CheckpointManager,
    inspect_checkpoint,
    read_checkpoint,
    write_checkpoint,
)
from magicsoup_tpu.guard.resume import (
    restore_run,
    restore_stepper,
    save_run,
    snapshot_run,
    stepper_config,
)
from magicsoup_tpu.guard.retry import is_transient_error, retry_call
from magicsoup_tpu.guard.sentinel import (
    SENTINEL_POLICIES,
    decode_health,
    quarantine_world,
)
from magicsoup_tpu.guard.signals import GracefulShutdown
from magicsoup_tpu.guard.watchdog import Watchdog, dump_diagnostics

__all__ = [
    "GuardError",
    "CheckpointError",
    "GuardConfigError",
    "InvariantTripped",
    "SentinelTripped",
    "TransientDispatchError",
    "WatchdogTimeout",
    "atomic_write_bytes",
    "SCHEMA_VERSION",
    "CheckpointManager",
    "write_checkpoint",
    "read_checkpoint",
    "inspect_checkpoint",
    "snapshot_run",
    "save_run",
    "restore_run",
    "restore_stepper",
    "stepper_config",
    "retry_call",
    "is_transient_error",
    "SENTINEL_POLICIES",
    "decode_health",
    "quarantine_world",
    "GracefulShutdown",
    "Watchdog",
    "dump_diagnostics",
    "flip_byte",
    "inject_nan",
    "inject_dispatch_failures",
    "desync_cell_map",
    "inject_dead_residue",
    "corrupt_params_row",
    "poison_world_mm",
    "corrupt_world_params",
]
