"""Graceful-preemption handling for long runs.

TPU VMs get preempted with a SIGTERM and a short grace window; an
interactive run gets SIGINT.  Either way the right move is the same:
finish the current host iteration, drain the stepper pipeline, flush
telemetry durably, write a final checkpoint, exit cleanly.  Killing the
process mid-megastep instead costs up to a full checkpoint interval of
work (recoverable — that is what the checkpoints are for — but wasteful
when the OS literally asked nicely).

:class:`GracefulShutdown` converts the signals into a flag the driver
loop polls between steps::

    with GracefulShutdown() as stop:
        for i in range(n_steps):
            if stop:
                break
            stepper.step()
    # drain/flush/checkpoint in the driver's normal epilogue

A second signal while draining re-raises the default behaviour, so a
wedged drain can still be interrupted.
"""
from __future__ import annotations

import signal
import threading

from magicsoup_tpu.analysis import ownership


class GracefulShutdown:
    """Context manager that latches SIGTERM/SIGINT into a bool flag.

    Inside the ``with`` block the first signal sets the flag (and
    records which signal it was in ``.signum``); the second occurrence
    of the same signal falls through to the previous handler — two
    Ctrl-C still kills a stuck process.  Handlers are restored on exit.
    Signal handlers can only be installed from the main thread; on any
    other thread this degrades to a never-set flag rather than raising.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = tuple(signals)
        self.signum: int | None = None
        self._event = threading.Event()
        self._previous: dict[int, object] = {}

    def __bool__(self) -> bool:
        return self._event.is_set()

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._event.wait(timeout)

    def _handle(self, signum, frame):
        # Python delivers signals on the main thread only; assert the
        # installing thread and the handling thread agree
        ownership.assert_owner(
            self, "signal-owner", attribute="GracefulShutdown.signum"
        )
        if self._event.is_set():
            # second signal: restore + re-deliver the default behaviour
            previous = self._previous.get(signum, signal.SIG_DFL)
            signal.signal(signum, previous)
            if callable(previous):
                previous(signum, frame)
            else:
                signal.raise_signal(signum)
            return
        self.signum = signum
        self._event.set()

    def __enter__(self) -> "GracefulShutdown":
        if threading.current_thread() is not threading.main_thread():
            return self
        ownership.bind(self, "signal-owner")
        for signum in self.signals:
            self._previous[signum] = signal.signal(signum, self._handle)
        return self

    def __exit__(self, *exc) -> None:
        for signum, previous in self._previous.items():
            try:
                signal.signal(signum, previous)
            except (ValueError, OSError, TypeError):
                pass
        self._previous.clear()
        return None
