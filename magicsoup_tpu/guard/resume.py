"""Deterministic resume: snapshot/restore the full trajectory state.

The contract (pinned by ``tests/fast/test_guard.py`` and the chaos
smoke): in det mode, ``[run K, checkpoint, run K]`` is BIT-identical to
``[run K, checkpoint, SIGKILL, restore in a fresh process, run K]`` for
both the classic driver and :class:`~magicsoup_tpu.stepper.PipelinedStepper`
at any megastep — the byte-equality contract PRs 2/5 established for
fusion and sharding, extended across process death.  The surviving
reference checkpoints at the same boundary because a pipelined
checkpoint IS a flush, and draining the pipeline is itself part of the
deterministic schedule (it re-packs the row space and applies in-flight
phenotype pushes, bracketing float work differently than an unflushed
run).  The classic driver has no pipeline, so there the checkpoint is
trajectory-invisible and ``[run 2K]`` equals the killed/restored run
outright.

What a run snapshot must carry beyond ``pickle(world)``:

- **Every PRNG stream.** The world pickle carries ``world._rng`` /
  ``world._nprng``, but a fresh stepper's constructor DRAWS from
  ``world._rng`` twice (its own rng seed + the device PRNG key), so
  :func:`restore_stepper` re-seats all three streams AFTER construction
  — otherwise the restored trajectory forks at the first random draw.
- **The device PRNG key.** ``DeviceState.key`` is device state the
  world pickle never sees.
- **Stepper schedule state.** Spawn queue, growth history (feeds the
  division-budget estimate, which changes compiled upper bounds and
  hence trajectories), change/dispatch sequence counters, and stats.

Snapshots are taken at the stepper's FLUSH boundary — the one point
where the pipeline is drained, the evolution worker joined, all
phenotype pushes applied, and the World is the source of truth — so
pending dispatches never need serializing and no extra device sync is
introduced.  Mesh runs snapshot via the world's normal host fetch
(already-replicated record + sharded-state device_get) and re-shard on
restore via ``restore_run(..., mesh=...)``.
"""
from __future__ import annotations

import numpy as np

from magicsoup_tpu.guard.checkpoint import CheckpointManager, read_checkpoint
from magicsoup_tpu.guard.errors import CheckpointError

RUN_FORMAT = "magicsoup_tpu.guard.run/1"

# constructor-fixed knobs that must match between the checkpointing
# stepper and the restoring one — a mismatch silently changes the
# trajectory, so restore_stepper refuses it instead
_CONFIG_FIELDS = (
    "mol_idx",
    "kill_below",
    "divide_above",
    "divide_cost",
    "target_cells",
    "genome_size",
    "lag",
    "max_lag",
    "megastep",
    "max_divisions",
    "spawn_block",
    "push_block",
    "n_rounds",
    "p_mutation",
    "p_indel",
    "p_del",
    "p_recombination",
    "compact_headroom",
    "compact_dead_slack",
    "auto_grow",
)


def stepper_config(stepper) -> dict:
    """The trajectory-determining constructor knobs of a stepper."""
    cfg = {name: getattr(stepper, name) for name in _CONFIG_FIELDS}
    cfg["overlap_evolution"] = stepper._evo_worker is not None
    cfg["n_tiles"] = stepper._n_tiles
    cfg["deterministic"] = bool(stepper.world.deterministic)
    return cfg


def snapshot_run(world, stepper=None) -> dict:
    """Build the checkpoint payload for a run.

    With a stepper, flushes it first (drain + evolution join + push
    apply + world sync) so the World alone is the full simulation state
    and the stepper contributes only its host schedule state.  The
    classic driver passes ``stepper=None`` — the world pickle already
    carries its PRNG streams.
    """
    from magicsoup_tpu.util import fetch_host

    aux = None
    if stepper is not None:
        stepper.flush()
        aux = {
            "config": stepper_config(stepper),
            "key": np.asarray(fetch_host(stepper._state.key)),
            "rng_state": stepper._rng.bit_generator.state,
            "spawn_queue": [tuple(item) for item in stepper._spawn_queue],
            "growth_hist": list(stepper._growth_hist),
            "change_seq": int(stepper._change_seq),
            "dispatched_seq": int(stepper._dispatched_seq),
            "stats": dict(stepper.stats),
        }
    return {
        "format": RUN_FORMAT,
        "world": world,
        "stepper": aux,
        # captured AFTER any stepper flush; restore_stepper re-seats
        # these post-construction (the ctor draws from world._rng)
        "world_rng_state": world._rng.getstate(),
        "world_nprng_state": world._nprng.bit_generator.state,
    }


def save_run(
    manager: CheckpointManager,
    world,
    stepper=None,
    *,
    step: int | None = None,
    meta: dict | None = None,
):
    """Snapshot + write one retained checkpoint; returns its path.

    ``step`` defaults to the stepper's replayed-step counter (or the
    number of existing checkpoints for stepper-less classic runs).
    """
    payload = snapshot_run(world, stepper)
    if step is None:
        if stepper is not None:
            step = int(stepper.stats["replayed"])
        else:
            step = len(manager.checkpoints())
    return manager.save(payload, step=step, meta=meta)


def _remesh_world(world, mesh) -> None:
    """Re-shard a freshly unpickled world over ``mesh`` (pickles drop
    meshes/shardings — they are bound to live runtimes)."""
    from magicsoup_tpu.parallel import tiled
    from magicsoup_tpu.util import fetch_host

    import jax

    n_tiles = int(mesh.shape[mesh.axis_names[0]])
    if world.map_size % n_tiles != 0:
        # typed: a restore loop falling back across checkpoints must be
        # able to tell "this snapshot cannot live on this mesh" from an
        # arbitrary crash (see guard.errors)
        raise CheckpointError(
            f"map_size={world.map_size} must be divisible by the first"
            f" mesh axis size {n_tiles} for row sharding",
            check="config",
        )
    if world._capacity % n_tiles != 0:
        raise CheckpointError(
            f"restored capacity {world._capacity} does not split across"
            f" {n_tiles} tiles; checkpoint was taken under a different"
            " mesh size",
            check="config",
        )
    world._mesh = mesh
    world._map_sharding = tiled.map_sharding(mesh)
    world._cell_sharding = tiled.cell_sharding(mesh)
    world._molecule_map = world._place_map(fetch_host(world._molecule_map))
    world._cell_molecules = world._place_cells(
        fetch_host(world._cell_molecules)
    )
    world._sync_positions()
    world._mm_cache = None
    world._cm_cache = None
    kin = world.kinetics
    kin.cell_sharding = world._cell_sharding
    kin.params = type(kin.params)(
        *(
            jax.device_put(fetch_host(t), world._cell_sharding)
            for t in kin.params
        )
    )
    if world._genome_store is not None:
        world._genome_store.place(world._place_cells)


def restore_run(
    source, *, mesh=None, audit: bool = False, genome_backend=None
) -> tuple:
    """Load a run checkpoint; returns ``(world, stepper_aux, meta)``.

    ``source`` is a :class:`CheckpointManager` (loads the newest
    verifiable snapshot, walking back over corrupt ones) or a path to a
    single ``.msck`` file.  Pass ``mesh`` to re-shard the restored world
    (pickles are mesh-free by design).  Pass ``genome_backend`` to
    continue the run on a specific genome storage path — the typed
    entry for resuming a migrated schema-1 string checkpoint on the
    device-token backend (``genome_backend="token"``); the conversion
    is storage-only and trajectory-invisible in det mode (pinned by the
    differential token axes).  ``stepper_aux`` is ``None`` for
    classic-driver checkpoints; otherwise construct a stepper with the
    SAME kwargs and hand both to :func:`restore_stepper`.

    Pass ``audit=True`` to run the graftcheck deep audit
    (:func:`magicsoup_tpu.check.assert_consistent`) on the restored
    world — a checkpoint that verified its digest can still carry a
    semantic desync from BEFORE the save, and a restore boundary is the
    cheapest place to catch one (the state was just fetched anyway and
    the pipeline is empty).  Raises
    :class:`magicsoup_tpu.check.AuditFailed` listing the violations.
    """
    if isinstance(source, CheckpointManager):
        payload, meta, _path = source.load_latest()
    else:
        payload, meta = read_checkpoint(source)
    world, aux = restore_run_payload(
        payload, mesh=mesh, audit=audit, genome_backend=genome_backend
    )
    return world, aux, meta


def restore_run_payload(
    payload, *, mesh=None, audit: bool = False, genome_backend=None
) -> tuple:
    """Restore a single run from an in-memory snapshot payload (the dict
    :func:`snapshot_run` produces); returns ``(world, stepper_aux)``.

    The verification/IO layers stay with the caller — this is the
    payload-level half of :func:`restore_run`, split out so the fleet
    checkpoint format (``magicsoup_tpu.fleet.persist``), which nests one
    run payload per world inside ONE verified file, can reuse the exact
    single-run restore semantics per world."""
    if not isinstance(payload, dict) or payload.get("format") != RUN_FORMAT:
        raise CheckpointError(
            f"checkpoint payload is not a {RUN_FORMAT} run snapshot "
            f"(got {type(payload).__name__}"
            + (
                f" with format={payload.get('format')!r})"
                if isinstance(payload, dict)
                else ")"
            ),
            check="format",
        )
    world = payload["world"]
    if mesh is not None:
        _remesh_world(world, mesh)
    if genome_backend is not None:
        if genome_backend not in ("string", "token"):
            raise CheckpointError(
                f"unknown genome_backend {genome_backend!r} "
                '(want "string" or "token")',
                check="config",
            )
        if genome_backend != world.genome_backend:
            world.convert_genome_backend(genome_backend)
    # classic resume: re-seat the world streams here (no stepper ctor
    # will draw from them); stepper resume re-seats in restore_stepper
    aux = payload["stepper"]
    if aux is None:
        world._rng.setstate(payload["world_rng_state"])
        world._nprng.bit_generator.state = payload["world_nprng_state"]
    else:
        aux = dict(aux)
        aux["world_rng_state"] = payload["world_rng_state"]
        aux["world_nprng_state"] = payload["world_nprng_state"]
    if audit:
        from magicsoup_tpu.check import assert_consistent

        assert_consistent(world)
    return world, aux


def restore_stepper(stepper, aux: dict) -> None:
    """Re-seat a freshly constructed stepper to the checkpointed
    schedule state (call with the world returned by
    :func:`restore_run` and a stepper built with the SAME kwargs).

    Refuses (``CheckpointError``, ``check="config"``) when a
    trajectory-determining knob differs — a silently different config
    would break bit-identity invisibly.  The one knob that is NOT
    trajectory-determining in det mode is the mesh shape: the sharded
    det trajectory is pinned bit-identical to the single-device one
    (``performance/mesh_sweep.py --check``), so a det checkpoint may
    restore onto a different tile count (single -> mesh or back); in
    non-det mode reduction orders differ by shape and the refusal
    stands.
    """
    want = aux["config"]
    have = stepper_config(stepper)
    diff = sorted(
        k for k in set(want) | set(have) if want.get(k) != have.get(k)
    )
    if (
        "n_tiles" in diff
        and want.get("deterministic")
        and have.get("deterministic")
    ):
        diff.remove("n_tiles")
    if diff:
        detail = ", ".join(
            f"{k}: checkpoint={want.get(k)!r} != stepper={have.get(k)!r}"
            for k in diff
        )
        raise CheckpointError(
            f"stepper config does not match the checkpoint ({detail})",
            check="config",
        )
    # the ctor drew from world._rng (twice) — rewind all streams to the
    # snapshot point so the next draw matches the uninterrupted run
    stepper.world._rng.setstate(aux["world_rng_state"])
    stepper.world._nprng.bit_generator.state = aux["world_nprng_state"]
    stepper._rng.bit_generator.state = aux["rng_state"]
    stepper._spawn_queue = [tuple(item) for item in aux["spawn_queue"]]
    stepper._growth_hist = list(aux["growth_hist"])
    stepper._change_seq = int(aux["change_seq"])
    stepper._dispatched_seq = int(aux["dispatched_seq"])
    stepper.stats.update(aux["stats"])
    # re-enter through the post-flush path: the next step() re-attaches
    # from the (restored) World with the checkpointed device key —
    # exactly what the uninterrupted run does after its flush
    import jax.numpy as jnp

    stepper._state = stepper._state._replace(
        key=jnp.asarray(aux["key"])
        if stepper._mesh is None
        else stepper._dev(aux["key"])
    )
    stepper._needs_attach = True
