"""
graftchaos: the central deterministic fault-injection plane.

Every robustness boundary in the tree carries a named *fault point* —
``chaos.site("checkpoint.write")``-style probes at checkpoint
write/read, the serve registry write, step dispatch, the step-record
fetch, telemetry emission, and the serve HTTP edge.  A disarmed probe
is one global read and a ``None`` return (the same zero-cost-off
pattern as ``analysis/ownership.py``); an armed probe consults the
schedule parsed from ``MAGICSOUP_CHAOS`` (or :func:`arm`) and returns a
:class:`Fault` describing what the instrumented code must inflict on
itself — raise an errno-carrying ``OSError``, tear a write, delay a
fetch past its watchdog budget, drop an HTTP response mid-body.

Spec grammar (clauses joined by ``;``)::

    MAGICSOUP_CHAOS = clause [";" clause]...
    clause = site ":" kind [":" arg] ["@" after] ["x" count]
                               ["%" prob] ["~" seed]

- ``site``/``kind`` must come from :data:`SITES` (unknown names raise a
  typed :class:`GuardConfigError` naming the variable at parse time),
- ``arg`` is a float payload (seconds for ``delay``/``slow``),
- ``@N`` starts firing at the N-th probe hit (default 1 = first),
- ``xM`` fires at most M times (default 1; ``x0`` = unlimited),
- ``%p`` fires each eligible hit with probability ``p`` from the
  stream seeded by ``~seed`` (default seed 0) — deterministic: the
  draw is keyed on ``(seed, site, hit index)``, so the same seed
  always fires the same schedule.

Examples::

    MAGICSOUP_CHAOS="checkpoint.write:enospc@2"      # 2nd save fails
    MAGICSOUP_CHAOS="dispatch:transient x3"          # (API form) 3 faults
    MAGICSOUP_CHAOS="fetch:delay:10;telemetry.emit:eio"

This module also hosts the process-wide **degraded-state registry**:
subsystems that choose graceful degradation over crashing (a warden
skipping a failed cadence save, a telemetry stream disarming itself on
``EIO``, the serve registry writer) record the transition here via
:func:`note_degraded` / :func:`clear_degraded`; ``/healthz`` and
``analysis.runtime.snapshot`` surface the registry, so no failure is
ever swallowed invisibly.

Stdlib-pure on purpose: ``guard.io`` (itself stdlib-pure by contract)
receives this module's probe by REGISTRATION — :data:`guard.io` is
imported here and handed :func:`site`, never the other way around — so
loading ``io.py`` as a standalone file still works and pays nothing.
"""
from __future__ import annotations

import os
import random
import re
import threading

from magicsoup_tpu.guard import io as _io
from magicsoup_tpu.guard.errors import GuardConfigError

__all__ = [
    "FAULT_POINTS",
    "SITES",
    "Fault",
    "arm",
    "armed",
    "clear_degraded",
    "counters",
    "degraded_states",
    "disarm",
    "events_since",
    "fault_points",
    "fired_counts",
    "note_counter",
    "note_degraded",
    "parse_spec",
    "reset_counters",
    "site",
    "spec",
]

#: every instrumented fault point and the fault kinds it understands —
#: the parse-time contract that keeps a typo'd spec from silently
#: arming nothing
SITES: dict[str, tuple[str, ...]] = {
    "io.write": ("enospc", "eio", "torn"),
    "checkpoint.write": ("enospc", "eio", "torn"),
    "checkpoint.read": ("eio",),
    "checkpoint.delete": ("eio",),
    "registry.write": ("enospc", "eio"),
    "dispatch": ("transient",),
    "fetch": ("delay",),
    "telemetry.emit": ("enospc", "eio"),
    "serve.response": ("drop", "malformed"),
    "serve.queue": ("full", "slow"),
}

#: where each fault point is probed: site -> (module, qualified callable).
#: This literal is the machine-readable half of the probe contract —
#: graftlint GL021 parses it straight out of this file's AST and fails
#: the lint gate when it disagrees with the probes actually present in
#: the tree, so the analyzer and the runtime plane can never drift.
FAULT_POINTS: dict[str, tuple[str, str]] = {
    "io.write": ("magicsoup_tpu.guard.io", "atomic_write_bytes"),
    "checkpoint.write": ("magicsoup_tpu.guard.checkpoint", "write_checkpoint"),
    "checkpoint.read": ("magicsoup_tpu.guard.checkpoint", "_read_header"),
    "checkpoint.delete": (
        "magicsoup_tpu.guard.checkpoint",
        "CheckpointManager.prune",
    ),
    "registry.write": (
        "magicsoup_tpu.serve.service",
        "FleetService._write_registry",
    ),
    "dispatch": ("magicsoup_tpu.stepper", "PipelinedStepper.step"),
    "fetch": ("magicsoup_tpu.stepper", "PipelinedStepper._replay"),
    "telemetry.emit": (
        "magicsoup_tpu.telemetry.recorder",
        "TelemetryRecorder._flush_locked",
    ),
    "serve.response": ("magicsoup_tpu.serve.api", "make_handler"),
    "serve.queue": ("magicsoup_tpu.serve.service", "FleetService.submit"),
}


def fault_points() -> list[dict]:
    """Machine-readable fault-point registry: one row per site with its
    fault kinds and the (module, callable) that probes it.  The single
    source of truth shared by the runtime plane, the chaos campaign
    matrix, and the static analyzer (GL021)."""
    return [
        {
            "site": name,
            "kinds": list(SITES[name]),
            "module": module,
            "callable": qualname,
        }
        for name, (module, qualname) in sorted(FAULT_POINTS.items())
    ]

#: kinds that require a float ``arg`` (seconds)
_ARG_REQUIRED = ("delay", "slow")

_ERRNO_BY_KIND = {"enospc": 28, "eio": 5}  # errno.ENOSPC, errno.EIO


class Fault:
    """One firing of an armed fault point.

    Attributes:
        site: The fault-point name that fired.
        kind: The fault kind from the matched clause.
        arg: The clause's float payload (seconds for delays), or None.
        index: 1-based fire count of the clause (for telemetry rows).
    """

    __slots__ = ("site", "kind", "arg", "index")

    def __init__(self, site: str, kind: str, arg: float | None, index: int):
        self.site = site
        self.kind = kind
        self.arg = arg
        self.index = index

    def as_oserror(self) -> OSError:
        """The errno-carrying ``OSError`` this fault stands for —
        instrumented I/O sites raise it from inside their real handler
        path, so the recovery code under test is the production code."""
        import errno as _errno

        code = _ERRNO_BY_KIND.get(self.kind, _errno.EIO)
        return OSError(
            code,
            f"chaos-injected {self.kind.upper()} at fault point "
            f"{self.site!r} (fire #{self.index})",
        )

    def __repr__(self) -> str:
        return (
            f"Fault(site={self.site!r}, kind={self.kind!r}, "
            f"arg={self.arg!r}, index={self.index})"
        )


class _Clause:
    """One parsed spec clause plus its live hit/fire counters."""

    __slots__ = ("site", "kind", "arg", "after", "count", "prob", "seed",
                 "hits", "fires")

    def __init__(self, site, kind, arg, after, count, prob, seed):
        self.site = site
        self.kind = kind
        self.arg = arg
        self.after = after
        self.count = count  # 0 = unlimited
        self.prob = prob
        self.seed = seed
        self.hits = 0
        self.fires = 0


_CLAUSE_RE = re.compile(
    r"^(?P<site>[a-z][a-z0-9_.]*):(?P<kind>[a-z]+)"
    r"(?::(?P<arg>\d+(?:\.\d+)?))?"
    r"(?:\s*@(?P<after>\d+))?"
    r"(?:\s*x(?P<count>\d+))?"
    r"(?:\s*%(?P<prob>\d*\.?\d+))?"
    r"(?:\s*~(?P<seed>\d+))?$"
)

_lock = threading.Lock()
_plane: dict[str, list[_Clause]] | None = None
_spec: str | None = None
_fired: dict[str, int] = {}
_counters: dict[str, int] = {}
# subsystem -> {"count": transitions-into-degraded, "detail": last reason}
_degraded: dict[str, dict] = {}
# bounded ring of "chaos"/"degraded" telemetry rows.  Recorders DRAIN
# this at their counter-emit boundaries (cursor-based, see
# :func:`events_since`) instead of being called synchronously — a fault
# can fire while a recorder holds its own buffer lock (the
# ``telemetry.emit`` site fires INSIDE the flush), so a push-style hook
# would deadlock exactly when it matters most.
_events: list[dict] = []
_events_base = 0  # global sequence index of _events[0]
_EVENT_CAP = 1024


def _record_event(row: dict) -> None:
    # caller holds _lock
    global _events_base
    _events.append(row)
    if len(_events) > _EVENT_CAP:
        drop = len(_events) - _EVENT_CAP
        del _events[:drop]
        _events_base += drop


def events_since(cursor: int) -> tuple[int, list[dict]]:
    """Telemetry rows recorded after ``cursor`` (a value this function
    previously returned; start from 0).  Returns ``(new_cursor, rows)``
    — each attached recorder keeps its own cursor, so several streams
    can observe the same transitions without stealing from each other.
    Rows older than the ring capacity are gone; the cursor just skips
    ahead."""
    with _lock:
        start = max(cursor - _events_base, 0)
        return _events_base + len(_events), [dict(r) for r in _events[start:]]


def parse_spec(
    text: str, *, variable: str = "MAGICSOUP_CHAOS"
) -> dict[str, list[_Clause]]:
    """Parse a chaos spec into per-site clause lists; bad specs raise
    :class:`GuardConfigError` naming ``variable`` (parse-time refusal,
    same contract as the watchdog's env knobs)."""
    plane: dict[str, list[_Clause]] = {}
    for raw in text.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        m = _CLAUSE_RE.match(raw.replace(" ", ""))
        if m is None:
            raise GuardConfigError(
                f"{variable}: unparseable chaos clause {raw!r}: expected "
                "site:kind[:arg][@after][xcount][%prob][~seed]",
                variable=variable,
                value=raw,
            )
        name, kind = m.group("site"), m.group("kind")
        kinds = SITES.get(name)
        if kinds is None:
            raise GuardConfigError(
                f"{variable}: unknown chaos site {name!r}; known sites: "
                f"{', '.join(sorted(SITES))}",
                variable=variable,
                value=raw,
            )
        if kind not in kinds:
            raise GuardConfigError(
                f"{variable}: site {name!r} does not understand fault "
                f"kind {kind!r}; "
                f"it takes: {', '.join(kinds)}",
                variable=variable,
                value=raw,
            )
        arg = m.group("arg")
        if arg is None and kind in _ARG_REQUIRED:
            raise GuardConfigError(
                f"{variable}: fault kind {kind!r} needs a seconds "
                "argument, e.g. "
                f"{name}:{kind}:0.5",
                variable=variable,
                value=raw,
            )
        prob = float(m.group("prob") or 1.0)
        if not 0.0 < prob <= 1.0:
            raise GuardConfigError(
                f"{variable}: chaos probability must be in (0, 1], "
                f"got {prob}",
                variable=variable,
                value=raw,
            )
        clause = _Clause(
            site=name,
            kind=kind,
            arg=None if arg is None else float(arg),
            after=int(m.group("after") or 1),
            count=int(m.group("count") if m.group("count") is not None else 1),
            prob=prob,
            seed=int(m.group("seed") or 0),
        )
        plane.setdefault(name, []).append(clause)
    return plane


def arm(text: str) -> None:
    """Arm the fault plane from a spec string (replaces any prior
    schedule; clause counters start fresh)."""
    global _plane, _spec
    plane = parse_spec(text)
    with _lock:
        _plane = plane or None
        _spec = text if plane else None


def disarm() -> None:
    """Drop the armed schedule; every probe goes back to zero-cost."""
    global _plane, _spec
    with _lock:
        _plane = None
        _spec = None


def armed() -> bool:
    return _plane is not None


def spec() -> str | None:
    """The armed spec string, or None."""
    return _spec


def site(name: str) -> Fault | None:
    """Probe one fault point.  Returns ``None`` (the overwhelmingly
    common case — also when disarmed: one global read, no lock) or the
    :class:`Fault` the instrumented caller must inflict.

    Deterministic: each clause counts probe HITS; firing is a pure
    function of (hit index, clause schedule, clause seed), so the same
    spec over the same execution fires the same sites in the same
    order.  With several clauses on one site, the first eligible clause
    wins and later clauses still observe the hit."""
    plane = _plane
    if plane is None:
        return None
    clauses = plane.get(name)
    if not clauses:
        return None
    with _lock:
        fault = None
        for c in clauses:
            c.hits += 1
            if fault is not None:
                continue
            if c.hits < c.after:
                continue
            if c.count and c.fires >= c.count:
                continue
            if c.prob < 1.0:
                draw = random.Random(f"{c.seed}:{name}:{c.hits}").random()
                if draw >= c.prob:
                    continue
            c.fires += 1
            _fired[name] = _fired.get(name, 0) + 1
            fault = Fault(name, c.kind, c.arg, c.fires)
            _record_event(
                {
                    "type": "chaos",
                    "site": name,
                    "kind": c.kind,
                    "index": c.fires,
                }
            )
    return fault


def fired_counts() -> dict[str, int]:
    """Fires per site since the last :func:`arm`/:func:`reset_counters`."""
    with _lock:
        return dict(_fired)


# ----------------------------------------------------------------- #
# degraded-state registry + generic failure counters                #
# ----------------------------------------------------------------- #

def note_degraded(subsystem: str, detail: str = "") -> int:
    """Record that ``subsystem`` entered (or stayed in) its degraded
    state; returns the transition count.  Callers pair this with a
    telemetry ``degraded`` row and a single ``warnings.warn`` so the
    failure is visible in all three places a run is observed from."""
    with _lock:
        rec = _degraded.setdefault(subsystem, {"count": 0, "detail": ""})
        rec["count"] += 1
        rec["detail"] = detail
        _record_event(
            {
                "type": "degraded",
                "subsystem": subsystem,
                "state": "degraded",
                "count": rec["count"],
                "detail": detail,
            }
        )
        return rec["count"]


def clear_degraded(subsystem: str) -> None:
    """Mark ``subsystem`` recovered (drops it from the registry; its
    transition count remains visible via :func:`counters`)."""
    with _lock:
        rec = _degraded.pop(subsystem, None)
        if rec is not None:
            _counters[f"degraded_transitions:{subsystem}"] = (
                _counters.get(f"degraded_transitions:{subsystem}", 0)
                + rec["count"]
            )
            _record_event(
                {
                    "type": "degraded",
                    "subsystem": subsystem,
                    "state": "recovered",
                    "count": rec["count"],
                }
            )


def degraded_states() -> dict[str, dict]:
    """Currently degraded subsystems -> {"count", "detail"} (the map
    ``/healthz`` publishes)."""
    with _lock:
        return {k: dict(v) for k, v in _degraded.items()}


def note_counter(name: str, n: int = 1) -> int:
    """Bump a named chaos/robustness counter (retention-delete
    failures, dropped telemetry rows, ...).  Merged into
    ``analysis.runtime.snapshot()`` so the one flat counter dict the
    telemetry rows carry includes every counted failure."""
    with _lock:
        _counters[name] = _counters.get(name, 0) + int(n)
        return _counters[name]


def counters() -> dict[str, int]:
    with _lock:
        return dict(_counters)


def runtime_counters() -> dict[str, int]:
    """The chaos contribution to ``analysis.runtime.snapshot()``:
    ``chaos_fired`` (total fault firings), ``degraded`` (subsystems
    currently degraded), plus every :func:`note_counter` key."""
    with _lock:
        out = {
            "chaos_fired": sum(_fired.values()),
            "degraded": len(_degraded),
        }
        out.update(_counters)
        return out


def reset_counters() -> None:
    """Zero fired counts, failure counters, and the degraded registry
    (the armed schedule, if any, keeps its clause state) — test
    isolation, called by ``analysis.runtime.reset_counters``."""
    global _events_base
    with _lock:
        _fired.clear()
        _counters.clear()
        _degraded.clear()
        # keep the event sequence monotone across resets so recorder
        # cursors never point past rows that haven't happened yet
        _events_base += len(_events)
        _events.clear()


# hand guard.io the probe (registration, not import — see module docs)
_io._chaos_probe = site

# env arming: read once at import, same as analysis/ownership.py; a bad
# spec fails HERE with the variable named, not deep inside a write
_env_spec = os.environ.get("MAGICSOUP_CHAOS", "").strip()
if _env_spec:
    arm(_env_spec)
