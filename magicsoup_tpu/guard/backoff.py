"""One deterministic backoff policy for every retry ladder.

Three copies of "exponential backoff" had grown in the tree — the
dispatch retry delays in :mod:`guard.retry`, the warden's
``backoff_base * 2**restarts`` heal cooldown, and the serve edge's
retry hinting — each with its own clamp and growth code.  This module
is the single shared policy; the divergence risk it removes is real: a
ladder whose jitter draws from the global PRNG would fork det-mode
trajectories, and a ladder with no cap turns a persistent fault into an
unbounded sleep.

Determinism contract: :meth:`BackoffPolicy.delay` is a PURE function of
``(policy config, attempt)`` — jitter, when enabled, draws from a
private ``random.Random`` keyed on ``(seed, attempt)``, never from the
global stream, so the same policy replays the same delays and a jittered
retry schedule cannot desynchronize two det-mode runs.

The clock is injectable (:meth:`sleep` takes the sleep function), so
tests and the chaos campaign runner assert exact schedules without
waiting them out.
"""
from __future__ import annotations

import time
from typing import Callable

__all__ = ["BackoffPolicy"]


class BackoffPolicy:
    """Seeded, capped, optionally jittered exponential backoff.

    Parameters:
        base: Delay for attempt 1 (seconds, or scheduler steps — the
            unit is the caller's).
        factor: Growth per attempt (default 2.0).
        max_delay: Upper clamp applied after growth AND after jitter;
            ``float("inf")`` disables the cap.
        jitter: Fractional spread in ``[0, 1)``: attempt ``n``'s delay
            is scaled by a factor drawn uniformly from
            ``[1 - jitter, 1 + jitter]``.  0 (default) = exact ladder.
        seed: Jitter stream seed; two policies with equal config
            produce identical schedules.
    """

    def __init__(
        self,
        *,
        base: float,
        factor: float = 2.0,
        max_delay: float = float("inf"),
        jitter: float = 0.0,
        seed: int = 0,
    ):
        if base < 0:
            raise ValueError(f"base must be >= 0, got {base}")
        if factor < 1.0:
            raise ValueError(f"factor must be >= 1, got {factor}")
        if not 0.0 <= jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {jitter}")
        self.base = float(base)
        self.factor = float(factor)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self.seed = int(seed)

    def delay(self, attempt: int) -> float:
        """Delay before retry ``attempt`` (1-based).  Pure: no clock,
        no global randomness, no internal state."""
        if attempt < 1:
            from magicsoup_tpu.guard.errors import GuardConfigError

            raise GuardConfigError(
                f"attempt is 1-based, got {attempt}",
                variable="attempt",
                value=str(attempt),
            )
        d = min(self.max_delay, self.base * self.factor ** (attempt - 1))
        if self.jitter:
            import random

            u = random.Random(f"{self.seed}:{attempt}").random()
            d = min(self.max_delay, d * (1.0 + self.jitter * (2.0 * u - 1.0)))
        return d

    def sleep(
        self, attempt: int, *, sleep: Callable[[float], None] = time.sleep
    ) -> float:
        """Sleep out attempt ``attempt``'s delay (injectable clock);
        returns the delay slept."""
        d = self.delay(attempt)
        sleep(d)
        return d

    def schedule(self, attempts: int) -> list[float]:
        """The first ``attempts`` delays — what a bounded retry loop
        will pay end to end (tests pin these exactly)."""
        return [self.delay(i) for i in range(1, attempts + 1)]

    def __repr__(self) -> str:
        return (
            f"BackoffPolicy(base={self.base}, factor={self.factor}, "
            f"max_delay={self.max_delay}, jitter={self.jitter}, "
            f"seed={self.seed})"
        )
