"""Health sentinels: decode the packed flag word, quarantine bad cells.

The fused step folds a 4-bit health word into the step record it
already fetches (stepper record word 8), so detection is unconditional,
det-safe, and costs ZERO extra device-to-host transfers — the same
packed-lane pattern the metric lanes use.  This module is the HOST side:
interpreting the flags and acting on them under the configured policy.

Policies (``PipelinedStepper(sentinel_policy=...)``):

- ``"warn"`` (default): count the trip in stats + emit a telemetry note.
- ``"quarantine"``: additionally kill the poisoned cells and sanitize
  the molecule map at the next safe host boundary (the stepper flushes
  its pipeline first — quarantine mutates world state, which would
  otherwise race in-flight megasteps).
- ``"rollback"``: raise :class:`~magicsoup_tpu.guard.errors.SentinelTripped`
  so the driver restores the last good checkpoint.

Bit layout of the flag word (must match ``ms:sentinel`` in stepper.py)::

    bit 0  molecule map has a non-finite value
    bit 1  molecule map has a value below -NEG_EPS
    bit 2  a live cell's molecules have a non-finite value
    bit 3  a live cell's molecules have a value below -NEG_EPS
"""
from __future__ import annotations

import numpy as np

SENTINEL_POLICIES = ("warn", "quarantine", "rollback")

# tolerance below zero before a concentration counts as "negative":
# the integrator clips at 0 but fp arithmetic on clipped values can
# transiently dip an epsilon below — only a materially negative value
# indicates divergence
NEG_EPS = 1e-4

FLAG_MM_NONFINITE = 1 << 0
FLAG_MM_NEGATIVE = 1 << 1
FLAG_CM_NONFINITE = 1 << 2
FLAG_CM_NEGATIVE = 1 << 3


def decode_health(flags: int) -> dict:
    """Expand the packed flag word into named booleans."""
    flags = int(flags)
    return {
        "mm_nonfinite": bool(flags & FLAG_MM_NONFINITE),
        "mm_negative": bool(flags & FLAG_MM_NEGATIVE),
        "cm_nonfinite": bool(flags & FLAG_CM_NONFINITE),
        "cm_negative": bool(flags & FLAG_CM_NEGATIVE),
    }


def quarantine_world(world) -> int:
    """Kill cells carrying non-finite/negative concentrations and
    sanitize the molecule map.  Returns how many cells were killed.

    Host-boundary operation: callers (the stepper's quarantine hook)
    must have drained in-flight device work first.
    """
    n_killed = 0
    if world.n_cells > 0:
        cm = np.asarray(world.cell_molecules)
        bad = ~np.isfinite(cm) | (cm < -NEG_EPS)
        rows = np.nonzero(bad.any(axis=1))[0]
        if len(rows) > 0:
            world.kill_cells([int(r) for r in rows])
            n_killed = len(rows)
    mm = np.asarray(world.molecule_map)
    if not np.isfinite(mm).all() or (mm < -NEG_EPS).any():
        world.molecule_map = np.clip(
            np.nan_to_num(mm, nan=0.0, posinf=0.0, neginf=0.0), 0.0, None
        )
    return n_killed
