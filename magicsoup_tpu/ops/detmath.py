"""
Deterministic, backend-independent math building blocks.

The CPU-vs-TPU bit-reproducibility target (BASELINE.md north star) fails
on exactly three classes of primitives, because XLA lowers them to
backend-specific implementations:

1. transcendentals (`exp`, `pow`) — each backend ships its own
   approximation, so results differ by a few ULP;
2. reductions (`sum`, `prod`, convolutions) — each backend picks its own
   reduction tree, and float addition is not associative;
3. excess-precision rewrites (FMA contraction) — measured to happen ONLY
   inside large fusions on TPU (an isolated ``a*b+c`` jit two-rounds, the
   same expression fused into a big program contracts), so every
   multiply feeding an add/sub below is separated by
   ``lax.optimization_barrier``; `scripts/bitrepro.py` additionally sets
   ``XLA_FLAGS=--xla_allow_excess_precision=false``.

Everything here is built ONLY from IEEE-754-exact single ops (add, sub,
mul, div, compare, select, integer bit ops) applied in a fixed order, so
any two IEEE-conforming backends produce bit-identical results.  The
constructions are also TPU-friendly: masked square-and-multiply replaces
`pow` (faster than a transcendental on the VPU), and the fixed binary
reduction trees vectorize exactly like the backend's own.
"""
import jax
import jax.numpy as jnp


def _nofma(x: jax.Array) -> jax.Array:
    """Pin a multiply result so XLA cannot contract it into a dependent
    add/sub as an FMA (which rounds once instead of twice and does so
    backend-dependently)."""
    return jax.lax.optimization_barrier(x)

_LOG2E = 1.4426950408889634
# Taylor coefficients of 2^f = exp(f ln2) on f in [-0.5, 0.5]
_EXP2_COEFFS = (
    1.0,
    6.931471805599453e-1,
    2.402265069591007e-1,
    5.550410866482158e-2,
    9.618129107628477e-3,
    1.3333558146428441e-3,
    1.5403530393381606e-4,
    1.525273380405984e-5,
)
_POW_BITS = 7  # supports |n| <= 127; stoichiometries/hill sums stay far below


def ipow(x: jax.Array, n: jax.Array) -> jax.Array:
    """
    ``x ** n`` for float ``x >= 0`` and integer ``n`` via masked
    square-and-multiply — bit-identical across backends, and matching
    ``jnp.power``'s edge semantics on the integrator's domain:
    ``0**0 = 1``, ``0**+n = 0``, ``0**-n = inf``.

    Exponents with ``|n| >= 2**_POW_BITS`` (beyond any real stoichiometry
    or hill sum) saturate to the limit value 0/1/inf of ``x**±inf``
    instead of silently dropping high bits.
    """
    n = n.astype(jnp.int32)
    absn = jnp.abs(n)
    r = jnp.ones_like(x)
    xp = x
    for bit in range(_POW_BITS):
        r = jnp.where((absn >> bit) & 1 == 1, r * xp, r)
        if bit < _POW_BITS - 1:
            xp = xp * xp
    # saturate out-of-range exponents: x**(huge n) -> 0 / 1 / inf
    huge = jnp.where(
        x > 1.0, jnp.float32(jnp.inf), jnp.where(x == 1.0, 1.0, 0.0)
    )
    r = jnp.where(absn >= (1 << _POW_BITS), huge, r)
    return jnp.where(n < 0, det_div(jnp.ones_like(r), r), r)


def det_exp(x: jax.Array) -> jax.Array:
    """
    ``exp(x)`` from exact ops only: split ``x·log2(e) = k + f`` with
    integer ``k`` and ``f ∈ [-0.5, 0.5]``, evaluate ``2^f`` by a fixed
    Horner polynomial, and scale by ``2^k`` built by integer bit
    assembly.  Accuracy ~1-2 ULP vs the libm exp; identical on every
    IEEE backend.
    """
    x = x.astype(jnp.float32)
    y = x * jnp.float32(_LOG2E)
    k = jnp.round(y)
    f = (y - k).astype(jnp.float32)

    p = jnp.full_like(f, _EXP2_COEFFS[-1])
    for c in _EXP2_COEFFS[-2::-1]:
        p = _nofma(p * f) + jnp.float32(c)

    # 2^k via exponent-field assembly; clamp into normal f32 range and
    # split into two factors so k in [-252, 252] is representable
    # (NaN -> 0 first: NaN-to-int conversion is backend-defined)
    k = jnp.clip(jnp.nan_to_num(k), -252.0, 252.0).astype(jnp.int32)
    k_half = k // 2
    k_rest = k - k_half

    def pow2i(e):
        return jax.lax.bitcast_convert_type(
            ((e + 127) << 23).astype(jnp.int32), jnp.float32
        )

    return p * pow2i(k_half) * pow2i(k_rest)


def det_div(a: jax.Array, b: jax.Array) -> jax.Array:
    """
    Deterministic float32 division.  Hardware f32 division is NOT
    correctly rounded on TPU (measured: up to 2 ULP off the CPU result),
    so ``a / b`` is the one arithmetic primitive that cannot be used
    directly for cross-backend bit-reproducibility.  This computes the
    reciprocal by the classic magic-constant bit hack plus Newton
    iterations — integer ops, multiplies and subtractions only, all of
    which ARE exact on both backends — then multiplies.  Accuracy ~1 ULP;
    more importantly, bit-identical everywhere.

    Non-finite/zero divisors fall back to hardware division: IEEE special
    cases (x/0 = ±inf, x/inf = 0, NaN propagation) are exact on every
    backend.  |b| must otherwise be in the normal range; the simulation
    clamps its divisors into [EPS, MAX] = [1e-36, 1e36], far inside it.
    """
    bn = jnp.abs(b)
    # seed: r0 ~ 1/bn with ~3% error (0x7EF311C3 bit trick)
    bits = jax.lax.bitcast_convert_type(bn, jnp.int32)
    r = jax.lax.bitcast_convert_type(jnp.int32(0x7EF311C3) - bits, jnp.float32)
    for _ in range(4):
        # Newton: quadratic convergence; barrier stops FMS contraction
        r = r * (2.0 - _nofma(bn * r))
    q = a * r
    q = jnp.where(jnp.signbit(b), -q, q)
    # soft path only where the seed is valid: NORMAL-range divisors below
    # ~1.6e38 (the magic-constant subtraction underflows above that, and
    # denormal divisors diverge at input level anyway via TPU FTZ);
    # outside, hardware division — IEEE special cases are exact everywhere
    ok = (
        (bn >= jnp.float32(1.17549435e-38))
        & (bn <= jnp.float32(1e37))
        & jnp.isfinite(bn)
    )
    return jnp.where(ok, q, a / b)


def tree_reduce(x: jax.Array, axis: int, op, identity: float) -> jax.Array:
    """
    Reduce one axis with a FIXED binary tree (padded with the exact
    identity element to a power of two).  One shared implementation for
    the deterministic sum and product trees — the tree SHAPE is
    load-bearing for cross-backend bit-identity, so it must not drift
    between them.  Slices along the ORIGINAL axis: no transpose/relayout,
    which would dominate the cost on TPU for (cells, proteins, signals)
    tensors.
    """
    axis = axis % x.ndim
    n = x.shape[axis]
    p = 1 << max(n - 1, 0).bit_length() if n > 1 else 1
    if p != n:
        pad = [(0, 0)] * x.ndim
        pad[axis] = (0, p - n)
        x = jnp.pad(x, pad, constant_values=identity)
    while x.shape[axis] > 1:
        h = x.shape[axis] // 2
        x = op(
            jax.lax.slice_in_dim(x, 0, h, axis=axis),
            jax.lax.slice_in_dim(x, h, 2 * h, axis=axis),
        )
    return jnp.squeeze(x, axis=axis)


def sum_axis(x: jax.Array, axis: int) -> jax.Array:
    """Deterministic float sum over one axis (fixed binary tree)."""
    # the summands are often products; stop the first tree level from
    # absorbing them as FMAs
    return tree_reduce(_nofma(x), axis, jnp.add, 0.0)


def prod_axis(x: jax.Array, axis: int) -> jax.Array:
    """Deterministic float product over one axis (fixed binary tree) —
    also the Pallas-lowerable form (`reduce_prod` has no Mosaic rule)."""
    return tree_reduce(x, axis, jnp.multiply, 1.0)


def sum_hw(x: jax.Array) -> jax.Array:
    """Sum over the trailing two (spatial) axes via one fixed tree."""
    return sum_axis(x.reshape(x.shape[:-2] + (-1,)), -1)
