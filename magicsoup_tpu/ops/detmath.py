"""
Deterministic, backend-independent math building blocks.

The CPU-vs-TPU bit-reproducibility target (BASELINE.md north star) fails
on exactly three classes of primitives, because XLA lowers them to
backend-specific implementations.  Each class was isolated empirically
on TPU v5e vs XLA:CPU (see BITREPRO.md):

1. transcendentals (`exp`, `pow`) — each backend ships its own
   approximation (measured: up to 67 ULP in exp-derived values);
2. reductions (`sum`, `prod`, convolutions) — each backend picks its own
   reduction tree, and float addition is not associative;
3. division and mixed multiply-add:
   - f32 (and even f64) HARDWARE division is not correctly rounded on
     TPU (measured: up to 2 ULP vs CPU);
   - a float32 multiply feeding an add/sub inside ANY fusion is
     FMA-contracted on TPU (single rounding) but not on CPU — and
     ``lax.optimization_barrier`` does NOT prevent it (measured: a
     standalone jitted Horner with barriers still differs by 1 ULP
     while the op-by-op eager execution is bit-identical).

The verified-deterministic primitive set on both backends is therefore:
float32 multiply CHAINS, float32 add/sub TREES (no multiply operands),
float64 multiply+add (the TPU emulates f64 in software, measured
bit-identical even fused), integer/bit ops, compares, selects, and
dtype conversions.  Everything here is built only from that set:

- `ipow` — masked square-and-multiply (f32 multiply chain + selects);
- `det_exp` — exp2-split + Horner polynomial evaluated in float64;
- `det_div` — magic-constant seeded Newton reciprocal iterated in
  float64 (no hardware division on the soft path);
- `tree_reduce`/`sum_axis`/`prod_axis` — fixed binary reduction trees;
  `sum_axis` accumulates in float64 so raw-product inputs are separated
  from the first add level by a dtype conversion (structurally
  un-contractable, unlike a barrier).

`scripts/bitrepro.py` additionally sets
``XLA_FLAGS=--xla_allow_excess_precision=false`` for both children.
"""
import jax
import jax.numpy as jnp
import numpy as np

# jax >= 0.4.26 removed the jax.enable_x64 alias; the experimental
# context manager is the stable spelling of the same x64 scope
from jax.experimental import enable_x64 as _enable_x64

_LOG2E = 1.4426950408889634
# Taylor coefficients of 2^f = exp(f ln2) on f in [-0.5, 0.5]
_EXP2_COEFFS = (
    1.0,
    6.931471805599453e-1,
    2.402265069591007e-1,
    5.550410866482158e-2,
    9.618129107628477e-3,
    1.3333558146428441e-3,
    1.5403530393381606e-4,
    1.525273380405984e-5,
)
_POW_BITS = 7  # supports |n| <= 127; stoichiometries/hill sums stay far below
_F32_MIN_NORMAL = 1.17549435e-38


def _f64(x: jax.Array) -> jax.Array:
    """Convert to float64 (requires the enclosing x64 context)."""
    return x.astype(jnp.float64)


def traced_zeros32(t: jax.Array) -> jax.Array:
    """
    A TRACED float32 zero array shaped like ``t`` (a tracer).

    The ``enable_x64`` scopes here only cover TRACING; jit lowers the
    jaxpr later, after the scope has exited, and with x64 globally off
    the lowering canonicalizes EVERY f64 (and i64) literal in the
    program to 32 bits — failing the StableHLO verifier against the f64
    avals the trace produced.  Wide constants therefore must be BUILT by
    traced ops whose jaxpr literals are all 32-bit, and traced ops need
    a tracer operand (ops on concrete values execute eagerly and
    collapse back into a wide literal).  This zero is that anchor: the
    bit pattern is integer-masked (exact even for inf/NaN inputs, unlike
    multiplying by zero), and XLA folds the whole ladder at compile
    time, so the runtime cost is nil.
    """
    bits = jax.lax.bitcast_convert_type(t.astype(jnp.float32), jnp.int32)
    return jax.lax.bitcast_convert_type(bits & jnp.int32(0), jnp.float32)


def _c64(value: float, zero32: jax.Array) -> jax.Array:
    """A traced float64 constant broadcast over ``zero32``'s shape (a
    traced f32 zero from :func:`traced_zeros32`).  Three f32 pieces
    (hi + mid + lo, each holding the next 24 bits) are added to the
    traced zero and converted, so every jaxpr literal stays f32; the
    converted pieces reconstruct any normal f64 exactly — the first f64
    sum is exact (<= 49 significant bits) and the second rounds back to
    the original value (error < 2^-73 relative)."""
    v = np.float64(value)
    hi = np.float32(v)
    mid = np.float32(v - np.float64(hi))
    lo = np.float32(v - np.float64(hi) - np.float64(mid))
    out = (zero32 + jnp.float32(hi)).astype(jnp.float64)
    out = out + (zero32 + jnp.float32(mid)).astype(jnp.float64)
    return out + (zero32 + jnp.float32(lo)).astype(jnp.float64)


def _ci64(value: int, zero_i32: jax.Array) -> jax.Array:
    """A traced int64 constant broadcast over ``zero_i32``'s shape: an
    i32 literal added to a traced i32 zero, then a traced convert (see
    traced_zeros32 for why the literal must stay 32-bit)."""
    return (zero_i32 + jnp.int32(value)).astype(jnp.int64)


def ipow(x: jax.Array, n: jax.Array, nonneg: bool = False) -> jax.Array:
    """
    ``x ** n`` for float ``x >= 0`` and integer ``n`` via masked
    square-and-multiply — a pure f32 multiply chain plus selects, both
    bit-identical across backends — matching ``jnp.power``'s edge
    semantics on the integrator's domain: ``0**0 = 1``, ``0**+n = 0``,
    ``0**-n = inf``.

    Exponents with ``|n| >= 2**_POW_BITS`` (beyond any real stoichiometry
    or hill sum) saturate to the limit value 0/1/inf of ``x**±inf``
    instead of silently dropping high bits.

    ``nonneg=True`` (static) promises ``n >= 0`` and skips the Newton
    reciprocal for the negative-exponent branch entirely — the
    substrate/product stoichiometries (Nf/Nb) are non-negative by
    construction and are the integrator's hottest ipow sites.
    """
    n = n.astype(jnp.int32)
    absn = jnp.abs(n)
    r = jnp.ones_like(x)
    xp = x
    for bit in range(_POW_BITS):
        r = jnp.where((absn >> bit) & 1 == 1, r * xp, r)
        if bit < _POW_BITS - 1:
            xp = xp * xp
    # saturate out-of-range exponents: x**(huge n) -> 0 / 1 / inf
    huge = jnp.where(
        x > 1.0, jnp.float32(jnp.inf), jnp.where(x == 1.0, 1.0, 0.0)
    )
    r = jnp.where(absn >= (1 << _POW_BITS), huge, r)
    if nonneg:
        return r
    return jnp.where(n < 0, det_div(jnp.ones_like(r), r), r)


def det_exp(x: jax.Array) -> jax.Array:
    """
    ``exp(x)`` deterministic across backends: split ``x·log2(e) = k + f``
    with integer ``k`` and ``f ∈ [-0.5, 0.5]``, evaluate ``2^f`` by a
    Horner polynomial in FLOAT64 (f64 multiply+add is deterministic on
    both backends even when fused; the f32 Horner gets FMA-contracted on
    TPU only), and scale by ``2^k`` built by integer bit assembly.
    Returns float32; accuracy ~1 ULP vs libm, saturating to 0/inf exactly
    where float32 ``np.exp`` does.
    """
    with _enable_x64(True):
        z32 = traced_zeros32(x)
        zi32 = jax.lax.bitcast_convert_type(z32, jnp.int32)
        x64 = _f64(x)
        y = x64 * _c64(_LOG2E, z32)
        k = jnp.round(y)
        f = y - k

        p = _c64(_EXP2_COEFFS[-1], z32)
        for c in _EXP2_COEFFS[-2::-1]:
            p = p * f + _c64(c, z32)

        # 2^k via f64 exponent-field assembly (one factor covers the
        # whole f64 range; overflow/underflow happens at the final f32
        # downcast, exactly like np.exp on float32).  The clamp runs in
        # f32 — k is already integral and the post-clip range [-1022,
        # 1023] is f32-exact, while out-of-range |k| only saturates
        # harder (f32 overflow -> inf -> clip limit, same result).
        # (NaN -> 0 first: NaN-to-int conversion is backend-defined;
        # strong f32 scalars throughout — a bare Python float is WEAK
        # f64 under the x64 trace and trips the same lowering mismatch)
        k32 = k.astype(jnp.float32)
        k32 = jnp.where(jnp.isnan(k32), jnp.float32(0.0), k32)
        k32 = jnp.clip(k32, jnp.float32(-1022.0), jnp.float32(1023.0))
        ki = k32.astype(jnp.int64)
        scale = jax.lax.bitcast_convert_type(
            (ki + _ci64(1023, zi32)) << _ci64(52, zi32), jnp.float64
        )
        out = (p * scale).astype(jnp.float32)
    # ±inf inputs: f = inf - inf = NaN poisons the polynomial; restore the
    # np.exp saturation contract (exp(inf) = inf, exp(-inf) = 0)
    out = jnp.where(x == jnp.inf, jnp.float32(jnp.inf), out)
    out = jnp.where(x == -jnp.inf, jnp.float32(0.0), out)
    return out


def det_div(a: jax.Array, b: jax.Array) -> jax.Array:
    """
    Deterministic float32 division.  Hardware division is NOT correctly
    rounded on TPU in f32 or f64 (measured: up to 2 ULP off the CPU
    result), so ``a / b`` cannot be used directly.  The divisor's
    mantissa is extracted by integer bit ops into [1, 2), its reciprocal
    is seeded by the classic magic-constant bit hack (exact) and refined
    by Newton iterations in FLOAT64 — whose fused multiply+add is
    deterministic on both backends — then rescaled by the exact power of
    two of the original exponent, so EVERY normal-range f32 divisor takes
    the deterministic path.  Relative error ~1e-16 before the single
    rounding to f32.

    Subnormal, zero, and non-finite divisors fall back to hardware
    division: IEEE special cases (x/0 = ±inf, x/inf = 0, NaN propagation)
    are exact everywhere, and subnormal divisors diverge at input level
    anyway via the TPU's flush-to-zero.
    """
    bn = jnp.abs(b)
    bits = jax.lax.bitcast_convert_type(bn, jnp.int32)
    # normalize: mantissa m in [1, 2) with bn = m * 2^e (all exact bit ops)
    e = (bits >> 23) - 127  # unbiased exponent (normal bn only)
    m = jax.lax.bitcast_convert_type(
        (bits & jnp.int32(0x007FFFFF)) | jnp.int32(0x3F800000), jnp.float32
    )
    # seed: r0 ~ 1/m with ~3% error (0x7EF311C3 bit trick, f32 exact)
    seed = jax.lax.bitcast_convert_type(
        jnp.int32(0x7EF311C3)
        - jax.lax.bitcast_convert_type(m, jnp.int32),
        jnp.float32,
    )
    with _enable_x64(True):
        z32 = traced_zeros32(m)
        zi32 = jax.lax.bitcast_convert_type(z32, jnp.int32)
        m64 = _f64(m)
        r = _f64(seed)
        two = _c64(2.0, z32)
        for _ in range(4):
            r = r * (two - m64 * r)  # f64 Newton: deterministic fused
        # 1/bn = (1/m) * 2^-e; scale by exact f64 exponent assembly
        scale = jax.lax.bitcast_convert_type(
            (_ci64(1023, zi32) - e.astype(jnp.int64)) << _ci64(52, zi32),
            jnp.float64,
        )
        q = (_f64(a) * (r * scale)).astype(jnp.float32)
    q = jnp.where(jnp.signbit(b), -q, q)
    ok = (bn >= jnp.float32(_F32_MIN_NORMAL)) & jnp.isfinite(bn)
    return jnp.where(ok, q, a / b)


def tree_reduce(x: jax.Array, axis: int, op, identity: float) -> jax.Array:
    """
    Reduce one axis with a FIXED binary tree (padded with the exact
    identity element to a power of two).  One shared implementation for
    the deterministic sum and product trees — the tree SHAPE is
    load-bearing for cross-backend bit-identity, so it must not drift
    between them.  Slices along the ORIGINAL axis: no transpose/relayout,
    which would dominate the cost on TPU for (cells, proteins, signals)
    tensors.
    """
    axis = axis % x.ndim
    n = x.shape[axis]
    p = 1 << max(n - 1, 0).bit_length() if n > 1 else 1
    if p != n:
        pad = [(0, 0)] * x.ndim
        pad[axis] = (0, p - n)
        x = jnp.pad(x, pad, constant_values=identity)
    while x.shape[axis] > 1:
        h = x.shape[axis] // 2
        x = op(
            jax.lax.slice_in_dim(x, 0, h, axis=axis),
            jax.lax.slice_in_dim(x, h, 2 * h, axis=axis),
        )
    return jnp.squeeze(x, axis=axis)


def sum_axis(x: jax.Array, axis: int) -> jax.Array:
    """
    Deterministic float sum over one axis.  The tree accumulates in
    FLOAT64: the up-conversion structurally separates raw-product inputs
    from the first add level (an f32 multiply feeding an f32 add would be
    FMA-contracted on TPU regardless of optimization barriers), and f64
    multiply/add is itself deterministic on both backends.  Returns the
    input dtype.
    """
    # pad to the tree's power-of-two width BEFORE the f64 up-conversion:
    # tree_reduce's pad constant would otherwise be a float64 literal,
    # which jit canonicalizes to f32 at lowering time (the x64 scope only
    # covers tracing — see traced_zeros32); padding the f32 input with an
    # f32 zero is exact and leaves tree_reduce nothing to pad
    axis = axis % x.ndim
    n = x.shape[axis]
    p = 1 << max(n - 1, 0).bit_length() if n > 1 else 1
    if p != n:
        pad = [(0, 0)] * x.ndim
        pad[axis] = (0, p - n)
        x = jnp.pad(x, pad, constant_values=0.0)
    with _enable_x64(True):
        out = tree_reduce(_f64(x), axis, jnp.add, 0.0)
        return out.astype(x.dtype)


def prod_axis(x: jax.Array, axis: int) -> jax.Array:
    """Deterministic float product over one axis (fixed binary f32
    multiply tree — multiply chains do not get contracted) — also the
    Pallas-lowerable form (`reduce_prod` has no Mosaic rule)."""
    return tree_reduce(x, axis, jnp.multiply, 1.0)


def sum_hw(x: jax.Array) -> jax.Array:
    """Sum over the trailing two (spatial) axes via one fixed tree."""
    return sum_axis(x.reshape(x.shape[:-2] + (-1,)), -1)
