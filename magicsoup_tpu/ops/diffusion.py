"""
Molecule-map physics as jit-compiled XLA kernels: diffusion (depthwise 3x3
torus convolution), membrane permeation, and degradation.

Math parity reference: `python/magicsoup/world.py:627-678,935-984` —
diffusion kernel ``a = 1/(1/d + 8)`` off-center / ``b = 1 - 8a`` center with
circular padding, the total-mass correction spread over all pixels, clamping
at zero, permeation factor ``1/(1/p + 1)`` exchanging between a cell and its
pixel, and per-species exponential decay.

TPU-first deltas (SURVEY.md §7 design delta 4): one batched depthwise
convolution over all molecule channels (the reference loops one Conv2d per
molecule), wrap-padding via ``jnp.pad(mode="wrap")``, and all three physics
ops exposed as pure functions over the full slot-capacity state so they fuse
under a single jit with the gather/scatter of cell signals.
"""
import jax
import jax.numpy as jnp
import numpy as np


def diffusion_kernels(diffusivities: list[float]) -> np.ndarray:
    """(n_mols, 3, 3) depthwise kernels from per-molecule diffusivities"""
    kernels = np.zeros((len(diffusivities), 3, 3), dtype=np.float32)
    for i, rate in enumerate(diffusivities):
        rate = min(abs(rate), 1.0)
        if rate == 0.0:
            a, b = 0.0, 1.0
        else:
            a = 1.0 / (1.0 / rate + 8.0)
            b = 1.0 - 8.0 * a
        kernels[i] = a
        kernels[i, 1, 1] = b
    return kernels


def permeation_factors(permeabilities: list[float]) -> np.ndarray:
    """(n_mols,) per-step exchange ratios from permeabilities"""
    out = np.zeros(len(permeabilities), dtype=np.float32)
    for i, rate in enumerate(permeabilities):
        rate = min(abs(rate), 1.0)
        out[i] = 0.0 if rate == 0.0 else 1.0 / (1.0 / rate + 1.0)
    return out


def degradation_factors(half_lives: list[float]) -> np.ndarray:
    """(n_mols,) per-step decay factors exp(-ln2 / half_life)"""
    return np.exp(-np.log(2.0) / np.array(half_lives, dtype=np.float64)).astype(
        np.float32
    )


@jax.jit
def diffuse(molecule_map: jax.Array, kernels: jax.Array) -> jax.Array:
    """
    One diffusion step: depthwise 3x3 convolution on the torus for every
    molecule channel at once, followed by the reference's mass-conservation
    fixup (convolution rounding errors spread over all pixels) and a clamp
    at zero.
    """
    n_mols, m, _ = molecule_map.shape
    total_before = jnp.sum(molecule_map, axis=(1, 2))  # (mols,)

    padded = jnp.pad(molecule_map, ((0, 0), (1, 1), (1, 1)), mode="wrap")
    out = jax.lax.conv_general_dilated(
        padded[None],  # (1, mols, m+2, m+2)
        kernels[:, None],  # (mols, 1, 3, 3)
        window_strides=(1, 1),
        padding="VALID",
        feature_group_count=n_mols,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )[0]

    total_after = jnp.sum(out, axis=(1, 2))
    out = out + ((total_before - total_after) / (m * m))[:, None, None]
    return jnp.clip(out, min=0.0)


@jax.jit
def permeate(
    cell_molecules: jax.Array,  # (c, n_mols) intracellular
    ext_molecules: jax.Array,  # (c, n_mols) the cells' map pixels
    factors: jax.Array,  # (n_mols,)
) -> tuple[jax.Array, jax.Array]:
    """Exchange molecules between each cell and its pixel by the per-species
    permeation ratio (reference world.py:654-665)."""
    d_int = cell_molecules * factors
    d_ext = ext_molecules * factors
    return cell_molecules + d_ext - d_int, ext_molecules + d_int - d_ext


@jax.jit
def degrade(
    molecule_map: jax.Array, cell_molecules: jax.Array, factors: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Decay all molecules by one step (reference world.py:667-678)"""
    return molecule_map * factors[:, None, None], cell_molecules * factors
