"""
Molecule-map physics as jit-compiled XLA kernels: diffusion (depthwise 3x3
torus convolution), membrane permeation, and degradation.

Math parity reference: `python/magicsoup/world.py:627-678,935-984` —
diffusion kernel ``a = 1/(1/d + 8)`` off-center / ``b = 1 - 8a`` center with
circular padding, the total-mass correction spread over all pixels, clamping
at zero, permeation factor ``1/(1/p + 1)`` exchanging between a cell and its
pixel, and per-species exponential decay.

TPU-first deltas (SURVEY.md §7 design delta 4): one batched depthwise
convolution over all molecule channels (the reference loops one Conv2d per
molecule), wrap-padding via ``jnp.pad(mode="wrap")``, and all three physics
ops exposed as pure functions over the full slot-capacity state so they fuse
under a single jit with the gather/scatter of cell signals.
"""
import jax
import jax.numpy as jnp
import numpy as np

from magicsoup_tpu.ops.detmath import _nofma, det_div, sum_hw


def diffusion_kernels(diffusivities: list[float]) -> np.ndarray:
    """(n_mols, 3, 3) depthwise kernels from per-molecule diffusivities"""
    kernels = np.zeros((len(diffusivities), 3, 3), dtype=np.float32)
    for i, rate in enumerate(diffusivities):
        rate = min(abs(rate), 1.0)
        if rate == 0.0:
            a, b = 0.0, 1.0
        else:
            a = 1.0 / (1.0 / rate + 8.0)
            b = 1.0 - 8.0 * a
        kernels[i] = a
        kernels[i, 1, 1] = b
    return kernels


def permeation_factors(permeabilities: list[float]) -> np.ndarray:
    """(n_mols,) per-step exchange ratios from permeabilities"""
    out = np.zeros(len(permeabilities), dtype=np.float32)
    for i, rate in enumerate(permeabilities):
        rate = min(abs(rate), 1.0)
        out[i] = 0.0 if rate == 0.0 else 1.0 / (1.0 / rate + 1.0)
    return out


def degradation_factors(half_lives: list[float]) -> np.ndarray:
    """(n_mols,) per-step decay factors exp(-ln2 / half_life)"""
    return np.exp(-np.log(2.0) / np.array(half_lives, dtype=np.float64)).astype(
        np.float32
    )


@jax.jit
def diffuse(molecule_map: jax.Array, kernels: jax.Array) -> jax.Array:
    """
    One diffusion step: a depthwise 3x3 torus stencil for every molecule
    channel at once, followed by the reference's mass-conservation fixup
    (rounding errors spread over all pixels) and a clamp at zero.

    The stencil is 9 explicit roll-multiply-adds in a FIXED order and the
    map totals use a fixed binary reduction tree — a backend convolution
    would pick its own tap/reduction order, breaking CPU-vs-TPU
    bit-reproducibility.  Unlike the integrator there is no fast/det
    split: a 3x3 depthwise conv cannot use the MXU, so the stencil costs
    the same as the convolution it replaces (~1 ms at 128x128).
    """
    m = molecule_map.shape[1]
    total_before = sum_hw(molecule_map)  # (mols,)

    out = jnp.zeros_like(molecule_map)
    for i in range(3):
        for j in range(3):
            # correlation semantics: out[x,y] += k[i,j] * map[x+i-1, y+j-1]
            # (_nofma: keep the tap multiply from contracting into the
            # accumulating add as a backend-dependent FMA)
            term = _nofma(
                kernels[:, i, j][:, None, None]
                * jnp.roll(molecule_map, shift=(1 - i, 1 - j), axis=(1, 2))
            )
            out = out + term

    total_after = sum_hw(out)
    fix = det_div(total_before - total_after, jnp.float32(m * m))
    out = out + fix[:, None, None]
    return jnp.clip(out, min=0.0)


@jax.jit
def permeate(
    cell_molecules: jax.Array,  # (c, n_mols) intracellular
    ext_molecules: jax.Array,  # (c, n_mols) the cells' map pixels
    factors: jax.Array,  # (n_mols,)
) -> tuple[jax.Array, jax.Array]:
    """Exchange molecules between each cell and its pixel by the per-species
    permeation ratio (reference world.py:654-665)."""
    d_int = _nofma(cell_molecules * factors)
    d_ext = _nofma(ext_molecules * factors)
    return cell_molecules + d_ext - d_int, ext_molecules + d_int - d_ext


@jax.jit
def degrade(
    molecule_map: jax.Array, cell_molecules: jax.Array, factors: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Decay all molecules by one step (reference world.py:667-678)"""
    return molecule_map * factors[:, None, None], cell_molecules * factors
