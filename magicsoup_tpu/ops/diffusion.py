"""
Molecule-map physics as jit-compiled XLA kernels: diffusion (depthwise 3x3
torus convolution), membrane permeation, and degradation.

Math parity reference: `python/magicsoup/world.py:627-678,935-984` —
diffusion kernel ``a = 1/(1/d + 8)`` off-center / ``b = 1 - 8a`` center with
circular padding, the total-mass correction spread over all pixels, clamping
at zero, permeation factor ``1/(1/p + 1)`` exchanging between a cell and its
pixel, and per-species exponential decay.

TPU-first deltas (SURVEY.md §7 design delta 4): one batched depthwise
convolution over all molecule channels (the reference loops one Conv2d per
molecule), wrap-padding via ``jnp.pad(mode="wrap")``, and all three physics
ops exposed as pure functions over the full slot-capacity state so they fuse
under a single jit with the gather/scatter of cell signals.
"""
from functools import partial

import jax
import jax.numpy as jnp

# jax >= 0.4.26 removed the jax.enable_x64 alias; the experimental
# context manager is the stable spelling of the same x64 scope
from jax.experimental import enable_x64 as _enable_x64
import numpy as np

from magicsoup_tpu.ops.detmath import det_div, sum_hw, traced_zeros32


def diffusion_kernels(diffusivities: list[float]) -> np.ndarray:
    """(n_mols, 3, 3) depthwise kernels from per-molecule diffusivities"""
    kernels = np.zeros((len(diffusivities), 3, 3), dtype=np.float32)
    for i, rate in enumerate(diffusivities):
        rate = min(abs(rate), 1.0)
        if rate == 0.0:
            a, b = 0.0, 1.0
        else:
            a = 1.0 / (1.0 / rate + 8.0)
            b = 1.0 - 8.0 * a
        kernels[i] = a
        kernels[i, 1, 1] = b
    return kernels


def permeation_factors(permeabilities: list[float]) -> np.ndarray:
    """(n_mols,) per-step exchange ratios from permeabilities"""
    out = np.zeros(len(permeabilities), dtype=np.float32)
    for i, rate in enumerate(permeabilities):
        rate = min(abs(rate), 1.0)
        out[i] = 0.0 if rate == 0.0 else 1.0 / (1.0 / rate + 1.0)
    return out


def degradation_factors(half_lives: list[float]) -> np.ndarray:
    """(n_mols,) per-step decay factors exp(-ln2 / half_life)"""
    return np.exp(
        # host-side precompute in f64 for accuracy, downcast before device
        -np.log(2.0) / np.array(half_lives, dtype=np.float64)  # graftlint: disable=GL003
    ).astype(np.float32)


def stencil_3x3(map_: jax.Array, kernels: jax.Array) -> jax.Array:
    """The 9-tap torus stencil in its one canonical FIXED tap order —
    shared by the fast and deterministic branches and mirrored (with halo
    slices instead of row rolls) by the sharded version in
    parallel/tiled.py; the order is load-bearing for det/fast and
    sharded/unsharded agreement, so it must not drift between copies.
    Correlation semantics: out[x,y] += k[i,j] * map[x+i-1, y+j-1]."""
    # TRACED zeros: in det mode map_ is float64, and a float64 zero
    # literal would be canonicalized to f32 when jit lowers the program
    # outside the x64 scope (see detmath.traced_zeros32)
    out = traced_zeros32(map_).astype(map_.dtype)
    for i in range(3):
        for j in range(3):
            out = out + kernels[:, i, j][:, None, None] * jnp.roll(
                map_, shift=(1 - i, 1 - j), axis=(1, 2)
            )
    return out


@partial(jax.jit, static_argnames=("det", "mesh"))
def diffuse(
    molecule_map: jax.Array,
    kernels: jax.Array,
    det: bool = False,
    mesh=None,
) -> jax.Array:
    """
    One diffusion step: a depthwise 3x3 torus stencil for every molecule
    channel at once, followed by the reference's mass-conservation fixup
    (rounding errors spread over all pixels) and a clamp at zero.

    The stencil is 9 explicit roll-multiply-adds in a FIXED order — a
    backend convolution would pick its own tap order, and a 3x3 depthwise
    conv cannot use the MXU anyway, so the stencil costs the same.  In
    deterministic mode the accumulation runs in FLOAT64 (an f32 tap
    multiply feeding the f32 accumulating add would be FMA-contracted on
    TPU but not CPU; f64 multiply-add is deterministic on both) and the
    map totals use the fixed f64 reduction tree.

    ``mesh`` (static, hashable) routes a ROW-SHARDED map through the
    halo-exchange stencil in parallel/tiled.py: each tile computes its
    local rows plus 1-row ``ppermute`` halos instead of letting GSPMD
    partition the roll-based stencil (which would all-gather the map
    per tap).  Both routes share :func:`stencil_3x3`'s canonical tap
    order, and the det-mode sharded fixup replicates the single-device
    fixed reduction tree, so the result is bit-identical either way
    (pinned by test_parallel.py's halo bit-identity tests).
    """
    if mesh is not None and mesh.shape[mesh.axis_names[0]] > 1:
        # deferred import: parallel/tiled.py imports this module at top
        # level, so the mesh route resolves its helper lazily
        from magicsoup_tpu.parallel.tiled import halo_diffuse

        return halo_diffuse(molecule_map, kernels, mesh, det=det)
    m = molecule_map.shape[1]

    # totals use the f64 tree in BOTH modes: the fixup is a small
    # difference of large sums (catastrophic cancellation), and f32
    # totals make the single-device and halo-sharded paths disagree at
    # ~1e-5 rel
    total_before = sum_hw(molecule_map)  # (mols,)
    if det:
        with _enable_x64(True):
            out = stencil_3x3(
                # graftlint: disable=GL003 sanctioned det-mode f64 accumulation (BITREPRO.md)
                molecule_map.astype(jnp.float64), kernels.astype(jnp.float64)
            ).astype(jnp.float32)
        total_after = sum_hw(out)
        fix = det_div(total_before - total_after, jnp.float32(m * m))
    else:
        out = stencil_3x3(molecule_map, kernels)
        total_after = sum_hw(out)
        fix = (total_before - total_after) / (m * m)

    out = out + fix[:, None, None]
    return jnp.clip(out, min=0.0)


@partial(jax.jit, static_argnames=("det",))
def permeate(
    cell_molecules: jax.Array,  # (c, n_mols) intracellular
    ext_molecules: jax.Array,  # (c, n_mols) the cells' map pixels
    factors: jax.Array,  # (n_mols,)
    det: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Exchange molecules between each cell and its pixel by the per-species
    permeation ratio (reference world.py:654-665).  Deterministic mode
    computes in float64: the exchange products feed adds/subs, which f32
    would FMA-contract backend-dependently."""
    if det:
        with _enable_x64(True):
            # sanctioned det-mode f64 (BITREPRO.md)
            cm = cell_molecules.astype(jnp.float64)  # graftlint: disable=GL003
            ext = ext_molecules.astype(jnp.float64)  # graftlint: disable=GL003
            fac = factors.astype(jnp.float64)  # graftlint: disable=GL003
            d_int = cm * fac
            d_ext = ext * fac
            return (
                (cm + d_ext - d_int).astype(jnp.float32),
                (ext + d_int - d_ext).astype(jnp.float32),
            )
    d_int = cell_molecules * factors
    d_ext = ext_molecules * factors
    return cell_molecules + d_ext - d_int, ext_molecules + d_int - d_ext


@jax.jit
def degrade(
    molecule_map: jax.Array, cell_molecules: jax.Array, factors: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Decay all molecules by one step (reference world.py:667-678)"""
    return molecule_map * factors[:, None, None], cell_molecules * factors
