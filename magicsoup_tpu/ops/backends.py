"""
Integrator backend plane: the ONE selection path for the MM integrator.

The reversible-MM signal integrator is the per-step numeric core, and it
has three implementations with different capabilities:

- ``xla-fast`` — the log-space XLA path
  (:func:`magicsoup_tpu.ops.integrate._integrate_signals_jit` with
  ``det=False``).  Runs everywhere (mesh-sharded steps included), serves
  the stacked fleet programs, Mosaic-safe by construction.
- ``xla-det`` — the deterministic XLA path (``det=True``): detmath
  fixed-order reductions, bit-reproducible across IEEE backends.  The
  float64 accumulation has no Mosaic lowering, but XLA emulates f64 on
  TPU so the backend itself runs everywhere.
- ``pallas`` — the VMEM-resident Pallas kernel
  (:mod:`magicsoup_tpu.ops.pallas_integrate`): fast-mode body only, no
  SPMD partitioning rule (mesh-excluded), batched over a leading world
  axis for fleet shapes.

Historically the choice was plumbed as two ad-hoc bools (``det`` +
``use_pallas``) with the capability rules scattered as ``raise``s in
``world.py``.  This registry replaces that: each backend carries
capability flags, :func:`resolve` maps every selection source (explicit
``World(integrator=...)``, the ``MAGICSOUP_TPU_INTEGRATOR`` env var, the
legacy ``use_pallas`` flag / ``MAGICSOUP_TPU_PALLAS`` env var, the
numeric mode) onto a backend name and enforces the flags in one place,
and :func:`integrate` is the trace-safe dispatcher the hot step bodies
route through (graftlint GL026 flags hot-path calls that bypass it).

The backend NAME is the static jit-cache key the step programs carry
(``integrator=...`` static argument) — strings are hashable, and the
name fully determines the traced integrator body.
"""
from __future__ import annotations

import functools
import os
import warnings
from typing import NamedTuple

from magicsoup_tpu.ops.integrate import _integrate_signals_jit

__all__ = [
    "ENV_VAR",
    "REGISTRY",
    "IntegratorBackend",
    "get_backend",
    "integrate",
    "integrator_fn",
    "resolve",
]

#: env var naming a backend explicitly (same precedence as the
#: ``World(integrator=...)`` argument, below it)
ENV_VAR = "MAGICSOUP_TPU_INTEGRATOR"

#: legacy opt-in env var for the Pallas kernel (kept working; resolves
#: to the ``pallas`` backend)
LEGACY_ENV_VAR = "MAGICSOUP_TPU_PALLAS"


class IntegratorBackend(NamedTuple):
    """One registered integrator backend and its capability flags.

    ``det_able``: bit-reproducible across IEEE backends (may serve a
    world in deterministic mode).  ``mesh_able``: has an SPMD
    partitioning rule (may serve a mesh-sharded step).
    ``fleet_batchable``: serves the stacked fleet megastep programs.
    ``mosaic_safe``: every primitive in its body has a Mosaic lowering
    (can compile for TPU without the XLA fallback path).
    """

    name: str
    det_able: bool
    mesh_able: bool
    fleet_batchable: bool
    mosaic_safe: bool


REGISTRY: dict[str, IntegratorBackend] = {
    b.name: b
    for b in (
        IntegratorBackend(
            "xla-fast",
            det_able=False,
            mesh_able=True,
            fleet_batchable=True,
            mosaic_safe=True,
        ),
        IntegratorBackend(
            "xla-det",
            det_able=True,
            mesh_able=True,
            fleet_batchable=True,
            # detmath accumulates in f64; XLA emulates it on TPU, Mosaic
            # refuses it (the round-2 kernel crash — see
            # ops/pallas_integrate.py history note)
            mosaic_safe=False,
        ),
        IntegratorBackend(
            "pallas",
            det_able=False,
            mesh_able=False,
            fleet_batchable=True,
            mosaic_safe=True,
        ),
    )
}


def get_backend(name: str) -> IntegratorBackend:
    """Look up a backend by name; unknown names are a ``ValueError``."""
    try:
        return REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown integrator backend {name!r} "
            f"(want one of {sorted(REGISTRY)})"
        ) from None


def _refuse_mesh(backend: IntegratorBackend) -> None:
    if backend.name == "pallas":
        # the exact message the legacy use_pallas plumbing raised —
        # callers (and tests) match on it
        raise ValueError(
            "use_pallas is not supported with a mesh: pallas_call has"
            " no partitioning rule; the sharded step uses the XLA"
            " integrator"
        )
    raise ValueError(
        f"integrator backend {backend.name!r} is not supported with a"
        " mesh (no SPMD partitioning rule)"
    )


def _refuse_det(backend: IntegratorBackend) -> None:
    if backend.name == "pallas":
        raise ValueError(
            "use_pallas is not supported in deterministic mode: the"
            " kernel has no bit-reproducible variant; unset"
            " MAGICSOUP_TPU_DETERMINISTIC or use the XLA integrator"
        )
    raise ValueError(
        f"integrator backend {backend.name!r} is not bit-reproducible:"
        " deterministic mode needs a det-able backend"
        " ('xla-det'); unset MAGICSOUP_TPU_DETERMINISTIC or pick one"
    )


def resolve(
    integrator: str | None = None,
    *,
    use_pallas: bool | None = None,
    deterministic: bool = False,
    mesh=None,
) -> tuple[str, bool]:
    """Resolve every selection source onto one backend name.

    Precedence: explicit ``integrator`` argument > ``MAGICSOUP_TPU_INTEGRATOR``
    env var > legacy ``use_pallas`` flag > ``MAGICSOUP_TPU_PALLAS`` env
    var > the numeric mode (``xla-det`` when deterministic, else
    ``xla-fast``).  Capability flags are enforced HERE: an explicit
    choice that violates one raises ``ValueError`` (the exact legacy
    messages for pallas), an env-sourced choice that conflicts with a
    mesh warns and falls back to the XLA path (the legacy
    ``MAGICSOUP_TPU_PALLAS`` behavior).

    Returns ``(name, pinned)`` — ``pinned`` is False when the name was
    derived from the numeric mode only, so a caller tracking the choice
    can keep following the mode (a world whose ``deterministic`` flag is
    flipped later re-derives ``xla-det``/``xla-fast``).
    """
    if integrator is not None and use_pallas is not None:
        if bool(use_pallas) != (get_backend(integrator).name == "pallas"):
            raise ValueError(
                f"integrator={integrator!r} conflicts with"
                f" use_pallas={use_pallas!r}; pass only integrator="
            )
    choice = integrator
    from_env = False
    if choice is None:
        env = os.environ.get(ENV_VAR, "")
        if env:
            choice, from_env = env, True
    if choice is None and use_pallas is None:
        if os.environ.get(LEGACY_ENV_VAR) == "1":
            choice, from_env = "pallas", True
    if choice is None and use_pallas:
        choice = "pallas"
    if choice is None:
        return ("xla-det" if deterministic else "xla-fast", False)

    backend = get_backend(choice)
    if mesh is not None and not backend.mesh_able:
        if from_env:
            # env opt-ins never break a mesh-placed world — same
            # behavior (and message) the legacy env plumbing had
            warnings.warn(
                f"{LEGACY_ENV_VAR}=1 is ignored for mesh-placed"
                " worlds: the sharded step uses the XLA integrator"
                if backend.name == "pallas" and not os.environ.get(ENV_VAR)
                else f"{ENV_VAR}={backend.name} is ignored for"
                " mesh-placed worlds: the sharded step uses the XLA"
                " integrator"
            )
            return ("xla-det" if deterministic else "xla-fast", False)
        _refuse_mesh(backend)
    if deterministic and not backend.det_able:
        _refuse_det(backend)
    return (backend.name, True)


@functools.lru_cache(maxsize=None)
def integrator_fn(name: str):
    """The backend's integrator as a plain ``(X, params) -> X1``
    callable (trace-safe; cached per name).  The pallas backend runs
    interpret mode automatically off-TPU so the same world works on CPU
    tests and TPU runs."""
    backend = get_backend(name)
    if backend.name == "pallas":
        import jax

        from magicsoup_tpu.ops.pallas_integrate import integrate_signals_pallas

        interpret = jax.default_backend() != "tpu"
        return functools.partial(integrate_signals_pallas, interpret=interpret)
    det = backend.name == "xla-det"

    def fn(X, params, _det=det):
        return _integrate_signals_jit(X, params, _det)

    return fn


def integrate(name: str, X, params):
    """Dispatch one integrator step through backend ``name`` — the
    registry-routed spelling hot step bodies must use (graftlint GL026
    flags direct ``integrate_signals``/``integrate_signals_pallas``
    calls in stepper/fleet/serve-scoped hot functions)."""
    return integrator_fn(name)(X, params)
