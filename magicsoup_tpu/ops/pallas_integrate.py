"""
Pallas TPU kernel for the reversible-MM signal integrator.

The jitted XLA integrator (:mod:`magicsoup_tpu.ops.integrate`) re-reads
the five (cells, proteins, signals) parameter tensors from HBM across
the many signal-product reductions in a step (3 trim passes x
(velocities + 4 equilibrium-correction iterations)).  This kernel tiles
the cell axis and keeps one tile's parameters resident in VMEM for the
WHOLE step, so HBM traffic drops toward 1x the parameter bytes — the
classic memory-bound fusion case from the Pallas playbook
(`/opt/skills/guides/pallas_guide.md`, Memory Hierarchy).

**Kernel body = the FAST (log-space) numeric mode**, with the two
primitives Mosaic cannot lower rewritten in closed form:

- ``prod_s(X^N)`` is already ``exp(sum_s N*logX)`` in fast mode
  (:func:`magicsoup_tpu.ops.integrate._prod_pow`) — plain mul/sum/exp;
- the allosteric ``X^A`` (float-exponent ``jnp.power``) and the product
  over its signal factors become the same exp-sum-log form
  (:func:`magicsoup_tpu.ops.integrate._a_reg_logspace`, selected by
  ``_integrate_part(..., mosaic_safe=True)`` — the kernel body IS the
  shared fast-mode trim pass), with saturation at ``MAX`` reproducing the
  reference's Inf semantics (a zero inhibitor concentration -> factor 1,
  a zero activator -> factor 0; reference kinetics.py:790-800).

History: the round-2 kernel used the DETERMINISTIC body (fixed-tree
products) because ``reduce_prod``/``pow`` have no Mosaic lowering — but
that body accumulates in float64 (`ops/detmath.py`), which the remote
Mosaic compiler crashed on with no diagnostics (HTTP 500; XLA emulates
f64 on TPU, Mosaic does not).  The fast-mode body is f32 end to end.
`performance/pallas_bisect.py` is the rung-by-rung ladder that isolates
each lowering hypothesis on hardware; run it after any platform update.

One deliberate semantic delta vs the XLA path, unchanged from round 2:
the equilibrium correction's early-stop flag (reference
kinetics.py:846-847, a GLOBAL ``torch.any`` over the whole batch) is
evaluated per cell TILE here, decoupling cells in different tiles —
strictly closer to the per-cell ideal the heuristic approximates.  The
XLA path keeps the batch-global flag for exact reference parity, which
is why the kernel is opt-in (``World(integrator="pallas")`` — the
backend registry in :mod:`magicsoup_tpu.ops.backends` is the selection
path) and why sharded steps (no partitioning rule for ``pallas_call``)
always use the XLA path.  A consequence worth knowing when changing the
tile table: the DEFAULT tile size is part of the kernel's observable
numerics — cells early-stop with their tile-mates.

**Batched world axis**: a rank-3 ``X`` of shape ``(B, cells, signals)``
with params carrying the same leading axis runs a 2D grid
``(B, cells // tile_c)`` — ONE kernel launch serves all B worlds of a
fleet rung group.  Tiles never cross the world axis, so world ``w``'s
output is bit-equal to its own ``B=1`` launch at the same ``tile_c``
(pinned by test).

**Tile table**: the default ``tile_c`` is the largest divisor of the
cell capacity whose per-grid-step VMEM working set fits a configurable
budget (``MAGICSOUP_TPU_PALLAS_VMEM_BUDGET`` bytes, default 8 MiB) —
replacing the old ``gcd(c, 128)`` heuristic, whose degenerate case (an
odd capacity -> ``tile_c=1`` -> one grid step PER CELL) is now a typed
refusal instead of a silent pathological launch.

``interpret=True`` runs the kernel on CPU for tests.
"""
import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from magicsoup_tpu.ops.integrate import (
    TRIM_FACTORS,
    CellParams,
    _integrate_part,
)

#: default per-grid-step VMEM working-set budget (bytes).  TPU cores
#: have ~16 MiB of VMEM; half of it leaves headroom for Mosaic's own
#: scratch and the next tile's prefetch window.
DEFAULT_VMEM_BUDGET = 8 * 1024 * 1024

#: f32 sublane tile height — a cell tile that is not a multiple of 8
#: pads every (tile, p) / (tile, p, s) operand in registers on TPU
_MIN_TILE = 8


def vmem_budget() -> int:
    """The configured VMEM working-set budget (bytes) for the default
    tile table — ``MAGICSOUP_TPU_PALLAS_VMEM_BUDGET`` or the default."""
    env = os.environ.get("MAGICSOUP_TPU_PALLAS_VMEM_BUDGET", "")
    return int(env) if env else DEFAULT_VMEM_BUDGET


def tile_vmem_bytes(tile_c: int, p: int, s: int) -> int:
    """Resident VMEM bytes of one ``tile_c``-cell grid step.

    Operands: X in + out ``(tile, s)`` f32; Ke/Kmf/Kmb/Vmax ``(tile,
    p)`` f32; Kmr ``(tile, p, s)`` f32; N/Nf/Nb/A ``(tile, p, s)`` i16 —
    plus two ``(tile, p, s)`` f32 live intermediates (the negative-guard
    ``NV``/``F_prots`` tensors are the widest scratch the fast-mode body
    materializes at once)."""
    f32, i16 = 4, 2
    per_row = (
        2 * s * f32  # X in + out
        + 4 * p * f32  # Ke, Kmf, Kmb, Vmax
        + p * s * f32  # Kmr
        + 4 * p * s * i16  # N, Nf, Nb, A
        + 2 * p * s * f32  # live f32 intermediates
    )
    return tile_c * per_row


def select_tile_c(
    c: int, p: int, s: int, budget: int | None = None
) -> int:
    """The tile table: largest divisor of capacity ``c`` whose working
    set (:func:`tile_vmem_bytes`) fits ``budget``, restricted to
    sublane-aligned tiles (multiples of 8) — except that the whole
    capacity is always an admissible single tile, so small or oddly
    sized batches that fit VMEM outright still run in one grid step.

    Raises ``ValueError`` when no admissible tile exists (e.g. an odd
    capacity too big for one tile: its only aligned divisor would be the
    degenerate ``tile_c=1``, one grid step per cell)."""
    if budget is None:
        budget = vmem_budget()
    fitting = [
        d
        for d in range(1, c + 1)
        if c % d == 0
        and (d % _MIN_TILE == 0 or d == c)
        and tile_vmem_bytes(d, p, s) <= budget
    ]
    if not fitting:
        raise ValueError(
            f"no usable pallas tile for capacity {c} (proteins={p},"
            f" signals={s}): no sublane-aligned (multiple-of-{_MIN_TILE})"
            f" divisor of {c} fits the {budget}-byte VMEM budget"
            " (MAGICSOUP_TPU_PALLAS_VMEM_BUDGET); use a power-of-two"
            " capacity, raise the budget, or use the XLA integrator"
        )
    return max(fitting)


def _body(x, ke, kmf, kmb, kmr, vmax, n, nf, nb, a):
    params = CellParams(
        Ke=ke, Kmf=kmf, Kmb=kmb, Kmr=kmr, Vmax=vmax, N=n, Nf=nf, Nb=nb, A=a
    )
    X = x
    for trim in TRIM_FACTORS:
        # the SHARED fast-mode trim pass with the one Mosaic-safe
        # sub-expression swap — fixes to the integrator apply here too
        X = _integrate_part(
            X, jnp.clip(params.Vmax * trim, min=0.0), params,
            det=False, mosaic_safe=True,
        )
    return X


def _kernel(
    x_ref,
    ke_ref,
    kmf_ref,
    kmb_ref,
    kmr_ref,
    vmax_ref,
    n_ref,
    nf_ref,
    nb_ref,
    a_ref,
    out_ref,
):
    out_ref[:] = _body(
        x_ref[:],
        ke_ref[:],
        kmf_ref[:],
        kmb_ref[:],
        kmr_ref[:],
        vmax_ref[:],
        n_ref[:],
        nf_ref[:],
        nb_ref[:],
        a_ref[:],
    )


def _kernel_batched(
    x_ref,
    ke_ref,
    kmf_ref,
    kmb_ref,
    kmr_ref,
    vmax_ref,
    n_ref,
    nf_ref,
    nb_ref,
    a_ref,
    out_ref,
):
    # blocks carry a leading world axis of 1; squeeze it so the body is
    # the EXACT rank-2 trim pass the solo kernel runs (bit-equal per
    # world to a B=1 launch at the same tile_c)
    out_ref[0] = _body(
        x_ref[0],
        ke_ref[0],
        kmf_ref[0],
        kmb_ref[0],
        kmr_ref[0],
        vmax_ref[0],
        n_ref[0],
        nf_ref[0],
        nb_ref[0],
        a_ref[0],
    )


# graftlint: disable=GL006 params is read-only; only the signal matrix is returned
@functools.partial(
    jax.jit, static_argnames=("tile_c", "interpret")
)
def integrate_signals_pallas(
    X: jax.Array,
    params: CellParams,
    *,
    tile_c: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """
    Pallas-tiled equivalent of
    :func:`magicsoup_tpu.ops.integrate.integrate_signals` (fast mode).

    ``X`` is ``(cells, signals)``, or ``(B, cells, signals)`` with every
    ``params`` leaf carrying the same leading world axis — the batched
    form runs a 2D grid ``(B, cells // tile_c)``, one launch for all B
    worlds.  ``tile_c`` is the number of cells per grid step (must
    divide the cell capacity; default from :func:`select_tile_c`, the
    VMEM-budget tile table).
    """
    batched = X.ndim == 3
    c, s = X.shape[-2], X.shape[-1]
    p = params.Ke.shape[-1]
    if tile_c is None:
        tile_c = select_tile_c(c, p, s)
    if c % tile_c != 0:
        raise ValueError(f"cell count {c} not divisible by tile_c={tile_c}")

    if not batched:
        cp = lambda i: (i, 0)  # noqa: E731
        cps = lambda i: (i, 0, 0)  # noqa: E731
        bs_cs = pl.BlockSpec((tile_c, s), cp)
        bs_cp = pl.BlockSpec((tile_c, p), cp)
        bs_cps = pl.BlockSpec((tile_c, p, s), cps)
        kernel = _kernel
        grid = (c // tile_c,)
        out_shape = jax.ShapeDtypeStruct((c, s), X.dtype)
    else:
        B = X.shape[0]
        bcp = lambda b, i: (b, i, 0)  # noqa: E731
        bcps = lambda b, i: (b, i, 0, 0)  # noqa: E731
        bs_cs = pl.BlockSpec((1, tile_c, s), bcp)
        bs_cp = pl.BlockSpec((1, tile_c, p), bcp)
        bs_cps = pl.BlockSpec((1, tile_c, p, s), bcps)
        kernel = _kernel_batched
        grid = (B, c // tile_c)
        out_shape = jax.ShapeDtypeStruct((B, c, s), X.dtype)

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            bs_cs,  # X
            bs_cp,  # Ke
            bs_cp,  # Kmf
            bs_cp,  # Kmb
            bs_cps,  # Kmr
            bs_cp,  # Vmax
            bs_cps,  # N
            bs_cps,  # Nf
            bs_cps,  # Nb
            bs_cps,  # A
        ],
        out_specs=bs_cs,
        out_shape=out_shape,
        interpret=interpret,
    )(
        X,
        params.Ke,
        params.Kmf,
        params.Kmb,
        params.Kmr,
        params.Vmax,
        params.N,
        params.Nf,
        params.Nb,
        params.A,
    )
