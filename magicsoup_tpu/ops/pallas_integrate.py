"""
Pallas TPU kernel for the reversible-MM signal integrator.

The jitted XLA integrator (:mod:`magicsoup_tpu.ops.integrate`) re-reads
the five (cells, proteins, signals) parameter tensors from HBM across
the many signal-product reductions in a step (3 trim passes x
(velocities + 4 equilibrium-correction iterations)).  This kernel tiles
the cell axis and keeps one tile's parameters resident in VMEM for the
WHOLE step, so HBM traffic drops toward 1x the parameter bytes — the
classic memory-bound fusion case from the Pallas playbook
(`/opt/skills/guides/pallas_guide.md`, Memory Hierarchy).

**Kernel body = the FAST (log-space) numeric mode**, with the two
primitives Mosaic cannot lower rewritten in closed form:

- ``prod_s(X^N)`` is already ``exp(sum_s N*logX)`` in fast mode
  (:func:`magicsoup_tpu.ops.integrate._prod_pow`) — plain mul/sum/exp;
- the allosteric ``X^A`` (float-exponent ``jnp.power``) and the product
  over its signal factors become the same exp-sum-log form
  (:func:`magicsoup_tpu.ops.integrate._a_reg_logspace`, selected by
  ``_integrate_part(..., mosaic_safe=True)`` — the kernel body IS the
  shared fast-mode trim pass), with saturation at ``MAX`` reproducing the
  reference's Inf semantics (a zero inhibitor concentration -> factor 1,
  a zero activator -> factor 0; reference kinetics.py:790-800).

History: the round-2 kernel used the DETERMINISTIC body (fixed-tree
products) because ``reduce_prod``/``pow`` have no Mosaic lowering — but
that body accumulates in float64 (`ops/detmath.py`), which the remote
Mosaic compiler crashed on with no diagnostics (HTTP 500; XLA emulates
f64 on TPU, Mosaic does not).  The fast-mode body is f32 end to end.
`performance/pallas_bisect.py` is the rung-by-rung ladder that isolates
each lowering hypothesis on hardware; run it after any platform update.

One deliberate semantic delta vs the XLA path, unchanged from round 2:
the equilibrium correction's early-stop flag (reference
kinetics.py:846-847, a GLOBAL ``torch.any`` over the whole batch) is
evaluated per cell TILE here, decoupling cells in different tiles —
strictly closer to the per-cell ideal the heuristic approximates.  The
XLA path keeps the batch-global flag for exact reference parity, which
is why the kernel is opt-in (``World(use_pallas=True)`` /
``MAGICSOUP_TPU_PALLAS=1``) and why sharded steps (no partitioning rule
for ``pallas_call``) always use the XLA path.

``interpret=True`` runs the kernel on CPU for tests.
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from magicsoup_tpu.ops.integrate import (
    TRIM_FACTORS,
    CellParams,
    _integrate_part,
)


def _kernel(
    x_ref,
    ke_ref,
    kmf_ref,
    kmb_ref,
    kmr_ref,
    vmax_ref,
    n_ref,
    nf_ref,
    nb_ref,
    a_ref,
    out_ref,
):
    params = CellParams(
        Ke=ke_ref[:],
        Kmf=kmf_ref[:],
        Kmb=kmb_ref[:],
        Kmr=kmr_ref[:],
        Vmax=vmax_ref[:],
        N=n_ref[:],
        Nf=nf_ref[:],
        Nb=nb_ref[:],
        A=a_ref[:],
    )
    X = x_ref[:]
    for trim in TRIM_FACTORS:
        # the SHARED fast-mode trim pass with the one Mosaic-safe
        # sub-expression swap — fixes to the integrator apply here too
        X = _integrate_part(
            X, jnp.clip(params.Vmax * trim, min=0.0), params,
            det=False, mosaic_safe=True,
        )
    out_ref[:] = X


# graftlint: disable=GL006 params is read-only; only the signal matrix is returned
@functools.partial(
    jax.jit, static_argnames=("tile_c", "interpret")
)
def integrate_signals_pallas(
    X: jax.Array,
    params: CellParams,
    *,
    tile_c: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """
    Pallas-tiled equivalent of
    :func:`magicsoup_tpu.ops.integrate.integrate_signals` (fast mode).

    ``tile_c`` is the number of cells per grid step (must divide the cell
    capacity; defaults to 128 or the whole batch if smaller).  VMEM per
    tile is ~tile_c * proteins * signals * 4 B * ~10 live tensors — with
    the default 128 cells, 64 proteins, 12 signals that is ~4 MB.
    """
    c, s = X.shape
    if tile_c is None:
        # largest power-of-two tile <= 128 that divides c (any batch size
        # works; capacity pools are pow2 so they get the full 128)
        tile_c = math.gcd(c, 128)
    if c % tile_c != 0:
        raise ValueError(f"cell count {c} not divisible by tile_c={tile_c}")
    p = params.Ke.shape[1]

    cp = lambda i: (i, 0)  # noqa: E731
    cps = lambda i: (i, 0, 0)  # noqa: E731
    bs_cs = pl.BlockSpec((tile_c, s), cp)
    bs_cp = pl.BlockSpec((tile_c, p), cp)
    bs_cps = pl.BlockSpec((tile_c, p, s), cps)

    return pl.pallas_call(
        _kernel,
        grid=(c // tile_c,),
        in_specs=[
            bs_cs,  # X
            bs_cp,  # Ke
            bs_cp,  # Kmf
            bs_cp,  # Kmb
            bs_cps,  # Kmr
            bs_cp,  # Vmax
            bs_cps,  # N
            bs_cps,  # Nf
            bs_cps,  # Nb
            bs_cps,  # A
        ],
        out_specs=bs_cs,
        out_shape=jax.ShapeDtypeStruct((c, s), X.dtype),
        interpret=interpret,
    )(
        X,
        params.Ke,
        params.Kmf,
        params.Kmb,
        params.Kmr,
        params.Vmax,
        params.N,
        params.Nf,
        params.Nb,
        params.A,
    )
