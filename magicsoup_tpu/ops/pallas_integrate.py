"""
Pallas TPU kernel for the reversible-MM signal integrator.

The jitted XLA integrator (:mod:`magicsoup_tpu.ops.integrate`) re-reads the
five (cells, proteins, signals) parameter tensors from HBM for every one of
the ~30 signal-product reductions in a step (3 trim passes x (velocities +
4 equilibrium-correction iterations)).  This kernel tiles the cell axis and
keeps one tile's parameters resident in VMEM for the WHOLE step, so HBM
traffic drops from ~30x to ~1x the parameter bytes — the classic
memory-bound fusion case from the Pallas playbook
(`/opt/skills/guides/pallas_guide.md`, Memory Hierarchy).

Math parity is by construction: the kernel body loads the tile into values
and calls the exact same `_integrate_part` used by the XLA path.  One
deliberate semantic delta: the equilibrium correction's early-stop flag
(reference kinetics.py:846-847, a GLOBAL `torch.any` over the whole batch —
i.e. in the reference a cell's result depends on which other cells are in
the batch) is evaluated per cell TILE here, decoupling cells in different
tiles.  That is strictly closer to the per-cell ideal the heuristic
approximates; the XLA path keeps the batch-global flag for exact reference
parity.

Enable with ``MAGICSOUP_TPU_PALLAS=1`` (or call
:func:`integrate_signals_pallas` directly).  `interpret=True` runs the
kernel on CPU for tests.

**Hardware status (2026-07-29, TPU v5e via remote Mosaic compile
service):** OFF by default, and for now prove-or-drop resolves to
"documented, not default".  Two successive blockers were found on real
hardware: (1) ``reduce_prod`` has no Mosaic lowering — fixed by the
fixed-tree `_prod_last` / `ipow` now shared with the deterministic XLA
mode; (2) the remaining kernel body crashes the Mosaic compiler itself
(``remote_compile: HTTP 500: tpu_compile_helper subprocess exit code 1``
with no diagnostics; a trivial Pallas kernel compiles fine on the same
chip, and the crash reproduces with just the `_multiply_signals`
sub-kernel).  The fall-back XLA integrator measures 13 ms/step at
benchmark shapes (16384 cells x 32 proteins x 28 signals) vs a ~0.4 ms
1x-HBM-read bound, so a working kernel remains worth ~12 ms/step of
device time — relevant once steps are not dominated by host round-trip
latency (see performance/README.md).
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from magicsoup_tpu.ops.integrate import TRIM_FACTORS, CellParams, _integrate_part


def _kernel(
    x_ref,
    ke_ref,
    kmf_ref,
    kmb_ref,
    kmr_ref,
    vmax_ref,
    n_ref,
    nf_ref,
    nb_ref,
    a_ref,
    out_ref,
):
    params = CellParams(
        Ke=ke_ref[:],
        Kmf=kmf_ref[:],
        Kmb=kmb_ref[:],
        Kmr=kmr_ref[:],
        Vmax=vmax_ref[:],
        N=n_ref[:],
        Nf=nf_ref[:],
        Nb=nb_ref[:],
        A=a_ref[:],
    )
    X = x_ref[:]
    for trim in TRIM_FACTORS:
        # det=True: reduce_prod/pow have no Mosaic lowering; the
        # deterministic fixed-tree/square-and-multiply forms lower
        X = _integrate_part(
            X, jnp.clip(params.Vmax * trim, min=0.0), params, det=True
        )
    out_ref[:] = X


@functools.partial(
    jax.jit, static_argnames=("tile_c", "interpret")
)
def integrate_signals_pallas(
    X: jax.Array,
    params: CellParams,
    *,
    tile_c: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """
    Pallas-tiled equivalent of
    :func:`magicsoup_tpu.ops.integrate.integrate_signals`.

    ``tile_c`` is the number of cells per grid step (must divide the cell
    capacity; defaults to 128 or the whole batch if smaller).  VMEM per
    tile is ~tile_c * proteins * signals * 4 B * ~10 live tensors — with
    the default 128 cells, 64 proteins, 12 signals that is ~4 MB.
    """
    c, s = X.shape
    if tile_c is None:
        # largest power-of-two tile <= 128 that divides c (any batch size
        # works; capacity pools are pow2 so they get the full 128)
        tile_c = math.gcd(c, 128)
    if c % tile_c != 0:
        raise ValueError(f"cell count {c} not divisible by tile_c={tile_c}")
    p = params.Ke.shape[1]

    cp = lambda i: (i, 0)  # noqa: E731
    cps = lambda i: (i, 0, 0)  # noqa: E731
    bs_cs = pl.BlockSpec((tile_c, s), cp)
    bs_cp = pl.BlockSpec((tile_c, p), cp)
    bs_cps = pl.BlockSpec((tile_c, p, s), cps)

    return pl.pallas_call(
        _kernel,
        grid=(c // tile_c,),
        in_specs=[
            bs_cs,  # X
            bs_cp,  # Ke
            bs_cp,  # Kmf
            bs_cp,  # Kmb
            bs_cps,  # Kmr
            bs_cp,  # Vmax
            bs_cps,  # N
            bs_cps,  # Nf
            bs_cps,  # Nb
            bs_cps,  # A
        ],
        out_specs=bs_cs,
        out_shape=jax.ShapeDtypeStruct((c, s), X.dtype),
        interpret=interpret,
    )(
        X,
        params.Ke,
        params.Kmf,
        params.Kmb,
        params.Kmr,
        params.Vmax,
        params.N,
        params.Nf,
        params.Nb,
        params.A,
    )
