"""
Jit-compiled XLA kernels: the Michaelis-Menten signal integrator
(:mod:`magicsoup_tpu.ops.integrate`), molecule-map physics
(:mod:`magicsoup_tpu.ops.diffusion`), and cell-parameter assembly
(:mod:`magicsoup_tpu.ops.params`).
"""
