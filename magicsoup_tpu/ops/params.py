"""
Jit-compiled cell-parameter assembly: dense domain-token tensors -> the 9
kinetic parameter tensors, plus the masked scatter/gather helpers used for
slot-based state updates (set/unset/copy/compact).

Math parity reference: `python/magicsoup/kinetics.py:521-625` (set_cell_params)
— Vmax nanmean over domains, allosteric A = sum(effector*sign*hill),
Kmr = nanmean(Km_reg per signal)^A, stoichiometry N split into Nf/Nb to
preserve zero-net cofactors, Ke = exp(-(N.E)/(R.T)) clamped, and the Kmf/Kmb
split that puts the sampled Km on the smaller side of the equilibrium.

TPU-first deltas: the reference builds its dense (c,p,d) index tensors in a
nested Python loop (`_collect_proteome_idxs`, kinetics.py:920-970 — half the
documented spawn bottleneck); here the dense tensors arrive directly from
the genome engine's flat buffers via vectorized numpy scatter
(:func:`flat_to_dense`), and everything downstream is one fused XLA program.
Batch sizes are padded to powers of two and scattered with ``mode="drop"``
so recompiles stay logarithmic in batch size.
"""
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from magicsoup_tpu.constants import EPS, GAS_CONSTANT, MAX
from magicsoup_tpu.ops.detmath import det_div, det_exp, ipow, sum_axis
from magicsoup_tpu.ops.integrate import INT_PARAM_DTYPE, CellParams

# floors of the per-cell assembly rung grid (see Kinetics.set_cell_params_flat):
# cells are grouped by the pow2 sizes that actually cover their proteome —
# (pad_pow2(n_proteins), pad_pow2(max domains per protein)) — and each group's
# compute runs at that rung instead of the world's grow-only worst-case
# capacities.  The floors bound the number of compiled variants (p rungs
# {16, 32, ...}, d rungs {4, 8, ...}) while still capturing the bulk of the
# win: at benchmark genomes ~95% of cells fit (32, 4) while the capacities
# sit at (64, 16) — a ~7x cut of the (b, p, d, s) assembly volume
RUNG_P_MIN = 16
RUNG_D_MIN = 4


class TokenTables(NamedTuple):
    """Token -> parameter lookup tables (row 0 = empty/zero token)."""

    km_weights: jax.Array  # (T1+1,) f32, NaN at 0
    vmax_weights: jax.Array  # (T1+1,) f32, NaN at 0
    signs: jax.Array  # (T1+1,) i32, 0 at 0
    hills: jax.Array  # (T1+1,) i32, 0 at 0
    reactions: jax.Array  # (T2+1, s) i32 signed stoichiometry vectors
    transports: jax.Array  # (T2+1, s) i32 in/out transport vectors
    effectors: jax.Array  # (T2+1, s) i32 one-hot effector vectors
    mol_energies: jax.Array  # (s,) f32 molecule energies (duplicated x2)


def pad_pow2(n: int, minimum: int = 8) -> int:
    """Smallest power of two >= max(n, minimum)"""
    m = max(n, minimum)
    return 1 << (m - 1).bit_length()


# live-row prefix quantum for integrator dispatches: the hottest op reads
# the five (rows, proteins, signals) parameter tensors, and running it
# over all capacity slots taxes every step with the dead tail (24-39% at
# pow2 capacities, BENCH_NOTES.md "Dead-slot tax").  Live rows are always
# a compacted prefix, so callers slice the integrator's READ-ONLY inputs
# to the row count rounded up to this quantum — >= 90% of the computed
# prefix is live at benchmark populations, and the bounded set of
# distinct quantized sizes keeps recompiles rare (and compile-cached)
ROW_QUANTUM = 1024


def quantize_rows(n: int, cap: int, quantum: int = ROW_QUANTUM) -> int:
    """Smallest multiple of ``quantum`` >= n, clamped to ``cap``."""
    if n >= cap:
        return cap
    return min(cap, max(quantum, -(-n // quantum) * quantum))


def next_rung(rung: int, cap: int) -> int:
    """The row-ladder rung above ``rung`` (== ``rung`` at the cap) — the
    one rule for 'warm one rung ahead', shared by every prewarm site."""
    return quantize_rows(rung + 1, cap) if rung < cap else rung


# minimum padded length for index batches.  Every distinct padded length
# is a separate compile of the program consuming it, and on this platform
# compiles go through a remote compile service at seconds each — one
# landing inside a measured (or merely latency-sensitive) window costs
# more than years of the scatter work the padding adds.  256 covers the
# typical per-step kill/divide/mutate batches at benchmark populations
# with ONE variant; only genuine bursts (>256) step up the pow2 ladder.
IDX_BLOCK = 256


def pad_idxs(idxs: np.ndarray, oob: int, minimum: int = IDX_BLOCK) -> np.ndarray:
    """Pad an int index array to a power-of-two length with an out-of-bounds
    fill value (dropped by scatters with mode='drop')."""
    n = pad_pow2(len(idxs), minimum)
    out = np.full(n, oob, dtype=np.int32)
    out[: len(idxs)] = idxs
    return out


def flat_to_dense(
    prot_counts: np.ndarray,
    prots: np.ndarray,
    doms: np.ndarray,
    n_prots_cap: int,
    n_doms_cap: int | None = None,
) -> tuple[np.ndarray, int]:
    """
    Vectorized scatter of the genome engine's flat buffers into one dense
    int16 tensor (b, n_prots_cap, n_doms_cap, 5) holding
    ``[dom_type, i0, i1, i2, i3]`` per domain (0 = padding).

    Returns the dense tensor and the (possibly padded) domain capacity.
    """
    b = len(prot_counts)
    n_doms_per_prot = prots[:, 3] if len(prots) else np.zeros(0, dtype=np.int32)
    max_doms = int(n_doms_per_prot.max()) if len(prots) else 1
    if n_doms_cap is None:
        n_doms_cap = pad_pow2(max_doms, minimum=1)

    # i16 is enough: entries are the domain type (1..3) and token indices
    # (<= 3904 two-codon tokens); halves the host->device bytes of the
    # spawn path's biggest buffer
    dense = np.zeros((b, n_prots_cap, n_doms_cap, 5), dtype=np.int16)
    if len(doms) == 0:
        return dense, n_doms_cap

    # cell index of each protein / protein index within its cell
    prot_cell = np.repeat(np.arange(b, dtype=np.int64), prot_counts)
    prot_starts = np.concatenate([[0], np.cumsum(prot_counts)])[:-1]
    prot_in_cell = np.arange(len(prots), dtype=np.int64) - np.repeat(
        prot_starts, prot_counts
    )
    # protein index of each domain / domain index within its protein
    dom_prot = np.repeat(np.arange(len(prots), dtype=np.int64), n_doms_per_prot)
    dom_starts = np.concatenate([[0], np.cumsum(n_doms_per_prot)])[:-1]
    dom_in_prot = np.arange(len(doms), dtype=np.int64) - np.repeat(
        dom_starts, n_doms_per_prot
    )

    dense[prot_cell[dom_prot], prot_in_cell[dom_prot], dom_in_prot] = doms[:, :5]
    return dense, n_doms_cap


def _nanmean0(x: jax.Array, axis: int) -> jax.Array:
    """nanmean with all-NaN slices giving 0 (torch nanmean().nan_to_num(0));
    fixed-order float sum so the result is backend-independent"""
    mask = ~jnp.isnan(x)
    total = sum_axis(jnp.where(mask, x, 0.0), axis=axis)
    count = jnp.sum(mask, axis=axis)
    mean = det_div(total, jnp.maximum(count, 1).astype(total.dtype))
    return jnp.where(count > 0, mean, 0.0)


@partial(jax.jit, static_argnames=())
def compute_cell_params(
    dense: jax.Array,  # (b, p, d, 5) i16 [dom_type, i0, i1, i2, i3]
    tables: TokenTables,
    abs_temp: jax.Array,
) -> CellParams:
    """
    Map domain tokens to concrete values and aggregate them into the 9
    per-cell parameter tensors for a batch of b cells.
    """
    dom_types = dense[..., 0]
    idxs0 = dense[..., 1]
    idxs1 = dense[..., 2]
    idxs2 = dense[..., 3]
    idxs3 = dense[..., 4]

    # 1=catalytic, 2=transporter, 3=regulatory
    is_catal = dom_types == 1
    is_trnsp = dom_types == 2
    is_reg = dom_types == 3
    not_reg = (is_catal | is_trnsp).astype(jnp.int32)

    # scalar tokens; zeroed indices hit the empty row (NaN / 0)
    Vmaxs = tables.vmax_weights[idxs0 * not_reg]  # (b,p,d) f32
    Hills = tables.hills[idxs0 * is_reg.astype(jnp.int32)]  # (b,p,d) i32
    Kms = tables.km_weights[idxs1]  # (b,p,d) f32
    signs = tables.signs[idxs2]  # (b,p,d) i32

    # vector tokens
    reacts = tables.reactions[idxs3 * is_catal.astype(jnp.int32)]  # (b,p,d,s)
    trnspts = tables.transports[idxs3 * is_trnsp.astype(jnp.int32)]
    effectors = tables.effectors[idxs3 * is_reg.astype(jnp.int32)]

    # Vmax: average over defined domains
    Vmax = _nanmean0(Vmaxs, axis=2)  # (b,p)

    # allosteric exponents: effector vectors weighted by sign*hill
    A = jnp.sum(effectors * (signs * Hills)[..., None], axis=2)  # (b,p,s) i32

    # regulatory Kms separated per effector signal, averaged over domains
    Kmr_d = jnp.where(is_reg, Kms, jnp.nan)  # (b,p,d)
    Kmr_ds = effectors.astype(jnp.float32) * Kmr_d[..., None]  # (b,p,d,s)
    Kmr_ds = jnp.where(Kmr_ds == 0.0, jnp.nan, Kmr_ds)  # effectors add 0s
    Kmr = _nanmean0(Kmr_ds, axis=2)  # (b,p,s)
    Kmr = ipow(Kmr, A)  # pre-exponentiated by hill

    # stoichiometry; Nf/Nb split keeps zero-net cofactors alive
    N_d = (reacts + trnspts) * signs[..., None]  # (b,p,d,s) i32
    N = jnp.sum(N_d, axis=2)
    Nf = jnp.sum(jnp.where(N_d < 0, -N_d, 0), axis=2)
    Nb = jnp.sum(jnp.where(N_d > 0, N_d, 0), axis=2)

    # Km of catalytic/transporter domains
    Kmn = _nanmean0(jnp.where(~is_reg, Kms, jnp.nan), axis=2)  # (b,p)

    # energies -> equilibrium constant, clamped against Inf/0; fixed-order
    # sum + deterministic exp/div keep Ke bit-identical across backends
    E = sum_axis(N.astype(jnp.float32) * tables.mol_energies, axis=2)
    Ke = jnp.clip(
        det_exp(det_div(det_div(-E, abs_temp), jnp.float32(GAS_CONSTANT))),
        EPS,
        MAX,
    )

    # sampled Km defines the smaller side of Ke = Kmf/Kmb
    is_fwd = Ke >= 1.0
    Kmf = jnp.clip(jnp.where(is_fwd, Kmn, det_div(Kmn, Ke)), EPS, MAX)
    Kmb = jnp.clip(jnp.where(is_fwd, Kmn * Ke, Kmn), EPS, MAX)

    # integer tensors are stored narrow: they are 4 of the 5 big (c,p,s)
    # tensors and the integrator is HBM-bound, so halving their bytes cuts
    # its memory traffic ~40%.  Saturating cast — the domain sums only
    # approach +-2^15 for ~80kb genomes (thousands of domains), far past
    # any practical proteome
    def narrow(x: jax.Array) -> jax.Array:
        return jnp.clip(x, -32768, 32767).astype(INT_PARAM_DTYPE)

    return CellParams(
        Ke=Ke, Kmf=Kmf, Kmb=Kmb, Kmr=Kmr, Vmax=Vmax,
        N=narrow(N), Nf=narrow(Nf), Nb=narrow(Nb), A=narrow(A),
    )


# graftlint: disable=GL006 inlined into donated assemble/megastep callers; direct eager calls are cold one-off scatters
@jax.jit
def scatter_params(
    state: CellParams, batch: CellParams, cell_idxs: jax.Array
) -> CellParams:
    """Write batch parameter rows into state at cell_idxs (OOB = dropped)."""
    return CellParams(
        *(
            s.at[cell_idxs].set(b, mode="drop")
            for s, b in zip(state, batch)
        )
    )


# graftlint: disable=GL006 cold reference path (tests/fallbacks); hot scatters go through the donated assemble twins
@jax.jit
def compute_and_scatter_params(
    state: CellParams,
    dense: jax.Array,
    tables: TokenTables,
    abs_temp: jax.Array,
    cell_idxs: jax.Array,
) -> CellParams:
    """:func:`compute_cell_params` + :func:`scatter_params` as ONE
    program — the hot spawn/update path pays per-dispatch latency on
    remote accelerators, and fusing also keeps the batch tensors from
    materializing in HBM."""
    return scatter_params(
        state, compute_cell_params(dense, tables, abs_temp), cell_idxs
    )


def rung_pow2(values: np.ndarray, minimum: int, cap: int) -> np.ndarray:
    """Vectorized pow2 rung per value, floored at ``minimum`` and clamped
    to ``cap`` — the group key of the rung-grouped assembly."""
    v = np.maximum(np.asarray(values, dtype=np.int64), 1)
    rung = np.power(2, np.ceil(np.log2(v)).astype(np.int64))
    return np.minimum(np.maximum(rung, minimum), cap).astype(np.int64)


def _assemble_rows(
    state: CellParams,
    dense: jax.Array,
    tables: TokenTables,
    abs_temp: jax.Array,
    cell_idxs: jax.Array,
) -> CellParams:
    """:func:`compute_cell_params` at the dense batch's OWN (p, d) rung,
    padded back out to the state's protein capacity, then scattered.

    The pad rows use the values the full-capacity compute produces for
    all-zero token slots (Ke=1, Kmf=Kmb=EPS, Kmr=1, the rest 0) — derived
    in-program from a zero token so rung-grouped assembly stays
    BIT-identical to assembling every cell at worst-case capacities
    (pinned by tests/fast/test_kinetics.py)."""
    batch = compute_cell_params(dense, tables, abs_temp)
    p_cap = state.Vmax.shape[1]
    pad = p_cap - batch.Vmax.shape[1]
    if pad:
        b = dense.shape[0]
        fills = compute_cell_params(
            jnp.zeros((1, 1, 1, 5), dtype=dense.dtype), tables, abs_temp
        )
        batch = CellParams(
            *(
                jnp.concatenate(
                    [x, jnp.broadcast_to(f[:, :1], (b, pad) + x.shape[2:])],
                    axis=1,
                )
                for x, f in zip(batch, fills)
            )
        )
    return scatter_params(state, batch, cell_idxs)


def _assemble_rows_scan(
    state: CellParams,
    dense: jax.Array,  # (n_chunks, chunk, p, d, 5)
    tables: TokenTables,
    abs_temp: jax.Array,
    cell_idxs: jax.Array,  # (n_chunks, chunk)
) -> CellParams:
    """:func:`_assemble_rows` folded over row chunks with ``lax.scan`` —
    a big spawn burst is ONE dispatch carrying the params through the
    chunks instead of one dispatch (and, undonated, one full-pytree
    copy) per chunk."""

    def body(st: CellParams, xs):
        d, i = xs
        return _assemble_rows(st, d, tables, abs_temp, i), ()

    out, _ = jax.lax.scan(body, state, (dense, cell_idxs))
    return out


# Donated variants for accelerator backends: steady-state assembly holds
# ONE params copy (the scan carry aliases the input buffers) instead of
# double-buffering the full pytree per chunk.  XLA:CPU races donated-buffer
# reuse against its async runtime (BENCH_NOTES.md "CPU donation
# corruption"), so Kinetics dispatches the retained twins there — exactly
# the stepper's donation gate (stepper._donate_step_buffers).
assemble_params = partial(jax.jit, donate_argnums=(0,))(_assemble_rows)
assemble_params_scan = partial(jax.jit, donate_argnums=(0,))(_assemble_rows_scan)
# retained twins — graftlint: disable=GL006 XLA:CPU donated-buffer reuse races async execution; accelerator dispatches use the donated builds above
assemble_params_retained = jax.jit(_assemble_rows)  # graftlint: disable=GL006 CPU retained twin of assemble_params
assemble_params_scan_retained = jax.jit(_assemble_rows_scan)  # graftlint: disable=GL006 CPU retained twin of assemble_params_scan


# graftlint: disable=GL006 fires on discrete unset events, not per step; CPU in-place scatter reuse races (see assemble twins)
@jax.jit
def unset_params(state: CellParams, cell_idxs: jax.Array) -> CellParams:
    """Zero parameter rows at cell_idxs (OOB = dropped)."""
    return CellParams(
        *(
            s.at[cell_idxs].set(jnp.zeros((), dtype=s.dtype), mode="drop")
            for s in state
        )
    )


# graftlint: disable=GL006 fires on discrete divide events; self-referencing gather+scatter cannot alias in place
@jax.jit
def copy_params(
    state: CellParams, from_idxs: jax.Array, to_idxs: jax.Array
) -> CellParams:
    """Copy parameter rows from from_idxs to to_idxs (OOB = dropped).
    Padding slots must point both indices at the same OOB value."""
    return CellParams(
        *(s.at[to_idxs].set(s[from_idxs], mode="drop") for s in state)
    )


def compact_rows(arr: jax.Array, perm: jax.Array, n_keep: jax.Array) -> jax.Array:
    """Gather rows by a full-capacity permutation, zero rank >= n_keep —
    the one implementation of stable compaction-on-kill (SURVEY.md §7
    design delta 1), shared by every per-cell tensor."""
    out = arr[perm]
    keep = (jnp.arange(perm.shape[0]) < n_keep).reshape(
        (-1,) + (1,) * (out.ndim - 1)
    )
    return jnp.where(keep, out, jnp.zeros((), dtype=out.dtype))


# graftlint: disable=GL006 compaction gather cannot alias in place (arbitrary row permutation); fires on kill events only
@jax.jit
def permute_params(state: CellParams, perm: jax.Array, n_keep: jax.Array) -> CellParams:
    """:func:`compact_rows` over all nine parameter tensors.

    Under a device mesh the permutation gather crosses tile boundaries
    (a compacted row's new slot may live on another tile), so GSPMD
    inserts a cell-axis redistribution here; callers that need the
    OUTPUT pinned back to the cell sharding (the stepper's in-step and
    flush compaction) wrap the result in :func:`constrain_rows` —
    without the constraint XLA may leave the compacted tensors
    replicated, silently de-sharding every later step."""
    return CellParams(*(compact_rows(s, perm, n_keep) for s in state))


def constrain_rows(tree, sharding):
    """Pin every array leaf of ``tree`` (a :class:`CellParams` or any
    pytree of per-cell row tensors) to ``sharding`` via
    ``with_sharding_constraint`` — the shard-awareness hook the mesh
    step programs apply after row gathers/scatters whose output
    sharding GSPMD would otherwise infer (and sometimes infer as
    replicated).  ``sharding=None`` is the identity, so unsharded
    callers share the same code path."""
    if sharding is None:
        return tree
    return jax.tree_util.tree_map(
        lambda t: jax.lax.with_sharding_constraint(t, sharding), tree
    )
