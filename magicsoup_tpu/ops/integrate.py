"""
The reversible Michaelis-Menten signal integrator — the per-step numeric
core of the simulation, as pure jit-compiled JAX functions.

Math parity reference: `python/magicsoup/kinetics.py:725-918` and
`docs/mechanics.md:168-237` of mRcSchwering/magic-soup:

- three passes with Vmax trim factors (0.7, 0.2, 0.1) so equilibria overshot
  in one pass can be re-approached in the next
- per pass: reversible MM velocity ``(kf - kb) / (1 + kf + kb)`` with
  ``kf = prod(X^Nf) / Kmf``, non-competitive allosteric modulation
  ``prod(X^A / (X^A + Kmr))``, a downward adjustment so no signal goes
  negative, and an iterative Q-vs-Ke overshoot correction with increments
  (0.5, 0.25, 0.125, 0.0625)
- numerical guards: EPS/MAX clamps and NaN/Inf scrubbing exactly as in the
  reference (they are load-bearing for the no-explosion invariants)

TPU-first deltas (SURVEY.md §7): the reference's data-dependent early exits
(`torch.any` at kinetics.py:846-847) become fixed-iteration masked updates —
mathematically identical (an all-false adjustment mask leaves X unchanged)
but free of device->host syncs; the three trim passes are unrolled under one
``jit``.  Everything is float32, mask-driven, and shape-static so XLA can
fuse the whole step; dead cell slots (all-zero parameter rows) are naturally
inert.

Two numeric modes (the ``det`` static argument, default from
``MAGICSOUP_TPU_DETERMINISTIC=1``):

- **fast** (default): signal products in log space — ``prod(X^N)`` as
  ``exp(sum(N * log X))`` fused into single reductions over the narrow
  integer tensors (SURVEY.md §7 design delta 3), with zero signals
  carried as a finite log sentinel so the reference's 0/NaN/Inf
  semantics survive.  The step is HBM-bound, and this form never
  materializes a (c,p,s) float intermediate.
- **deterministic**: the fixed-order constructions from
  :mod:`magicsoup_tpu.ops.detmath` (integer powers by square-and-multiply,
  fixed binary reduction trees), which produce bit-identical results on
  every IEEE backend — this is what the CPU-vs-TPU bit-reproducibility
  check (`scripts/bitrepro.py`, BITREPRO.md) runs.  (The Pallas kernel
  runs the FAST mode with a ``mosaic_safe`` rewrite of the allosteric
  factor — detmath's float64 accumulation has no Mosaic lowering, which
  is why the backend registry (:mod:`magicsoup_tpu.ops.backends`)
  marks the pallas backend ``det_able=False`` and refuses it under
  deterministic mode; see :mod:`magicsoup_tpu.ops.pallas_integrate`.)

Both modes implement the same math; all hand-math golden tests run in both.
"""
import os
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from magicsoup_tpu.constants import EPS, MAX, MIN
from magicsoup_tpu.ops.detmath import det_div, ipow, prod_axis, sum_axis

TRIM_FACTORS = (0.7, 0.2, 0.1)
INCREMENTS = (0.5, 0.25, 0.125, 0.0625)
UPPER_THRESH = 1.5
LOWER_THRESH = 1 / 1.5


def default_deterministic() -> bool:
    """Read the deterministic-mode default from the environment (at call
    time, so test code and bitrepro children can flip it per process)."""
    return os.environ.get("MAGICSOUP_TPU_DETERMINISTIC") == "1"


# the four integer tensors are stored i16: they are 4 of the 5 big
# (c,p,s) tensors and the HBM-bound integrator re-reads them every pass,
# so narrow storage cuts its memory traffic ~40%.  Values are domain sums
# of stoichiometry*sign / hill*sign and only approach +-2^15 for ~80kb
# genomes; the assembly saturates instead of wrapping
INT_PARAM_DTYPE = jnp.int16


class CellParams(NamedTuple):
    """The 9 per-cell kinetic parameter tensors (c cells, p proteins,
    s signals = 2 * n_molecules; see reference kinetics.py:323-337)."""

    Ke: jax.Array  # (c,p) f32 equilibrium constants
    Kmf: jax.Array  # (c,p) f32 forward Michaelis constants
    Kmb: jax.Array  # (c,p) f32 backward Michaelis constants
    Kmr: jax.Array  # (c,p,s) f32 regulatory Km^hill per signal
    Vmax: jax.Array  # (c,p) f32 maximum velocities
    N: jax.Array  # (c,p,s) i16 net stoichiometry
    Nf: jax.Array  # (c,p,s) i16 forward (substrate) stoichiometry, >= 0
    Nb: jax.Array  # (c,p,s) i16 backward (product) stoichiometry, >= 0
    A: jax.Array  # (c,p,s) i16 allosteric hill exponents (+-)


def _pow(
    x: jax.Array, n: jax.Array, det: bool, nonneg: bool = False
) -> jax.Array:
    if det:
        return ipow(x, n, nonneg=nonneg)
    return jnp.power(x, n.astype(jnp.float32))


def _prod2(x: jax.Array, det: bool) -> jax.Array:
    """Product over the last axis of a (c,p,s) tensor."""
    return prod_axis(x, axis=-1) if det else jnp.prod(x, axis=2)


def _sum1(x: jax.Array, det: bool) -> jax.Array:
    """Float sum over the protein axis of a (c,p,s) tensor."""
    return sum_axis(x, axis=1) if det else jnp.sum(x, axis=1)


def _div(a: jax.Array, b: jax.Array, det: bool) -> jax.Array:
    """Division; hardware f32 divide is not correctly rounded on TPU, so
    the deterministic mode routes through detmath.det_div."""
    return det_div(a, b) if det else a / b


# stand-in for log(0): large-negative but finite, so 0 * LOG0 == 0 keeps
# N=0 terms neutral (no 0 * -Inf = NaN), while one N>0 term at X=0 drags
# the log-space sum far below f32 exp underflow.  Margin: the largest
# positive counterweight is sum_s 32767 * log(MAX) ~ s * 2.7e6, so -1e12
# dominates for any s below ~370k signals; the all-zeros extreme
# (32767 * s * LOG0 ~ 1e18 at s=28) stays well inside f32 range
LOG0 = -1e12


def _safe_log(X: jax.Array) -> jax.Array:
    """log(X) with X clamped into (0, MAX]: X=0 (and any NaN) maps to the
    LOG0 sentinel, X=Inf to log(MAX) — so a non-finite concentration
    saturates like the reference's NaN->0 / Inf->MAX scrubs instead of
    poisoning the log-space sum with 0 * Inf = NaN."""
    return jnp.where(X > 0.0, jnp.log(jnp.minimum(X, MAX)), LOG0)


def _prod_pow(logX: jax.Array, N: jax.Array) -> jax.Array:
    """``prod_s(X^N)`` per (cell, protein) as ``exp(sum_s N*logX)`` — one
    fused multiply-reduce over the narrow integer exponent tensor with NO
    (c,p,s) float intermediate (SURVEY.md §7 design delta 3).  The
    integrator is HBM-bound, so each avoided materialization is won
    wall-clock.  Overflow saturates to MAX like the reference's Inf
    scrub; a zero signal with a positive exponent underflows the sum to
    exp(-huge) = 0, matching the reference's 0*Inf -> NaN -> 0 scrub;
    negative/NaN results cannot arise (exp is nonnegative, all inputs
    finite)."""
    e = jnp.sum(N.astype(jnp.float32) * logX[:, None, :], axis=2)
    xx = jnp.exp(e)
    return jnp.where(jnp.isinf(xx), MAX, xx)


def _multiply_signals(
    X: jax.Array, N: jax.Array, det: bool = False
) -> tuple[jax.Array, jax.Array]:
    """
    ``prod_s(X^N)`` per (cell, protein) with the reference's zero/NaN/Inf
    handling (kinetics.py:894-918), plus which proteins are involved at
    all.  Fast mode goes through the log-space :func:`_prod_pow`;
    deterministic mode keeps square-and-multiply integer powers and
    fixed-order reduction trees (exp/log are not bit-identical across
    backends, repeated multiplies are).
    """
    prots = jnp.any(N > 0, axis=2)  # (c,p)
    if not det:
        return _prod_pow(_safe_log(X), N), prots
    M = N > 0  # (c,p,s)
    x = jnp.where(M, X[:, None, :], 0.0)
    # all callers pass Nf/Nb, which are >= 0 by construction
    xx = _prod2(_pow(x, N, det, nonneg=True), det)  # (c,p)
    xx = jnp.where(jnp.isnan(xx), 0.0, xx)
    xx = jnp.where(xx < 0.0, 0.0, xx)
    xx = jnp.where(jnp.isinf(xx), MAX, xx)
    return xx, prots


def _a_reg_logspace(X: jax.Array, A: jax.Array, Kmr: jax.Array) -> jax.Array:
    """Allosteric activity ``prod_s(X^A / (X^A + Kmr))`` with BOTH the
    float-exponent power and the signal product in exp-sum-log form —
    the ``mosaic_safe`` variant of the regulation factor (Mosaic has no
    lowering for ``pow``/``reduce_prod``; see
    :mod:`magicsoup_tpu.ops.pallas_integrate`).  ``X^A`` saturates at
    MAX instead of overflowing to Inf, so a zero concentration with A<0
    yields MAX/(MAX+Kmr) ~ 1 — the reference's "inhibitor absent ->
    fully active" NaN-scrub (kinetics.py:790-800) — and with A>0
    underflows to 0/(0+Kmr) = 0."""
    is_reg = A != 0
    t = A.astype(jnp.float32) * _safe_log(X)[:, None, :]
    xa = jnp.exp(jnp.minimum(t, jnp.log(MAX)))
    r = xa / (xa + Kmr)
    r = jnp.where(jnp.isnan(r), 1.0, r)
    r = jnp.where(~is_reg, 1.0, r)
    # product over signals; factors are in [0, 1] so log is safe with
    # the same zero sentinel as the main product
    lr = jnp.where(r > 0.0, jnp.log(r), LOG0)
    return jnp.exp(jnp.sum(lr, axis=2))


def _velocities(
    X: jax.Array,
    Vmax: jax.Array,
    p: CellParams,
    det: bool = False,
    mosaic_safe: bool = False,
) -> jax.Array:
    """Reversible-MM velocity with allosteric modulation
    (reference kinetics.py:771-806).  ``mosaic_safe`` (fast mode only)
    swaps the regulation factor's ``pow``/``prod`` for the exp-sum-log
    :func:`_a_reg_logspace` — the one sub-expression the Pallas kernel
    cannot share with this path verbatim."""
    kf, f_prots = _multiply_signals(X, p.Nf, det)
    kf = _div(kf, p.Kmf, det)
    kf = jnp.where(f_prots, kf, 0.0)
    kf = jnp.where(jnp.isinf(kf), MAX, kf)

    kb, b_prots = _multiply_signals(X, p.Nb, det)
    kb = _div(kb, p.Kmb, det)
    kb = jnp.where(b_prots, kb, 0.0)
    kb = jnp.where(jnp.isinf(kb), MAX, kb)

    a_cat = _div(kf - kb, 1 + kf + kb, det)  # (c,p)

    # non-competitive regulation: X^A / (X^A + Kmr); A<0 inhibits,
    # A<0 with X=0 gives Inf/Inf=NaN -> inhibitor absent -> fully active
    if mosaic_safe:
        assert not det, "mosaic_safe is a fast-mode rewrite"
        a_reg = _a_reg_logspace(X, p.A, p.Kmr)
    else:
        is_reg = p.A != 0
        x_reg = jnp.where(is_reg, X[:, None, :], 0.0)
        a_reg_s = _pow(x_reg, p.A, det)
        a_reg_s = _div(a_reg_s, a_reg_s + p.Kmr, det)
        a_reg_s = jnp.where(jnp.isnan(a_reg_s), 1.0, a_reg_s)
        a_reg_s = jnp.where(~is_reg, 1.0, a_reg_s)
        a_reg = _prod2(a_reg_s, det)  # (c,p)
        a_reg = jnp.where(jnp.isinf(a_reg), MAX, a_reg)

    V = a_cat * Vmax * a_reg
    return jnp.clip(V, MIN, MAX)


def _quotient(X: jax.Array, p: CellParams, det: bool = False) -> jax.Array:
    """Reaction quotient Q = prod(X^Nb) / prod(X^Nf)
    (reference kinetics.py:881-892)."""
    xx_prod, prod_prots = _multiply_signals(X, p.Nb, det)
    xx_prod = jnp.where(prod_prots, xx_prod, 0.0)
    xx_prod = jnp.where(jnp.isinf(xx_prod), MAX, xx_prod)

    xx_subs, subs_prots = _multiply_signals(X, p.Nf, det)
    xx_subs = jnp.where(subs_prots, xx_subs, 0.0)
    xx_subs = jnp.where(jnp.isinf(xx_subs), MAX, xx_subs)

    q = _div(xx_prod, xx_subs, det)
    return jnp.nan_to_num(jnp.clip(q, EPS, MAX), nan=1.0)


def _negative_factors(
    X: jax.Array, N: jax.Array, V: jax.Array, det: bool = False
) -> jax.Array:
    """Per-protein slow-down factors F_min (c,p) so no signal is removed
    below zero (reference kinetics.py:861-879).  Works on the narrow
    integer N and the (c,p) velocities directly; the velocity-weighted
    stoichiometry N*V is an elementwise expression XLA re-fuses into each
    reduction, so the (c,p,s) float tensor never lands in HBM."""
    NV = N.astype(jnp.float32) * V[:, :, None]  # (c,p,s), fused
    removed = _sum1(jnp.clip(-NV, min=0.0), det)  # (c,s)
    F = _div(X, removed, det)  # may be NaN/Inf where nothing is removed
    F = jnp.where(F > 1.0, 1.0, F)
    F_prots = jnp.where(NV < 0.0, F[:, None, :], 1.0)
    return jnp.min(F_prots, axis=2)  # (c,p); min is order-independent


def _weighted_dx(
    X0: jax.Array, N: jax.Array, W: jax.Array, det: bool = False
) -> jax.Array:
    """``X0 + sum_p N*W`` — scatter per-protein velocity weights W (c,p)
    back onto the signals through the stoichiometry, again with the
    float (c,p,s) product fused into the reduction."""
    return X0 + _sum1(N.astype(jnp.float32) * W[:, :, None], det)


def _equilibrium_adjusted_x(
    X0: jax.Array,
    X1: jax.Array,
    N: jax.Array,
    W: jax.Array,
    V: jax.Array,
    p: CellParams,
    det: bool = False,
) -> jax.Array:
    """
    Iteratively adjust velocities downward (or back up) so the reaction
    quotient does not overshoot Ke (reference kinetics.py:808-859).  The
    reference early-exits when no protein needs adjustment; here all 4
    increments always run with masked updates — identical fixed point,
    no host sync.  ``W`` are the negative-adjusted per-protein velocity
    weights (V*F_min); ``V`` the unadjusted velocities driving the
    impact threshold.
    """
    has_impact = jnp.abs(V) > 0.1
    is_fwd = V > 0.0
    F = jnp.ones_like(V)  # (c,p)

    # The reference stops iterating globally (`torch.any`, a device->host
    # sync) once no *impactful* protein needs adjustment; F-updates
    # themselves are applied regardless of impact.  A traced `stopped` flag
    # reproduces that exactly without the sync.
    stopped = jnp.asarray(False)

    for increment in INCREMENTS:
        Q1 = _quotient(X1, p, det)
        QKe = _div(Q1, p.Ke, det)

        # fwd: Q approaches Ke from below, QKe > 1 is overshoot; bwd mirrored
        v_too_low = jnp.where(is_fwd, QKe < LOWER_THRESH, QKe > UPPER_THRESH)
        v_too_low = jnp.where(is_fwd & (F == 1.0), False, v_too_low)
        v_too_high = jnp.where(is_fwd, QKe > UPPER_THRESH, QKe < LOWER_THRESH)
        v_too_high = jnp.where(~is_fwd & (F == 0.0), False, v_too_high)

        needs_adj = (v_too_low | v_too_high) & has_impact
        stopped = stopped | (jnp.sum(needs_adj) == 0)
        apply = ~stopped

        F = jnp.where(apply & v_too_high, F - increment, F)
        F = jnp.where(apply & v_too_low, F + increment, F)
        F = jnp.clip(F, 0.0, 1.0)

        X_new = _weighted_dx(X0, N, W * F, det)
        X_new = jnp.where(X_new < 0.0, 0.0, X_new)
        X1 = jnp.where(apply, X_new, X1)

    return X1


def _integrate_part(
    X0: jax.Array,
    adj_vmax: jax.Array,
    p: CellParams,
    det: bool = False,
    mosaic_safe: bool = False,
) -> jax.Array:
    """One trim pass (reference kinetics.py:753-769).  The Pallas kernel
    runs THIS function (``det=False, mosaic_safe=True``) so a fix to the
    negative guard or the equilibrium correction applies to both paths."""
    V = _velocities(X0, adj_vmax, p, det, mosaic_safe)  # (c,p)
    W = V * _negative_factors(X0, p.N, V, det)  # (c,p)
    X1 = _weighted_dx(X0, p.N, W, det)
    X1 = jnp.where(X1 < 0.0, 0.0, X1)  # small fp errors can give -1e-7
    return _equilibrium_adjusted_x(X0, X1, p.N, W, V, p, det)


# graftlint: disable=GL006 params is read-only here; the signal matrix X is the successor (donated in the steps variant)
@partial(jax.jit, static_argnames=("det",))
def _integrate_signals_jit(
    X: jax.Array, params: CellParams, det: bool
) -> jax.Array:
    for trim in TRIM_FACTORS:
        X = _integrate_part(X, jnp.clip(params.Vmax * trim, min=0.0), params, det)
    return X


def integrate_signals(
    X: jax.Array, params: CellParams, det: bool | None = None
) -> jax.Array:
    """
    Simulate protein work for one time step over signals ``X`` (c, s).
    Returns the updated signals; all inputs must be >= 0.

    ``det=True`` selects the deterministic (bit-reproducible across
    backends) numeric mode; default is the fast mode, or the environment
    override ``MAGICSOUP_TPU_DETERMINISTIC=1``.  The env default is
    resolved HERE, outside the jit, so the jit cache is keyed on the
    resolved bool and a mid-process env change cannot serve a
    stale-mode executable.  When tracing inside another jit, the env is
    read at that outer trace time instead.

    This is the pure-XLA implementation (exact reference parity including
    the batch-global equilibrium early-stop).  The VMEM-tiled Pallas
    variant lives in :mod:`magicsoup_tpu.ops.pallas_integrate` and is
    selected per :class:`World` through the backend registry
    (``World(integrator="pallas")`` / :mod:`magicsoup_tpu.ops.backends`)
    — never implicitly, so sharded steps (where ``pallas_call`` has no
    partitioning rule, ``mesh_able=False`` in the registry) always use
    this path.
    """
    if det is None:
        det = default_deterministic()
    return _integrate_signals_jit(X, params, det)


# X is donated: the scan consumes the signal matrix and returns its
# successor, so the n_steps burst updates it in place instead of holding
# two (c, s) copies for its whole duration
# graftlint: disable=GL006 params is read-only; X (the successor) is donated
@partial(jax.jit, static_argnames=("n_steps", "det"), donate_argnums=(0,))
def _integrate_signals_steps_jit(
    X: jax.Array, params: CellParams, n_steps: int, det: bool
) -> jax.Array:
    def body(x, _):
        return _integrate_signals_jit(x, params, det), None

    X, _ = jax.lax.scan(body, X, None, length=n_steps)
    return X


def integrate_signals_steps(
    X: jax.Array, params: CellParams, n_steps: int = 1, det: bool | None = None
) -> jax.Array:
    """Multiple integrator steps fused under one jit (scan over steps).

    Donates ``X`` when it is already a device array (a caller's
    reference to the input buffer is deleted by the call); pass a copy
    if the pre-step signals are still needed."""
    if det is None:
        det = default_deterministic()
    return _integrate_signals_steps_jit(
        jnp.asarray(X, dtype=jnp.float32), params, n_steps, det
    )
