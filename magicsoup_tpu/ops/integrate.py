"""
The reversible Michaelis-Menten signal integrator — the per-step numeric
core of the simulation, as pure jit-compiled JAX functions.

Math parity reference: `python/magicsoup/kinetics.py:725-918` and
`docs/mechanics.md:168-237` of mRcSchwering/magic-soup:

- three passes with Vmax trim factors (0.7, 0.2, 0.1) so equilibria overshot
  in one pass can be re-approached in the next
- per pass: reversible MM velocity ``(kf - kb) / (1 + kf + kb)`` with
  ``kf = prod(X^Nf) / Kmf``, non-competitive allosteric modulation
  ``prod(X^A / (X^A + Kmr))``, a downward adjustment so no signal goes
  negative, and an iterative Q-vs-Ke overshoot correction with increments
  (0.5, 0.25, 0.125, 0.0625)
- numerical guards: EPS/MAX clamps and NaN/Inf scrubbing exactly as in the
  reference (they are load-bearing for the no-explosion invariants)

TPU-first deltas (SURVEY.md §7): the reference's data-dependent early exits
(`torch.any` at kinetics.py:846-847) become fixed-iteration masked updates —
mathematically identical (an all-false adjustment mask leaves X unchanged)
but free of device->host syncs; the three trim passes are unrolled under one
``jit``.  Everything is float32, mask-driven, and shape-static so XLA can
fuse the whole step; dead cell slots (all-zero parameter rows) are naturally
inert.
"""
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from magicsoup_tpu.constants import EPS, MAX, MIN

TRIM_FACTORS = (0.7, 0.2, 0.1)
INCREMENTS = (0.5, 0.25, 0.125, 0.0625)
UPPER_THRESH = 1.5
LOWER_THRESH = 1 / 1.5


class CellParams(NamedTuple):
    """The 9 per-cell kinetic parameter tensors (c cells, p proteins,
    s signals = 2 * n_molecules; see reference kinetics.py:323-337)."""

    Ke: jax.Array  # (c,p) f32 equilibrium constants
    Kmf: jax.Array  # (c,p) f32 forward Michaelis constants
    Kmb: jax.Array  # (c,p) f32 backward Michaelis constants
    Kmr: jax.Array  # (c,p,s) f32 regulatory Km^hill per signal
    Vmax: jax.Array  # (c,p) f32 maximum velocities
    N: jax.Array  # (c,p,s) i32 net stoichiometry
    Nf: jax.Array  # (c,p,s) i32 forward (substrate) stoichiometry, >= 0
    Nb: jax.Array  # (c,p,s) i32 backward (product) stoichiometry, >= 0
    A: jax.Array  # (c,p,s) i32 allosteric hill exponents (+-)


def _multiply_signals(X: jax.Array, N: jax.Array) -> tuple[jax.Array, jax.Array]:
    """
    ``prod_s(X^N)`` per (cell, protein) with the reference's zero/NaN/Inf
    handling (kinetics.py:894-918): signals with N<=0 are masked to 0 before
    the power so 0^0=1 keeps them neutral; NaN/negative results are scrubbed
    to 0, Inf to MAX.  Also returns which proteins are involved at all.
    """
    M = N > 0  # (c,p,s)
    x = jnp.where(M, X[:, None, :], 0.0)
    xx = jnp.prod(jnp.power(x, N.astype(jnp.float32)), axis=2)  # (c,p)
    xx = jnp.where(jnp.isnan(xx), 0.0, xx)
    xx = jnp.where(xx < 0.0, 0.0, xx)
    xx = jnp.where(jnp.isinf(xx), MAX, xx)
    return xx, jnp.any(M, axis=2)


def _velocities(X: jax.Array, Vmax: jax.Array, p: CellParams) -> jax.Array:
    """Reversible-MM velocity with allosteric modulation
    (reference kinetics.py:771-806)."""
    kf, f_prots = _multiply_signals(X, p.Nf)
    kf = kf / p.Kmf
    kf = jnp.where(f_prots, kf, 0.0)
    kf = jnp.where(jnp.isinf(kf), MAX, kf)

    kb, b_prots = _multiply_signals(X, p.Nb)
    kb = kb / p.Kmb
    kb = jnp.where(b_prots, kb, 0.0)
    kb = jnp.where(jnp.isinf(kb), MAX, kb)

    a_cat = (kf - kb) / (1 + kf + kb)  # (c,p)

    # non-competitive regulation: X^A / (X^A + Kmr); A<0 inhibits,
    # A<0 with X=0 gives Inf/Inf=NaN -> inhibitor absent -> fully active
    is_reg = p.A != 0
    x_reg = jnp.where(is_reg, X[:, None, :], 0.0)
    a_reg_s = jnp.power(x_reg, p.A.astype(jnp.float32))
    a_reg_s = a_reg_s / (a_reg_s + p.Kmr)
    a_reg_s = jnp.where(jnp.isnan(a_reg_s), 1.0, a_reg_s)
    a_reg_s = jnp.where(~is_reg, 1.0, a_reg_s)
    a_reg = jnp.prod(a_reg_s, axis=2)  # (c,p)
    a_reg = jnp.where(jnp.isinf(a_reg), MAX, a_reg)

    V = a_cat * Vmax * a_reg
    return jnp.clip(V, MIN, MAX)


def _quotient(X: jax.Array, p: CellParams) -> jax.Array:
    """Reaction quotient Q = prod(X^Nb) / prod(X^Nf)
    (reference kinetics.py:881-892)."""
    xx_prod, prod_prots = _multiply_signals(X, p.Nb)
    xx_prod = jnp.where(prod_prots, xx_prod, 0.0)
    xx_prod = jnp.where(jnp.isinf(xx_prod), MAX, xx_prod)

    xx_subs, subs_prots = _multiply_signals(X, p.Nf)
    xx_subs = jnp.where(subs_prots, xx_subs, 0.0)
    xx_subs = jnp.where(jnp.isinf(xx_subs), MAX, xx_subs)

    q = xx_prod / xx_subs
    return jnp.nan_to_num(jnp.clip(q, EPS, MAX), nan=1.0)


def _negative_adjusted_nv(NV: jax.Array, X: jax.Array) -> jax.Array:
    """Slow proteins down so no signal is removed below zero
    (reference kinetics.py:861-879)."""
    removed = jnp.sum(jnp.clip(-NV, min=0.0), axis=1)  # (c,s)
    F = X / removed  # may be NaN/Inf where nothing is removed
    F = jnp.where(F > 1.0, 1.0, F)
    M_rm = NV < 0.0  # (c,p,s)
    F_prots = jnp.where(M_rm, F[:, None, :], 1.0)
    F_min = jnp.min(F_prots, axis=2)  # (c,p)
    return NV * F_min[:, :, None]


def _equilibrium_adjusted_x(
    X0: jax.Array, X1: jax.Array, NV: jax.Array, V: jax.Array, p: CellParams
) -> jax.Array:
    """
    Iteratively adjust velocities downward (or back up) so the reaction
    quotient does not overshoot Ke (reference kinetics.py:808-859).  The
    reference early-exits when no protein needs adjustment; here all 4
    increments always run with masked updates — identical fixed point,
    no host sync.
    """
    has_impact = jnp.abs(V) > 0.1
    is_fwd = V > 0.0
    F = jnp.ones_like(V)  # (c,p)

    # The reference stops iterating globally (`torch.any`, a device->host
    # sync) once no *impactful* protein needs adjustment; F-updates
    # themselves are applied regardless of impact.  A traced `stopped` flag
    # reproduces that exactly without the sync.
    stopped = jnp.asarray(False)

    for increment in INCREMENTS:
        Q1 = _quotient(X1, p)
        QKe = Q1 / p.Ke

        # fwd: Q approaches Ke from below, QKe > 1 is overshoot; bwd mirrored
        v_too_low = jnp.where(is_fwd, QKe < LOWER_THRESH, QKe > UPPER_THRESH)
        v_too_low = jnp.where(is_fwd & (F == 1.0), False, v_too_low)
        v_too_high = jnp.where(is_fwd, QKe > UPPER_THRESH, QKe < LOWER_THRESH)
        v_too_high = jnp.where(~is_fwd & (F == 0.0), False, v_too_high)

        stopped = stopped | ~jnp.any((v_too_low | v_too_high) & has_impact)
        apply = ~stopped

        F = jnp.where(apply & v_too_high, F - increment, F)
        F = jnp.where(apply & v_too_low, F + increment, F)
        F = jnp.clip(F, 0.0, 1.0)

        X_new = X0 + jnp.einsum("cps,cp->cs", NV, F)
        X_new = jnp.where(X_new < 0.0, 0.0, X_new)
        X1 = jnp.where(apply, X_new, X1)

    return X1


def _integrate_part(X0: jax.Array, adj_vmax: jax.Array, p: CellParams) -> jax.Array:
    """One trim pass (reference kinetics.py:753-769)."""
    V = _velocities(X0, adj_vmax, p)  # (c,p)
    NV = p.N.astype(jnp.float32) * V[:, :, None]  # (c,p,s)
    NV_adj = _negative_adjusted_nv(NV, X0)
    X1 = X0 + jnp.sum(NV_adj, axis=1)
    X1 = jnp.where(X1 < 0.0, 0.0, X1)  # small fp errors can give -1e-7
    return _equilibrium_adjusted_x(X0, X1, NV_adj, V, p)


@jax.jit
def integrate_signals(X: jax.Array, params: CellParams) -> jax.Array:
    """
    Simulate protein work for one time step over signals ``X`` (c, s).
    Returns the updated signals; all inputs must be >= 0.

    This is the pure-XLA implementation (exact reference parity including
    the batch-global equilibrium early-stop).  The VMEM-tiled Pallas
    variant lives in :mod:`magicsoup_tpu.ops.pallas_integrate` and is
    selected per :class:`World` via ``use_pallas`` — never implicitly, so
    sharded steps (where ``pallas_call`` has no partitioning rule) always
    use this path.
    """
    for trim in TRIM_FACTORS:
        X = _integrate_part(X, jnp.clip(params.Vmax * trim, min=0.0), params)
    return X


@partial(jax.jit, static_argnames=("n_steps",))
def integrate_signals_steps(
    X: jax.Array, params: CellParams, n_steps: int = 1
) -> jax.Array:
    """Multiple integrator steps fused under one jit (scan over steps)."""

    def body(x, _):
        return integrate_signals(x, params), None

    X, _ = jax.lax.scan(body, X, None, length=n_steps)
    return X
