"""Tier B: the host-side deep audit.

:func:`audit_world` fetches the device-resident state ONCE (one
``fetch_host`` of a pytree — on a remote accelerator separate fetches
are a tunnel round trip each) and runs the full semantic suite over it:
host/device mirror agreement, occupancy-map consistency, dead-row
residue, concentration sanity, and a sampled genome → proteome
re-translation cross-check against the assembled kinetics parameters.
The re-translation deliberately BYPASSES the PhenotypeCache (it calls
``genetics.translate_genomes_flat`` directly), so a poisoned cache
entry, a stale push, or a corrupted parameter row all surface as the
same typed :class:`InvariantViolation`.

The audit runs on a World that is the source of truth — for pipelined
runs call ``stepper.flush()`` first (``guard.restore_run(...,
audit=True)`` audits at exactly such a boundary).  It is read-only and
never mutates state.

This module imports numpy only at module scope; jax enters through the
functions (keeping ``import magicsoup_tpu.check`` backend-free).
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass(frozen=True)
class InvariantViolation:
    """One semantic invariant the audited state breaks.

    Attributes:
        code: Stable machine-readable slug (e.g. ``"dead_cm_residue"``,
            ``"params_genome_mismatch"``).
        message: Human-readable description with the observed values.
        rows: Offending cell rows, when the violation is row-local.
        details: Structured extras (counts, maxima) for tooling.
    """

    code: str
    message: str
    rows: tuple = ()
    details: dict = field(default_factory=dict)

    def __str__(self) -> str:  # pragma: no cover - convenience repr
        where = f" rows={list(self.rows)}" if self.rows else ""
        return f"[{self.code}]{where} {self.message}"


class AuditFailed(RuntimeError):
    """Raised by :func:`assert_consistent` when the audit finds
    violations; carries them in ``.violations``."""

    def __init__(self, violations: list[InvariantViolation]):
        lines = "\n".join(f"  - {v}" for v in violations)
        super().__init__(
            f"world audit found {len(violations)} invariant "
            f"violation(s):\n{lines}"
        )
        self.violations = list(violations)


def _sample_rows(n: int, sample: int) -> list[int]:
    """Deterministic, spread-out row sample: both ends plus an even
    stride between them — no RNG, so the audit itself can never fork a
    deterministic trajectory."""
    if n <= sample:
        return list(range(n))
    idx = np.linspace(0, n - 1, num=sample)
    return sorted({int(round(i)) for i in idx})


def audit_world(world, *, sample: int = 8) -> list[InvariantViolation]:
    """Run the full semantic audit; returns typed violations (empty =
    consistent).

    ``sample`` bounds the genome → proteome re-translation cross-check
    (translation is the expensive part); the structural checks always
    cover every row.
    """
    from magicsoup_tpu.guard.sentinel import NEG_EPS
    from magicsoup_tpu.util import fetch_host

    violations: list[InvariantViolation] = []
    n = int(world.n_cells)
    kin = world.kinetics

    # THE one device fetch: molecule map, cell molecules, the device
    # position mirror, and all nine parameter tensors as a single pytree
    mm, cm, pos_dev, params = fetch_host(
        (
            world._molecule_map,
            world._cell_molecules,
            world._positions_dev,
            kin.params,
        )
    )
    mm = np.asarray(mm)
    cm = np.asarray(cm)
    pos_dev = np.asarray(pos_dev)
    cap = cm.shape[0]
    m = mm.shape[1]

    # ---- host bookkeeping agrees with itself --------------------------
    genomes = list(world.cell_genomes)
    if len(genomes) != n:
        violations.append(
            InvariantViolation(
                "host_counts",
                f"{len(genomes)} genomes for n_cells={n}",
            )
        )
    if n > cap:
        violations.append(
            InvariantViolation(
                "host_counts",
                f"n_cells={n} exceeds device capacity {cap}",
            )
        )
        n = min(n, cap)

    # ---- token-store invariants (token genome backend only) -----------
    store = getattr(world, "genome_store", None)
    if store is not None:
        violations += _audit_token_store(store, n, sample)

    pos = np.asarray(world.cell_positions)[:n]
    cell_map = np.asarray(world.cell_map)

    # ---- positions: in range, unique, mirrored on device --------------
    if n and (
        (pos < 0).any() or (pos >= m).any()
    ):
        bad = np.nonzero(((pos < 0) | (pos >= m)).any(axis=1))[0]
        violations.append(
            InvariantViolation(
                "pos_out_of_range",
                f"{bad.size} live cells hold positions outside the "
                f"{m}x{m} map",
                rows=tuple(bad[:16].tolist()),
            )
        )
    else:
        lin = pos[:, 0] * m + pos[:, 1]
        uniq, counts = np.unique(lin, return_counts=True)
        if (counts > 1).any():
            dup_lin = set(uniq[counts > 1].tolist())
            rows = [
                i for i, v in enumerate(lin.tolist()) if v in dup_lin
            ]
            violations.append(
                InvariantViolation(
                    "dup_position",
                    f"{len(rows)} live cells share pixels",
                    rows=tuple(rows[:16]),
                )
            )
        if not np.array_equal(pos_dev[:n], pos):
            rows = np.nonzero((pos_dev[:n] != pos).any(axis=1))[0]
            violations.append(
                InvariantViolation(
                    "device_pos_desync",
                    f"device position mirror differs from the host at "
                    f"{rows.size} rows",
                    rows=tuple(rows[:16].tolist()),
                )
            )
        # occupancy map: exactly the live pixels, nothing else
        want = np.zeros((m, m), dtype=bool)
        if n:
            want[pos[:, 0], pos[:, 1]] = True
        if not np.array_equal(cell_map, want):
            extra = int((cell_map & ~want).sum())
            missing = int((~cell_map & want).sum())
            violations.append(
                InvariantViolation(
                    "cell_map_desync",
                    f"occupancy map disagrees with live positions "
                    f"({extra} phantom, {missing} missing pixels)",
                    details={"phantom": extra, "missing": missing},
                )
            )

    # ---- dead-row residue: rows beyond n must be exact zeros ----------
    if (cm[n:] != 0.0).any():
        rows = n + np.nonzero((cm[n:] != 0.0).any(axis=1))[0]
        violations.append(
            InvariantViolation(
                "dead_cm_residue",
                f"{rows.size} dead rows hold nonzero intracellular "
                "concentrations",
                rows=tuple(rows[:16].tolist()),
            )
        )
    dead_param_rows: set[int] = set()
    for leaf in params:
        t = np.asarray(leaf)
        tail = t[n:].reshape(cap - n, -1)
        hit = np.nonzero((tail != 0).any(axis=1))[0]
        dead_param_rows.update((n + hit).tolist())
    if dead_param_rows:
        rows = sorted(dead_param_rows)
        violations.append(
            InvariantViolation(
                "dead_param_residue",
                f"{len(rows)} dead rows hold nonzero kinetics "
                "parameters",
                rows=tuple(rows[:16]),
            )
        )

    # ---- concentration sanity (mirrors the Tier A sentinel lanes) -----
    if not np.isfinite(mm).all() or (mm < -NEG_EPS).any():
        violations.append(
            InvariantViolation(
                "mm_bad_values",
                "molecule map holds non-finite or negative "
                "concentrations",
            )
        )
    live_cm = cm[:n]
    bad = ~np.isfinite(live_cm).all(axis=1) | (
        live_cm < -NEG_EPS
    ).any(axis=1)
    if bad.any():
        rows = np.nonzero(bad)[0]
        violations.append(
            InvariantViolation(
                "cm_bad_values",
                f"{rows.size} live cells hold non-finite or negative "
                "concentrations",
                rows=tuple(rows[:16].tolist()),
            )
        )

    # ---- sampled genome -> proteome -> params cross-check -------------
    if n and len(genomes) == n and sample > 0:
        violations += _cross_check_params(
            world, params, _sample_rows(n, sample), genomes
        )
    return violations


def _audit_token_store(store, n: int, sample: int) -> list[InvariantViolation]:
    """Packed-token invariants for the device genome store: length
    ranges, PAD discipline beyond each genome and in dead rows, and a
    sampled decode -> re-encode round trip.  These hold by construction
    (every kernel normalizes PAD past the new length; compaction zeroes
    evicted rows), so any hit means a kernel or scatter wrote outside
    its mask."""
    from magicsoup_tpu.genomes import PAD, decode_tokens, encode_genomes

    out: list[InvariantViolation] = []
    tok, lens = store.host_arrays()
    tok = np.asarray(tok)
    lens = np.asarray(lens)
    cap, g = tok.shape
    if n > cap:
        out.append(
            InvariantViolation(
                "token_capacity",
                f"n_cells={n} exceeds token store capacity {cap}",
            )
        )
        n = cap
    if (lens < 0).any() or (lens > g).any():
        rows = np.nonzero((lens < 0) | (lens > g))[0]
        out.append(
            InvariantViolation(
                "token_length_range",
                f"{rows.size} rows hold lengths outside [0, {g}]",
                rows=tuple(rows[:16].tolist()),
            )
        )
        return out  # masks below would be nonsense
    col = np.arange(g)
    in_len = col[None, :] < lens[:, None]
    bad_val = in_len & ((tok < 0) | (tok > 3))
    if bad_val.any():
        rows = np.nonzero(bad_val.any(axis=1))[0]
        out.append(
            InvariantViolation(
                "token_range",
                f"{rows.size} rows hold non-nucleotide tokens inside "
                "their genome length",
                rows=tuple(rows[:16].tolist()),
            )
        )
    bad_pad = ~in_len & (tok != PAD)
    if bad_pad.any():
        rows = np.nonzero(bad_pad.any(axis=1))[0]
        out.append(
            InvariantViolation(
                "token_pad_residue",
                f"{rows.size} rows hold non-PAD bytes beyond their "
                "genome length",
                rows=tuple(rows[:16].tolist()),
            )
        )
    if (lens[n:] != 0).any():
        rows = n + np.nonzero(lens[n:] != 0)[0]
        out.append(
            InvariantViolation(
                "token_dead_residue",
                f"{rows.size} dead rows hold nonzero genome lengths",
                rows=tuple(rows[:16].tolist()),
            )
        )
    # sampled decode -> re-encode round trip (codec self-consistency)
    if n and not out:
        rows = _sample_rows(n, sample)
        seqs = decode_tokens(tok[rows], lens[rows])
        re_tok, re_lens = encode_genomes(seqs, length_cap=g)
        if not (
            np.array_equal(re_tok, tok[rows])
            and np.array_equal(re_lens, lens[rows])
        ):
            bad = [
                r
                for k, r in enumerate(rows)
                if not np.array_equal(re_tok[k], tok[r])
            ]
            out.append(
                InvariantViolation(
                    "token_roundtrip",
                    f"{len(bad)} sampled rows fail the decode -> "
                    "re-encode round trip",
                    rows=tuple(bad[:16]),
                )
            )
    return out


def _cross_check_params(
    world, params, rows: list[int], genomes: list[str]
) -> list[InvariantViolation]:
    """Re-translate sampled genomes from scratch and compare the
    full-capacity parameter assembly against the resident rows,
    byte-exact over each cell's REAL protein columns (rung-grouped
    assembly is pinned bit-identical to full-width assembly, so exact
    equality is the contract, not an approximation).  Columns beyond a
    cell's protein count are excluded: they hold either the
    zero-token fill values or exact zeros depending on whether the row
    predates a capacity growth, and both are inert."""
    import jax.numpy as jnp

    from magicsoup_tpu.native import engine as _engine
    from magicsoup_tpu.ops.params import compute_cell_params
    from magicsoup_tpu.util import fetch_host

    kin = world.kinetics
    out: list[InvariantViolation] = []
    pc, prots, doms = world.genetics.translate_genomes_flat(
        [genomes[i] for i in rows]
    )
    need_p = int(pc.max()) if len(pc) else 0
    need_d = (
        int(np.asarray(prots)[:, 3].max()) if len(prots) else 0
    )
    if need_p > kin.max_proteins or need_d > kin.max_doms:
        return [
            InvariantViolation(
                "token_capacity_exceeded",
                f"sampled genomes need (p={need_p}, d={need_d}) tokens "
                f"but capacities are (p={kin.max_proteins}, "
                f"d={kin.max_doms}) — capacities only ever grow, so "
                "genomes and kinetics state are out of sync",
                rows=tuple(rows),
            )
        ]
    dense = _engine.pack_dense(
        pc, prots, doms, kin.max_proteins, max(kin.max_doms, 1)
    )
    expect = fetch_host(
        compute_cell_params(
            jnp.asarray(dense), kin.tables, kin._abs_temp_arr
        )
    )
    names = type(params)._fields
    n_prot = np.asarray(pc, dtype=np.int64)
    bad: dict[int, list[str]] = {}
    for name, have_leaf, want_leaf in zip(names, params, expect):
        have_leaf = np.asarray(have_leaf)
        want_leaf = np.asarray(want_leaf)
        for k, row in enumerate(rows):
            p = int(n_prot[k])
            have = have_leaf[row][:p]
            want = want_leaf[k][:p]
            if have.tobytes() != want.tobytes():
                bad.setdefault(row, []).append(name)
    for row in sorted(bad):
        out.append(
            InvariantViolation(
                "params_genome_mismatch",
                f"cell {row}: resident kinetics params differ from the "
                f"genome's re-translation in {', '.join(bad[row])}",
                rows=(row,),
                details={"tensors": bad[row]},
            )
        )
    return out


def assert_consistent(world, *, sample: int = 8) -> None:
    """:func:`audit_world`, raising :class:`AuditFailed` on any
    violation (the ``restore_run(..., audit=True)`` entry point)."""
    violations = audit_world(world, sample=sample)
    if violations:
        raise AuditFailed(violations)
