"""Tier A: the on-device invariant lane contract.

The fused step program (``stepper._step_body``, ``ms:invariants`` phase)
computes one i32 flag word per step, UNCONDITIONALLY and pre-compaction,
and packs it into the step record next to the graftguard health word —
the compiled device program is byte-identical whether or not anything
consumes the lanes, and the replay still costs exactly one fetch.  This
module pins the bit layout and the mass-drift tolerance; it is
numpy/stdlib-only at import time so both the stepper (device side) and
the host policy code can import it without initialising a backend.

Bit layout of the invariant flag word (word 9 of the record header):

- bit 0 ``occ_alive_mismatch`` — occupied-pixel count != live-row count
  (an occupancy-map desync: a kill/divide/spawn lost track of a pixel);
- bit 1 ``pos_unoccupied`` — some live row's pixel is not marked
  occupied in the map;
- bit 2 ``dup_position`` — two live rows share a pixel;
- bit 3 ``dead_cm_residue`` — a row at or beyond the high-water mark
  holds a nonzero intracellular concentration (dead rows must be exact
  zeros: the mass lanes and the det reductions rely on it);
- bit 4 ``dead_param_residue`` — same, for any of the nine kinetics
  parameter tensors;
- bit 5 ``mass_drift`` — the physics phase (diffusion + permeation,
  both closed-system) changed the total molecule mass by more than
  ``MASS_DRIFT_RTOL`` relative to the post-degradation total.

Word 10 of the header is the measured ABSOLUTE mass drift, an f32
bitcast into the i32 record (divide on device would be the one
non-deterministic op in the lane — the host divides if it wants the
relative number).
"""

FLAG_OCC_ALIVE_MISMATCH = 1 << 0
FLAG_POS_UNOCCUPIED = 1 << 1
FLAG_DUP_POSITION = 1 << 2
FLAG_DEAD_CM_RESIDUE = 1 << 3
FLAG_DEAD_PARAM_RESIDUE = 1 << 4
FLAG_MASS_DRIFT = 1 << 5

# bit -> stable telemetry/report key, in bit order
INVARIANT_NAMES = (
    "occ_alive_mismatch",
    "pos_unoccupied",
    "dup_position",
    "dead_cm_residue",
    "dead_param_residue",
    "mass_drift",
)

# relative tolerance for the closed-system mass-conservation lane: the
# det-mode fixed-tree f32 sums agree to ~1e-7 relative; 1e-4 leaves
# headroom for the non-det hardware reduction order while still
# catching any real leak (a lost cell's worth of molecules is orders of
# magnitude larger)
MASS_DRIFT_RTOL = 1e-4


def decode_invariants(flags: int) -> dict:
    """Invariant flag word -> ``{name: bool}`` in bit order."""
    flags = int(flags)
    return {
        name: bool(flags & (1 << bit))
        for bit, name in enumerate(INVARIANT_NAMES)
    }
