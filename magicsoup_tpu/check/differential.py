"""Tier C: the differential correctness harness.

Four execution paths advance the same simulation: the classic
:class:`World` driver, the :class:`PipelinedStepper` at ``K=1`` and
``K=4`` (megastep fusion), and the stepper over a 2-tile device mesh.
In det mode they are all documented BIT-identical — this module makes
that a gating check instead of a promise: one seeded
spawn/step/mutate/kill/divide/compact schedule is driven through every
path, the full semantic state is digested at each schedule boundary,
and any digest mismatch names the boundary where the trajectories
forked.

The schedule's structural ops (spawn, mutate, kill, divide — kill also
exercises row compaction) run through the classic World API on EVERY
path, with the world's RNG streams re-seeded from the schedule seed
before each op: the differential axis is the CHEMISTRY execution path
(``World.step_many`` vs the fused/pipelined/sharded stepper), not the
host-side op implementations, and pinning the streams keeps a
divergence report pointing at the device programs rather than at RNG
consumption differences between drivers.

The fleet axes extend the same contract to batched execution: a B=1
fleet (``FLEET_PATHS``) and a cross-rung FUSED mixed fleet
(``FUSED_PATHS``) drive the schedule world through the
:class:`FleetScheduler`, and its digests must still match the solo
stepper bit-for-bit — stacking worlds on a batch axis and fusing rung
groups into one program are both pinned structurally invisible.

A second axis crosses the first: every path re-runs with the world's
genome backend flipped to device token arrays (``TOKEN_PATHS``).  The
schedule's host-engine ops then operate through the string
import/export boundary (``world.cell_genomes`` decodes the device
store), so matching digests pin the packed-token storage bit-identical
to the host string lists across spawn/mutate/kill/divide/compact —
the contract that lets the token path replace the string path in hot
loops.

``performance/smoke.py --differential`` gates on
:func:`run_differential`; ``scripts/test.sh`` runs it after the unit
tiers.  Import is numpy/stdlib-only; jax loads inside the entry points.
"""
from __future__ import annotations

import hashlib
import random

import numpy as np

#: the four gated execution paths, in report order
PATHS = ("classic", "k1", "k4", "mesh2")

#: fleet execution paths — a B=1 fleet driven through the
#: FleetScheduler at K=1 / K=4.  Not in the default gated tuple (the
#: fleet has its own gating smoke); tests/fast/test_fleet.py pins these
#: against the solo digests per boundary.
FLEET_PATHS = ("fleet1", "fleet4")

#: cross-rung FUSED dispatch paths — the schedule world steps inside a
#: mixed-rung fleet whose rung groups are merged into ONE batched
#: program + ONE physical fetch per megastep.  ``fused2`` drives K=1
#: under ``fusion="fleet"`` with one companion world on a DIFFERENT
#: capacity rung (double map size); ``fused_fleet`` drives K=4 under
#: ``fusion="auto"`` with two companions across rungs.  Digests must
#: equal the solo reference bit-for-bit at every boundary — the fused
#: program runs each rung's body at native shapes, so fusion is pinned
#: to be structurally invisible to every world's trajectory.
FUSED_PATHS = ("fused2", "fused_fleet")

#: the token genome-backend axis: every base path re-run with the
#: world's genomes held as device token arrays instead of host strings.
#: ``token_fleet3`` drives the schedule world through a B=3 fleet (two
#: companion token worlds share the group) — the ISSUE-pinned fleet
#: shape.  A token path's digests must equal the string reference
#: BIT-for-bit at every boundary: ``state_digest`` reads
#: ``world.cell_genomes``, which in token mode decodes the device
#: arrays, so a single byte of storage divergence forks the digest.
TOKEN_PATHS = (
    "token_classic",
    "token_k1",
    "token_k4",
    "token_mesh2",
    "token_fleet3",
)

#: the integrator-backend axis: the schedule world constructed with
#: ``integrator="pallas"`` (the VMEM-resident kernel, interpret mode on
#: CPU) driven through the K=1 pipelined stepper.  The pallas backend is
#: fast-mode only, so this path runs WITHOUT deterministic mode and is
#: pinned by the committed golden STRUCTURAL digest rather than by
#: bit-comparison against the det reference: selection is disabled and
#: mutation rates are zero in the chem phases, so the structural
#: trajectory (cells, positions, genomes, counters) must not depend on
#: the integrator's float output at all — a pallas regression that
#: perturbs structure (wrong shapes, NaNs tripping the sentinels,
#: misrouted records) forks the digest.
PALLAS_PATHS = ("pallas_k1",)

#: chem-phase lengths between structural ops — multiples of 4 so the
#: K=4 megastep divides every phase evenly
PHASES = (4, 8, 4)

#: schedule boundary names, in digest order (one digest per boundary)
BOUNDARIES = (
    "spawn",
    "chem_a",
    "mutate",
    "chem_b",
    "kill",
    "divide",
    "chem_c",
)


def _chemistry():
    import magicsoup_tpu as ms

    mols = [
        ms.Molecule("dfx-a", 10e3),
        ms.Molecule("dfx-atp", 8e3, half_life=100_000),
    ]
    return ms.Chemistry(
        molecules=mols, reactions=[([mols[0]], [mols[1]])]
    )


def _reseed(world, seed: int, op_index: int) -> None:
    """Pin both world RNG streams to a schedule-derived state before a
    structural op (see module docstring)."""
    world._rng.seed(seed * 10_007 + op_index)
    world._nprng = np.random.default_rng(seed * 20_011 + op_index)


def state_digest(world) -> str:
    """sha256 over the full semantic state: map + live cell tensors,
    positions, counters, and genomes.  Excludes RNG streams (the
    schedule pins them) and dead capacity rows (capacity growth timing
    is part of the digest only through ``n_cells``)."""
    from magicsoup_tpu.util import fetch_host

    n = int(world.n_cells)
    mm, cm = fetch_host((world._molecule_map, world._cell_molecules))
    h = hashlib.sha256()
    for tag, part in (
        ("n", np.int64(n).tobytes()),
        ("mm", np.asarray(mm).tobytes()),
        ("cm", np.asarray(cm)[:n].tobytes()),
        ("pos", np.asarray(world.cell_positions).tobytes()),
        ("map", np.asarray(world.cell_map).tobytes()),
        ("lt", np.asarray(world.cell_lifetimes).tobytes()),
        ("div", np.asarray(world.cell_divisions).tobytes()),
        ("gen", "\x00".join(world.cell_genomes).encode()),
    ):
        h.update(tag.encode())
        h.update(part)
    return h.hexdigest()


def structural_digest(world) -> str:
    """sha256 over the jax-independent STRUCTURAL state only — cell
    count, positions, occupancy map, lifetime/division counters, and
    genomes.  Float tensors (molecule map, concentrations) are
    excluded: XLA codegen details may legitimately move float bits
    across jax versions and cache states, while the structure the
    seeded schedule produces must never change — that is the contract
    the committed golden-trajectory files under
    ``tests/fast/data/golden/`` pin."""
    n = int(world.n_cells)
    h = hashlib.sha256()
    for tag, part in (
        ("n", np.int64(n).tobytes()),
        ("pos", np.asarray(world.cell_positions).tobytes()),
        ("map", np.asarray(world.cell_map).tobytes()),
        ("lt", np.asarray(world.cell_lifetimes).tobytes()),
        ("div", np.asarray(world.cell_divisions).tobytes()),
        ("gen", "\x00".join(world.cell_genomes).encode()),
    ):
        h.update(tag.encode())
        h.update(part)
    return h.hexdigest()


def _chem_phase(world, n_steps: int, path: str) -> None:
    """Advance ``n_steps`` chemistry steps through the path's driver.

    The stepper paths build a fresh chem-only stepper (selection
    disabled: the schedule owns all structural ops) and flush it, so
    the world is the source of truth again at the boundary."""
    base = path[len("token_"):] if path.startswith("token_") else path
    if base == "classic":
        world.step_many(n_steps)
        return
    import magicsoup_tpu as ms

    # pallas_k1 rides the K=1 stepper branch; the backend itself came in
    # with the world (World(integrator="pallas") at construction)
    k = 4 if base in ("k4", "fleet4", "fleet3", "fused_fleet") else 1
    kwargs = dict(
        mol_name="dfx-atp",
        kill_below=-1.0,
        divide_above=1e30,
        divide_cost=0.0,
        target_cells=None,
        genome_size=200,
        lag=1,
        megastep=k,
        p_mutation=0.0,
        p_recombination=0.0,
    )
    assert n_steps % k == 0
    if path in FLEET_PATHS or path == "token_fleet3" or base in FUSED_PATHS:
        # B=1 fleet: same world, same kwargs, driven through the
        # scheduler's stacked program — digests must not move a bit.
        # token_fleet3 admits two companion token worlds alongside, so
        # the schedule world steps from slot 0 of a B=3 group.  The
        # fused paths admit companions on a DIFFERENT capacity rung
        # (double map size) so the schedule world steps inside a
        # cross-rung fused dispatch.
        from magicsoup_tpu.fleet import FleetScheduler

        if base in FUSED_PATHS:
            fleet = FleetScheduler(
                block=2,
                fusion="fleet" if base == "fused2" else "auto",
            )
        else:
            fleet = FleetScheduler(block=4 if path == "token_fleet3" else 1)
        lane = fleet.admit(world, **kwargs)
        companions = []
        if path == "token_fleet3":
            for j in range(2):
                cw = ms.World(
                    chemistry=world.chemistry,
                    map_size=world.map_size,
                    seed=1000 + j,
                    genome_backend="token",
                )
                cw.deterministic = True
                crng = random.Random(500 + j)
                cw.spawn_cells(
                    [ms.random_genome(s=200, rng=crng) for _ in range(4)]
                )
                companions.append(fleet.admit(cw, **kwargs))
        elif base in FUSED_PATHS:
            for j in range(1 if base == "fused2" else 2):
                cw = ms.World(
                    chemistry=world.chemistry,
                    map_size=world.map_size * 2,
                    seed=1500 + j,
                )
                cw.deterministic = True
                crng = random.Random(700 + j)
                cw.spawn_cells(
                    [ms.random_genome(s=200, rng=crng) for _ in range(4)]
                )
                companions.append(fleet.admit(cw, **kwargs))
        for _ in range(n_steps // k):
            fleet.step()
        fleet.flush()
        for c in companions:
            fleet.retire(c)
        fleet.retire(lane)
        return
    st = ms.PipelinedStepper(world, **kwargs)
    for _ in range(n_steps // k):
        st.step()
    st.flush()


def run_path(
    path: str,
    *,
    seed: int = 11,
    map_size: int = 16,
    n_cells: int = 16,
    digest_fn=None,
) -> list[str]:
    """Drive the seeded schedule through one execution path; returns the
    per-boundary digests (same length for every path).  ``digest_fn``
    defaults to the full :func:`state_digest`; the golden-trajectory
    regression passes :func:`structural_digest` instead."""
    import magicsoup_tpu as ms

    known = PATHS + FLEET_PATHS + FUSED_PATHS + TOKEN_PATHS + PALLAS_PATHS
    if path not in known:
        raise ValueError(
            f"unknown path {path!r} (want one of {known})"
        )
    if digest_fn is None:
        digest_fn = state_digest
    backend = "string"
    base = path
    if path.startswith("token_"):
        backend = "token"
        base = path[len("token_"):]
    mesh = None
    if base == "mesh2":
        from magicsoup_tpu.parallel import tiled

        mesh = tiled.make_mesh(2)
    del base  # _chem_phase re-derives it from the full path name
    world = ms.World(
        chemistry=_chemistry(),
        map_size=map_size,
        seed=seed,
        mesh=mesh,
        genome_backend=backend,
        integrator="pallas" if path in PALLAS_PATHS else None,
    )
    if path not in PALLAS_PATHS:
        # pallas is fast-mode only (no bit-reproducible variant); its
        # axis gates on the committed golden STRUCTURAL digest instead
        world.deterministic = True
    digests: list[str] = []

    # op 0: seeded spawn
    _reseed(world, seed, 0)
    rng = random.Random(seed)
    world.spawn_cells(
        [ms.random_genome(s=200, rng=rng) for _ in range(n_cells)]
    )
    digests.append(digest_fn(world))

    # chem phase A
    _chem_phase(world, PHASES[0], path)
    digests.append(digest_fn(world))

    # op 1: seeded point mutations (explicitly seeded stream)
    _reseed(world, seed, 1)
    mutated = ms.point_mutations(
        list(world.cell_genomes), p=1e-3, seed=seed
    )
    world.update_cells(mutated)
    digests.append(digest_fn(world))

    # chem phase B
    _chem_phase(world, PHASES[1], path)
    digests.append(digest_fn(world))

    # op 2: seeded kill (compacts surviving rows down)
    _reseed(world, seed, 2)
    pick = random.Random(seed + 1)
    idxs = sorted(pick.sample(range(world.n_cells), world.n_cells // 4))
    world.kill_cells(idxs)
    digests.append(digest_fn(world))

    # op 3: seeded divisions
    _reseed(world, seed, 3)
    idxs = sorted(pick.sample(range(world.n_cells), world.n_cells // 3))
    world.divide_cells(idxs)
    digests.append(digest_fn(world))

    # chem phase C
    _chem_phase(world, PHASES[2], path)
    digests.append(digest_fn(world))
    return digests


def run_differential(
    paths=PATHS, *, seed: int = 11, map_size: int = 16, n_cells: int = 16
) -> dict:
    """Run the schedule through every path and compare digests.

    Returns ``{"ok": bool, "digests": {path: [...]}, "mismatches":
    [{"boundary": i, "path": p, "want": d0, "got": d}, ...]}`` with the
    first listed path as the reference.  Caller decides whether to gate
    (the smoke exits nonzero on ``ok == False``).
    """
    digests = {
        p: run_path(p, seed=seed, map_size=map_size, n_cells=n_cells)
        for p in paths
    }
    ref_path = paths[0]
    ref = digests[ref_path]
    mismatches = []
    for p in paths[1:]:
        for i, (want, got) in enumerate(zip(ref, digests[p])):
            if want != got:
                mismatches.append(
                    {
                        "boundary": i,
                        "boundary_name": BOUNDARIES[i],
                        "path": p,
                        "reference": ref_path,
                        "want": want,
                        "got": got,
                    }
                )
    return {
        "ok": not mismatches,
        "digests": digests,
        "mismatches": mismatches,
    }
