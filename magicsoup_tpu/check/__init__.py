"""graftcheck: semantic correctness checking for simulation state.

Three tiers, ordered by cost:

- **Tier A — device invariant lanes** (:mod:`~magicsoup_tpu.check.invariants`):
  per-step invariant flags computed unconditionally inside the fused
  step program and packed into the same one-fetch record as the
  telemetry and sentinel lanes (occupancy/alive agreement, duplicate
  positions, dead-row residue, closed-system mass drift).  The stepper
  routes trips through its ``sentinel_policy``.
- **Tier B — host deep audit** (:func:`~magicsoup_tpu.check.audit.audit_world`):
  fetches the device state once and runs the full semantic suite plus a
  sampled genome→proteome re-translation cross-check against the
  assembled kinetics params, returning typed
  :class:`~magicsoup_tpu.check.audit.InvariantViolation` reports.
  ``guard.restore_run(..., audit=True)`` runs it after every restore.
- **Tier C — differential harness**
  (:mod:`~magicsoup_tpu.check.differential`): one seeded
  spawn/step/mutate/kill/divide/compact schedule driven through the
  classic World driver, the pipelined stepper at K=1 and K=4, and a
  2-tile mesh, comparing det-mode per-boundary state digests
  (``performance/smoke.py --differential`` gates on it).

This package is numpy/stdlib-only at import time (like ``guard``):
importing it never initialises the XLA backend.  The differential
runner imports jax lazily inside its entry points.
"""
from magicsoup_tpu.check.audit import (
    AuditFailed,
    InvariantViolation,
    assert_consistent,
    audit_world,
)
from magicsoup_tpu.check.invariants import (
    INVARIANT_NAMES,
    MASS_DRIFT_RTOL,
    decode_invariants,
)

__all__ = [
    "INVARIANT_NAMES",
    "MASS_DRIFT_RTOL",
    "AuditFailed",
    "InvariantViolation",
    "assert_consistent",
    "audit_world",
    "decode_invariants",
]
