"""
Device-resident pipelined step driver: the whole selection workload step —
activity, threshold selection, kill, divide (with on-device child
placement), spawn, degradation/diffusion/permeation — runs as ONE fused
device program per step, and the host processes each step's small output
record (selection masks, child/spawn placements) asynchronously, a few
steps behind the device.

Why: the classic :class:`magicsoup_tpu.world.World` loop fetches a
selection column every step and decides kill/divide on the host, so one
device->host round trip sits on every step's critical path — on a remote
accelerator that RTT bounds steps/s at 1/RTT no matter how fast the
kernels get, and even co-located it serializes host bookkeeping with
device compute.  Here the device never waits for the host: selection is
evaluated on device, placement is resolved on device, and the host's
genome bookkeeping (string mutation, recombination, translation) runs
concurrently on a replay of the trajectory, pushing refreshed kinetic
parameters back a few steps later.

The reference (mRcSchwering/magic-soup) has no counterpart — its loop is
strictly serial (`performance/run_simulation.py:61-100`).  This is the
TPU-native design SURVEY.md §7 asks for, generalized to the outer loop.

Semantics vs the serial loop (all deltas are documented, bounded, and
seed-reproducible at a fixed ``lag``):

- **Phenotype lag.** Mutations and recombinations are drawn from the
  replayed state of step ``t`` and their re-translated parameters reach
  the device a few steps later (the pipeline depth, typically 2-6).  The
  genome history itself is exact and serial — only the genotype ->
  phenotype refresh trails, as in asynchronous evolution.
- **Spawn-decision lag.**  Population top-up (``target_cells``) reacts to
  the replayed population count, so it also trails by the pipeline depth.
- **Slot (not compacted) indices between flushes.**  Killed rows stay in
  place as dead slots until a compaction step folds them out; cell
  indices visible to the host replay are therefore row ids, not the
  reference's densely-compacted indices.  :meth:`flush` compacts and
  syncs everything back into the attached :class:`World`, restoring the
  reference's dense-index view.
- **Bounded placement.**  Child/spawn placement resolves conflicts in
  ``n_rounds`` vectorized rounds (lowest row wins, like the host path);
  a candidate still conflicted after the last round does not divide that
  step.  Divisions are also bounded per step (``max_divisions``) and by
  remaining slot capacity; drops are counted in :attr:`stats`.

Determinism: with ``lag`` set to an integer the dispatch/replay schedule
is fixed, so a given seed reproduces the trajectory exactly;
``lag="auto"`` adapts to measured readiness (faster, not reproducible).
"""
import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as _P

from magicsoup_tpu.analysis import runtime as _runtime
from magicsoup_tpu.analysis.ownership import owned_by
from magicsoup_tpu.guard import chaos as _chaos
from magicsoup_tpu.native import engine as _engine
from magicsoup_tpu.ops import backends as _backends
from magicsoup_tpu.ops import detmath as _detmath
from magicsoup_tpu.ops import diffusion as _diff
from magicsoup_tpu.ops.integrate import CellParams
from magicsoup_tpu.ops.params import (
    compact_rows,
    compute_cell_params,
    constrain_rows,
    copy_params,
    next_rung,
    permute_params,
    quantize_rows,
    scatter_params,
)
from magicsoup_tpu.util import (
    WarmScheduler,
    fetch_host as _fetch_host,
    moore_pairs,
    random_genome,
    randstr,
    register_exit_join as _register_exit_join,
)

# graftguard sentinel tolerance (host policy + device lanes must agree
# on what counts as "negative"); the guard package is numpy/stdlib-only
# at import time, so this does not pull jax machinery in twice
from magicsoup_tpu.guard.sentinel import NEG_EPS as _SENTINEL_NEG_EPS

# graftcheck invariant-lane contract (bit layout + mass-drift tolerance
# shared between the device lanes and the host policy); numpy/stdlib-only
# at import time, same as the guard package
from magicsoup_tpu.check.invariants import (
    MASS_DRIFT_RTOL as _MASS_DRIFT_RTOL,
)

# numpy on purpose: a module-level jnp array would initialise the XLA
# backend at import time, which breaks jax.distributed.initialize() in
# multi-host programs importing this package
_MOORE_DX = np.asarray([-1, -1, -1, 0, 0, 1, 1, 1], dtype=np.int32)
_MOORE_DY = np.asarray([-1, 0, 1, -1, 1, -1, 0, 1], dtype=np.int32)


class StepOutputs(NamedTuple):
    """The per-step device->host record, as host numpy after unpacking.

    On device the whole record is PACKED into one i32 vector
    (:func:`_pack_bits` + concatenate) so the replay costs exactly ONE
    device->host transfer — on a remote accelerator each separate fetch
    is a full tunnel round trip (~60-100 ms), and the round-2 layout
    (eight arrays) put ~8 RTTs on every replayed step."""

    kill: Any  # (cap,) bool — rows killed this step
    parents: Any  # (max_div,) i32 rows that divided (cap = none)
    child_pos: Any  # (max_div, 2) i32 child pixels
    n_placed: int  # number of successful divisions
    n_candidates: int  # division candidates before the budget clamp
    n_attempted: int  # candidates after the budget clamp (cost payers)
    spawn_ok: Any  # (b_spawn,) bool — which queued spawns landed
    spawn_pos: Any  # (b_spawn, 2) i32 spawn pixels
    n_rows: int  # high-water row count after the step
    n_alive: int  # live cells after the step
    # telemetry lanes (graftscope): computed on device every step so the
    # recorder's per-step rows cost zero extra transfers
    n_occupied: int  # occupied map pixels after the step
    mm_mass: float  # total molecule mass on the map (pre-compaction sum)
    cm_mass: float  # total intracellular molecule mass
    # mesh-placed runs only: occupied pixels per map-row tile (n_tiles,)
    # i32 — the load-balance lane riding the same packed record; None on
    # single-device runs (the record layout is unchanged there)
    tile_occupancy: Any = None
    # graftguard health lanes: computed UNCONDITIONALLY like the metric
    # lanes (device program identical guard-on vs guard-off, zero extra
    # D2H) — the flag word per guard.sentinel's bit layout, and the
    # per-row bad-concentration bitmask behind it
    health: int = 0
    bad_cells: Any = None
    # graftcheck invariant lanes (same unconditional contract): the flag
    # word per check.invariants' bit layout and the measured absolute
    # mass drift across the physics phase
    invariants: int = 0
    mass_drift: float = 0.0


_BITS = 16  # bits packed per i32 word (16 keeps every value positive)
# leading scalar words of the packed record: [n_placed, n_candidates,
# n_attempted, n_rows, n_alive, n_occupied, mm_mass(f32 bits),
# cm_mass(f32 bits), health_flags, invariant_flags,
# mass_drift(f32 bits)] — _step_body's pack and _unpack_outputs must
# agree (tests/fast/test_bench_parsing.py pins the record-length
# formula)
_HEADER_WORDS = 11
# flag-word positions inside that header — the graftguard health
# sentinel word and the graftcheck invariant word
HEALTH_WORD = 8
INVARIANT_WORD = 9


def record_flag_views(records) -> tuple[np.ndarray, np.ndarray]:
    """Zero-copy ``(health, invariants)`` flag-word views of a packed
    step-record buffer of ANY leading shape: ``(record,)`` for one
    step, ``(k, record)`` for a megastep fetch, ``(B, k, record)`` for
    a fleet group's shared fetch — index ``[slot]`` on the views for a
    single world's flags WITHOUT another D2H transfer (the fleet
    warden's per-slot consumption path)."""
    arr = np.asarray(records)
    return arr[..., HEALTH_WORD], arr[..., INVARIANT_WORD]


def _pack_bits(b: jax.Array) -> jax.Array:
    """(n,) bool -> ceil(n/16) i32 words (little-endian bit order)."""
    n = b.shape[0]
    pad = (-n) % _BITS
    if pad:
        b = jnp.concatenate([b, jnp.zeros(pad, dtype=bool)])
    w = b.reshape(-1, _BITS).astype(jnp.int32)
    return jnp.sum(w << jnp.arange(_BITS, dtype=jnp.int32)[None, :], axis=1)


def _unpack_bits(words: np.ndarray, n: int) -> np.ndarray:
    """Inverse of :func:`_pack_bits` on host numpy."""
    bits = (words.astype(np.int64)[:, None] >> np.arange(_BITS)) & 1
    return bits.reshape(-1)[:n].astype(bool)


def record_length(
    cap: int, max_divisions: int, spawn_block: int, n_tiles: int = 1
) -> int:
    """Words in a packed step record for a given stepper config — THE
    layout formula (pinned by tests/fast/test_bench_parsing.py; the
    device-side pack in ``_step_body`` and the host-side
    ``_unpack_outputs`` must both agree with it).  Mesh runs
    (``n_tiles > 1``) append one per-tile occupancy word at the tail;
    single-device records carry no tail."""
    nw_k = -(-cap // _BITS)  # kill / bad-cell bitmask words
    nw_s = -(-spawn_block // _BITS)  # spawn-ok bitmask words
    return (
        _HEADER_WORDS
        + nw_k  # kill bitmask
        + max_divisions  # division parent rows
        + 2 * max_divisions  # division child positions
        + nw_s  # spawn-ok bitmask
        + 2 * spawn_block  # spawn positions
        + nw_k  # bad-cell bitmask (graftguard)
        + (n_tiles if n_tiles > 1 else 0)  # mesh tile occupancy tail
    )


def crop_fused_record(records, k: int, length: int) -> np.ndarray:
    """A lane's NATIVE ``(k, record)`` view out of one world-row of a
    cross-rung fused fetch buffer.

    The fused dispatch pads every rung's ``(B_r, k_r, L_r)`` records to
    the fleet-wide grow-only ``(k_env, rec_env)`` envelope so the whole
    fleet comes back in one physical fetch; the envelope lives ONLY in
    that buffer — ``_unpack_outputs`` still asserts the exact
    :func:`record_length` of the lane's own config, so the record-length
    contract is enforced at native shapes on every replay.  ``records``
    is one world's ``(k_env, rec_env)`` slice, ``k`` its megastep and
    ``length`` its native record length; both must fit the envelope."""
    arr = np.asarray(records)
    if arr.shape[0] < k or arr.shape[1] < length:
        raise ValueError(
            f"fused record envelope {arr.shape} cannot hold a native "
            f"({k}, {length}) megastep record — the grow-only envelope "
            "contract was violated"
        )
    return arr[:k, :length]


class DeviceState(NamedTuple):
    """All device-resident simulation state threaded step to step."""

    mm: jax.Array  # (mols, m, m) molecule map
    cm: jax.Array  # (cap, mols) intracellular molecules
    pos: jax.Array  # (cap, 2) i32 positions
    occ: jax.Array  # (m, m) bool pixel occupancy
    alive: jax.Array  # (cap,) bool
    n_rows: jax.Array  # i32 high-water row count (rows >= n_rows unused)
    key: jax.Array  # PRNG key for on-device placement draws


def _resolve_conflicts(
    want: jax.Array, tx: jax.Array, ty: jax.Array, m: int
) -> jax.Array:
    """Among concurrent requests for target pixels, the lowest slot wins
    (mirrors the host path's sorted sequential semantics,
    world.py:_place_in_neighborhood)."""
    n = want.shape[0]
    slots = jnp.arange(n, dtype=jnp.int32)
    target = tx * m + ty
    winner = jnp.full((m * m,), n, dtype=jnp.int32)
    winner = winner.at[jnp.where(want, target, m * m)].min(
        jnp.where(want, slots, n), mode="drop"
    )
    return want & (winner[target] == slots)


def _occupy(occ: jax.Array, win: jax.Array, tx: jax.Array, ty: jax.Array):
    m = occ.shape[0]
    return occ.at[
        jnp.where(win, tx, m), jnp.where(win, ty, m)
    ].set(True, mode="drop")


def _place_moore(
    key: jax.Array,
    occ: jax.Array,
    pos: jax.Array,
    cand: jax.Array,
    n_rounds: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Place one free Moore-neighborhood pixel per candidate row, no two
    on the same pixel (reference rust/world.rs:59-97; host counterpart
    world.py:_place_in_neighborhood).  Returns (placed, child_pos, occ)."""
    cap = cand.shape[0]
    m = occ.shape[0]
    placed = jnp.zeros_like(cand)
    cpos = jnp.zeros_like(pos)
    rows = jnp.arange(cap, dtype=jnp.int32)

    def body(_, carry):
        key, occ, placed, cpos = carry
        key, sub = jax.random.split(key)
        pending = cand & ~placed
        nx = (pos[:, 0:1] + _MOORE_DX[None, :]) % m  # (cap, 8)
        ny = (pos[:, 1:2] + _MOORE_DY[None, :]) % m
        free = ~occ[nx, ny] & pending[:, None]
        n_free = free.sum(axis=1)
        r = (jax.random.uniform(sub, (cap,)) * n_free).astype(jnp.int32)
        opt_rank = jnp.cumsum(free, axis=1) - 1
        sel = jnp.argmax(free & (opt_rank == r[:, None]), axis=1)
        tx = nx[rows, sel]
        ty = ny[rows, sel]
        want = pending & (n_free > 0)
        win = _resolve_conflicts(want, tx, ty, m)
        occ = _occupy(occ, win, tx, ty)
        cpos = jnp.where(
            win[:, None], jnp.stack([tx, ty], axis=1), cpos
        )
        return key, occ, placed | win, cpos

    _, occ, placed, cpos = jax.lax.fori_loop(
        0, n_rounds, body, (key, occ, placed, cpos)
    )
    return placed, cpos, occ


def _place_global(
    key: jax.Array, occ: jax.Array, valid: jax.Array, n_rounds: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Place each valid slot on a uniformly random FREE pixel (rejection
    sampling over the whole torus — the conditional distribution over
    free pixels is uniform, like the host spawn path).  Slots still
    conflicted after the last round are dropped (host retries later)."""
    b = valid.shape[0]
    m = occ.shape[0]
    placed = jnp.zeros_like(valid)
    spos = jnp.zeros((b, 2), dtype=jnp.int32)

    def body(_, carry):
        key, occ, placed, spos = carry
        key, sub = jax.random.split(key)
        xy = jax.random.randint(sub, (b, 2), 0, m, dtype=jnp.int32)
        tx, ty = xy[:, 0], xy[:, 1]
        want = valid & ~placed & ~occ[tx, ty]
        win = _resolve_conflicts(want, tx, ty, m)
        occ = _occupy(occ, win, tx, ty)
        spos = jnp.where(win[:, None], xy, spos)
        return key, occ, placed | win, spos

    _, occ, placed, spos = jax.lax.fori_loop(
        0, n_rounds, body, (key, occ, placed, spos)
    )
    return placed, spos, occ


def _step_body(
    state: DeviceState,
    params: CellParams,
    kernels: jax.Array,
    perm_factors: jax.Array,
    degrad_factors: jax.Array,
    mol_idx: jax.Array,  # i32 — selection molecule column
    kill_below: jax.Array,
    divide_above: jax.Array,
    divide_cost: jax.Array,
    div_budget: jax.Array,  # i32 — host-chosen division cap this step
    spawn_dense: jax.Array,  # (b_spawn, p, d, 5) i16; all-zero rows inert
    spawn_valid: jax.Array,  # (b_spawn,) bool; all-False = no spawns
    push_dense: jax.Array,  # (b_push, p, d, 5) i16; all-zero rows inert
    push_rows: jax.Array,  # (b_push,) i32; OOB rows are dropped
    tables: Any,  # TokenTables
    abs_temp: jax.Array,
    *,
    det: bool,
    max_div: int,
    n_rounds: int,
    compact: bool,
    q: int | None = None,
    integrator: str = "xla-fast",
    mesh=None,
) -> tuple[DeviceState, CellParams, jax.Array]:
    """One fused workload step (spawn -> activity -> select -> kill ->
    divide -> degrade/diffuse/permeate [-> compact]) — a single dispatch,
    no host round trip.  Traced both standalone (:func:`_pipeline_step`)
    and as the :func:`_megastep` scan body.

    ``q`` (static) bounds the live-row prefix: the integrator reads only
    the first q rows of the big parameter tensors (dead-slot tax), and
    spawn/divide allocation is clamped so ``n_rows`` never exceeds q —
    the host raises q as the population grows.

    Spawn and push batches are ALWAYS present at their fixed block shapes
    (cached all-zero/all-OOB device buffers stand in on steps without
    them) so neither forks an extra compiled variant of this program —
    on a remote-compile platform every variant is seconds of stall the
    first time it appears (ops/params.py IDX_BLOCK has the same
    rationale).  The compiled-variant axes are exactly ``q`` (bounded
    ladder, prewarmed one rung ahead) and ``compact``.

    ``mesh`` (static, hashable) runs the whole program SPMD over a 1D
    device mesh: the molecule map stays row-sharded (diffusion routes
    through tiled.py's ppermute halo exchange), cell state and all nine
    CellParams tensors stay cell-sharded, and the packed output record
    is constrained REPLICATED so the host replay still costs exactly one
    fetch.  The body's math is sharding-agnostic — GSPMD inserts the
    cell<->map exchange collectives — and the trailing constraints pin
    the state shardings so the scan carry / dispatch loop never drifts
    placements between steps.  Mesh runs add ``n_tiles`` per-tile
    occupancy lanes to the record tail (single-device layout unchanged).
    In det mode every cross-row reduction is either integer-exact, a
    detmath fixed tree, or the halo stencil's replicated-tree fixup, so
    the sharded trajectory is bit-identical to the single-device one
    (pinned by test_parallel.py)."""
    mm, cm, pos, occ, alive, n_rows, key = state
    cap, n_mols = cm.shape
    if q is None or q > cap:
        q = cap
    # sharding pins for the mesh route (None mesh = all no-ops): state
    # leaves keep the placement the world chose (map by rows, cells by
    # slots), everything host-visible is replicated
    if mesh is not None:
        _axis = mesh.axis_names[0]
        _map_sh = NamedSharding(mesh, _P(None, _axis, None))
        _cell_sh = NamedSharding(mesh, _P(_axis))
        _rep_sh = NamedSharding(mesh, _P())
    else:
        _map_sh = _cell_sh = _rep_sh = None

    def _pin(x, sh):
        return x if sh is None else jax.lax.with_sharding_constraint(x, sh)
    m = occ.shape[0]
    rows = jnp.arange(cap, dtype=jnp.int32)
    key, k_spawn, k_div = jax.random.split(key, 3)
    mol_onehot = (jnp.arange(n_mols, dtype=jnp.int32) == mol_idx).astype(
        jnp.float32
    )

    # jax.named_scope on every phase: pure metadata (op name prefixes),
    # zero effect on lowering/results, but a jax.profiler trace captured
    # via telemetry.trace_window resolves XLA ops to simulation phases
    # ---- -1. parameter pushes riding this dispatch ---------------------
    # the phenotype refresh for genomes changed in recent replays — rides
    # the step program instead of paying its own dispatch round trip;
    # rows whose proteome emptied carry all-zero token rows (their
    # computed params are inert)
    with jax.named_scope("ms:push_params"):
        params = scatter_params(
            params,
            compute_cell_params(push_dense, tables, abs_temp),
            push_rows,
        )

    # ---- 0. spawn queued newcomers ------------------------------------
    with jax.named_scope("ms:spawn"):
        budget = q - n_rows
        valid = spawn_valid & ((jnp.cumsum(spawn_valid) - 1) < budget)
        spawn_ok, spawn_pos, occ = _place_global(
            k_spawn, occ, valid, n_rounds
        )
        srank = jnp.cumsum(spawn_ok) - 1
        srow = jnp.where(spawn_ok, n_rows + srank, cap).astype(jnp.int32)
        sx, sy = spawn_pos[:, 0], spawn_pos[:, 1]
        pickup = mm[:, sx, sy] * 0.5 * spawn_ok[None, :]  # (mols, b)
        mm = mm.at[:, sx, sy].add(-pickup)
        cm = cm.at[srow].set(pickup.T, mode="drop")
        pos = pos.at[srow].set(spawn_pos, mode="drop")
        alive = alive.at[srow].set(True, mode="drop")
        params = scatter_params(
            params, compute_cell_params(spawn_dense, tables, abs_temp), srow
        )
        n_rows = n_rows + spawn_ok.sum(dtype=jnp.int32)

    # ---- 1. enzymatic activity (live-row prefix only) ------------------
    with jax.named_scope("ms:activity"):
        xs_q, ys_q = pos[:q, 0], pos[:q, 1]
        ext = mm[:, xs_q, ys_q].T  # (q, mols)
        params_q = jax.tree_util.tree_map(lambda t: t[:q], params)
        X0q = jnp.concatenate([cm[:q], ext], axis=1)
        # registry-routed integrator dispatch (GL026: the backend name
        # static is the ONLY selection axis; no ad-hoc kernel branching)
        X1 = _backends.integrate(integrator, X0q, params_q)
        alive_q = alive[:q, None]
        cm = jax.lax.dynamic_update_slice_in_dim(
            cm, jnp.where(alive_q, X1[:, :n_mols], cm[:q]), 0, axis=0
        )
        mm = mm.at[:, xs_q, ys_q].add(
            jnp.where(alive_q, X1[:, n_mols:] - ext, 0.0).T
        )

    # ---- 2. selection + kill ------------------------------------------
    with jax.named_scope("ms:select_kill"):
        xs, ys = pos[:, 0], pos[:, 1]
        atp = jnp.einsum("cm,m->c", cm, mol_onehot)
        kill = alive & (atp < kill_below)
        spill = jnp.where(kill[:, None], cm, 0.0)
        mm = mm.at[:, xs, ys].add(spill.T)
        cm = jnp.where(kill[:, None], 0.0, cm)
        occ = occ.at[
            jnp.where(kill, xs, m), jnp.where(kill, ys, m)
        ].set(False, mode="drop")
        alive = alive & ~kill

    # ---- 3. divide -----------------------------------------------------
    with jax.named_scope("ms:divide"):
        cand = alive & (atp > divide_above)
        n_candidates = cand.sum(dtype=jnp.int32)
        budget = jnp.minimum(jnp.minimum(max_div, div_budget), q - n_rows)
        cand = cand & ((jnp.cumsum(cand) - 1) < budget)
        n_attempted = cand.sum(dtype=jnp.int32)
        # every attempting candidate pays the division cost, whether or
        # not a free pixel is found — exactly the canonical workload's
        # order (performance/workload.py:69-75 subtracts before
        # divide_cells)
        cm = cm - (jnp.where(cand, divide_cost, 0.0)[:, None] * mol_onehot)
        placed, cpos, occ = _place_moore(k_div, occ, pos, cand, n_rounds)
        crank = jnp.cumsum(placed) - 1
        crow = jnp.where(placed, n_rows + crank, cap).astype(jnp.int32)
        half = jnp.where(placed[:, None], cm * 0.5, cm)
        cm = half.at[crow].add(
            jnp.where(placed[:, None], half, 0.0), mode="drop"
        )
        pos = pos.at[crow].set(cpos, mode="drop")
        alive = alive.at[crow].set(True, mode="drop")
        p_idx = jnp.nonzero(placed, size=max_div, fill_value=cap)[0].astype(
            jnp.int32
        )
        c_idx = jnp.where(
            p_idx < cap, n_rows + jnp.arange(max_div, dtype=jnp.int32), cap
        )
        params = copy_params(params, p_idx, c_idx)
        n_placed = placed.sum(dtype=jnp.int32)
        n_rows = n_rows + n_placed

    # ---- 4. degrade + diffuse + permeate ------------------------------
    with jax.named_scope("ms:physics"):
        mm = mm * degrad_factors[:, None, None]
        cm = cm * degrad_factors[None, :]
        # graftcheck mass anchor: diffusion (normalized torus kernel)
        # and permeation (cell<->pixel exchange) are closed-system, so
        # the total mass right after degradation is what the post-step
        # metric sums must reproduce.  Same reduction as ms:metrics so
        # det mode compares fixed trees against fixed trees.
        if det:
            mass_pre = _detmath.sum_axis(
                mm.reshape(-1), 0
            ) + _detmath.sum_axis(cm.reshape(-1), 0)
        else:
            mass_pre = jnp.sum(mm) + jnp.sum(cm)
        mm = _diff.diffuse(mm, kernels, det=det, mesh=mesh)
        xs, ys = pos[:, 0], pos[:, 1]
        ext = mm[:, xs, ys].T
        new_cm, new_ext = _diff.permeate(cm, ext, perm_factors, det=det)
        alive_c = alive[:, None]
        cm = jnp.where(alive_c, new_cm, cm)
        mm = mm.at[:, xs, ys].add(jnp.where(alive_c, new_ext - ext, 0.0).T)

    # ---- 4.5 telemetry metric lanes -----------------------------------
    # computed UNCONDITIONALLY (the compiled program is identical whether
    # a recorder is attached or not, so det-mode trajectories cannot
    # differ telemetry on vs off) and BEFORE compaction (the det-mode
    # fixed-tree reduction must not see a permuted row order).  Dead rows
    # hold zeros by invariant (kill zeroes cm, compaction folds), so the
    # full-cap sums are the true mass totals.
    with jax.named_scope("ms:metrics"):
        if det:
            mm_mass = _detmath.sum_axis(mm.reshape(-1), 0)
            cm_mass = _detmath.sum_axis(cm.reshape(-1), 0)
        else:
            mm_mass = jnp.sum(mm)
            cm_mass = jnp.sum(cm)
        n_occupied = occ.sum(dtype=jnp.int32)
        if mesh is not None:
            # per-tile occupancy: one i32 lane per map-row tile (the
            # row-block split matches tiled.map_sharding), riding the
            # packed record so load-balance telemetry costs zero extra
            # transfers.  Integer sum — exact under any partitioning.
            n_tiles = mesh.shape[mesh.axis_names[0]]
            tile_occ = (
                occ.reshape(n_tiles, -1).sum(axis=1).astype(jnp.int32)
            )
        else:
            tile_occ = None

    # ---- 4.6 graftguard health sentinel lanes -------------------------
    # same contract as the metric lanes: unconditional (the compiled
    # program is byte-identical whatever the host-side sentinel policy
    # is), det-safe (boolean AND/OR reductions are exact in any order),
    # and BEFORE compaction so the bad-cell bitmask uses the same row
    # space as the kill lane.  The negative check tolerates the fp
    # epsilon the clipped integrator can transiently dip below zero.
    with jax.named_scope("ms:sentinel"):
        mm_nonfin = ~jnp.isfinite(mm).all()
        mm_neg = (mm < -_SENTINEL_NEG_EPS).any()
        alive_rows = alive[:, None]
        cm_nonfin_rows = (~jnp.isfinite(cm) & alive_rows).any(axis=1)
        cm_neg_rows = ((cm < -_SENTINEL_NEG_EPS) & alive_rows).any(axis=1)
        bad_cells = cm_nonfin_rows | cm_neg_rows
        health = (
            mm_nonfin.astype(jnp.int32)
            | (mm_neg.astype(jnp.int32) << 1)
            | (cm_nonfin_rows.any().astype(jnp.int32) << 2)
            | (cm_neg_rows.any().astype(jnp.int32) << 3)
        )

    # ---- 4.7 graftcheck invariant lanes -------------------------------
    # semantic state invariants, same contract again: unconditional (the
    # program is byte-identical whether a policy consumes them), zero
    # extra D2H (two more header words in the packed record), and BEFORE
    # compaction so row indices match the kill/bad-cell lanes.  Every
    # reduction is integer/boolean (exact in any order) except the mass
    # comparison, which is an f32 sub/abs/compare of detmath tree sums —
    # det-safe on both backends (see ops/detmath.py).
    with jax.named_scope("ms:invariants"):
        # occupied pixels vs live rows: each live cell owns exactly one
        # pixel, so any desync (lost kill, phantom occupancy) breaks the
        # count equality
        occ_alive_mismatch = n_occupied != alive.sum(dtype=jnp.int32)
        # every live row's pixel must be marked occupied
        pos_unoccupied = (alive & ~occ[pos[:, 0], pos[:, 1]]).any()
        # duplicate live positions: integer scatter-add of per-pixel
        # counts (dead rows park at the dropped OOB slot)
        lin = jnp.where(alive, pos[:, 0] * m + pos[:, 1], m * m)
        pix_counts = jnp.zeros(m * m, dtype=jnp.int32).at[lin].add(
            1, mode="drop"
        )
        dup_position = (pix_counts > 1).any()
        # dead-row residue: rows at/beyond the high-water mark must be
        # exact zeros in cm and in every params leaf — kill zeroes, the
        # compaction fold zeroes, and scatter drops OOB, so any residue
        # means a write escaped the row accounting
        dead = rows >= n_rows
        dead_cm_residue = (dead[:, None] & (cm != 0.0)).any()
        row_has_params = jnp.zeros((cap,), dtype=bool)
        for leaf in jax.tree_util.tree_leaves(params):
            row_has_params = row_has_params | (
                (leaf != 0).reshape(cap, -1).any(axis=1)
            )
        dead_param_residue = (dead & row_has_params).any()
        # closed-system mass conservation across the physics phase,
        # relative to the post-degradation anchor (multiply feeding a
        # compare — no division on device)
        mass_post = mm_mass.astype(jnp.float32) + cm_mass.astype(
            jnp.float32
        )
        mass_drift = jnp.abs(mass_post - mass_pre.astype(jnp.float32))
        drift_denom = jnp.maximum(jnp.abs(mass_pre), jnp.float32(1.0))
        mass_drifted = mass_drift > jnp.float32(_MASS_DRIFT_RTOL) * (
            drift_denom
        )
        invariants = (
            occ_alive_mismatch.astype(jnp.int32)
            | (pos_unoccupied.astype(jnp.int32) << 1)
            | (dup_position.astype(jnp.int32) << 2)
            | (dead_cm_residue.astype(jnp.int32) << 3)
            | (dead_param_residue.astype(jnp.int32) << 4)
            | (mass_drifted.astype(jnp.int32) << 5)
        )

    # ---- 5. optional compaction ---------------------------------------
    child_pos_out = cpos[jnp.clip(p_idx, 0, cap - 1)]
    if compact:
        with jax.named_scope("ms:compact"):
            # stable sort of ~alive: live rows keep order, dead fold out.
            # np.argsort(~alive, kind="stable") on the host replay
            # produces the IDENTICAL permutation (stability makes it
            # unique), so the host needs no extra fetch to follow.
            perm = jnp.argsort(~alive, stable=True).astype(jnp.int32)
            n_keep = alive.sum(dtype=jnp.int32)
            cm = compact_rows(cm, perm, n_keep)
            pos = compact_rows(pos, perm, n_keep)
            params = permute_params(params, perm, n_keep)
            alive = rows < n_keep
            n_rows = n_keep

    # one packed i32 output vector = one device->host transfer per replay.
    # header words 5-7 are the telemetry lanes: occupied-pixel count and
    # the two f32 mass totals bitcast into i32 (the host re-views the
    # bits as float32 — exact, no rounding through a cast); word 8 is
    # the graftguard health flag word (per-row bad-cell bitmask as the
    # last pre-tail lane); words 9-10 are the graftcheck invariant flag
    # word and the f32 mass-drift measurement, bitcast the same way
    with jax.named_scope("ms:pack_record"):
        lanes = [
            jnp.stack(
                [
                    n_placed,
                    n_candidates,
                    n_attempted,
                    n_rows,
                    alive.sum(dtype=jnp.int32),
                    n_occupied,
                    jax.lax.bitcast_convert_type(
                        mm_mass.astype(jnp.float32), jnp.int32
                    ),
                    jax.lax.bitcast_convert_type(
                        cm_mass.astype(jnp.float32), jnp.int32
                    ),
                    health,
                    invariants,
                    jax.lax.bitcast_convert_type(
                        mass_drift.astype(jnp.float32), jnp.int32
                    ),
                ]
            ).astype(jnp.int32),
            _pack_bits(kill),
            p_idx,
            child_pos_out.reshape(-1).astype(jnp.int32),
            _pack_bits(spawn_ok),
            spawn_pos.reshape(-1).astype(jnp.int32),
            _pack_bits(bad_cells),
        ]
        if tile_occ is not None:
            # mesh lanes ride the TAIL so every single-device offset in
            # _unpack_outputs stays byte-for-byte unchanged
            lanes.append(tile_occ)
        out = jnp.concatenate(lanes)
    # mesh: pin the outgoing shardings.  The header scalars fold via
    # psum-style partial reductions, the kill/parent/spawn lanes are
    # assembled from cell-sharded pieces, and the replicated constraint
    # on `out` makes XLA all-gather them ONCE here — one small record
    # all-gather per step instead of a host-side multi-shard fetch.  The
    # state constraints keep the scan carry / dispatch loop on the same
    # placements every step (no inferred-sharding drift, no implicit
    # resharding at the next dispatch).
    new_state = DeviceState(
        mm=_pin(mm, _map_sh),
        cm=_pin(cm, _cell_sh),
        pos=_pin(pos, _cell_sh),
        occ=_pin(occ, _rep_sh),
        alive=_pin(alive, _cell_sh),
        n_rows=_pin(n_rows, _rep_sh),
        key=_pin(key, _rep_sh),
    )
    return new_state, constrain_rows(params, _cell_sh), _pin(out, _rep_sh)


# donate_argnums=(0, 1): the step consumes (state, params) and returns
# their successors, so XLA reuses the input HBM in place — without it
# steady-state holds TWO copies of every world tensor (the old and new
# molecule map alone are the largest allocations in the program)
_pipeline_step = functools.partial(
    jax.jit,
    static_argnames=(
        "det", "max_div", "n_rounds", "compact", "q", "integrator",
        "mesh",
    ),
    donate_argnums=(0, 1),
)(_step_body)

# CPU twin WITHOUT donation: jax 0.4.37's XLA:CPU runtime races donated-
# buffer reuse against its async execution on the compact step variant
# (the one where CPU buffer assignment honors EVERY state/params alias) —
# observed as nondeterministic occupancy corruption confined to map row 0
# in ~half of fresh processes, gone with donation disabled.  CPU donation
# buys nothing anyway (host RAM, and the big buffers are usually declined
# on the non-compact variants), so steps retain their inputs there;
# _donate_step_buffers() picks the variant per backend at stepper init.
_pipeline_step_retained = functools.partial(  # graftlint: disable=GL006 CPU twin of _pipeline_step; donation races XLA:CPU async execution
    jax.jit,
    static_argnames=(
        "det", "max_div", "n_rounds", "compact", "q", "integrator",
        "mesh",
    ),
)(_step_body)


def _donate_step_buffers() -> bool:
    """Whether the step programs may donate (state, params) on this
    backend — True everywhere except XLA:CPU (see the retained-twin
    comment above for the observed CPU corruption)."""
    return jax.default_backend() != "cpu"


@functools.partial(
    jax.jit,
    static_argnames=(
        "det", "max_div", "n_rounds", "compact", "q", "integrator", "k",
        "mesh",
    ),
    donate_argnums=(0, 1),
)
def _megastep(
    state: DeviceState,
    params: CellParams,
    kernels: jax.Array,
    perm_factors: jax.Array,
    degrad_factors: jax.Array,
    mol_idx: jax.Array,
    kill_below: jax.Array,
    divide_above: jax.Array,
    divide_cost: jax.Array,
    div_budget: jax.Array,
    spawn_dense: jax.Array,
    spawn_valid: jax.Array,
    push_dense: jax.Array,
    push_rows: jax.Array,
    tables: Any,
    abs_temp: jax.Array,
    *,
    det: bool,
    max_div: int,
    n_rounds: int,
    compact: bool,
    q: int | None = None,
    integrator: str = "xla-fast",
    k: int = 1,
    mesh=None,
) -> tuple[DeviceState, CellParams, jax.Array]:
    """``k`` fused pipeline steps in ONE dispatch: a ``lax.scan`` over
    :func:`_step_body`, per-step packed output records stacked into one
    ``(k, record)`` buffer the host replay unpacks row by row — dispatch
    count drops ``k``×, and XLA fuses across step boundaries.

    Semantics are EXACTLY ``k`` serial :func:`_pipeline_step` calls
    where the spawn/push batches ride step 0 and steps 1..k-1 run with
    the cached empty buffers (the only schedule the host dispatch path
    produces): inside the scan, steps after the first mask
    ``spawn_valid`` to all-False and ``push_rows`` to the OOB sentinel,
    which makes those phases bitwise no-ops (OOB scatters drop; pickup
    is zeroed by the all-False spawn mask).  ``compact`` (static)
    applies to the LAST step only, so the host's stable-argsort
    compaction replay stays a per-dispatch tail event."""

    def body(carry, first):
        state, params = carry
        state, params, out = _step_body(
            state,
            params,
            kernels,
            perm_factors,
            degrad_factors,
            mol_idx,
            kill_below,
            divide_above,
            divide_cost,
            div_budget,
            spawn_dense,
            spawn_valid & first,
            push_dense,
            jnp.where(first, push_rows, jnp.iinfo(jnp.int32).max),
            tables,
            abs_temp,
            det=det,
            max_div=max_div,
            n_rounds=n_rounds,
            compact=False,
            q=q,
            integrator=integrator,
            mesh=mesh,
        )
        return (state, params), out

    if k > 1:
        firsts = jnp.arange(k - 1, dtype=jnp.int32) == 0
        (state, params), outs = jax.lax.scan(body, (state, params), firsts)
        sv_last = jnp.zeros_like(spawn_valid)
        pr_last = jnp.full_like(push_rows, jnp.iinfo(jnp.int32).max)
    else:
        outs = None
        sv_last, pr_last = spawn_valid, push_rows
    # the final step is unrolled OUTSIDE the scan so ``compact`` can stay
    # a static flag (row compaction reshapes nothing, but keeping it out
    # of the scan body avoids paying its sort on every iteration)
    state, params, out_last = _step_body(
        state,
        params,
        kernels,
        perm_factors,
        degrad_factors,
        mol_idx,
        kill_below,
        divide_above,
        divide_cost,
        div_budget,
        spawn_dense,
        sv_last,
        push_dense,
        pr_last,
        tables,
        abs_temp,
        det=det,
        max_div=max_div,
        n_rounds=n_rounds,
        compact=compact,
        q=q,
        integrator=integrator,
        mesh=mesh,
    )
    if outs is None:
        outs = out_last[None]
    else:
        outs = jnp.concatenate([outs, out_last[None]], axis=0)
    return state, params, outs


# CPU twin — same rationale as _pipeline_step_retained
_megastep_retained = functools.partial(  # graftlint: disable=GL006 CPU twin of _megastep; donation races XLA:CPU async execution
    jax.jit,
    static_argnames=(
        "det", "max_div", "n_rounds", "compact", "q", "integrator", "k",
        "mesh",
    ),
)(_megastep.__wrapped__)


def _compact_body(
    state: DeviceState,
    params: CellParams,
    perm: jax.Array,
    n_keep: jax.Array,
    *,
    mesh=None,
) -> tuple[DeviceState, CellParams]:
    """Standalone compaction (used by :meth:`PipelinedStepper.flush`).
    Under a mesh the row gathers cross tile boundaries, so the outputs
    are constrained back to the cell sharding (see permute_params)."""
    cell_sh = (
        NamedSharding(mesh, _P(mesh.axis_names[0]))
        if mesh is not None
        else None
    )
    return (
        DeviceState(
            mm=state.mm,
            cm=constrain_rows(compact_rows(state.cm, perm, n_keep), cell_sh),
            pos=constrain_rows(
                compact_rows(state.pos, perm, n_keep), cell_sh
            ),
            occ=state.occ,
            alive=constrain_rows(
                jnp.arange(state.alive.shape[0]) < n_keep, cell_sh
            ),
            n_rows=n_keep,
            key=state.key,
        ),
        constrain_rows(permute_params(params, perm, n_keep), cell_sh),
    )


_compact_program = functools.partial(
    jax.jit, donate_argnums=(0, 1), static_argnames=("mesh",)
)(_compact_body)
# CPU twin — same rationale as _pipeline_step_retained
_compact_program_retained = functools.partial(  # graftlint: disable=GL006 CPU twin of _compact_program; donation races XLA:CPU async execution
    jax.jit, static_argnames=("mesh",)
)(_compact_body)


class _Worker:
    """One DAEMON thread running queued callables in FIFO order.  Daemon
    on purpose: work hung on a dead tunnel must never block interpreter
    exit (a ThreadPoolExecutor's workers are joined at exit and would).
    :meth:`close` (hooked to the owner via ``weakref.finalize``) ends the
    thread when the owner is collected, and the
    :func:`magicsoup_tpu.util.register_exit_join` atexit hook stops +
    joins it (bounded) before runtime teardown — a daemon thread still
    inside a device fetch during teardown corrupts the heap."""

    def __init__(self, name: str):
        import queue
        import threading

        self._q: Any = queue.SimpleQueue()
        self._closed = False
        # serializes the closed-check-and-put in submit() against close()
        # (which runs from weakref.finalize/atexit on OTHER threads): an
        # item enqueued behind the shutdown sentinel would never resolve
        self._lock = threading.Lock()
        self._t = threading.Thread(target=self._run, daemon=True, name=name)
        self._t.start()
        _register_exit_join(self)

    @owned_by("stepper-worker")
    def _run(self) -> None:  # graftlint: owner=stepper-worker
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, fut = item
            try:
                fut.set_result(fn())
            except BaseException as exc:  # noqa: BLE001 - delivered to result()  # graftlint: disable=GL013 error re-surfaces from the future
                fut.set_exception(exc)

    def submit(self, fn):
        # a bare stdlib Future (no executor, so nothing joins it at exit)
        from concurrent.futures import Future

        fut: Future = Future()
        with self._lock:
            closed = self._closed or not self._t.is_alive()
            if not closed:
                self._q.put((fn, fut))
        if closed:
            # a submit after close() (or with a dead worker) would queue
            # behind the shutdown sentinel and hang its consumer forever;
            # resolve inline instead — slower, never silent.  fn runs
            # OUTSIDE the lock: it may block on a device fetch and close()
            # must never wait on that.
            try:
                fut.set_result(fn())
            except BaseException as exc:  # noqa: BLE001  # graftlint: disable=GL013 error re-surfaces from the future
                fut.set_exception(exc)
        return fut

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._q.put(None)

    def exit_join(self, timeout: float | None = None) -> None:
        self.close()
        if self._t.is_alive():
            self._t.join(timeout)


class _Fetcher(_Worker):
    """:class:`_Worker` pulling packed step outputs to host in dispatch
    order (one fetch per replayed step)."""

    def __init__(self):
        super().__init__(name="ms-stepper-fetch")

    def submit(self, arr, on_ready=None):
        # through the sanctioned explicit-transfer boundary (GL005):
        # survives jax.transfer_guard("disallow") in guarded test runs.
        # ``on_ready`` fires on the worker thread the moment the fetch
        # resolves — the graftpulse device-time bracket closes here,
        # riding the sync point the pipeline already pays for (no new
        # block_until_ready, no extra D2H)
        def _fetch():
            value = _fetch_host(arr)
            if on_ready is not None:
                on_ready()
            return value

        return super().submit(_fetch)


class _LazyFetch:
    """Inline stand-in for a fetch Future on backends without a worker
    thread (CPU): resolves on the replay thread, exactly the pre-worker
    semantics.  The ``on_ready`` device-time callback fires once, on
    first ``result()`` — on this path the bracket closes at replay
    rather than transfer-done, an upper bound that still conserves."""

    __slots__ = ("_arr", "_on_ready")

    def __init__(self, arr, on_ready=None):
        self._arr = arr
        self._on_ready = on_ready

    def done(self) -> bool:
        try:
            return self._arr.is_ready()
        except AttributeError:
            return True

    def result(self, timeout=None):
        value = _fetch_host(self._arr)
        if self._on_ready is not None:
            self._on_ready, cb = None, self._on_ready
            cb()
        return value


class _Pending(NamedTuple):
    """One dispatched step (or megastep) awaiting host replay."""

    out: Any  # Future[np.ndarray] — packed i32 output (see StepOutputs)
    spawn_genomes: list  # genomes queued into this dispatch (b_spawn order)
    spawn_labels: list
    compacted: bool  # final record of this dispatch compacted
    change_seq: int  # genome-change counter at dispatch time
    div_budget: int  # TOTAL division cap of this dispatch (k x per-step)
    k: int  # fused steps in this dispatch (records in ``out``)


class _DispatchPlan(NamedTuple):
    """Host-side decisions for ONE dispatch, produced by
    :meth:`PipelinedStepper._prepare_dispatch` before any device input
    is densified.

    The prepare/finalize/commit split exists for the fleet coordinator
    (``magicsoup_tpu.fleet``): it runs ``_prepare_dispatch`` on every
    lane FIRST (so token-capacity growth across the group settles
    before any dense tensor is built), stacks the planned batches into
    one batched upload, dispatches once, and hands each lane its slice
    of the shared fetch via ``_commit_dispatch``.  The solo ``step()``
    recomposes the same three pieces back-to-back.
    """

    t_start: float  # perf_counter at step entry (step_ms accounting)
    fetch0: float  # _fetch_acc at step entry (fetch_ms accounting)
    spawn: list  # [(genome, label)] taken off the spawn queue
    spawn_entries: Any  # phenotype-cache entries for ``spawn`` (or None)
    ride: Any  # (entries, rows) push refresh riding this dispatch
    compact: bool  # this dispatch's final record compacts
    div_budget: int  # per-step division budget (quantized int)
    k: int  # fused steps in this dispatch
    t_asm0: float  # param_assembly span start
    t_spawn0: float  # spawn span start


class PipelinedStepper:
    """
    Pipelined driver for the canonical selection workload over a
    :class:`World` (see module docstring for the execution model and its
    documented deltas vs the serial loop).

    Parameters:
        world: The world to drive; its current population becomes the
            starting state.  Mesh-placed worlds are fully supported: the
            fused step (and the megastep scan) runs SPMD over the 1D
            mesh with the map row-sharded, cell state and parameters
            cell-sharded, halo-exchange diffusion, and a replicated
            packed record — the host replay and one-fetch-per-step
            contract are identical to the single-device driver, and in
            det mode the sharded trajectory is bit-identical to the
            single-device one (README "Scaling across a mesh").
        mol_name: Molecule whose intracellular amount drives selection
            (``"ATP"`` in the canonical workload).
        kill_below: Kill cells below this amount.
        divide_above: Divide cells above this amount...
        divide_cost: ...at this cost, paid before sharing.
        target_cells: Population size to top up to with random genomes
            (``None`` disables spawning).
        genome_size: Size of top-up genomes.
        lag: Pipeline depth, counted in DISPATCHES.  An integer fixes
            the schedule (seed-exact reproducibility); ``"auto"``
            processes outputs as their transfers complete, bounded by
            ``max_lag``.  With ``megastep=K`` each dispatch is K steps,
            so the phenotype/spawn replay trails the device by up to
            ``lag x K`` STEPS — choose ``lag`` and ``K`` together (see
            README "Choosing K").
        megastep: Fused steps per dispatch (``K``).  Each :meth:`step`
            call dispatches ONE ``lax.scan``-fused program advancing the
            device K steps and returning the K packed per-step records
            in one buffer; the host replays them record by record, so
            the replayed trajectory is the same serial one.  Spawn
            batches and riding parameter refreshes enter at megastep
            boundaries only (step 0 of each dispatch).  Default 1 (the
            classic one-step dispatch, byte-identical schedule to
            previous releases).
        max_divisions: Static per-step division budget (slot allocation
            is bounded so the step program compiles once).
        spawn_block: Static per-step spawn budget.
        push_block: Static size of the parameter-refresh batch riding a
            step dispatch; bigger change sets pay their own dispatch.
        n_rounds: Conflict-resolution rounds for on-device placement.
        p_mutation / p_indel / p_del / p_recombination: Mutation
            parameters (reference defaults).
        compact_headroom: Compact when fewer than this many free rows
            are estimated to remain (default 256).
        compact_dead_slack: Also compact once this many dead rows have
            accumulated (default 768) — dead rows inflate the live-row
            prefix the integrator reads, and compaction rides the step
            program, so reclaiming early keeps slot occupancy >= ~85%
            at steady-state churn for free.
        auto_grow: Double the world's slot capacity (a rare full
            pipeline drain) when the live population crowds it; with
            ``False`` the allocation clamps instead and drops are
            counted in :attr:`stats`.
        overlap_evolution: Run the evolution phase (recombination +
            point mutations, the largest host-replay item — the C++
            engine releases the GIL) on a worker thread, overlapping the
            next step's dispatch and fetch wait.  Deterministic by
            construction: the worker only COMPUTES the changed-genome
            set (drawing from the stepper's own rng, which nothing else
            uses); every replay starts by joining the previous
            evolution and applying it on the main thread, so at fixed
            lag the resulting phenotype pushes always ride the
            second-next dispatch — a transfer-speed-independent
            schedule, like the rest of the fixed-lag contract.  Note
            the two modes are each seed-reproducible but differ from
            EACH OTHER (pushes ride the second-next vs the next
            dispatch), so toggling this flag — like upgrading past the
            release that introduced it — changes the trajectory a given
            seed produces.
        sentinel_policy: Host reaction to the graftguard health lanes
            (non-finite / materially negative concentrations, computed
            on device every step regardless of this setting): ``"warn"``
            counts + notes the trip, ``"quarantine"`` kills the poisoned
            cells and sanitizes the map at the next flush boundary,
            ``"rollback"`` raises
            :class:`~magicsoup_tpu.guard.errors.SentinelTripped` so the
            driver restores the last good checkpoint.  The compiled
            device program is identical for all three.
        dispatch_retries: Retry a FAILED step dispatch up to this many
            times with bounded exponential backoff when the error looks
            transient (``guard.retry``); 0 (default) propagates the
            first failure.  Never retries after a donated input was
            consumed.
        fetch_timeout: Wall-clock budget (seconds) for one step-record
            fetch before the watchdog dumps diagnostics and raises
            :class:`~magicsoup_tpu.guard.errors.WatchdogTimeout`
            (default: ``MAGICSOUP_GUARD_FETCH_TIMEOUT`` or 300).
    """

    def __init__(
        self,
        world,
        *,
        mol_name: str = "ATP",
        kill_below: float = 1.0,
        divide_above: float = 5.0,
        divide_cost: float = 4.0,
        target_cells: int | None = None,
        genome_size: int = 500,
        lag: int | str = "auto",
        max_lag: int = 8,
        megastep: int = 1,
        max_divisions: int = 2048,
        spawn_block: int = 1024,
        push_block: int = 256,
        n_rounds: int = 4,
        p_mutation: float = 1e-6,
        p_indel: float = 0.4,
        p_del: float = 0.66,
        p_recombination: float = 1e-7,
        compact_headroom: int | None = None,
        compact_dead_slack: int = 768,
        auto_grow: bool = True,
        overlap_evolution: bool = True,
        sentinel_policy: str = "warn",
        dispatch_retries: int = 0,
        fetch_timeout: float | None = None,
    ):
        # mesh-placed worlds run the fused step SPMD (see _step_body's
        # mesh note); all host->device placements below go through
        # _dev()/device= so every dispatch input is explicitly placed —
        # an uncommitted input would be implicitly replicated at EVERY
        # dispatch (a transfer per step, and a transfer-guard violation
        # under hot_path_guard)
        self._mesh = world._mesh
        if self._mesh is not None:
            from magicsoup_tpu.parallel import tiled as _tiled

            self._n_tiles = int(
                self._mesh.shape[self._mesh.axis_names[0]]
            )
            self._rep_sh = _tiled.replicated_sharding(self._mesh)
            self._map_sh = world._map_sharding
            self._cell_sh = world._cell_sharding
        else:
            self._n_tiles = 1
            self._rep_sh = self._map_sh = self._cell_sh = None
        self.world = world
        self.kin = world.kinetics
        self.mol_idx = world.chemistry.molname_2_idx[mol_name]
        self.kill_below = float(kill_below)
        self.divide_above = float(divide_above)
        self.divide_cost = float(divide_cost)
        self.target_cells = target_cells
        self.genome_size = genome_size
        if lag != "auto" and (not isinstance(lag, int) or lag < 0):
            raise ValueError("lag must be 'auto' or a non-negative int")
        self.lag = lag
        self.max_lag = max_lag if lag == "auto" else max(int(lag), 1)
        if not isinstance(megastep, int) or megastep < 1:
            raise ValueError("megastep must be an int >= 1")
        self.megastep = megastep
        self.max_divisions = max_divisions
        self.spawn_block = spawn_block
        self.push_block = push_block
        self.n_rounds = n_rounds
        self.p_mutation = p_mutation
        self.p_indel = p_indel
        self.p_del = p_del
        self.p_recombination = p_recombination
        self.compact_headroom = (
            compact_headroom if compact_headroom is not None else 256
        )
        self.compact_dead_slack = compact_dead_slack
        self.auto_grow = auto_grow
        # graftguard: host-side policy over the unconditional sentinel
        # lanes, bounded dispatch retry, and the fetch watchdog budget.
        # None of these change the compiled device program.
        from magicsoup_tpu.guard.sentinel import SENTINEL_POLICIES
        from magicsoup_tpu.guard.watchdog import fetch_timeout as _ft

        if sentinel_policy not in SENTINEL_POLICIES:
            raise ValueError(
                f"sentinel_policy must be one of {SENTINEL_POLICIES}"
            )
        self.sentinel_policy = sentinel_policy
        self.dispatch_retries = int(dispatch_retries)
        self._fetch_timeout = (
            float(fetch_timeout) if fetch_timeout is not None else _ft()
        )
        self._quarantine_pending = False
        self._sentinel_warned = False
        self._invariant_warned = False
        self._fault_dispatch = 0  # armed by guard.faults
        self.stats = {
            "steps": 0,
            "replayed": 0,
            "compactions": 0,
            "growths": 0,
            "divisions": 0,
            "division_drops": 0,  # budget clamps (a pipeline delta)
            "division_blocked": 0,  # no free Moore pixel (classic too)
            "kills": 0,
            "spawned": 0,
            "spawn_drops": 0,
            "pushes": 0,
            "genome_changes": 0,  # mutated/recombined genomes applied
            # whole-run aggregates mirroring the (bounded) trace ring, so
            # totals stay exact for windows longer than the ring
            "cold_dispatches": 0,
            "fetch_ms": 0,
            "dispatch_ms": 0,
            "step_ms": 0,
            # graftguard counters
            "sentinel_trips": 0,
            "quarantined": 0,
            "dispatch_retries": 0,
            # graftcheck counter: replayed steps whose invariant flag
            # word was nonzero
            "invariant_trips": 0,
        }
        # graftscope: share the world's recorder so one JSONL stream
        # carries both; detached recorders cost one dict update per
        # dispatch and emit nothing
        from magicsoup_tpu.telemetry import TelemetryRecorder

        self.telemetry = TelemetryRecorder.coerce(
            getattr(world, "telemetry", None)
        )

        # constant device scalars, built once — jnp.asarray per dispatch
        # would put five tiny host->device transfers on the very critical
        # path this driver exists to clear
        self._mol_idx_dev = self._dev(self.mol_idx, jnp.int32)
        self._kill_below_dev = self._dev(self.kill_below, jnp.float32)
        self._divide_above_dev = self._dev(self.divide_above, jnp.float32)
        self._divide_cost_dev = self._dev(self.divide_cost, jnp.float32)
        self._abs_temp_dev = self._dev(world.abs_temp, jnp.float32)
        # world-owned program constants: under a mesh keep stepper-local
        # REPLICATED placements — the world's uncommitted copies would be
        # implicitly re-replicated at every dispatch
        if self._mesh is not None:
            self._kernels_dev = jax.device_put(
                world._diff_kernels, self._rep_sh
            )
            self._perm_dev = jax.device_put(
                world._perm_factors, self._rep_sh
            )
            self._degrad_dev = jax.device_put(
                world._degrad_factors, self._rep_sh
            )
        else:
            self._kernels_dev = world._diff_kernels
            self._perm_dev = world._perm_factors
            self._degrad_dev = world._degrad_factors
        # (tables object, replicated placement) — see _tables()
        self._tables_cache: tuple = (None, None)

        self._rng = np.random.default_rng(world._rng.randrange(2**63))
        self.trace: list[dict] = []  # per-step timing/diagnostic records
        self._fetch_acc = 0.0  # seconds spent blocked on output fetches
        self._budget_cache: dict[int, jax.Array] = {}
        # one background worker pulls each step's packed output record to
        # host as soon as it is dispatched, so the replay path never puts
        # a device->host round trip (~70-100 ms through a tunnel) on the
        # step loop; a single worker keeps fetches in dispatch order.
        # CPU backend: no worker (no RTT to hide, and a background fetch
        # racing a compile segfaults jaxlib's CPU client — see
        # util.async_workers_enabled)
        # one source of truth: the world resolved the per-client policy
        self._async = world._async_workers
        if self._async:
            import weakref

            self._fetcher = _Fetcher()
            weakref.finalize(self, self._fetcher.close)
        else:
            self._fetcher = None
        # evolution overlap runs on ALL backends (it calls only the C++
        # engine + numpy — none of the jax-client hazards that gate the
        # fetcher off CPU apply), so the CPU test tier exercises the
        # exact threading the TPU path uses.  Token-backed worlds run
        # evolution INLINE instead: the compute half dispatches jitted
        # device kernels, and jax dispatch from a second thread breaks
        # the single-owner contract the ownership assertions pin — the
        # kernels also remove the host latency the overlap existed to
        # hide, so there is nothing left to overlap
        if overlap_evolution and world._genome_store is None:
            import weakref

            self._evo_worker = _Worker("ms-stepper-evo")
            weakref.finalize(self, self._evo_worker.close)
        else:
            self._evo_worker = None
        self._evo_future = None
        self._pending: list[_Pending] = []
        self._spawn_queue: list[tuple[str, str]] = []  # (genome, label)
        # deferred pushes: (genomes, rows, change seq) held while a
        # compaction is in flight
        self._push_buffer: list[tuple[list[str], list[int], int]] = []
        # translated-parameter refreshes ready to RIDE the next step
        # dispatch (saves one program dispatch per step)
        self._push_queue: list[tuple[list[str], list[int], int]] = []
        self._compact_outstanding = False
        self._growth_hist: list[int] = []  # recent per-step row growth
        self._change_seq = 0  # bumps on every genome-change batch CREATED
        self._dispatched_seq = 0  # highest batch seq actually DISPATCHED
        # persistent on-disk compile cache: the q-ladder / megastep
        # variants this driver compiles are exactly the entries a second
        # process wants warm (idempotent, env-overridable — see cache.py)
        from magicsoup_tpu.cache import ensure_compile_cache

        ensure_compile_cache()
        # donated vs retained step programs is a per-backend choice,
        # fixed at init (see _pipeline_step_retained)
        self._donate = _donate_step_buffers()
        # compiled-variant bookkeeping (keys include the token capacities
        # the program shapes depend on) + cached empty spawn/push buffers
        self._warm_sched = WarmScheduler()
        self._empty_cache: dict = {}
        # identity fingerprint of the World as OUR last flush left it
        # (None = no flush yet / invalidated); lets the next re-attach
        # prove the World untouched and skip the host replay rebuild
        self._flush_token: tuple | None = None
        self._attach(jax.random.PRNGKey(world._rng.randrange(2**31)))
        self._needs_attach = False

    def _dev(self, value, dtype=None) -> jax.Array:
        """Host value -> device, EXPLICITLY placed: replicated over the
        mesh when one is set (``device=None`` keeps the default
        single-device placement, so unsharded behavior is unchanged).
        Every per-dispatch host input funnels through here — an
        uncommitted input to a sharded jit is an implicit replication
        transfer on every dispatch (the GL009 footgun)."""
        return jnp.asarray(value, dtype=dtype, device=self._rep_sh)

    def _tables(self):
        """``kin.tables`` for dispatch: replicated on the mesh, cached
        per rebuild (ensure_token_limits replaces the tables object when
        token capacities grow, invalidating the placement)."""
        tabs = self.kin.tables
        if self._mesh is None:
            return tabs
        if self._tables_cache[0] is not tabs:
            self._tables_cache = (
                tabs,
                jax.device_put(tabs, self._rep_sh),
            )
        return self._tables_cache[1]

    def _world_token(self) -> tuple:
        """Identity fingerprint of the attached World's mutable state.

        Stamped by :meth:`flush` (after it syncs the World) and compared
        at the next re-attach: equal tokens prove no classic-API
        mutation touched the World in between, so the serial host-replay
        rebuild can be skipped.  Every functional mutator replaces one
        of these array/list objects; the few pure in-place mutators bump
        ``World._host_epoch`` instead.  The token holds STRONG
        references to the objects themselves (compared with ``is`` by
        :meth:`_token_unchanged`, never ``id()``): a stored raw id could
        compare equal after the original object is freed and a
        same-sized replacement lands at the recycled address, silently
        skipping a rebuild the replacement requires.  The references
        cost nothing extra — at stamp time they alias the World's own
        live arrays, and the token is dropped at the next attach.
        Direct in-place edits of ``cell_genomes``/``cell_labels``
        ENTRIES are not observable here — but those already desync
        kinetics params and were never a supported mutation path
        (``update_cells`` is).
        """
        w = self.world
        return (
            w._host_epoch,
            w.n_cells,
            w._capacity,
            w._molecule_map,
            w._cell_molecules,
            w._positions_dev,
            w.kinetics,
            w.kinetics.params,
            # token backend: the store's token ARRAY stands in for the
            # genome list (every store mutator replaces it) — comparing
            # the decoded view would force a whole-population export
            (
                w._genome_store.tokens
                if w._genome_store is not None
                else w._genomes_list  # graftlint: disable=GL023 identity probe only — no decode
            ),
            w.cell_labels,
            w._np_positions,
            w._np_lifetimes,
            w._np_divisions,
            w._np_cell_map,
        )

    @staticmethod
    def _token_unchanged(stamped: tuple | None, current: tuple) -> bool:
        """Whether two :meth:`_world_token` fingerprints prove the World
        untouched: scalar slots by value, object slots by IDENTITY (an
        equal-valued copy is still a mutation — its rows may be stale)."""
        if stamped is None:
            return False
        return stamped[:3] == current[:3] and all(
            a is b for a, b in zip(stamped[3:], current[3:])
        )

    def _attach(self, key: jax.Array) -> None:
        """(Re)build device + replay state from the attached world —
        used at construction and after a capacity growth."""
        self._flush_token = None
        w = self.world
        self._cap = w._capacity
        # capacity growth changes every program's shapes: compiled-variant
        # bookkeeping and the cached empty buffers start over
        self._warm_sched.reset()
        self._empty_cache = {}
        # COPIES, not the world's own arrays: the step program donates its
        # DeviceState inputs, and donating `w._molecule_map` itself would
        # delete the buffer the classic API (world.molecule_map & friends)
        # still reads between pipelined phases.  Mesh worlds: mm/cm/pos
        # arrive already sharded (jnp.copy preserves placement, pinned by
        # the device_put below), and the host-built leaves are placed
        # explicitly — occ/n_rows/key replicated, alive cell-sharded —
        # matching _step_body's output constraints so the steady-state
        # dispatch never reshards its own carry.
        mesh = self._mesh
        self._state = DeviceState(
            mm=jnp.copy(w._molecule_map),
            cm=jnp.copy(w._cell_molecules),
            pos=jnp.copy(w._positions_dev),
            occ=self._dev(w._np_cell_map),
            alive=(
                jax.device_put(
                    np.arange(self._cap) < w.n_cells, self._cell_sh
                )
                if mesh is not None
                else jnp.arange(self._cap) < w.n_cells  # graftlint: disable=GL009 single-device branch; placement would commit the array and change jit-cache identity
            ),
            n_rows=self._dev(w.n_cells, jnp.int32),
            key=key if mesh is None else jax.device_put(key, self._rep_sh),
        )
        # host replay state (row-indexed, append-only between compactions).
        # Token-backed worlds keep genomes ON DEVICE: the stepper checks
        # out an array-sharing clone of the world's store (no decode, no
        # copy) and replays genome events with device programs; the host
        # genome list stays None and every consumer branches on it.
        if w._genome_store is not None:
            self._token_store = w._genome_store.clone()
            self._genomes = None
        else:
            self._token_store = None
            # graftlint: disable=GL023 string-backend attach boundary
            self._genomes = list(w.cell_genomes) + [""] * (
                self._cap - w.n_cells
            )
        self._labels: list = list(w.cell_labels) + [""] * (
            self._cap - w.n_cells
        )
        self._lifetimes = np.zeros(self._cap, dtype=np.int32)
        self._lifetimes[: w.n_cells] = w.cell_lifetimes
        self._divisions = np.zeros(self._cap, dtype=np.int32)
        self._divisions[: w.n_cells] = w.cell_divisions
        self._positions = w._np_positions.copy()
        self._alive = np.zeros(self._cap, dtype=bool)
        self._alive[: w.n_cells] = True
        self._n_rows = w.n_cells
        # per-row: change counter of the last genome change whose params
        # the device may not have had when older in-flight steps were
        # dispatched (-1 = device params match the genome)
        self._last_change = np.full(self._cap, -1, dtype=np.int64)

    def _grow_capacity(self) -> None:
        """Drain, sync into the world, double its slot capacity, and
        reattach — the pipelined analog of the classic loop's amortized
        pow2 growth (a rare full pipeline bubble)."""
        self.flush()
        # AFTER the flush: its compaction program donates the old state,
        # so a key captured before it would be a deleted buffer
        key = self._state.key
        self.world._ensure_capacity(self.world._capacity + 1)
        self._attach(key)
        self._needs_attach = False
        self.stats["growths"] += 1

    # -------------------------------------------------------------- #
    # dispatch side                                                  #
    # -------------------------------------------------------------- #

    def _dispatch_with_retry(self, fn):
        """Run one dispatch, absorbing up to ``dispatch_retries``
        transient failures with bounded exponential backoff.

        Only transient errors (guard.retry's marker classification)
        retry, and never after a failed dispatch has consumed a donated
        input — re-sending a deleted buffer would crash differently, so
        that case propagates the original error instead."""
        if self.dispatch_retries <= 0:
            return fn()
        from magicsoup_tpu.guard.retry import is_transient_error, retry_call

        def _retryable(exc: BaseException) -> bool:
            if not is_transient_error(exc):
                return False
            if self._donate:
                leaves = (*self._state, *self.kin.params)
                if any(
                    getattr(leaf, "is_deleted", lambda: False)()
                    for leaf in leaves
                ):
                    return False
            return True

        def _note(attempt: int, exc: BaseException) -> None:
            self.stats["dispatch_retries"] += 1
            self.telemetry.note("dispatch_retry", 1.0)

        return retry_call(
            fn,
            retries=self.dispatch_retries,
            retry_if=_retryable,
            on_retry=_note,
        )

    def step(self) -> None:
        """Dispatch one workload step (``megastep`` fused device steps)
        and replay any arrived outputs.

        Internally this is ``_prepare_dispatch`` (host decisions) →
        ``_finalize_inputs`` (densify to device buffers) → the dispatch
        itself → ``_commit_dispatch`` (pending bookkeeping, stats,
        telemetry).  The fleet coordinator reuses the same pieces around
        ONE batched dispatch for B worlds (``magicsoup_tpu.fleet``).
        """
        import time as _time

        plan = self._prepare_dispatch()
        (
            spawn_dense,
            spawn_valid,
            push_dense,
            push_rows,
            dev_budget,
            q,
        ) = self._finalize_inputs(plan)

        cold = not self._warm_sched.is_warm(self._variant_key(q, plan.compact))
        t_dispatch0 = _time.perf_counter()
        step_fn = self._step_fn()
        compact = plan.compact

        def _dispatch():
            # armed chaos faults fire BEFORE any buffer is touched, so a
            # retried dispatch re-sends bit-identical inputs
            if self._fault_dispatch > 0:
                from magicsoup_tpu.guard.faults import consume_dispatch_fault

                consume_dispatch_fault(self)
            fault = _chaos.site("dispatch")
            if fault is not None:
                from magicsoup_tpu.guard.errors import TransientDispatchError

                raise TransientDispatchError(
                    "injected fault: UNAVAILABLE: chaos dispatch fault "
                    f"#{fault.index}"
                )
            return step_fn(
                self._state,
                self.kin.params,
                self._kernels_dev,
                self._perm_dev,
                self._degrad_dev,
                self._mol_idx_dev,
                self._kill_below_dev,
                self._divide_above_dev,
                self._divide_cost_dev,
                dev_budget,
                spawn_dense,
                spawn_valid,
                push_dense,
                push_rows,
                self._tables(),
                self._abs_temp_dev,
                det=self.world.deterministic,
                max_div=self.max_divisions,
                n_rounds=self.n_rounds,
                compact=compact,
                q=q,
                integrator=self.world.integrator,
            )

        self._state, self.kin.params, out = self._dispatch_with_retry(
            _dispatch
        )
        t_dispatched = _time.perf_counter()
        # integrator census: ONE physical program launch carried the
        # megastep's k integrator calls — counted per backend name
        _runtime.note_integrator_dispatch(self.world.integrator)
        self._note_warm(q, compact)
        out_fut = (
            self._fetcher.submit(out, on_ready=self._device_ready(t_dispatched))
            if self._fetcher is not None
            else _LazyFetch(out, on_ready=self._device_ready(t_dispatched))
        )
        self._commit_dispatch(
            plan,
            out_fut,
            q=q,
            cold=cold,
            t_dispatch0=t_dispatch0,
            t_dispatched=t_dispatched,
        )

    def _device_ready(self, t_dispatched: float):
        """graftpulse device-time bracket: build the fetch-ready
        callback that closes the commit-to-fetch-ready span of ONE
        physical dispatch.  It feeds the process device-time census
        (``telemetry.metrics.note_device_time`` — what graftserve
        bills per-tenant ``device_us`` from) and this stepper's
        recorder ``"device"`` phase window, so the span lands on the
        NEXT dispatch row exactly like the fetch/replay spans do.
        Fires on the fetch worker thread (or at first ``result()`` on
        the CPU lazy path): zero extra sync, zero extra transfers."""
        import time as _time

        from magicsoup_tpu.telemetry import metrics as _metrics

        recorder = self.telemetry

        def _ready():
            dt = _time.perf_counter() - t_dispatched
            _metrics.note_device_time(dt)
            recorder.note("device", dt)

        return _ready

    def _prepare_dispatch(self) -> _DispatchPlan:
        """Host half of one dispatch: drain, growth/compaction decisions,
        spawn/push batch selection, token-capacity growth — everything
        that must settle BEFORE device inputs are densified.  Returns the
        :class:`_DispatchPlan` consumed by ``_finalize_inputs`` (solo) or
        the fleet coordinator's batched densify."""
        import time as _time

        # plan-carried reading: noted as step_ms at commit  # graftlint: disable=GL025
        t_start = _time.perf_counter()
        fetch0 = self._fetch_acc
        if self._quarantine_pending:
            # sentinel quarantine runs at the next safe host boundary:
            # drain + sync first — killing cells under in-flight
            # megasteps would race the replay's row bookkeeping.  The
            # flush leaves _needs_attach set, so the block below re-pulls
            # the sanitized world.
            self._quarantine_pending = False
            self.flush()
            from magicsoup_tpu.guard.sentinel import quarantine_world

            self.stats["quarantined"] += quarantine_world(self.world)
        if self._needs_attach:
            # after a flush the World may have been advanced/mutated with
            # the classic API; re-pulling its state here (cheap: the
            # arrays are already on device) is what makes pipelined and
            # classic phases compose without silent divergence
            from magicsoup_tpu.analysis import runtime as _rt

            if (
                self._token_unchanged(self._flush_token, self._world_token())
                and self.world._capacity >= self.world.n_cells + 1
            ):
                # fast re-attach: nothing touched the World since our own
                # flush wrote it, so the host replay lists are already
                # exact — skip the serial per-world rebuild and KEEP the
                # warm-variant bookkeeping and cached empty buffers.
                # Only the device leaves the flush aliased into the World
                # need fresh copies: the next dispatch donates
                # self._state, and donating the World's own buffers would
                # delete what the classic API still reads.
                self._state = self._state._replace(
                    mm=jnp.copy(self.world._molecule_map),
                    cm=jnp.copy(self.world._cell_molecules),
                    pos=jnp.copy(self.world._positions_dev),
                )
                _rt.note_attach(skipped=1)
            else:
                self.world._ensure_capacity(self.world.n_cells + 1)
                self._attach(self._state.key)
                _rt.note_attach(full=1)
            self._needs_attach = False
        self._drain(block=False)

        # Compaction scheduling is a prediction: the replayed row count
        # lags the device, so project forward with the recent per-step
        # growth (x2 margin).  A mis-prediction is safe — the device
        # clamps allocations at capacity and the drops are counted.
        g_est = max(self._growth_hist[-8:], default=0)
        g_est = max(g_est, 32)

        # compaction cannot free more than the dead rows; when the LIVE
        # population itself crowds the capacity (>7/8 full), grow (drain
        # + double + reattach, like the classic loop's pow2 growth).  The
        # demand term is clamped: a transient division wave (everyone
        # above threshold after a fresh spawn) must raise the division
        # BUDGET, not permanently double capacity — growth is a response
        # to live crowding, clamps merely defer divisions a step
        if self.auto_grow:
            grow_at = max(2 * min(g_est, 256), self._cap // 8)
            if self._cap - int(self._alive.sum()) < grow_at:
                self.drain()
                if self._cap - int(self._alive.sum()) < grow_at:
                    self._grow_capacity()

        # outstanding STEPS, not dispatches: each pending megastep holds
        # p.k fused steps' worth of unreplayed growth
        pend_steps = sum(p.k for p in self._pending) + self.megastep
        projected = (
            self._n_rows
            + pend_steps * 2 * g_est
            + len(self._spawn_queue)
        )
        # two triggers: (a) running out of rows, and (b) enough dead rows
        # accumulated that the live-row prefix q carries a whole ladder
        # rung of dead-slot tax (VERDICT round-2 #9: keep the integrator's
        # occupancy >= 85% at steady state).  Compaction rides the step
        # program — no extra dispatch, and the variant is prewarmed.
        dead_est = self._n_rows - int(self._alive.sum())
        compact = not self._compact_outstanding and (
            projected + self.compact_headroom > self._cap
            or dead_est > self.compact_dead_slack
        )

        # spawn batch + riding parameter refreshes for this dispatch:
        # translate BOTH first (through the phenotype cache — spawn
        # bursts from shared seed genomes dedupe to one translation),
        # grow token capacities for both, and only then densify — one
        # batch's protein-capacity growth must not invalidate the
        # other's already-built dense tensor
        # plan-carried reading: the param_assembly span is noted at commit  # graftlint: disable=GL025
        t_asm0 = _time.perf_counter()
        spawn = self._spawn_queue[: self.spawn_block]
        self._spawn_queue = self._spawn_queue[len(spawn) :]
        has_spawn = len(spawn) > 0
        # plan-carried reading: the spawn span is noted at commit  # graftlint: disable=GL025
        t_spawn0 = _time.perf_counter()
        spawn_entries = (
            self.world.phenotypes.lookup([g for g, _ in spawn])
            if has_spawn
            else None
        )
        ride = self._take_ride_push()
        if compact and self._push_queue:
            # refreshes NOT riding this compacting dispatch would reach
            # the device with pre-compaction row ids; park them in the
            # remap buffer until the compaction's replay provides the
            # permutation
            self._push_buffer += self._push_queue
            self._push_queue = []
        for ent in (spawn_entries, ride[0] if ride else None):
            if ent:
                self._grow_tokens(
                    max(e.n_prots for e in ent),
                    max(e.max_doms for e in ent),
                )

        # Division budget is adaptive (recent demand x2) so the live-row
        # bound stays tight; genuine demand spikes clamp for one step,
        # are counted as drops, and raise the next estimate.  Quantized
        # to 64 so the per-step scalar upload hits a small cache of
        # device constants instead of paying its own transfer each step.
        div_budget = int(
            min(self.max_divisions, -(-(2 * g_est + 64) // 64) * 64)
        )
        return _DispatchPlan(
            t_start=t_start,
            fetch0=fetch0,
            spawn=spawn,
            spawn_entries=spawn_entries,
            ride=ride,
            compact=compact,
            div_budget=div_budget,
            k=self.megastep,
            t_asm0=t_asm0,
            t_spawn0=t_spawn0,
        )

    def _grow_tokens(self, n_prots: int, n_doms: int) -> None:
        """Grow the kinetics token capacities for a planned batch.
        Split out so fleet lanes can check their params out of the
        group stack BEFORE the resize pads them (growing a stale copy
        would be silently discarded at the next checkout)."""
        self.kin.ensure_token_limits(n_prots, n_doms)

    def _finalize_inputs(self, plan: _DispatchPlan):
        """Densify the planned spawn/push batches at the CURRENT token
        capacities into device buffers, fetch the cached division-budget
        scalar, and pick the live-row prefix ``q`` — the device half of
        a solo dispatch.  Fleet lanes skip this and densify at their
        GROUP's unified capacities instead (fleet/scheduler.py)."""
        import time as _time

        spawn = plan.spawn
        spawn_entries = plan.spawn_entries
        ride = plan.ride
        if spawn_entries is not None:
            dense = self.world.phenotypes.dense_rows(
                spawn_entries, self.kin.max_proteins, self.kin.max_doms
            )
            pad = np.zeros(
                (self.spawn_block,) + dense.shape[1:], dtype=dense.dtype
            )
            pad[: len(spawn)] = dense
            spawn_dense = self._dev(pad)
            valid = np.zeros(self.spawn_block, dtype=bool)
            valid[: len(spawn)] = True
            spawn_valid = self._dev(valid)
            self.telemetry.note(
                "spawn", _time.perf_counter() - plan.t_spawn0
            )
        else:
            # cached all-zero device buffers: the spawn path always runs
            # (no extra compiled variant) but places nothing and scatters
            # inert rows — and nothing is re-uploaded on spawnless steps
            spawn_dense, spawn_valid = self._empty_spawn()
        if ride is not None:
            with self.telemetry.span("push"):
                push_dense, push_rows = self._densify_push(*ride)
        else:
            push_dense, push_rows = self._empty_push()
        self.telemetry.note(
            "param_assembly", _time.perf_counter() - plan.t_asm0
        )

        div_budget = plan.div_budget
        dev_budget = self._budget_cache.get(div_budget)
        if dev_budget is None:
            dev_budget = self._dev(div_budget, jnp.int32)
            self._budget_cache[div_budget] = dev_budget
        k = plan.k
        # Live-row prefix for this dispatch: an EXACT upper bound on the
        # device's row count (replayed rows + each outstanding step's
        # division budget + spawn batch), quantized — the integrator then
        # skips the dead tail.
        if self._mesh is not None:
            # the live-row prefix is a PREFIX slice of the cell-sharded
            # axis: any q < cap puts the whole prefix on the first tiles
            # (a redistribution collective per phase, and an unbalanced
            # one).  The mesh already divides the row work n_tiles ways,
            # so run full-capacity — dead rows are exact no-ops (zeroed
            # cm, OOB-dropped scatters), which also keeps the det-mode
            # trajectory independent of the single-device driver's q
            # ladder (the bit-identity tests rely on this).
            q = self._cap
        else:
            upper = self._n_rows + k * div_budget + len(spawn)
            for p in self._pending:
                upper += p.div_budget + len(p.spawn_genomes)
            q = quantize_rows(upper, self._cap)
        return spawn_dense, spawn_valid, push_dense, push_rows, dev_budget, q

    def _commit_dispatch(
        self,
        plan: _DispatchPlan,
        out_fut,
        *,
        q: int,
        cold: bool,
        t_dispatch0: float,
        t_dispatched: float,
        extra_row: dict | None = None,
    ) -> None:
        """Post-dispatch bookkeeping: append the pending replay, drain,
        update stats/trace, and emit the graftscope dispatch row.  The
        fleet coordinator calls this once per lane with that lane's
        SLICE of the shared fleet fetch (``extra_row`` carries the
        fleet slot/size annotations)."""
        import time as _time

        compact = plan.compact
        k = plan.k
        self._pending.append(
            _Pending(
                out=out_fut,
                spawn_genomes=[g for g, _ in plan.spawn],
                spawn_labels=[l for _, l in plan.spawn],
                compacted=compact,
                # what the device saw: only DISPATCHED pushes — a batch
                # still held in the compaction buffer is invisible to it
                change_seq=self._dispatched_seq,
                div_budget=k * plan.div_budget,
                k=k,
            )
        )
        if compact:
            self._compact_outstanding = True
        self.stats["steps"] += k
        self._drain(block=False)
        # per-step trace: ~100 B of host bookkeeping that makes a slow
        # hardware window self-diagnosing (bench.py summarises to stderr);
        # bounded so an unbounded simulation loop cannot leak host memory
        t_end = _time.perf_counter()
        self.stats["cold_dispatches"] += cold
        # float ms accumulators (bench.py int-casts on report): per-step
        # int truncation would zero out sub-ms fetches
        self.stats["fetch_ms"] += (self._fetch_acc - plan.fetch0) * 1e3
        self.stats["dispatch_ms"] += (t_dispatched - t_dispatch0) * 1e3
        self.stats["step_ms"] += (t_end - plan.t_start) * 1e3
        if len(self.trace) >= 4096:
            del self.trace[:2048]
        self.trace.append(
            {
                "t": t_end - plan.t_start,
                "dispatch": t_dispatched - t_dispatch0,
                "fetch": self._fetch_acc - plan.fetch0,
                "q": q,
                "rows": self._n_rows,
                "alive": int(self._alive.sum()),
                "cold": cold,
                "compact": compact,
                "k": k,
                "push": 0 if plan.ride is None else len(plan.ride[1]),
                "spawn": len(plan.spawn),
                "pend": len(self._pending),
            }
        )
        # graftscope: per-dispatch phase attribution + one JSONL row.
        # take_dispatch() drains the since-last-dispatch window, so the
        # fetch/replay spans _drain noted above land on THIS row
        rec = self.telemetry
        rec.note("dispatch", t_dispatched - t_dispatch0)
        if rec.attached:
            row = {
                "type": "dispatch",
                "phases": rec.take_dispatch(),
                "k": k,
                "q": q,
                "rows": self._n_rows,
                "pending": len(self._pending),
                "cold": bool(cold),
                "compact": bool(compact),
            }
            if self._mesh is not None:
                # mesh metadata: tile count + axis name, so a capture's
                # JSONL is self-describing about the sharded topology
                row["tiles"] = self._n_tiles
                row["mesh_axis"] = str(self._mesh.axis_names[0])
            if extra_row:
                row.update(extra_row)
            rec.emit(row)

    # -------------------------------------------------------------- #
    # replay side                                                    #
    # -------------------------------------------------------------- #

    @property
    def population(self) -> int:
        """Live cell count as of the last REPLAYED step — trails the
        device by the pipeline depth, like all host-visible state."""
        return int(self._alive.sum())

    def drain(self) -> None:
        """Block until every dispatched step has been replayed (the
        device may still be ahead on programs, but all outputs are in
        and the host state is caught up)."""
        self._drain(block=True)

    def _ready(self, pend: _Pending) -> bool:
        return pend.out.done()

    def _unpack_outputs(self, arr: np.ndarray) -> StepOutputs:
        """Host-side inverse of the step program's output packing."""
        md = self.max_divisions
        sb = self.spawn_block
        nw_k = -(-self._cap // _BITS)
        nw_s = -(-sb // _BITS)
        assert arr.shape[0] == record_length(
            self._cap, md, sb, self._n_tiles if self._mesh is not None else 1
        ), "step record length drifted from stepper.record_length"
        off = _HEADER_WORDS
        kill = _unpack_bits(arr[off : off + nw_k], self._cap)
        off += nw_k
        parents = arr[off : off + md]
        off += md
        child_pos = arr[off : off + 2 * md].reshape(md, 2)
        off += 2 * md
        spawn_ok = _unpack_bits(arr[off : off + nw_s], sb)
        off += nw_s
        spawn_pos = arr[off : off + 2 * sb].reshape(sb, 2)
        off += 2 * sb
        # graftguard: per-row bad-concentration bitmask (same width and
        # row space as the kill lane)
        bad_cells = _unpack_bits(arr[off : off + nw_k], self._cap)
        off += nw_k
        # mesh runs append n_tiles per-tile occupancy lanes at the TAIL
        # (single-device record layout is byte-identical to before)
        tile_occ = (
            arr[off : off + self._n_tiles].copy()
            if self._mesh is not None
            else None
        )
        # header words 6-7 are f32 mass totals bitcast into the i32
        # record on device; re-view the bits, don't value-cast them —
        # word 10 (graftcheck mass drift) gets the same treatment
        masses = np.ascontiguousarray(arr[6:8]).view(np.float32)
        drift = np.ascontiguousarray(arr[10:11]).view(np.float32)
        return StepOutputs(
            kill=kill,
            parents=parents,
            child_pos=child_pos,
            n_placed=int(arr[0]),
            n_candidates=int(arr[1]),
            n_attempted=int(arr[2]),
            spawn_ok=spawn_ok,
            spawn_pos=spawn_pos,
            n_rows=int(arr[3]),
            n_alive=int(arr[4]),
            n_occupied=int(arr[5]),
            mm_mass=float(masses[0]),
            cm_mass=float(masses[1]),
            tile_occupancy=tile_occ,
            health=int(arr[HEALTH_WORD]),
            bad_cells=bad_cells,
            invariants=int(arr[INVARIANT_WORD]),
            mass_drift=float(drift[0]),
        )

    def _drain(self, block: bool) -> None:
        while self._pending:
            if self.lag == "auto":
                must = block or len(self._pending) > self.max_lag
                if not must and not self._ready(self._pending[0]):
                    break
            elif not block and len(self._pending) < max(self.lag, 1):
                # fixed lag: replay on schedule only, NEVER on readiness —
                # push timing is part of the trajectory, so reproducibility
                # requires a transfer-speed-independent schedule
                break
            self._replay(self._pending.pop(0))
        if block:
            # "host state is caught up" includes the final replay's
            # evolution phase
            self._join_evolution()

    def _replay(self, pend: _Pending) -> None:
        import time as _time

        t0 = _time.perf_counter()
        # the ONE fetch per dispatch — usually already pulled by the
        # background worker; a megastep's k per-step records arrive
        # stacked in this single (k, record) buffer.  The watchdog
        # budget makes a dead worker or wedged tunnel surface as stack
        # dumps + a typed error instead of a silent hang
        try:
            fault = _chaos.site("fetch")
            if fault is not None:
                # a chaos "delay" stands in for a wedged transfer: hold
                # the fetch for the injected duration, capped at the
                # watchdog budget — a delay past the budget surfaces the
                # same TimeoutError the real result() raises, so the
                # diagnostics + typed-error path below is the production
                # path under test; a shorter delay is just a slow fetch
                delay = float(fault.arg or 0.0)
                _time.sleep(min(delay, self._fetch_timeout))
                if delay >= self._fetch_timeout:
                    raise TimeoutError(
                        f"chaos-injected fetch delay of {delay}s tripped "
                        f"the {self._fetch_timeout}s watchdog"
                    )
            arr = np.atleast_2d(
                np.asarray(pend.out.result(timeout=self._fetch_timeout))
            )
        except TimeoutError as exc:
            from magicsoup_tpu.guard.errors import WatchdogTimeout
            from magicsoup_tpu.guard.watchdog import dump_diagnostics

            dump_diagnostics(
                "stepper fetch timed out",
                {
                    "phase": "fetch",
                    "timeout_s": self._fetch_timeout,
                    "pending": len(self._pending),
                    "replayed": self.stats["replayed"],
                },
            )
            raise WatchdogTimeout(
                f"step-record fetch exceeded {self._fetch_timeout:.0f}s "
                "(wedged transfer or dead fetch worker); diagnostics "
                "dumped to stderr",
                phase="fetch",
                seconds=self._fetch_timeout,
            ) from exc
        dt_fetch = _time.perf_counter() - t0
        self._fetch_acc += dt_fetch
        self.telemetry.note("fetch", dt_fetch)
        t1 = _time.perf_counter()
        for i in range(pend.k):
            # record 0 carries the dispatch's spawn batch; only the final
            # record can be the compacting one — exactly what the device
            # program did (see _megastep)
            self._replay_record(
                self._unpack_outputs(arr[i]),
                spawn_genomes=pend.spawn_genomes if i == 0 else [],
                spawn_labels=pend.spawn_labels if i == 0 else [],
                compacted=pend.compacted and i == pend.k - 1,
                change_seq=pend.change_seq,
            )
        self.telemetry.note("replay", _time.perf_counter() - t1)

    def _guard_row_extra(self) -> dict:
        """Extra keys merged into guard telemetry rows (sentinel /
        invariant trips).  The fleet lane overrides this to tag rows
        with its ``fleet_slot``/``fleet_size``."""
        return {}

    def _handle_sentinel(self, out: StepOutputs) -> None:
        """Host-side policy over a tripped health flag word (the device
        lanes are unconditional; ONLY this reaction differs by policy)."""
        from magicsoup_tpu.guard.errors import SentinelTripped
        from magicsoup_tpu.guard.sentinel import decode_health

        flags = decode_health(out.health)
        n_bad = (
            int(out.bad_cells.sum()) if out.bad_cells is not None else 0
        )
        step = self.stats["replayed"]
        self.stats["sentinel_trips"] += 1
        names = ", ".join(k for k, v in flags.items() if v)
        if self.telemetry.attached:
            self.telemetry.emit(
                {
                    "type": "sentinel",
                    "step": step,
                    "flags": int(out.health),
                    "n_bad_cells": n_bad,
                    "policy": self.sentinel_policy,
                    **flags,
                    **self._guard_row_extra(),
                }
            )
        if self.sentinel_policy == "rollback":
            raise SentinelTripped(
                f"health sentinel tripped at replayed step {step}: "
                f"{names} ({n_bad} bad cells) — restore the last good "
                "checkpoint",
                flags=out.health,
                step=step,
                n_bad_cells=n_bad,
            )
        if self.sentinel_policy == "quarantine":
            self._quarantine_pending = True
        elif not self._sentinel_warned:
            self._sentinel_warned = True
            import warnings

            warnings.warn(
                f"health sentinel tripped at replayed step {step}: "
                f"{names} ({n_bad} bad cells); policy=warn — counting "
                "trips in stats['sentinel_trips'] (further trips warn "
                "only via telemetry)"
            )

    def _handle_invariant(self, out: StepOutputs) -> None:
        """Host-side policy over a tripped invariant flag word (Tier A
        graftcheck lanes) — routed through the SAME ``sentinel_policy``
        machinery as the health sentinel: rollback raises a typed
        :class:`~magicsoup_tpu.guard.errors.InvariantTripped`,
        quarantine schedules the flush -> quarantine -> reattach cycle
        (reattach rebuilds the occupancy map and cell index from the
        positions, repairing a desync), warn warns once and counts."""
        from magicsoup_tpu.check.invariants import decode_invariants
        from magicsoup_tpu.guard.errors import InvariantTripped

        flags = decode_invariants(out.invariants)
        step = self.stats["replayed"]
        self.stats["invariant_trips"] += 1
        names = ", ".join(k for k, v in flags.items() if v)
        if self.telemetry.attached:
            self.telemetry.emit(
                {
                    "type": "invariant",
                    "step": step,
                    "flags": int(out.invariants),
                    "mass_drift": float(out.mass_drift),
                    "policy": self.sentinel_policy,
                    **flags,
                    **self._guard_row_extra(),
                }
            )
        if self.sentinel_policy == "rollback":
            raise InvariantTripped(
                f"state invariant tripped at replayed step {step}: "
                f"{names} (mass drift {out.mass_drift:.3g}) — restore "
                "the last good checkpoint",
                flags=out.invariants,
                step=step,
            )
        if self.sentinel_policy == "quarantine":
            self._quarantine_pending = True
        elif not self._invariant_warned:
            self._invariant_warned = True
            import warnings

            warnings.warn(
                f"state invariant tripped at replayed step {step}: "
                f"{names}; policy=warn — counting trips in "
                "stats['invariant_trips'] (further trips warn only via "
                "telemetry)"
            )

    def _replay_record(
        self,
        out: StepOutputs,
        *,
        spawn_genomes: list,
        spawn_labels: list,
        compacted: bool,
        change_seq: int,
    ) -> None:
        """Replay ONE per-step record — the serial unit regardless of
        how many records arrived per dispatch."""
        # the previous record's evolution must land before anything here
        # touches genomes, positions or the push queues
        self._join_evolution()
        if out.health:
            self._handle_sentinel(out)
        if out.invariants:
            self._handle_invariant(out)
        kill = out.kill
        parents = out.parents
        n_placed = out.n_placed
        child_pos = out.child_pos
        spawn_ok = out.spawn_ok
        spawn_pos = out.spawn_pos

        # 0. spawns (allocation order matches the device: queue order)
        n_spawned = 0
        if spawn_genomes:
            tok_rows: list[int] = []
            tok_genomes: list[str] = []
            for i, (g, lab) in enumerate(
                zip(spawn_genomes, spawn_labels)
            ):
                if not spawn_ok[i]:
                    continue
                row = self._n_rows + n_spawned
                n_spawned += 1
                if self._token_store is not None:
                    tok_rows.append(row)
                    tok_genomes.append(g)
                else:
                    self._genomes[row] = g  # graftlint: disable=GL023 string-backend fallback
                self._labels[row] = lab
                self._lifetimes[row] = 0
                self._divisions[row] = 0
                self._positions[row] = spawn_pos[i]
                self._alive[row] = True
            if tok_rows:
                # one batched encode+scatter per record (the string
                # import boundary of the token replay)
                self._token_store.set_rows(tok_rows, tok_genomes)
            self._n_rows += n_spawned
            self.stats["spawned"] += n_spawned
            self.stats["spawn_drops"] += len(spawn_genomes) - n_spawned

        # 1. kills
        self._alive[kill] = False
        n_kills = int(kill.sum())
        self.stats["kills"] += n_kills

        # 2. divisions (parents ascending; children appended in order).
        # The device copied the parent's params as of this step's
        # DISPATCH; if the parent's genome changed in a replay since,
        # that copy is stale and the child needs its own push — without
        # it the child would keep the old phenotype forever.
        # token mode: repush values are None — the store row IS the
        # content, resolved hash-keyed at push-dispatch time
        repush: dict[int, str | None] = {}
        div_parents: list[int] = []
        div_children: list[int] = []
        for i in range(n_placed):
            p = int(parents[i])
            row = self._n_rows + i
            if self._token_store is not None:
                div_parents.append(p)
                div_children.append(row)
            else:
                self._genomes[row] = self._genomes[p]  # graftlint: disable=GL023 string-backend fallback
            self._labels[row] = self._labels[p]
            self._divisions[p] += 1
            self._divisions[row] = self._divisions[p]
            self._lifetimes[p] = 0
            self._lifetimes[row] = 0
            self._positions[row] = child_pos[i]
            self._alive[row] = True
            if self._last_change[p] > change_seq:
                repush[row] = (
                    None
                    if self._token_store is not None
                    else self._genomes[row]  # graftlint: disable=GL023 string-backend fallback
                )
            else:
                self._last_change[row] = self._last_change[p]
        if div_children:
            # parent->child genome copies stay on device
            self._token_store.copy_rows(div_parents, div_children)
        self._n_rows += n_placed
        self.stats["divisions"] += n_placed
        self.stats["division_drops"] += out.n_candidates - out.n_attempted
        self.stats["division_blocked"] += out.n_attempted - n_placed

        # 3. lifetimes
        self._lifetimes[: self._n_rows][
            self._alive[: self._n_rows]
        ] += 1

        # 4. compaction replay (same stable permutation as the device)
        if compacted:
            perm = np.argsort(~self._alive, kind="stable")
            n_keep = int(self._alive.sum())
            self._apply_perm(perm, n_keep)
            self._compact_outstanding = False
            self.stats["compactions"] += 1
            # remap deferred pushes and this step's child refreshes
            # through the permutation, then release the deferred ones
            inv = np.empty(self._cap, dtype=np.int64)
            inv[perm] = np.arange(self._cap)
            repush = {int(inv[r]): g for r, g in repush.items()}
            for genomes, rows, seq in self._push_buffer:
                self._dispatch_push(
                    genomes, [int(inv[r]) for r in rows], seq
                )
            self._push_buffer = []

        self.stats["replayed"] += 1
        # growth history feeds the division-budget/row-bound estimates;
        # drops count as demand so a clamp raises the next budget
        dropped = max(0, out.n_candidates - out.n_attempted)
        self._growth_hist.append(n_spawned + n_placed + dropped)
        if len(self._growth_hist) > 64:
            del self._growth_hist[:32]

        # 5. evolution on the replayed state (+ stale-child refreshes) —
        # computes on the worker, applied at the next replay's join
        self._submit_evolution(repush)

        # 6. population top-up (reacts with pipeline lag, documented)
        if self.target_cells is not None:
            n_alive = int(self._alive.sum())
            missing = (
                self.target_cells
                - n_alive
                - len(self._spawn_queue)
                - sum(len(p.spawn_genomes) for p in self._pending)
            )
            if missing > 0:
                rng = self.world._rng
                self._spawn_queue.extend(
                    (
                        random_genome(s=self.genome_size, rng=rng),
                        randstr(n=12, rng=rng),
                    )
                    for _ in range(missing)
                )

        # 7. graftscope per-step row: the device metric lanes are already
        # host scalars (they rode the packed record through the one
        # sanctioned fetch), so emission touches no device state
        if self.telemetry.attached:
            self.telemetry.emit(
                self._step_row(out, n_kills, n_placed, n_spawned)
            )

    def _step_row(
        self, out: StepOutputs, n_kills: int, n_divided: int, n_spawned: int
    ) -> dict:
        """One JSONL ``step`` row (schema: telemetry/summary.py)."""
        if self._token_store is not None:
            # length stats from the store's length vector (one cached
            # host fetch per store version — no decode)
            lens_arr = self._token_store.host_arrays()[1]
            lens = lens_arr[np.nonzero(self._alive)[0]].tolist()
        else:
            lens = [
                len(self._genomes[i])  # graftlint: disable=GL023 string-backend fallback
                for i in np.nonzero(self._alive)[0]
            ]
        n = len(lens)
        extra = {}
        if out.tile_occupancy is not None:
            # per-map-row-tile occupancy from the device lanes: the
            # load-balance signal for mesh runs (summary.py validates it
            # sums to `occupied`)
            extra["tile_occupancy"] = [
                int(v) for v in out.tile_occupancy
            ]
        return {
            "type": "step",
            "step": self.stats["replayed"],
            **extra,
            "alive": out.n_alive,
            "rows": out.n_rows,
            "occupied": out.n_occupied,
            "mm_mass": out.mm_mass,
            "cm_mass": out.cm_mass,
            "kills": n_kills,
            "divisions": n_divided,
            "spawned": n_spawned,
            "genome_len_mean": round(sum(lens) / n, 3) if n else 0.0,
            "genome_len_max": max(lens, default=0),
            "total_kills": self.stats["kills"],
            "total_divisions": self.stats["divisions"],
            "total_spawned": self.stats["spawned"],
            "total_mutations": self.stats["genome_changes"],
        }

    def _apply_perm(self, perm: np.ndarray, n_keep: int) -> None:
        if self._token_store is not None:
            self._token_store.permute(perm, n_keep)
        else:
            self._genomes = [self._genomes[i] for i in perm]  # graftlint: disable=GL023 string-backend fallback
        self._labels = [self._labels[i] for i in perm]
        self._lifetimes = self._lifetimes[perm]
        self._divisions = self._divisions[perm]
        self._positions = self._positions[perm]
        self._last_change = self._last_change[perm]
        self._alive = np.zeros(self._cap, dtype=bool)
        self._alive[:n_keep] = True
        for i in range(n_keep, self._cap):
            if self._genomes is not None:  # graftlint: disable=GL023 string-backend fallback
                self._genomes[i] = ""  # graftlint: disable=GL023 string-backend fallback
            self._labels[i] = ""
        self._lifetimes[n_keep:] = 0
        self._divisions[n_keep:] = 0
        self._positions[n_keep:] = 0
        self._last_change[n_keep:] = -1
        self._n_rows = n_keep

    def _evolution_compute(
        self, rows: np.ndarray, pos_rows: np.ndarray, repush: dict[int, str]
    ) -> dict[int, str]:
        """The evolution phase's COMPUTE half: recombination + point
        mutations over the live rows, returning the changed-genome dict.
        Reads shared state but never writes it, so it can run on the
        evolution worker while the main thread dispatches the next step
        (the join discipline in :meth:`_replay` guarantees nothing
        mutates genomes/positions while it runs); ``rows``/``pos_rows``
        are main-thread snapshots.  All rng draws come from
        ``self._rng``, which only this phase uses — a single FIFO worker
        keeps their order deterministic."""
        changed: dict[int, str] = dict(repush)

        # recombination among Moore neighbors (workload order: first)
        if len(rows) > 1 and self.p_recombination > 0:
            pairs_k = moore_pairs(pos_rows, self.world.map_size)
            if len(pairs_k):
                pair_rows = rows[pairs_k]
                seed = int(self._rng.integers(2**63))
                for g0, g1, k in _engine.recombinations_indexed(
                    self._genomes,  # graftlint: disable=GL023 string-backend fallback
                    pair_rows, p=self.p_recombination,
                    seed=seed,
                ):
                    r0, r1 = pair_rows[k]
                    changed[int(r0)] = g0
                    changed[int(r1)] = g1

        # point mutations (on the post-recombination genomes: overlay
        # this round's recombinants without touching the shared list)
        if len(rows) and self.p_mutation > 0:
            seqs = [
                changed.get(int(r), self._genomes[int(r)])  # graftlint: disable=GL023 string-backend fallback
                for r in rows
            ]
            seed = int(self._rng.integers(2**63))
            for g, i in _engine.point_mutations(  # graftlint: disable=GL023 string-backend fallback
                seqs, p=self.p_mutation, p_indel=self.p_indel,
                p_del=self.p_del, seed=seed,
            ):
                changed[int(rows[i])] = g
        return changed

    def _evolution_compute_tokens(
        self, rows: np.ndarray, pos_rows: np.ndarray, repush_rows
    ) -> list[int]:
        """Token-mode evolution: the SAME phase as
        :meth:`_evolution_compute`, but as two jitted kernel dispatches
        over the device store instead of per-string host engine calls.
        Runs on the main thread (no worker: jax dispatch is
        single-owner) and returns the changed ROW indices — row content
        lives in the store.  RNG draw order matches the string path
        (recombination seed first, then mutation seed) so both backends
        consume ``self._rng`` identically."""
        from magicsoup_tpu import genomes as _genomes

        store = self._token_store
        changed_rows: set[int] = set(int(r) for r in repush_rows)
        det = self.world.deterministic

        if len(rows) > 1 and self.p_recombination > 0:
            pairs_k = moore_pairs(pos_rows, self.world.map_size)
            if len(pairs_k):
                pair_rows = rows[pairs_k]
                seed = int(self._rng.integers(2**63))
                store.ensure_length_cap(
                    _genomes.length_capacity(2 * store.max_length())
                )
                t, l, ch = _genomes.recombinations_tokens(
                    store.tokens,
                    store.lengths,
                    pair_rows,
                    p=self.p_recombination,
                    seed=seed,
                    det=det,
                )
                store.apply(t, l)
                changed_rows.update(
                    np.nonzero(_fetch_host(ch))[0].tolist()
                )

        if len(rows) and self.p_mutation > 0:
            store.maybe_regrow()
            live = np.zeros(store.capacity, dtype=bool)
            live[rows] = True
            seed = int(self._rng.integers(2**63))
            t, l, ch = _genomes.point_mutations_tokens(
                store.tokens,
                store.lengths,
                p=self.p_mutation,
                p_indel=self.p_indel,
                p_del=self.p_del,
                seed=seed,
                live=store._place(live),
                det=det,
            )
            store.apply(t, l)
            changed_rows.update(np.nonzero(_fetch_host(ch))[0].tolist())
        return sorted(changed_rows)

    def _submit_evolution(self, repush: dict[int, "str | None"]) -> None:
        """Kick off the evolution phase for the just-replayed state —
        on the worker when overlap is on, inline otherwise.  Token mode
        is always inline (main-thread kernel dispatches) and tracks
        changed rows, not strings."""
        from functools import partial

        rows = np.nonzero(self._alive)[0]
        pos_rows = self._positions[rows]  # fancy indexing: already a copy
        if self._token_store is not None:
            changed_rows = self._evolution_compute_tokens(
                rows, pos_rows, list(repush)
            )
            self._apply_evolution_rows(changed_rows)
            return
        if self._evo_worker is not None:
            self._evo_future = self._evo_worker.submit(
                partial(self._evolution_compute, rows, pos_rows, repush)
            )
        else:
            self._apply_evolution(
                self._evolution_compute(rows, pos_rows, repush)
            )

    def _join_evolution(self) -> None:
        """Wait for (and apply) the in-flight evolution phase, if any.
        Called at the start of every replay — before anything touches
        genomes or positions — and at drain(block=True)."""
        fut = self._evo_future
        if fut is None:
            return
        self._evo_future = None
        self._apply_evolution(fut.result(timeout=300.0))

    def _apply_evolution_rows(self, changed_rows: list[int]) -> None:
        """Token-mode apply half: the store already holds the new rows;
        queue their hash-keyed phenotype refresh (genomes=None — content
        is resolved from the store at push-dispatch time, so a row
        changed twice naturally pushes its final content)."""
        if not changed_rows:
            return
        self.stats["genome_changes"] += len(changed_rows)
        self._change_seq += 1
        self._last_change[changed_rows] = self._change_seq
        if self._compact_outstanding:
            self._push_buffer.append(
                (None, list(changed_rows), self._change_seq)
            )
        else:
            self._dispatch_push(
                None, list(changed_rows), self._change_seq
            )

    def _apply_evolution(self, changed: dict[int, str]) -> None:
        """The evolution phase's APPLY half (main thread only): write the
        changed genomes and queue their phenotype refresh.  Runs under
        the same compaction routing as any other push — if a compaction
        is in flight, the batch waits in the push buffer for its row
        permutation."""
        for r, g in changed.items():
            self._genomes[r] = g  # graftlint: disable=GL023 string-backend fallback
        if changed:
            self.stats["genome_changes"] += len(changed)
            rows_c = sorted(changed)
            genomes_c = [changed[r] for r in rows_c]
            self._change_seq += 1
            self._last_change[rows_c] = self._change_seq
            if self._compact_outstanding:
                # row ids shift at the in-flight compaction; hold the
                # push until its replay provides the permutation
                self._push_buffer.append(
                    (genomes_c, rows_c, self._change_seq)
                )
            else:
                self._dispatch_push(genomes_c, rows_c, self._change_seq)

    def _dispatch_push(
        self, genomes: list[str], rows: list[int], seq: int
    ) -> None:
        """Queue the phenotype refresh for changed genomes; it rides the
        next step dispatch (one fewer program round trip).  Rows that
        died since the genome change receive stale parameters; those rows
        are alive-masked everywhere and fold out at the next compaction,
        so the write is harmless."""
        self._push_queue.append((genomes, rows, seq))

    def _apply_push_now(
        self, genomes: "list[str] | None", rows: list[int], seq: int
    ) -> None:
        """Apply one refresh batch with its own standalone program (used
        for oversized bursts and at flush, when no step dispatch
        follows).  ``genomes=None`` is the token-mode spelling: content
        comes from the store, translated through the hash-keyed cache."""
        entries = self._push_entries(genomes, rows)
        self.kin.set_cell_params_cached(rows, entries, self.world.phenotypes)
        self._dispatched_seq = max(self._dispatched_seq, seq)
        self.stats["pushes"] += 1

    def _push_entries(self, genomes: "list[str] | None", rows: list[int]):
        """Phenotype entries for one refresh batch — string-keyed lookup
        on the string backend, hash-keyed token lookup (no decode unless
        a row misses) on the token backend."""
        if genomes is not None:
            return self.world.phenotypes.lookup(genomes)
        tokens, lengths = self._token_store.host_arrays()
        return self.world.phenotypes.lookup_tokens(tokens, lengths, rows)

    def _take_ride_push(self):
        """Pop queued refreshes (in order) up to the fixed riding block
        and return their phenotype-cache entries + rows, or None.  The
        block size is FIXED so the fused step program compiles for at
        most one push shape; a batch bigger than the block gets its own
        standalone dispatch (rare burst), and queue order is never
        reordered across dispatch boundaries — for a row changed twice,
        the newest genome's parameters must land last."""
        taken: list[tuple[list[str], list[int], int]] = []
        total = 0
        while self._push_queue:
            g, r, seq = self._push_queue[0]
            if len(r) > self.push_block:
                if taken:
                    break  # keep order; the burst goes next dispatch
                self._push_queue.pop(0)
                self._apply_push_now(g, r, seq)
                continue
            if total + len(r) > self.push_block:
                break
            taken.append(self._push_queue.pop(0))
            total += len(r)
        if not taken:
            return None
        # duplicate rows across taken batches: the LAST queued genome
        # wins (dict update order) — one scatter with repeated indices
        # would apply them in undefined order.  Token batches (g=None)
        # carry no content at all: the store row is already final, so
        # merging is a plain row union
        merged: dict[int, "str | None"] = {}
        top_seq = self._dispatched_seq
        for g, r, seq in taken:
            merged.update(zip(r, g) if g is not None else ((i, None) for i in r))
            top_seq = max(top_seq, seq)
        rows = sorted(merged)
        if self._token_store is not None:
            entries = self._push_entries(None, rows)
        else:
            entries = self.world.phenotypes.lookup(
                [merged[r] for r in rows]
            )
        self._dispatched_seq = top_seq
        self.stats["pushes"] += 1
        return entries, rows

    def _densify_push(self, entries, rows):
        """Cache entries -> (dense, rows) device inputs at the FIXED push
        block shape.  Separate from :meth:`_take_ride_push` so all of a
        dispatch's capacity growth happens before any densify."""
        dense = self.world.phenotypes.dense_rows(
            entries, self.kin.max_proteins, self.kin.max_doms
        )
        dense_pad = np.zeros(
            (self.push_block,) + dense.shape[1:], dtype=dense.dtype
        )
        dense_pad[: len(rows)] = dense
        rows_pad = np.full(self.push_block, self._cap, dtype=np.int32)
        rows_pad[: len(rows)] = rows
        return self._dev(dense_pad), self._dev(rows_pad)

    # -------------------------------------------------------------- #
    # compiled-variant management                                    #
    # -------------------------------------------------------------- #

    def _empty_spawn(self) -> tuple[jax.Array, jax.Array]:
        """Cached all-zero spawn buffers at the current token capacities —
        device-resident so spawnless steps upload nothing."""
        key = ("spawn", self.kin.max_proteins, self.kin.max_doms)
        if key not in self._empty_cache:
            self._empty_cache[key] = (
                jnp.zeros(
                    (self.spawn_block, self.kin.max_proteins,
                     self.kin.max_doms, 5),
                    dtype=jnp.int16,
                    device=self._rep_sh,
                ),
                jnp.zeros(
                    self.spawn_block, dtype=bool, device=self._rep_sh
                ),
            )
        return self._empty_cache[key]

    def _empty_push(self) -> tuple[jax.Array, jax.Array]:
        """Cached all-zero/all-OOB push buffers.  The OOB row sentinel is
        INT32_MAX — not the current capacity, which a concurrent
        background build racing a capacity growth could capture stale,
        leaving rows that become IN-bounds after the doubling and would
        silently zero a live cell's params every pushless step."""
        key = ("push", self.kin.max_proteins, self.kin.max_doms)
        if key not in self._empty_cache:
            self._empty_cache[key] = (
                jnp.zeros(
                    (self.push_block, self.kin.max_proteins,
                     self.kin.max_doms, 5),
                    dtype=jnp.int16,
                    device=self._rep_sh,
                ),
                jnp.full(
                    self.push_block,
                    jnp.iinfo(jnp.int32).max,
                    dtype=jnp.int32,
                    device=self._rep_sh,
                ),
            )
        return self._empty_cache[key]

    def prewarm(self, *, q: int | None = None, compact: bool = False) -> None:
        """Compile (and persistently cache) the fused step program's
        ``(q, compact)`` variant WITHOUT advancing the simulation.  The
        step dispatch does this automatically one q-rung ahead in a
        background thread; call it explicitly (plus :meth:`wait_warm`)
        before a timing window so no remote compile can land inside it."""
        if q is None:
            if self._mesh is not None:
                # mesh dispatch always runs the full capacity (see step():
                # prefix-slicing a sharded axis would redistribute) — one
                # variant covers every population size
                self.prewarm(q=self._cap, compact=compact)
                return
            # warm the rung the current population uses AND the one above
            # it: before the first dispatch nothing is compiled yet, so
            # 'current' is only a no-op when a step already ran
            cur = quantize_rows(self._n_rows, self._cap)
            self.prewarm(q=cur, compact=compact)
            if (nxt := next_rung(cur, self._cap)) != cur:
                self.prewarm(q=nxt, compact=compact)
            return
        spawn_dense, spawn_valid = self._empty_spawn()
        push_dense, push_rows = self._empty_push()
        # warm on THROWAWAY zero-filled stand-ins, never the live state:
        # the program donates (state, params), so executing it on
        # `self._state` would DELETE the live buffers — and zeros built
        # from shape/dtype metadata (which survives donation) also make
        # this safe to run from the background warm thread while the
        # main thread's dispatch consumes the real arrays
        if self._mesh is not None:
            # shardings are part of the compiled-program key: warm
            # stand-ins must match the live arrays' placements exactly
            # or this compiles a variant the real dispatch never hits
            zeros = functools.partial(
                jax.tree_util.tree_map,
                lambda t: jnp.zeros(t.shape, t.dtype, device=t.sharding),
            )
        else:
            zeros = functools.partial(
                jax.tree_util.tree_map,
                lambda t: jnp.zeros(t.shape, t.dtype),  # graftlint: disable=GL009 single-device branch; committing the stand-ins would warm a variant the live dispatch never hits
            )
        step_fn = self._step_fn()
        step_fn(
            zeros(self._state),
            zeros(self.kin.params),
            self._kernels_dev,
            self._perm_dev,
            self._degrad_dev,
            self._mol_idx_dev,
            self._kill_below_dev,
            self._divide_above_dev,
            self._divide_cost_dev,
            self._dev(0, jnp.int32),
            spawn_dense,
            spawn_valid,
            push_dense,
            push_rows,
            self._tables(),
            self._abs_temp_dev,
            det=self.world.deterministic,
            max_div=self.max_divisions,
            n_rounds=self.n_rounds,
            compact=compact,
            q=q,
            integrator=self.world.integrator,
        )

    def _step_fn(self):
        """The dispatched step program: donated on accelerators, the
        retained twin on CPU (see _pipeline_step_retained).  k == 1
        keeps the classic single-step program — the megastep wrapper
        would trace an identical body, but this preserves the exact
        program/jit-cache identity previous releases dispatched."""
        if self.megastep == 1:
            base = _pipeline_step if self._donate else _pipeline_step_retained
            if self._mesh is None:
                # bare function, not a partial: preserves the exact
                # callable identity previous releases dispatched
                return base
            return functools.partial(base, mesh=self._mesh)
        base = _megastep if self._donate else _megastep_retained
        if self._mesh is None:
            return functools.partial(base, k=self.megastep)
        return functools.partial(base, k=self.megastep, mesh=self._mesh)

    def _variant_key(self, q: int, compact: bool) -> tuple:
        # token capacities are in the key: growing them reshapes the
        # params/spawn/push inputs, invalidating every compiled variant —
        # stale-capacity entries then simply never match again.  megastep
        # is in the key so steppers with different K (fixed per instance)
        # never mistake each other's variants for warm
        return (
            q, compact, self.megastep, self._n_tiles,
            self.kin.max_proteins, self.kin.max_doms,
        )

    def _note_warm(self, q: int, compact: bool) -> None:
        """Record a just-dispatched variant as compiled and keep the
        q ladder warm ONE RUNG AHEAD (plus the compact variants) in a
        background thread, so population growth or a scheduled
        compaction never meets a cold remote compile mid-run."""
        self._warm_sched.mark(self._variant_key(q, compact))
        if not self._async:
            # local compiles: first use compiles synchronously, which is
            # both cheap and the only thread-safe option on this backend
            return
        nxt = next_rung(q, self._cap)
        wanted = [
            self._variant_key(q, True),
            self._variant_key(nxt, False),
            self._variant_key(nxt, True),
        ]
        self._warm_sched.schedule(
            wanted, lambda k: self.prewarm(q=k[0], compact=k[1])
        )

    def wait_warm(self, timeout: float | None = None) -> None:
        """Block until any in-flight background compile warmer finishes —
        benchmarks call this after their warmup phase so the measured
        window starts with every nearby variant compiled."""
        self._warm_sched.wait(timeout)

    def _flush_push_queue(self) -> None:
        """Apply ALL queued refreshes standalone (used before a flush
        sync, when no step dispatch follows)."""
        for genomes, rows, seq in self._push_queue:
            self._apply_push_now(genomes, rows, seq)
        self._push_queue = []

    # -------------------------------------------------------------- #
    # flush                                                          #
    # -------------------------------------------------------------- #

    def flush(self) -> None:
        """Drain the pipeline, compact, and sync everything back into the
        attached :class:`World` (dense reference-style indices again)."""
        self._drain(block=True)
        # refreshes queued by the final replays have no next dispatch to
        # ride — apply them now so world params match world genomes
        self._flush_push_queue()
        n_keep = int(self._alive.sum())
        if self._n_rows != n_keep or not self._alive[:n_keep].all():
            perm = np.argsort(~self._alive, kind="stable")
            compact_fn = (
                _compact_program if self._donate else _compact_program_retained
            )
            if self._mesh is not None:
                compact_fn = functools.partial(compact_fn, mesh=self._mesh)
            with self.telemetry.span("compact"):
                self._state, self.kin.params = compact_fn(
                    self._state,
                    self.kin.params,
                    self._dev(perm.astype(np.int32)),
                    self._dev(n_keep, jnp.int32),
                )
            self._apply_perm(perm, n_keep)

        w = self.world
        w.n_cells = n_keep
        if self._token_store is not None:
            # hand the token arrays back wholesale — no decode, no
            # encode; the world's store takes ownership of the arrays
            # (functional updates make sharing safe)
            w._genome_store.adopt(
                self._token_store.tokens, self._token_store.lengths
            )
        else:
            w.cell_genomes = [self._genomes[i] for i in range(n_keep)]  # graftlint: disable=GL023 string-backend flush boundary
        w.cell_labels = [self._labels[i] for i in range(n_keep)]
        w._np_positions = self._positions.copy()
        w._np_lifetimes = self._lifetimes.copy()
        w._np_divisions = self._divisions.copy()
        cmap = np.zeros((w.map_size, w.map_size), dtype=bool)
        live = self._positions[:n_keep]
        cmap[live[:, 0], live[:, 1]] = True
        w._np_cell_map = cmap
        w._molecule_map = self._state.mm
        w._cell_molecules = self._state.cm
        w._positions_dev = self._state.pos
        w._mm_cache = None
        w._cm_cache = None
        # the World is now the source of truth; the next step() re-pulls
        # it so classic-API mutations in between are picked up.  Stamp
        # the World's identity as we leave it: if nothing mutates it
        # before the re-attach, the rebuild is skipped (fast re-attach)
        self._needs_attach = True
        self._flush_token = self._world_token()
        # a flush is a natural reporting boundary: land a counters row
        # (gives the summarizer a fresh "last" for deltas) and push the
        # buffered JSONL through to disk
        self.telemetry.emit_counters()
        self.telemetry.flush()

    def check_consistency(self) -> None:
        """Assert device and replayed-host state agree (test helper; costs
        full fetches — do not call in hot loops)."""
        occ = np.asarray(_fetch_host(self._state.occ))
        pos = np.asarray(_fetch_host(self._state.pos))
        alive_dev = np.asarray(_fetch_host(self._state.alive))
        n_rows_dev = int(_fetch_host(self._state.n_rows))
        assert n_rows_dev == self._n_rows, (n_rows_dev, self._n_rows)
        assert (alive_dev == self._alive).all()
        live = np.nonzero(self._alive)[0]
        assert (pos[live] == self._positions[live]).all()
        want_occ = np.zeros_like(occ)
        want_occ[self._positions[live, 0], self._positions[live, 1]] = True
        assert (occ == want_occ).all()
        assert len(np.unique(
            self._positions[live, 0].astype(np.int64) * occ.shape[0]
            + self._positions[live, 1]
        )) == len(live)
