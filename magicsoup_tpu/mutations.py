"""
Genome mutation functions: point mutations and recombinations.

Parity reference: `python/magicsoup/mutations.py:4-51` — same semantics and
defaults (p=1e-6 per bp, 40% indels of which 66% deletions; strand breaks at
p=1e-7 per bp), same return shape (only changed sequences, with their input
indices).  Backed by the C++/OpenMP genome engine (Python fallback available);
unlike the reference a ``seed`` can be passed for reproducible streams.
"""
import random

from magicsoup_tpu.native import engine as _engine


def point_mutations(
    seqs: list[str],
    p: float = 1e-6,
    p_indel: float = 0.4,
    p_del: float = 0.66,
    seed: int | None = None,
) -> list[tuple[str, int]]:
    """
    Add point mutations to a list of nucleotide sequences.

    Arguments:
        seqs: nucleotide sequences
        p: probability of a mutation per base pair
        p_indel: probability of any point mutation being an indel
            (vs. a substitution)
        p_del: probability of any indel being a deletion (vs. an insertion)
        seed: optional seed for a reproducible mutation stream

    Returns:
        List of mutated sequences and their indices in `seqs`; sequences
        without any mutation are not returned.
    """
    if seed is None:
        seed = random.SystemRandom().randrange(2**63)  # graftlint: disable=GL004 entropy only when the caller passed no seed
    return _engine.point_mutations(seqs, p=p, p_indel=p_indel, p_del=p_del, seed=seed)


def recombinations(
    seq_pairs: list[tuple[str, str]],
    p: float = 1e-7,
    seed: int | None = None,
) -> list[tuple[str, str, int]]:
    """
    Recombine pairs of nucleotide sequences through random strand breaks
    and random re-joining (length-conserving over each pair).

    Arguments:
        seq_pairs: nucleotide sequence pairs
        p: probability of a strand break per base pair
        seed: optional seed for a reproducible stream

    Returns:
        List of recombined sequence pairs and their indices in `seq_pairs`;
        pairs without any strand break are not returned.
    """
    if seed is None:
        seed = random.SystemRandom().randrange(2**63)  # graftlint: disable=GL004 entropy only when the caller passed no seed
    return _engine.recombinations(seq_pairs, p=p, seed=seed)


def _lazy_genomes():
    # the token kernels live in magicsoup_tpu.genomes and pull in jax;
    # importing lazily keeps this module usable for pure host-string
    # work (the engine above is jax-free)
    from magicsoup_tpu import genomes

    return genomes


def point_mutations_tokens(tokens, lengths, **kwargs):
    """Device-kernel counterpart of :func:`point_mutations` over packed
    token arrays: one jitted program mutates the whole population in
    place of the per-string host loop.  Returns
    ``(tokens, lengths, changed)`` — see
    :func:`magicsoup_tpu.genomes.point_mutations_tokens`."""
    return _lazy_genomes().point_mutations_tokens(tokens, lengths, **kwargs)


def recombinations_tokens(tokens, lengths, pairs, **kwargs):
    """Device-kernel counterpart of :func:`recombinations` over packed
    token arrays and an ``(n, 2)`` row-pair index array.  Returns
    ``(tokens, lengths, changed)`` — see
    :func:`magicsoup_tpu.genomes.recombinations_tokens`."""
    return _lazy_genomes().recombinations_tokens(
        tokens, lengths, pairs, **kwargs
    )
