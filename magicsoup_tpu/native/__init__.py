"""
Native layer of the framework: the host-side genome engine.

See :mod:`magicsoup_tpu.native.engine` (C++/ctypes primary) and
:mod:`magicsoup_tpu.native._pyengine` (pure-Python fallback + shared
lookup-table containers).
"""
from magicsoup_tpu.native.engine import (
    TranslationTables,
    has_native,
    pack_dense,
    point_mutations,
    recombinations,
    translate_genomes_flat,
)

__all__ = [
    "TranslationTables",
    "has_native",
    "pack_dense",
    "point_mutations",
    "recombinations",
    "translate_genomes_flat",
]
