"""
Pure-Python/numpy fallback implementation of the genome engine.

The genome engine is the host-side string-processing layer of the framework:
genome -> proteome translation, point mutations, and recombinations.  The
primary implementation is the multithreaded C++ library
(`magicsoup_tpu/native/src/genome.cpp`, loaded via
:mod:`magicsoup_tpu.native.engine`); this module provides the same flat-array
interface in pure Python/numpy so the framework works without a compiler.

Parity reference for the algorithms: `rust/genetics.rs:13-204` (per-frame
start stacks, nested/overlapping CDS emission, domain extraction with 3-nt /
21-nt jumps) and `rust/mutations.rs:11-154` (Poisson mutation counts,
distinct sorted positions, indel offset tracking, strand-break
recombination).

Flat translation output format (shared with the C++ engine):

- ``prot_counts``: int32 (n_genomes,) — number of proteins per genome
- ``prots``: int32 (P, 4) — per protein ``[cds_start, cds_end, is_fwd, n_doms]``
- ``doms``: int32 (D, 7) — per domain ``[dom_type, i0, i1, i2, i3, start, end]``

Proteins are ordered genome-by-genome; domains protein-by-protein.
"""
import numpy as np

from magicsoup_tpu.constants import CODON_SIZE

# nucleotide byte -> 2-bit code; order TCGA mirrors ALL_NTS.
# Unknown characters map to a sentinel so codons containing them are
# treated as matching nothing (the reference's Rust engine panics on
# them inside domain specs; here they are gracefully inert).
_NT_INVALID = 64
_NT_CODE = np.full(256, _NT_INVALID, dtype=np.uint8)
for _i, _nt in enumerate("TCGA"):
    _NT_CODE[ord(_nt)] = _i

_COMPLEMENT = bytes.maketrans(b"ACTG", b"TGAC")


def codon_code(codon: str) -> int:
    """Encode a 3-nt codon as a base-4 integer (T=0, C=1, G=2, A=3)"""
    c = [int(_NT_CODE[ord(d)]) for d in codon]
    return c[0] * 16 + c[1] * 4 + c[2]


def seq_code(seq: str) -> int:
    """Encode an arbitrary-length nt sequence as a base-4 integer"""
    code = 0
    for d in seq:
        code = code * 4 + int(_NT_CODE[ord(d)])
    return code


class TranslationTables:
    """
    Integer lookup tables derived from the Genetics token maps; consumed by
    both the Python and the C++ engine.

    - ``codon_flags``: uint8 (64,) — 1 for start codons, 2 for stop codons
    - ``dom_type_lut``: uint8 (4^(2*CODON_SIZE),) — 2-codon seq code ->
      domain type (0 = no domain)
    - ``one_codon_lut``: int32 (64,) — codon code -> scalar token (1-based)
    - ``two_codon_lut``: int32 (4096,) — 2-codon code -> vector token (1-based)
    """

    def __init__(
        self,
        start_codons: list[str],
        stop_codons: list[str],
        domain_map: dict[str, int],
        one_codon_map: dict[str, int],
        two_codon_map: dict[str, int],
        dom_size: int,
        dom_type_size: int,
    ):
        self.dom_size = dom_size
        self.dom_type_size = dom_type_size

        self.codon_flags = np.zeros(64, dtype=np.uint8)
        for codon in start_codons:
            self.codon_flags[codon_code(codon)] = 1
        for codon in stop_codons:
            self.codon_flags[codon_code(codon)] = 2

        # dom_type_size is in nucleotides (default 6 -> 4096 entries)
        self.dom_type_lut = np.zeros(4**dom_type_size, dtype=np.uint8)
        for seq, dom_type in domain_map.items():
            self.dom_type_lut[seq_code(seq)] = dom_type

        self.one_codon_lut = np.zeros(64, dtype=np.int32)
        for codon, idx in one_codon_map.items():
            self.one_codon_lut[codon_code(codon)] = idx

        self.two_codon_lut = np.zeros(4096, dtype=np.int32)
        for seq, idx in two_codon_map.items():
            self.two_codon_lut[seq_code(seq)] = idx


def _codon_codes(seq_bytes: bytes) -> np.ndarray:
    """Codon code at every nucleotide position i (code of seq[i:i+3]);
    -1 for codons containing a non-TCGA character."""
    nts = _NT_CODE[np.frombuffer(seq_bytes, dtype=np.uint8)].astype(np.int32)
    n = len(nts)
    if n < CODON_SIZE:
        return np.zeros(0, dtype=np.int32)
    c0, c1, c2 = nts[: n - 2], nts[1 : n - 1], nts[2:]
    codes = c0 * 16 + c1 * 4 + c2
    invalid = (c0 >= 4) | (c1 >= 4) | (c2 >= 4)
    return np.where(invalid, -1, codes)


def get_coding_regions(
    seq: str,
    min_cds_size: int,
    start_codons: list[str],
    stop_codons: list[str],
    is_fwd: bool,
) -> list[tuple[int, int, bool]]:
    """
    Find all CDSs using per-reading-frame start stacks: a stop codon closes
    *all* pending starts of its frame (nested/overlapping CDSs).  Emission
    order follows the single pass over the sequence: CDSs sorted by stop
    position, and for one stop the latest start comes first (LIFO pop).
    """
    flags = np.zeros(64, dtype=np.uint8)
    for codon in start_codons:
        flags[codon_code(codon)] = 1
    for codon in stop_codons:
        flags[codon_code(codon)] = 2
    return _coding_regions_from_codes(
        _codon_codes(seq.encode()), flags, min_cds_size, is_fwd
    )


def _coding_regions_from_codes(
    codes: np.ndarray, codon_flags: np.ndarray, min_cds_size: int, is_fwd: bool
) -> list[tuple[int, int, bool]]:
    res: list[tuple[int, int, bool]] = []
    if codes.shape[0] == 0:
        return res
    flags = np.where(codes >= 0, codon_flags[np.clip(codes, 0, None)], 0)
    interesting = np.nonzero(flags)[0]
    starts: list[list[int]] = [[], [], []]
    for i in interesting.tolist():
        frame = i % CODON_SIZE
        if flags[i] == 1:
            starts[frame].append(i)
        else:
            j = i + CODON_SIZE
            while starts[frame]:
                d = starts[frame].pop()
                if j - d >= min_cds_size:
                    res.append((d, j, is_fwd))
    return res


def _extract_domains_into(
    codes: np.ndarray,
    cdss: list[tuple[int, int, bool]],
    tables: TranslationTables,
    prots: list[list[int]],
    doms: list[list[int]],
) -> int:
    """Walk each CDS, appending protein/domain rows; returns #proteins"""
    dom_size = tables.dom_size
    dom_type_size = tables.dom_type_size
    n_codes = codes.shape[0]
    # code of the dom_type_size-nt sequence starting at i
    # (for the default 6-nt type region: codes[i]*64 + codes[i+3])
    n_prots = 0
    for cds_start, cds_stop, is_fwd in cdss:
        n = cds_stop - cds_start
        i = 0
        is_useful = False
        my_doms: list[list[int]] = []
        while i + dom_size <= n:
            dom_start = cds_start + i
            type_code = 0
            ok = True
            for k in range(0, dom_type_size, CODON_SIZE):
                p = dom_start + k
                if p >= n_codes or codes[p] < 0:
                    ok = False
                    break
                type_code = type_code * 64 + int(codes[p])
            dom_type = int(tables.dom_type_lut[type_code]) if ok else 0
            if dom_type != 0:
                if dom_type != 3:
                    is_useful = True
                spec = dom_start + dom_type_size

                def tok1(p: int) -> int:
                    c = int(codes[p])
                    return int(tables.one_codon_lut[c]) if c >= 0 else 0

                i0 = tok1(spec)
                i1 = tok1(spec + CODON_SIZE)
                i2 = tok1(spec + 2 * CODON_SIZE)
                c3a = int(codes[spec + 3 * CODON_SIZE])
                c3b = int(codes[spec + 4 * CODON_SIZE])
                i3 = (
                    int(tables.two_codon_lut[c3a * 64 + c3b])
                    if c3a >= 0 and c3b >= 0
                    else 0
                )
                my_doms.append([dom_type, i0, i1, i2, i3, i, i + dom_size])
                i += dom_size
            else:
                i += CODON_SIZE
        if is_useful:
            prots.append([cds_start, cds_stop, int(is_fwd), len(my_doms)])
            doms.extend(my_doms)
            n_prots += 1
    return n_prots


def translate_genomes_flat(
    genomes: list[str], tables: TranslationTables
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """
    Translate genomes (forward + reverse-complement) into the flat proteome
    format documented in the module docstring.
    """
    prot_counts = np.zeros(len(genomes), dtype=np.int32)
    prots: list[list[int]] = []
    doms: list[list[int]] = []
    min_cds = tables.dom_size
    for gi, genome in enumerate(genomes):
        n_prots = 0
        fwd = genome.encode()
        codes = _codon_codes(fwd)
        cdss = _coding_regions_from_codes(codes, tables.codon_flags, min_cds, True)
        n_prots += _extract_domains_into(codes, cdss, tables, prots, doms)

        bwd = fwd.translate(_COMPLEMENT)[::-1]
        codes_b = _codon_codes(bwd)
        cdss_b = _coding_regions_from_codes(
            codes_b, tables.codon_flags, min_cds, False
        )
        n_prots += _extract_domains_into(codes_b, cdss_b, tables, prots, doms)
        prot_counts[gi] = n_prots

    prots_arr = np.array(prots, dtype=np.int32).reshape(-1, 4)
    doms_arr = np.array(doms, dtype=np.int32).reshape(-1, 7)
    return prot_counts, prots_arr, doms_arr


_NTS = "ACTG"  # reference mutation alphabet order (rust/mutations.rs:6)


def point_mutations_flat(
    seqs: list[str],
    n_muts_per_seq: np.ndarray,
    orig_idxs: np.ndarray,
    p_indel: float,
    p_del: float,
    seed: int,
) -> list[tuple[str, int]]:
    """
    Apply the given number of point mutations (substitutions and indels)
    to each sequence.  Mutation counts are pre-drawn by the caller
    (vectorized Poisson); per-sequence deterministic RNG stream derived
    from ``seed`` and the sequence's index in the caller's full
    population (``orig_idxs``), so outcomes don't depend on which other
    sequences were batched in.  Returns only mutated sequences with
    their input index (position within ``seqs``).
    """
    out: list[tuple[str, int]] = []
    for idx, seq in enumerate(seqs):
        n = len(seq)
        if n < 1:
            continue
        rng = np.random.default_rng(
            np.random.PCG64(seed * 1_000_003 + int(orig_idxs[idx]))
        )
        n_muts = int(n_muts_per_seq[idx])
        if n_muts < 1:
            continue
        n_muts = min(n_muts, n)
        positions = np.sort(rng.choice(n, size=n_muts, replace=False))
        chars = list(seq)
        offset = 0
        # graftlint: disable=GL007 indel offsets shift per mutation; the scalar loop IS the algorithm (fallback path)
        for pos in positions.tolist():
            cur = pos + offset
            if rng.random() < p_indel:
                if rng.random() < p_del:
                    del chars[cur]
                    offset -= 1
                else:
                    chars.insert(cur, _NTS[rng.integers(4)])
                    offset += 1
            else:
                chars[cur] = _NTS[rng.integers(4)]
        out.append(("".join(chars), idx))
    return out


def recombinations_flat(
    seq_pairs: list[tuple[str, str]],
    n_breaks_per_pair: np.ndarray,
    orig_idxs: np.ndarray,
    seed: int,
) -> list[tuple[str, str, int]]:
    """
    Recombine sequence pairs by the given numbers of strand breaks: both
    sequences are cut at random positions, all fragments shuffled, and a
    random split point reassembles two new sequences (length-conserving).
    Break counts are pre-drawn by the caller (vectorized Poisson);
    per-pair RNG streams are keyed by ``orig_idxs`` (the pair's index in
    the caller's full pair list) for batch-independence.
    Returns only recombined pairs with their input index.
    """
    out: list[tuple[str, str, int]] = []
    for idx, (seq0, seq1) in enumerate(seq_pairs):
        n0 = len(seq0)
        n1 = len(seq1)
        n_both = n0 + n1
        if n_both < 1:
            continue
        rng = np.random.default_rng(
            np.random.PCG64(seed * 1_000_003 + int(orig_idxs[idx]))
        )
        n_muts = int(n_breaks_per_pair[idx])
        if n_muts < 1:
            continue
        n_muts = min(n_muts, n_both)
        cut_positions = np.sort(rng.choice(n_both, size=n_muts, replace=False))

        parts: list[str] = []
        i = 0
        # graftlint: disable=GL007 per-pair cut lists are tiny; this is the pure-python fallback, native engine is primary
        for j in cut_positions[cut_positions < n0].tolist():
            parts.append(seq0[i:j])
            i = j
        parts.append(seq0[i:])
        i = 0
        # graftlint: disable=GL007 see above: per-pair fallback loop
        for j in (cut_positions[cut_positions >= n0] - n0).tolist():
            parts.append(seq1[i:j])
            i = j
        parts.append(seq1[i:])

        order = rng.permutation(len(parts))
        parts = [parts[k] for k in order.tolist()]  # graftlint: disable=GL007 per-pair fallback shuffle
        s = int(rng.integers(len(parts)))
        out.append(("".join(parts[:s]), "".join(parts[s:]), idx))
    return out


def pack_dense(
    prot_counts: np.ndarray,
    prots: np.ndarray,
    doms: np.ndarray,
    p_cap: int,
    d_cap: int,
) -> np.ndarray:
    """Pack flat translation buffers into the padded dense token tensor
    (b, p_cap, d_cap, 5) int16 [dom_type, i0, i1, i2, i3] — the numpy
    fallback of the native ``ms_pack_dense`` (vectorized scatter via the
    repeat/cumsum index expansion)."""
    b = len(prot_counts)
    dense = np.zeros((b, p_cap, d_cap, 5), dtype=np.int16)
    if len(doms) == 0:
        return dense
    n_doms_per_prot = prots[:, 3]
    # cell index of each protein / protein index within its cell
    prot_cell = np.repeat(np.arange(b, dtype=np.int64), prot_counts)
    prot_starts = np.concatenate([[0], np.cumsum(prot_counts)])[:-1]
    prot_in_cell = np.arange(len(prots), dtype=np.int64) - np.repeat(
        prot_starts, prot_counts
    )
    # protein index of each domain / domain index within its protein
    dom_prot = np.repeat(np.arange(len(prots), dtype=np.int64), n_doms_per_prot)
    dom_starts = np.concatenate([[0], np.cumsum(n_doms_per_prot)])[:-1]
    dom_in_prot = np.arange(len(doms), dtype=np.int64) - np.repeat(
        dom_starts, n_doms_per_prot
    )
    dense[prot_cell[dom_prot], prot_in_cell[dom_prot], dom_in_prot] = doms[:, :5]
    return dense
