"""
Loader and ctypes bindings for the native genome engine.

Compiles `src/genome.cpp` with g++ (``-O3 -fopenmp``) into the package
directory on first use and exposes the flat-array API.  If no compiler is
available (or ``MAGICSOUP_TPU_NO_NATIVE=1``), transparently falls back to the
pure-Python engine in :mod:`magicsoup_tpu.native._pyengine` — same
signatures, same flat formats.

This replaces the reference's Rust/PyO3 `magicsoup._lib` cdylib
(`rust/lib.rs:1-205`): string work runs on host threads (OpenMP instead of
rayon) with the GIL released for the duration of each call (ctypes does that
automatically).
"""
import ctypes
import os
import subprocess
import threading
import warnings
from pathlib import Path

import numpy as np

from magicsoup_tpu.native import _pyengine
from magicsoup_tpu.native._pyengine import TranslationTables

_SRC = Path(__file__).parent / "src" / "genome.cpp"
_LIB_PATH = Path(__file__).parent / "_libmsgenome.so"
_BUILD_LOCK = threading.Lock()

_i64p = ctypes.POINTER(ctypes.c_int64)
_i32p = ctypes.POINTER(ctypes.c_int32)
_u8p = ctypes.POINTER(ctypes.c_uint8)
_charp = ctypes.POINTER(ctypes.c_char)


def _build_lib() -> Path | None:
    """Compile the C++ engine if needed; returns the .so path or None"""
    if _LIB_PATH.exists() and _LIB_PATH.stat().st_mtime >= _SRC.stat().st_mtime:
        return _LIB_PATH
    with _BUILD_LOCK:
        if _LIB_PATH.exists() and _LIB_PATH.stat().st_mtime >= _SRC.stat().st_mtime:
            return _LIB_PATH
        tmp = _LIB_PATH.with_suffix(".so.tmp")
        cmd = [
            "g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
            "-fopenmp", str(_SRC), "-o", str(tmp),
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        except (subprocess.SubprocessError, FileNotFoundError) as err:
            warnings.warn(
                f"Could not build native genome engine ({err});"
                " falling back to the pure-Python engine."
            )
            return None
        os.replace(tmp, _LIB_PATH)
        return _LIB_PATH


def _load_lib():
    if os.environ.get("MAGICSOUP_TPU_NO_NATIVE") == "1":
        return None
    path = _build_lib()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(str(path))
        return _declare_abi(lib)
    except (OSError, AttributeError) as err:
        # e.g. a stale library from an older source revision that lacks a
        # newly-added symbol (can happen when another process rebuilt
        # concurrently) — fall back rather than crash
        warnings.warn(
            f"Could not load native genome engine ({err});"
            " falling back to the pure-Python engine."
        )
        return None


def _declare_abi(lib):
    lib.ms_free.argtypes = [ctypes.c_void_p]
    lib.ms_free.restype = None
    lib.ms_translate_genomes.argtypes = [
        _charp, _i64p, ctypes.c_int64,  # data, offsets, n
        _u8p, _u8p, _i32p, _i32p,  # codon_flags, dom_type_lut, 1c lut, 2c lut
        ctypes.c_int, ctypes.c_int, ctypes.c_int,  # dom_size, type_size, threads
        _i32p,  # prot_counts out
        ctypes.POINTER(_i32p), _i64p,  # prots, n_prots
        ctypes.POINTER(_i32p), _i64p,  # doms, n_doms
    ]
    lib.ms_translate_genomes.restype = None
    lib.ms_pack_dense.argtypes = [
        _i32p, ctypes.c_int64,  # prot_counts, b
        _i32p, ctypes.c_int64,  # prots, n_prots
        _i32p, ctypes.c_int64,  # doms, n_doms
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int,  # p_cap, d_cap, threads
        ctypes.POINTER(ctypes.c_int16),  # out_dense (caller-allocated, zeroed)
    ]
    lib.ms_pack_dense.restype = None
    lib.ms_point_mutations.argtypes = [
        _charp, _i64p, ctypes.c_int64,
        _i64p,  # pre-drawn per-seq mutation counts
        _i64p,  # original population indices (RNG stream keys)
        ctypes.c_float, ctypes.c_float,
        ctypes.c_uint64, ctypes.c_int,
        ctypes.POINTER(_charp), ctypes.POINTER(_i64p),
        ctypes.POINTER(_i64p), _i64p,
    ]
    lib.ms_point_mutations.restype = None
    lib.ms_recombinations.argtypes = [
        _charp, _i64p, ctypes.c_int64,
        _i64p,  # pre-drawn per-pair strand-break counts
        _i64p,  # original population indices (RNG stream keys)
        ctypes.c_uint64, ctypes.c_int,
        ctypes.POINTER(_charp), ctypes.POINTER(_i64p),
        ctypes.POINTER(_i64p), _i64p,
    ]
    lib.ms_recombinations.restype = None
    lib.ms_neighbor_pairs.argtypes = [
        _i32p, ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(_i32p), _i64p,
    ]
    lib.ms_neighbor_pairs.restype = None
    return lib


_LIB = None
_LIB_TRIED = False


def get_lib():
    """The loaded native library, or None if unavailable"""
    global _LIB, _LIB_TRIED
    if not _LIB_TRIED:
        _LIB = _load_lib()
        _LIB_TRIED = True
    return _LIB


def has_native() -> bool:
    return get_lib() is not None


def _concat(seqs: list[str]) -> tuple[bytes, np.ndarray]:
    """Concatenate strings into one byte buffer + (n+1,) int64 offsets"""
    offsets = np.zeros(len(seqs) + 1, dtype=np.int64)
    lens = np.array([len(s) for s in seqs], dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    return "".join(seqs).encode(), offsets


def translate_genomes_flat(
    genomes: list[str], tables: TranslationTables, n_threads: int = 0
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """
    Flat-format genome translation (see `_pyengine` docstring for the
    format).  Deterministic: the native and Python engines produce
    identical output.
    """
    lib = get_lib()
    if lib is None:
        return _pyengine.translate_genomes_flat(genomes, tables)

    data, offsets = _concat(genomes)
    n = len(genomes)
    prot_counts = np.zeros(n, dtype=np.int32)
    out_prots = _i32p()
    out_doms = _i32p()
    n_prots = ctypes.c_int64()
    n_doms = ctypes.c_int64()
    one_lut = np.ascontiguousarray(tables.one_codon_lut, dtype=np.int32)
    two_lut = np.ascontiguousarray(tables.two_codon_lut, dtype=np.int32)
    lib.ms_translate_genomes(
        ctypes.cast(data, _charp),
        offsets.ctypes.data_as(_i64p),
        n,
        tables.codon_flags.ctypes.data_as(_u8p),
        tables.dom_type_lut.ctypes.data_as(_u8p),
        one_lut.ctypes.data_as(_i32p),
        two_lut.ctypes.data_as(_i32p),
        tables.dom_size,
        tables.dom_type_size,
        n_threads,
        prot_counts.ctypes.data_as(_i32p),
        ctypes.byref(out_prots),
        ctypes.byref(n_prots),
        ctypes.byref(out_doms),
        ctypes.byref(n_doms),
    )
    try:
        prots = np.ctypeslib.as_array(out_prots, shape=(n_prots.value, 4)).copy()
        doms = np.ctypeslib.as_array(out_doms, shape=(n_doms.value, 7)).copy()
    finally:
        lib.ms_free(out_prots)
        lib.ms_free(out_doms)
    return prot_counts, prots, doms


def pack_dense(
    prot_counts: np.ndarray,
    prots: np.ndarray,
    doms: np.ndarray,
    p_cap: int,
    d_cap: int,
    n_threads: int = 0,
) -> np.ndarray:
    """
    Pack flat translation buffers into the padded dense token tensor
    ``(b, p_cap, d_cap, 5)`` int16 — OpenMP in the native engine,
    vectorized numpy scatter in the fallback.  Both produce identical
    bytes.  Proteins/domains must fit the caps (callers grow capacities
    for every batch of a dispatch first — the capacity rule of
    :meth:`Kinetics.ensure_token_capacity`).
    """
    lib = get_lib()
    if lib is None:
        return _pyengine.pack_dense(prot_counts, prots, doms, p_cap, d_cap)
    b = len(prot_counts)
    counts = np.ascontiguousarray(prot_counts, dtype=np.int32)
    prots_c = np.ascontiguousarray(prots, dtype=np.int32)
    doms_c = np.ascontiguousarray(doms, dtype=np.int32)
    dense = np.zeros((b, int(p_cap), int(d_cap), 5), dtype=np.int16)
    if b == 0 or len(doms_c) == 0:
        return dense
    lib.ms_pack_dense(
        counts.ctypes.data_as(_i32p),
        b,
        prots_c.ctypes.data_as(_i32p),
        len(prots_c),
        doms_c.ctypes.data_as(_i32p),
        len(doms_c),
        int(p_cap),
        int(d_cap),
        n_threads,
        dense.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
    )
    return dense


def _unpack_seqs(
    lib, out_data, out_offsets, out_idxs, n: int, seqs_per_item: int
) -> list[tuple]:
    """Decode (data, offsets, idxs) triple returned by a mutation call"""
    try:
        if n == 0:
            return []
        offs = np.ctypeslib.as_array(out_offsets, shape=(seqs_per_item * n + 1,))
        total = int(offs[-1])
        buf = ctypes.string_at(out_data, total)
        idxs = np.ctypeslib.as_array(out_idxs, shape=(n,))
        out = []
        for k in range(n):
            parts = tuple(
                buf[offs[seqs_per_item * k + j] : offs[seqs_per_item * k + j + 1]].decode()
                for j in range(seqs_per_item)
            )
            out.append(parts + (int(idxs[k]),))
        return out
    finally:
        lib.ms_free(out_data)
        lib.ms_free(out_offsets)
        lib.ms_free(out_idxs)


def point_mutations(
    seqs: list[str],
    p: float,
    p_indel: float,
    p_del: float,
    seed: int,
    n_threads: int = 0,
) -> list[tuple[str, int]]:
    """
    Point mutations; returns only mutated sequences with input indices.

    The Poisson(p*len) mutation count per sequence is drawn vectorized on
    the host first, and only the (typically very few) sequences with a
    nonzero count are handed to the string engine — per-call work scales
    with the number of mutated genomes, not the population
    (reference rust/mutations.rs:11-73 iterates all genomes per call).
    """
    if len(seqs) == 0:
        return []
    lens = np.fromiter((len(s) for s in seqs), dtype=np.int64, count=len(seqs))
    sel, counts = _poisson_select(lens, p, seed)
    if len(sel) == 0:
        return []
    sub = [seqs[int(i)] for i in sel]
    orig = sel.astype(np.int64)  # RNG streams keyed by original index
    lib = get_lib()
    if lib is None:
        out = _pyengine.point_mutations_flat(sub, counts, orig, p_indel, p_del, seed)
    else:
        data, offsets = _concat(sub)
        out_data = _charp()
        out_offsets = _i64p()
        out_idxs = _i64p()
        out_n = ctypes.c_int64()
        lib.ms_point_mutations(
            ctypes.cast(data, _charp),
            offsets.ctypes.data_as(_i64p),
            len(sub),
            counts.ctypes.data_as(_i64p),
            orig.ctypes.data_as(_i64p),
            p_indel, p_del,
            seed & 0xFFFFFFFFFFFFFFFF,
            n_threads,
            ctypes.byref(out_data),
            ctypes.byref(out_offsets),
            ctypes.byref(out_idxs),
            ctypes.byref(out_n),
        )
        out = _unpack_seqs(lib, out_data, out_offsets, out_idxs, out_n.value, 1)
    return [(s, int(sel[i])) for s, i in out]


def recombinations(
    seq_pairs: list[tuple[str, str]],
    p: float,
    seed: int,
    n_threads: int = 0,
) -> list[tuple[str, str, int]]:
    """
    Strand-break recombinations; returns only recombined pairs.

    Like :func:`point_mutations`, the Poisson(p*(len0+len1)) break count
    per pair is pre-drawn vectorized on the host so only pairs with a
    break reach the string engine.
    """
    if len(seq_pairs) == 0:
        return []
    lens = np.fromiter(
        (len(a) + len(b) for a, b in seq_pairs), dtype=np.int64, count=len(seq_pairs)
    )
    sel, counts = _poisson_select(lens, p, seed)
    if len(sel) == 0:
        return []
    sub = [seq_pairs[int(i)] for i in sel]
    return _recombinations_selected(sub, counts, sel, seed, n_threads)


def recombinations_indexed(
    genomes: list[str],
    pair_idxs: np.ndarray,
    p: float,
    seed: int,
    n_threads: int = 0,
) -> list[tuple[str, str, int]]:
    """
    :func:`recombinations` over index pairs into a genome list, avoiding
    the materialization of one string-pair tuple per candidate pair —
    with ~2.4 neighbor pairs per cell and a per-pair break probability of
    ~1e-4, building the pair list costs more than the recombination
    itself.  Draws the identical Poisson stream (pair-list order), so
    ``recombinations(pairs, ...)`` and
    ``recombinations_indexed(genomes, idxs, ...)`` produce the same
    result for the same pairs.  Returned index = row into ``pair_idxs``.
    """
    if len(pair_idxs) == 0:
        return []
    lens = np.fromiter(
        (len(g) for g in genomes), dtype=np.int64, count=len(genomes)
    )
    pair_lens = lens[pair_idxs[:, 0]] + lens[pair_idxs[:, 1]]
    sel, counts = _poisson_select(pair_lens, p, seed)
    if len(sel) == 0:
        return []
    sub = [
        (genomes[int(a)], genomes[int(b)])
        for a, b in pair_idxs[sel]
    ]
    return _recombinations_selected(sub, counts, sel, seed, n_threads)


def neighbor_pairs(positions: np.ndarray, map_size: int) -> np.ndarray | None:
    """Unique Moore-adjacent index pairs (smaller first, sorted) among
    ``(k, 2)`` positions — the C++ occupancy-grid scan (reference
    rust/world.rs:9-54).  Returns None when the native engine is absent
    (the caller falls back to the vectorized numpy construction)."""
    lib = get_lib()
    if lib is None:
        return None
    pos = np.ascontiguousarray(positions, dtype=np.int32)
    if len(pos) and (pos.min() < 0 or pos.max() >= map_size):
        # the C scan indexes an occupancy grid with these coordinates;
        # an out-of-range position would silently overflow the heap
        # (observed as 'corrupted size vs. prev_size' at exit), so fail
        # loudly at the boundary instead
        raise ValueError(
            f"positions out of range for map_size={map_size}: "
            f"min={pos.min()}, max={pos.max()}"
        )
    out_pairs = _i32p()
    out_n = ctypes.c_int64()
    lib.ms_neighbor_pairs(
        pos.ctypes.data_as(_i32p),
        len(pos),
        np.int32(map_size),
        ctypes.byref(out_pairs),
        ctypes.byref(out_n),
    )
    try:
        return (
            np.ctypeslib.as_array(out_pairs, shape=(out_n.value, 2))
            .astype(np.int64)
        )
    finally:
        lib.ms_free(out_pairs)


def _poisson_select(
    lens: np.ndarray, p: float, seed: int
) -> tuple[np.ndarray, np.ndarray]:
    """Pre-draw Poisson(p*len) counts; return (selected idxs, their counts)"""
    nprng = np.random.default_rng(np.random.PCG64(seed & 0xFFFFFFFFFFFFFFFF))
    n_breaks = nprng.poisson(p * lens)
    sel = np.nonzero(n_breaks > 0)[0]
    return sel, n_breaks[sel].astype(np.int64)


def _recombinations_selected(
    sub: list[tuple[str, str]],
    counts: np.ndarray,
    sel: np.ndarray,
    seed: int,
    n_threads: int,
) -> list[tuple[str, str, int]]:
    orig = sel.astype(np.int64)  # RNG streams keyed by original index
    lib = get_lib()
    if lib is None:
        out = _pyengine.recombinations_flat(sub, counts, orig, seed)
    else:
        flat = [s for pair in sub for s in pair]
        data, offsets = _concat(flat)
        out_data = _charp()
        out_offsets = _i64p()
        out_idxs = _i64p()
        out_n = ctypes.c_int64()
        lib.ms_recombinations(
            ctypes.cast(data, _charp),
            offsets.ctypes.data_as(_i64p),
            len(sub),
            counts.ctypes.data_as(_i64p),
            orig.ctypes.data_as(_i64p),
            seed & 0xFFFFFFFFFFFFFFFF,
            n_threads,
            ctypes.byref(out_data),
            ctypes.byref(out_offsets),
            ctypes.byref(out_idxs),
            ctypes.byref(out_n),
        )
        out = _unpack_seqs(lib, out_data, out_offsets, out_idxs, out_n.value, 2)
    return [(s0, s1, int(sel[i])) for s0, s1, i in out]
