// Native genome engine: genome->proteome translation, point mutations,
// and recombinations over flat byte buffers.
//
// This is the TPU-framework counterpart of the reference's Rust cdylib
// (rust/genetics.rs, rust/mutations.rs in mRcSchwering/magic-soup): the
// heavy string work stays on host, parallelized with OpenMP threads, and
// results are emitted as dense arrays that feed the JAX device path
// directly.  Exposed through a plain C ABI consumed via ctypes
// (magicsoup_tpu/native/engine.py); all buffers crossing the boundary are
// caller-owned or allocated here and released with ms_free.
//
// Translation algorithm parity (rust/genetics.rs:13-123):
//  - per-reading-frame start stacks; a stop codon pops ALL pending starts
//    of its frame (nested/overlapping CDSs), emitting those >= min_cds_size
//  - domain extraction walks each CDS; on a domain-type match it reads
//    3 one-codon tokens + 1 two-codon token and jumps dom_size nts,
//    otherwise advances one codon
//  - proteins with only regulatory domains are discarded
// Mutation parity (rust/mutations.rs:11-154): Poisson(p*len) mutation
// counts, distinct sorted positions, indel offset tracking; recombination
// via strand-break fragments, shuffle, random split.  RNG here is seeded
// per sequence (seed, index) for reproducibility -- the reference uses
// thread-local OS RNG and is not reproducible.

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <random>
#include <string>
#include <vector>

#if defined(_OPENMP)
#include <omp.h>
#endif

namespace {

constexpr int CODON = 3;

// nucleotide byte -> 2-bit code, order TCGA (matches ALL_NTS); non-TCGA
// bytes map to -1 so codons containing them match nothing (parity with
// the Python fallback engine's sentinel handling)
int8_t NT_CODE[256];
struct NtCodeInit {
  NtCodeInit() {
    std::memset(NT_CODE, -1, sizeof(NT_CODE));
    NT_CODE[(unsigned char)'T'] = 0;
    NT_CODE[(unsigned char)'C'] = 1;
    NT_CODE[(unsigned char)'G'] = 2;
    NT_CODE[(unsigned char)'A'] = 3;
  }
} nt_code_init;

char COMPLEMENT[256];
struct ComplementInit {
  ComplementInit() {
    for (int i = 0; i < 256; ++i) COMPLEMENT[i] = (char)i;
    COMPLEMENT[(unsigned char)'A'] = 'T';
    COMPLEMENT[(unsigned char)'T'] = 'A';
    COMPLEMENT[(unsigned char)'C'] = 'G';
    COMPLEMENT[(unsigned char)'G'] = 'C';
  }
} complement_init;

// codon code (base-4 over 3 nts) at every position i of seq
void codon_codes(const char* seq, int64_t n, std::vector<int32_t>& out) {
  out.clear();
  if (n < CODON) return;
  out.resize(n - CODON + 1);
  for (int64_t i = 0; i + CODON <= n; ++i) {
    int c0 = NT_CODE[(unsigned char)seq[i]];
    int c1 = NT_CODE[(unsigned char)seq[i + 1]];
    int c2 = NT_CODE[(unsigned char)seq[i + 2]];
    out[i] = (c0 < 0 || c1 < 0 || c2 < 0) ? -1 : c0 * 16 + c1 * 4 + c2;
  }
}

struct Cds {
  int64_t start;
  int64_t stop;
  uint8_t is_fwd;
};

// per-frame start stacks; stop pops all pending starts of its frame
void coding_regions(const std::vector<int32_t>& codes,
                    const uint8_t* codon_flags, int min_cds, uint8_t is_fwd,
                    std::vector<Cds>& out) {
  std::vector<int64_t> starts[3];
  for (int f = 0; f < 3; ++f) starts[f].reserve(12);
  const int64_t n = (int64_t)codes.size();
  for (int64_t i = 0; i < n; ++i) {
    if (codes[i] < 0) continue;
    uint8_t flag = codon_flags[codes[i]];
    if (flag == 0) continue;
    int frame = (int)(i % CODON);
    if (flag == 1) {
      starts[frame].push_back(i);
    } else {
      int64_t j = i + CODON;
      while (!starts[frame].empty()) {
        int64_t d = starts[frame].back();
        starts[frame].pop_back();
        if (j - d >= min_cds) out.push_back({d, j, is_fwd});
      }
    }
  }
}

// per-genome result buffers
struct GenomeResult {
  std::vector<int32_t> prots;  // rows of 4: cds_start, cds_end, is_fwd, n_doms
  std::vector<int32_t> doms;   // rows of 7: dt, i0, i1, i2, i3, start, end
  int32_t n_prots = 0;
};

void extract_domains(const std::vector<int32_t>& codes,
                     const std::vector<Cds>& cdss, int dom_size,
                     int dom_type_size, const uint8_t* dom_type_lut,
                     const int32_t* one_codon_lut,
                     const int32_t* two_codon_lut, GenomeResult& res) {
  const int64_t n_codes = (int64_t)codes.size();
  std::vector<int32_t> my_doms;
  for (const Cds& cds : cdss) {
    int64_t n = cds.stop - cds.start;
    int64_t i = 0;
    bool useful = false;
    my_doms.clear();
    while (i + dom_size <= n) {
      int64_t dom_start = cds.start + i;
      int32_t type_code = 0;
      bool in_range = true;
      for (int k = 0; k < dom_type_size; k += CODON) {
        int64_t p = dom_start + k;
        if (p >= n_codes || codes[p] < 0) {
          in_range = false;
          break;
        }
        type_code = type_code * 64 + codes[p];
      }
      uint8_t dom_type = in_range ? dom_type_lut[type_code] : 0;
      if (dom_type != 0) {
        if (dom_type != 3) useful = true;
        int64_t spec = dom_start + dom_type_size;
        auto tok1 = [&](int64_t p) -> int32_t {
          return codes[p] >= 0 ? one_codon_lut[codes[p]] : 0;
        };
        int32_t i0 = tok1(spec);
        int32_t i1 = tok1(spec + CODON);
        int32_t i2 = tok1(spec + 2 * CODON);
        int32_t c3a = codes[spec + 3 * CODON];
        int32_t c3b = codes[spec + 4 * CODON];
        int32_t i3 = (c3a >= 0 && c3b >= 0) ? two_codon_lut[c3a * 64 + c3b] : 0;
        int32_t row[7] = {(int32_t)dom_type, i0,
                          i1,                i2,
                          i3,                (int32_t)i,
                          (int32_t)(i + dom_size)};
        my_doms.insert(my_doms.end(), row, row + 7);
        i += dom_size;
      } else {
        i += CODON;
      }
    }
    if (useful) {
      int32_t prow[4] = {(int32_t)cds.start, (int32_t)cds.stop,
                         (int32_t)cds.is_fwd,
                         (int32_t)(my_doms.size() / 7)};
      res.prots.insert(res.prots.end(), prow, prow + 4);
      res.doms.insert(res.doms.end(), my_doms.begin(), my_doms.end());
      res.n_prots += 1;
    }
  }
}

}  // namespace

extern "C" {

void ms_free(void* ptr) { std::free(ptr); }

// Translate n genomes (concatenated bytes + n+1 offsets).  Writes per-genome
// protein counts to prot_counts (caller-allocated, n entries) and allocates
// *out_prots (rows of 4) and *out_doms (rows of 7); row counts via
// *out_n_prots / *out_n_doms.  Caller frees with ms_free.
void ms_translate_genomes(const char* data, const int64_t* offsets, int64_t n,
                          const uint8_t* codon_flags,
                          const uint8_t* dom_type_lut,
                          const int32_t* one_codon_lut,
                          const int32_t* two_codon_lut, int dom_size,
                          int dom_type_size, int n_threads,
                          int32_t* prot_counts, int32_t** out_prots,
                          int64_t* out_n_prots, int32_t** out_doms,
                          int64_t* out_n_doms) {
  std::vector<GenomeResult> results((size_t)n);

#if defined(_OPENMP)
  if (n_threads > 0) omp_set_num_threads(n_threads);
#pragma omp parallel
#endif
  {
    std::vector<int32_t> codes;
    std::vector<Cds> cdss;
    std::string revcomp;
#if defined(_OPENMP)
#pragma omp for schedule(dynamic, 8)
#endif
    for (int64_t gi = 0; gi < n; ++gi) {
      const char* seq = data + offsets[gi];
      int64_t len = offsets[gi + 1] - offsets[gi];
      GenomeResult& res = results[gi];

      cdss.clear();
      codon_codes(seq, len, codes);
      coding_regions(codes, codon_flags, dom_size, 1, cdss);
      extract_domains(codes, cdss, dom_size, dom_type_size, dom_type_lut,
                      one_codon_lut, two_codon_lut, res);

      revcomp.resize((size_t)len);
      for (int64_t i = 0; i < len; ++i)
        revcomp[len - 1 - i] = COMPLEMENT[(unsigned char)seq[i]];
      cdss.clear();
      codon_codes(revcomp.data(), len, codes);
      coding_regions(codes, codon_flags, dom_size, 0, cdss);
      extract_domains(codes, cdss, dom_size, dom_type_size, dom_type_lut,
                      one_codon_lut, two_codon_lut, res);
    }
  }

  int64_t total_prots = 0, total_doms = 0;
  for (int64_t gi = 0; gi < n; ++gi) {
    prot_counts[gi] = results[gi].n_prots;
    total_prots += (int64_t)(results[gi].prots.size() / 4);
    total_doms += (int64_t)(results[gi].doms.size() / 7);
  }

  int32_t* prots =
      (int32_t*)std::malloc(sizeof(int32_t) * std::max<int64_t>(1, total_prots * 4));
  int32_t* doms =
      (int32_t*)std::malloc(sizeof(int32_t) * std::max<int64_t>(1, total_doms * 7));
  int64_t pi = 0, di = 0;
  for (int64_t gi = 0; gi < n; ++gi) {
    const GenomeResult& res = results[gi];
    std::memcpy(prots + pi, res.prots.data(), res.prots.size() * sizeof(int32_t));
    std::memcpy(doms + di, res.doms.data(), res.doms.size() * sizeof(int32_t));
    pi += (int64_t)res.prots.size();
    di += (int64_t)res.doms.size();
  }
  *out_prots = prots;
  *out_n_prots = total_prots;
  *out_doms = doms;
  *out_n_doms = total_doms;
}

// Pack flat translation buffers into the padded dense token tensor
// (b, p_cap, d_cap, 5) int16 [dom_type, i0, i1, i2, i3] consumed by the
// jitted parameter assembly — the native counterpart of the numpy scatter
// in ops/params.flat_to_dense.  out_dense is caller-allocated and
// ZEROED (b * p_cap * d_cap * 5 int16 entries); proteins/domains beyond
// the caps must not occur (the caller grows capacities per batch first).
void ms_pack_dense(const int32_t* prot_counts, int64_t b,
                   const int32_t* prots, int64_t n_prots,
                   const int32_t* doms, int64_t n_doms,
                   int64_t p_cap, int64_t d_cap, int n_threads,
                   int16_t* out_dense) {
  (void)n_doms;
  // per-genome protein row offsets (serial cumsum; b is small)
  std::vector<int64_t> prot_offs((size_t)b + 1, 0);
  for (int64_t gi = 0; gi < b; ++gi)
    prot_offs[(size_t)gi + 1] = prot_offs[(size_t)gi] + prot_counts[gi];
  // per-protein domain row offsets
  std::vector<int64_t> dom_offs((size_t)n_prots + 1, 0);
  for (int64_t pi = 0; pi < n_prots; ++pi)
    dom_offs[(size_t)pi + 1] = dom_offs[(size_t)pi] + prots[4 * pi + 3];

  const int64_t cell_stride = p_cap * d_cap * 5;
#if defined(_OPENMP)
  if (n_threads > 0) omp_set_num_threads(n_threads);
#pragma omp parallel for schedule(dynamic, 64)
#endif
  for (int64_t gi = 0; gi < b; ++gi) {
    int16_t* cell = out_dense + gi * cell_stride;
    const int64_t p0 = prot_offs[(size_t)gi], p1 = prot_offs[(size_t)gi + 1];
    for (int64_t pi = p0; pi < p1; ++pi) {
      int16_t* prot = cell + (pi - p0) * d_cap * 5;
      const int64_t d0 = dom_offs[(size_t)pi], d1 = dom_offs[(size_t)pi + 1];
      for (int64_t di = d0; di < d1; ++di) {
        const int32_t* src = doms + 7 * di;
        int16_t* dst = prot + (di - d0) * 5;
        dst[0] = (int16_t)src[0];
        dst[1] = (int16_t)src[1];
        dst[2] = (int16_t)src[2];
        dst[3] = (int16_t)src[3];
        dst[4] = (int16_t)src[4];
      }
    }
  }
}

namespace {

const char MUT_NTS[4] = {'A', 'C', 'T', 'G'};

// distinct sorted positions in [0, len)
void sample_positions(std::mt19937_64& rng, int64_t len, int64_t k,
                      std::vector<int64_t>& out) {
  out.clear();
  if (k * 3 >= len) {
    // dense case: partial Fisher-Yates
    std::vector<int64_t> idx((size_t)len);
    for (int64_t i = 0; i < len; ++i) idx[i] = i;
    for (int64_t i = 0; i < k; ++i) {
      std::uniform_int_distribution<int64_t> d(i, len - 1);
      std::swap(idx[i], idx[d(rng)]);
    }
    out.assign(idx.begin(), idx.begin() + k);
  } else {
    // sparse case: rejection
    out.reserve((size_t)k);
    std::uniform_int_distribution<int64_t> d(0, len - 1);
    while ((int64_t)out.size() < k) {
      int64_t cand = d(rng);
      if (std::find(out.begin(), out.end(), cand) == out.end())
        out.push_back(cand);
    }
  }
  std::sort(out.begin(), out.end());
}

struct MutResult {
  std::string seq0;
  std::string seq1;  // only used by recombinations
  int64_t idx = -1;  // -1 = unchanged
};

}  // namespace

// Point mutations over n sequences.  Returns only mutated sequences:
// *out_data is the concatenation of the mutated sequences, *out_offsets has
// *out_n + 1 entries, *out_idxs maps each to its input index.
// The caller pre-draws the Poisson(p*len) mutation count per sequence
// (vectorized numpy on the host) and passes only sequences with >= 1
// mutation — this keeps the per-call work proportional to the number of
// actually-mutated sequences instead of the population size.
// orig_idxs holds each sequence's index in the caller's full population:
// RNG streams are keyed by it (not by the position within this call) so a
// genome's mutations don't depend on which other genomes were batched in.
void ms_point_mutations(const char* data, const int64_t* offsets, int64_t n,
                        const int64_t* n_muts_in, const int64_t* orig_idxs,
                        float p_indel, float p_del,
                        uint64_t seed, int n_threads, char** out_data,
                        int64_t** out_offsets, int64_t** out_idxs,
                        int64_t* out_n) {
  std::vector<MutResult> results((size_t)n);

#if defined(_OPENMP)
  if (n_threads > 0) omp_set_num_threads(n_threads);
#pragma omp parallel
#endif
  {
    std::vector<int64_t> positions;
#if defined(_OPENMP)
#pragma omp for schedule(dynamic, 64)
#endif
    for (int64_t si = 0; si < n; ++si) {
      const char* seq = data + offsets[si];
      int64_t len = offsets[si + 1] - offsets[si];
      if (len < 1) continue;
      std::mt19937_64 rng(seed * 1000003ULL + (uint64_t)orig_idxs[si]);
      int64_t n_muts = n_muts_in[si];
      if (n_muts < 1) continue;
      if (n_muts > len) n_muts = len;
      sample_positions(rng, len, n_muts, positions);

      std::string s(seq, (size_t)len);
      std::uniform_real_distribution<double> uni(0.0, 1.0);
      std::uniform_int_distribution<int> nt(0, 3);
      int64_t offset = 0;
      for (int64_t pos : positions) {
        int64_t cur = pos + offset;
        if (cur < 0) cur = 0;
        if (uni(rng) < (double)p_indel) {
          if (uni(rng) < (double)p_del) {
            if (cur >= (int64_t)s.size()) cur = (int64_t)s.size() - 1;
            s.erase((size_t)cur, 1);
            offset -= 1;
          } else {
            if (cur > (int64_t)s.size()) cur = (int64_t)s.size();
            s.insert((size_t)cur, 1, MUT_NTS[nt(rng)]);
            offset += 1;
          }
        } else {
          if (cur >= (int64_t)s.size()) cur = (int64_t)s.size() - 1;
          s[(size_t)cur] = MUT_NTS[nt(rng)];
        }
      }
      results[si].seq0 = std::move(s);
      results[si].idx = si;
    }
  }

  int64_t n_out = 0, total_len = 0;
  for (const MutResult& r : results) {
    if (r.idx >= 0) {
      n_out += 1;
      total_len += (int64_t)r.seq0.size();
    }
  }
  char* odata = (char*)std::malloc((size_t)std::max<int64_t>(1, total_len));
  int64_t* ooffs = (int64_t*)std::malloc(sizeof(int64_t) * (size_t)(n_out + 1));
  int64_t* oidxs =
      (int64_t*)std::malloc(sizeof(int64_t) * (size_t)std::max<int64_t>(1, n_out));
  int64_t w = 0, k = 0;
  ooffs[0] = 0;
  for (const MutResult& r : results) {
    if (r.idx < 0) continue;
    std::memcpy(odata + w, r.seq0.data(), r.seq0.size());
    w += (int64_t)r.seq0.size();
    oidxs[k] = r.idx;
    ooffs[++k] = w;
  }
  *out_data = odata;
  *out_offsets = ooffs;
  *out_idxs = oidxs;
  *out_n = n_out;
}

// Recombinations over n sequence pairs (2*n sequences concatenated:
// pair i = sequences 2i and 2i+1).  Output mirrors ms_point_mutations but
// with two sequences per result (2*out_n sequences, out_n indices).
void ms_recombinations(const char* data, const int64_t* offsets, int64_t n,
                       const int64_t* n_breaks_in, const int64_t* orig_idxs,
                       uint64_t seed,
                       int n_threads, char** out_data, int64_t** out_offsets,
                       int64_t** out_idxs, int64_t* out_n) {
  std::vector<MutResult> results((size_t)n);

#if defined(_OPENMP)
  if (n_threads > 0) omp_set_num_threads(n_threads);
#pragma omp parallel
#endif
  {
    std::vector<int64_t> positions;
    std::vector<std::pair<int64_t, int64_t>> parts;  // (global_start, len)
#if defined(_OPENMP)
#pragma omp for schedule(dynamic, 64)
#endif
    for (int64_t pi = 0; pi < n; ++pi) {
      const char* s0 = data + offsets[2 * pi];
      int64_t n0 = offsets[2 * pi + 1] - offsets[2 * pi];
      const char* s1 = data + offsets[2 * pi + 1];
      int64_t n1 = offsets[2 * pi + 2] - offsets[2 * pi + 1];
      int64_t n_both = n0 + n1;
      if (n_both < 1) continue;
      std::mt19937_64 rng(seed * 1000003ULL + (uint64_t)orig_idxs[pi]);
      int64_t n_muts = n_breaks_in[pi];
      if (n_muts < 1) continue;
      if (n_muts > n_both) n_muts = n_both;
      sample_positions(rng, n_both, n_muts, positions);

      // split both strands into fragments at the cut positions
      parts.clear();
      int64_t i = 0;
      for (int64_t j : positions) {
        if (j >= n0) break;
        parts.emplace_back(i, j - i);
        i = j;
      }
      parts.emplace_back(i, n0 - i);
      i = 0;
      for (int64_t j : positions) {
        if (j < n0) continue;
        parts.emplace_back(n0 + i, j - n0 - i);
        i = j - n0;
      }
      parts.emplace_back(n0 + i, n1 - i);

      std::shuffle(parts.begin(), parts.end(), rng);
      std::uniform_int_distribution<size_t> split(0, parts.size() - 1);
      size_t s = split(rng);

      MutResult& res = results[pi];
      res.seq0.reserve((size_t)n0);
      res.seq1.reserve((size_t)n1);
      auto frag = [&](size_t k) {
        int64_t g = parts[k].first;
        const char* src = g < n0 ? s0 + g : s1 + (g - n0);
        return std::string(src, (size_t)parts[k].second);
      };
      for (size_t k = 0; k < s; ++k) res.seq0 += frag(k);
      for (size_t k = s; k < parts.size(); ++k) res.seq1 += frag(k);
      res.idx = pi;
    }
  }

  int64_t n_out = 0, total_len = 0;
  for (const MutResult& r : results) {
    if (r.idx >= 0) {
      n_out += 1;
      total_len += (int64_t)(r.seq0.size() + r.seq1.size());
    }
  }
  char* odata = (char*)std::malloc((size_t)std::max<int64_t>(1, total_len));
  int64_t* ooffs =
      (int64_t*)std::malloc(sizeof(int64_t) * (size_t)(2 * n_out + 1));
  int64_t* oidxs =
      (int64_t*)std::malloc(sizeof(int64_t) * (size_t)std::max<int64_t>(1, n_out));
  int64_t w = 0, k = 0;
  ooffs[0] = 0;
  int64_t oi = 0;
  for (const MutResult& r : results) {
    if (r.idx < 0) continue;
    std::memcpy(odata + w, r.seq0.data(), r.seq0.size());
    w += (int64_t)r.seq0.size();
    ooffs[++k] = w;
    std::memcpy(odata + w, r.seq1.data(), r.seq1.size());
    w += (int64_t)r.seq1.size();
    ooffs[++k] = w;
    oidxs[oi++] = r.idx;
  }
  *out_data = odata;
  *out_offsets = ooffs;
  *out_idxs = oidxs;
  *out_n = n_out;
}

// Unique Moore-adjacent pairs among cell positions on the torus
// (counterpart of the reference's rust/world.rs:9-54 pairwise scan, done
// with an occupancy grid instead).  positions: (n, 2) int32 row-major.
// Output pairs (smaller index first) sorted ascending by (lo, hi) —
// identical order to the numpy fallback's encoded-unique.  Caller frees
// *out_pairs with ms_free.
void ms_neighbor_pairs(const int32_t* positions, int64_t n, int32_t map_size,
                       int32_t** out_pairs, int64_t* out_n) {
  const int64_t m = map_size;
  std::vector<int32_t> grid((size_t)(m * m), -1);
  for (int64_t i = 0; i < n; ++i) {
    grid[(size_t)(positions[2 * i] * m + positions[2 * i + 1])] = (int32_t)i;
  }
  static const int dx[8] = {-1, -1, -1, 0, 0, 1, 1, 1};
  static const int dy[8] = {-1, 0, 1, -1, 1, -1, 0, 1};
  std::vector<int32_t> pairs;
  pairs.reserve((size_t)(n * 3));
  int32_t nb[8];
  for (int64_t i = 0; i < n; ++i) {
    const int64_t x = positions[2 * i], y = positions[2 * i + 1];
    size_t n_nb = 0;
    for (int k = 0; k < 8; ++k) {
      int64_t cx = x + dx[k], cy = y + dy[k];
      if (cx < 0) cx += m; else if (cx >= m) cx -= m;
      if (cy < 0) cy += m; else if (cy >= m) cy -= m;
      const int32_t cand = grid[(size_t)(cx * m + cy)];
      // emit each unordered pair once (from its smaller endpoint);
      // cand != i guards degenerate wraps at map_size <= 2
      if (cand > (int32_t)i) nb[n_nb++] = cand;
    }
    std::sort(nb, nb + n_nb);
    // degenerate maps can yield the same partner via several offsets
    for (size_t k = 0; k < n_nb; ++k) {
      if (k > 0 && nb[k] == nb[k - 1]) continue;
      pairs.push_back((int32_t)i);
      pairs.push_back(nb[k]);
    }
  }
  int32_t* out = (int32_t*)std::malloc(
      sizeof(int32_t) * std::max<size_t>(2, pairs.size()));
  std::memcpy(out, pairs.data(), sizeof(int32_t) * pairs.size());
  *out_pairs = out;
  *out_n = (int64_t)(pairs.size() / 2);
}

}  // extern "C"
