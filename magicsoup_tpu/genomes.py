"""Device-resident genomes: packed token arrays + jitted evolution kernels.

The reference keeps genomes as host Python strings and runs every
mutation/recombination round through the native engine — at 10k+ cells
that host round trip sits on the hot path (ROADMAP item 1).  This module
moves genomes onto the device as a fixed-capacity packed token tensor:

* ``tokens`` — ``(cap, G)`` int8, one row per cell slot, capacity-padded
  exactly like ``CellParams`` (pow2 slot capacity, cell-sharded on a
  mesh).  ``G`` is the pow2 per-genome length capacity; positions past a
  row's length hold :data:`PAD`.
* ``lengths`` — ``(cap,)`` int32 per-row genome lengths.

Token code ``i`` is nucleotide ``TOKEN_NTS[i]`` — the SAME ``TCGA`` →
``0..3`` order the native translation engine uses (``_NT_CODE``), so a
decoded row feeds ``Genetics`` without remapping.

Evolution runs as jitted, PRNG-keyed kernels over those arrays:

* :func:`point_mutations_tokens` — substitutions + indels in one fused
  program.  Indels are a masked scatter: an exclusive cumulative
  insert/delete offset per position maps every surviving source token to
  its destination column (deleted tokens scatter out of bounds with
  ``mode="drop"``; inserted bases land at their own offset column).
* :func:`recombinations_tokens` — pairwise segment swap: a firing pair
  draws one cut per strand and exchanges tails.  Rows touched by several
  pairs resolve deterministically (a max-scatter picks the LAST firing
  pair, matching the host engine's "update order, last wins").

Both kernels are integer-only after the uniform draws (threefry bits,
integer cumsums, gathers/scatters with unique destinations), so their
trajectories are bit-reproducible across dispatches regardless of
numeric mode; in deterministic mode the recombination fire probability
additionally goes through :func:`ops.detmath.det_exp` so the one
transcendental matches across backends.

The kernels' mutation SEMANTICS intentionally match the host engine
(per-bp event probability ``p``, indel fraction ``p_indel``, deletion
fraction ``p_del``, uniform ``ACTG`` substitution that may silently
redraw the same base) but their RNG streams are jax PRNG streams, not
the engine's PCG64 — trajectories are pinned by the string-replay
wrappers (:func:`point_mutations_strings` et al.), which run the SAME
kernels so a token-backed and a string-backed world replaying them stay
bit-identical (see ``check.differential`` token axes), and by the
distribution sanity tests against the engine at matched rates.

:class:`GenomeStore` owns the arrays for a World: all mutators are
functional (they replace the arrays and bump a version counter), decoded
string views and host token snapshots are cached per version, and
per-row content hashes key the :class:`~magicsoup_tpu.genetics.
PhenotypeCache` token path so translation is fed from device tokens.
"""
from __future__ import annotations

import functools
import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from magicsoup_tpu.ops.params import pad_idxs, pad_pow2


def _note_decode(rows: int) -> None:
    """Feed the analysis.runtime genome-decode counter (lazy import —
    the counter module pulls in guard.chaos, which this module must not
    load at import time)."""
    from magicsoup_tpu.analysis import runtime as _runtime

    _runtime.note_genome_decode(rows=rows)

def _upload(arr, like):
    """Explicitly place a small host operand next to ``like``
    (replicated across its mesh when sharded).  Every operand of the
    jitted store programs goes through here: implicit host->device
    argument conversion is illegal under the steady-state
    ``jax.transfer_guard("disallow")`` census, and an uncommitted
    upload would silently re-replicate per dispatch on a mesh."""
    if isinstance(like, jax.Array):
        sharding = like.sharding
        devices = sharding.device_set
        if len(devices) == 1:
            return jax.device_put(arr, next(iter(devices)))
        return jax.device_put(
            arr,
            jax.sharding.NamedSharding(
                sharding.mesh, jax.sharding.PartitionSpec()
            ),
        )
    return jnp.asarray(arr)


PAD = 4  # int8 fill value outside a row's live region (never a base)
TOKEN_NTS = "TCGA"  # token code i <-> TOKEN_NTS[i]; matches engine _NT_CODE
_MIN_G = 64  # minimum per-genome length capacity (pow2)
_G_SLACK = 8  # regrow headroom: insertions may exceed G by a few bases

_ENC = np.full(256, -1, dtype=np.int16)
for _i, _c in enumerate(TOKEN_NTS.encode()):
    _ENC[_c] = _i
_DEC = np.frombuffer(TOKEN_NTS.encode(), dtype=np.uint8)


# ------------------------------------------------------------------ #
# host codec (the string import/export boundary)                     #
# ------------------------------------------------------------------ #


def length_capacity(max_len: int) -> int:
    """The pow2 per-genome length capacity for a maximum genome length."""
    return pad_pow2(max(int(max_len), 1), minimum=_MIN_G)


def encode_genomes(
    genomes: list[str], length_cap: int | None = None
) -> tuple[np.ndarray, np.ndarray]:
    """Pack genome strings into ``(tokens (b, G) int8, lengths (b,) int32)``.

    ``G`` is ``length_cap`` or the pow2 capacity of the longest input.
    Any byte outside ``TCGA`` raises ``ValueError`` — genomes are the
    only alphabet the translation tables know, and a silent wrong code
    would translate to a wrong (not absent) proteome.
    """
    n = len(genomes)
    lengths = np.fromiter((len(g) for g in genomes), dtype=np.int32, count=n)
    cap = length_capacity(int(lengths.max()) if n else 1)
    if length_cap is not None:
        if n and int(lengths.max()) > length_cap:
            raise ValueError(
                f"genome of length {int(lengths.max())} exceeds the"
                f" requested length_cap={length_cap}"
            )
        cap = length_cap
    tokens = np.full((n, cap), PAD, dtype=np.int8)
    for i, g in enumerate(genomes):
        if not g:
            continue
        raw = np.frombuffer(g.encode("ascii", "replace"), dtype=np.uint8)
        row = _ENC[raw]
        if (row < 0).any():
            bad = g[int(np.argmax(row < 0))]
            raise ValueError(
                f"genome {i} contains non-TCGA byte {bad!r}; token"
                " packing accepts only the TCGA nucleotide alphabet"
            )
        tokens[i, : len(row)] = row.astype(np.int8)
    return tokens, lengths


def decode_tokens(tokens: np.ndarray, lengths: np.ndarray) -> list[str]:
    """Unpack host token rows back into genome strings (export boundary)."""
    tokens = np.asarray(tokens)
    return [
        bytes(_DEC[tokens[i, : int(l)].astype(np.uint8)]).decode("ascii")
        for i, l in enumerate(np.asarray(lengths))
    ]


def token_hashes(
    tokens: np.ndarray, lengths: np.ndarray, idxs=None
) -> list[bytes]:
    """Per-row content hashes of the LIVE region (the token-path
    phenotype-cache key: two rows with equal bases and length collide
    regardless of slot, capacity padding, or ``G``)."""
    tokens = np.asarray(tokens)
    lengths = np.asarray(lengths)
    rows = range(len(lengths)) if idxs is None else idxs
    return [
        hashlib.blake2b(
            tokens[i, : int(lengths[i])].tobytes(), digest_size=16
        ).digest()
        for i in rows
    ]


# ------------------------------------------------------------------ #
# jitted kernels                                                     #
# ------------------------------------------------------------------ #


@functools.partial(jax.jit, static_argnames=("det",))
def _point_mutations_program(
    tokens, lengths, live, key, p, p_indel, p_del, *, det: bool = False
):
    """Fused substitution+indel kernel.  Integer-only after the uniform
    draws; every scatter destination is unique, so the program is
    bit-reproducible (no ``det`` branch needed — the flag only keeps the
    jit-cache identity aligned with the caller's numeric mode)."""
    del det
    cap, g = tokens.shape
    ku, kk, kd, kb = jax.random.split(key, 4)
    col = jnp.arange(g, dtype=jnp.int32)[None, :]
    in_len = (col < lengths[:, None]) & live[:, None]

    event = (jax.random.uniform(ku, (cap, g)) < p) & in_len
    kind = jax.random.uniform(kk, (cap, g))
    is_indel = event & (kind < p_indel)
    is_sub = event & (kind >= p_indel)
    dd = jax.random.uniform(kd, (cap, g))
    is_del = is_indel & (dd < p_del)
    is_ins = is_indel & (dd >= p_del)
    base = jax.random.randint(kb, (cap, g), 0, 4, dtype=jnp.int8)

    # substitutions first, at original coordinates (engine order); a draw
    # equal to the current base is a silent substitution, as in the engine
    mutated = jnp.where(is_sub, base, tokens)

    # indel offsets: each destination column is `source + (#inserts
    # before) - (#deletes before)`; an insertion lands at its own offset
    # column and pushes its source token one further right
    delta = is_ins.astype(jnp.int32) - is_del.astype(jnp.int32)
    shift = jnp.cumsum(delta, axis=1) - delta  # exclusive cumsum
    dst_src = col + shift + is_ins.astype(jnp.int32)
    dst_ins = col + shift

    keep = in_len & ~is_del
    rows = jnp.arange(cap, dtype=jnp.int32)[:, None]
    out = jnp.full((cap, g), np.int8(PAD))
    out = out.at[rows, jnp.where(keep, dst_src, g)].set(
        mutated, mode="drop"
    )
    out = out.at[rows, jnp.where(is_ins, dst_ins, g)].set(
        base, mode="drop"
    )

    n_ins = is_ins.sum(axis=1, dtype=jnp.int32)
    n_del = is_del.sum(axis=1, dtype=jnp.int32)
    new_len = jnp.clip(lengths + n_ins - n_del, 0, g)
    new_len = jnp.where(live, new_len, lengths)
    out = jnp.where(col < new_len[:, None], out, np.int8(PAD))
    changed = event.any(axis=1)
    return out, new_len, changed


@functools.partial(jax.jit, static_argnames=("det",))
def _recombinations_program(
    tokens, lengths, pair_a, pair_b, valid, key, log1mp, *, det: bool = False
):
    """Pairwise segment-swap kernel.  Each valid pair fires with
    ``1 - (1-p)^(len_a + len_b)`` (one strand break over the combined
    sequence, matching the host engine's per-bp break probability), draws
    one cut per strand, and exchanges tails — total length is conserved
    per pair, truncated only at the ``G`` capacity.  Rows touched by
    several firing pairs resolve via a deterministic max-scatter: the
    LAST firing pair wins, the same order the host engine's update list
    applies."""
    cap, g = tokens.shape
    npairs = pair_a.shape[0]
    kf, ka, kb = jax.random.split(key, 3)

    la = jnp.where(valid, lengths[pair_a], 0)
    lb = jnp.where(valid, lengths[pair_b], 0)
    total = (la + lb).astype(jnp.float32)
    if det:
        from magicsoup_tpu.ops import detmath

        miss = detmath.det_exp(total * log1mp)
    else:
        miss = jnp.exp(total * log1mp)
    fire = (jax.random.uniform(kf, (npairs,)) >= miss) & valid

    # one cut per strand, uniform over [0, len] inclusive
    cut_a = jax.random.randint(ka, (npairs,), 0, la + 1, dtype=jnp.int32)
    cut_b = jax.random.randint(kb, (npairs,), 0, lb + 1, dtype=jnp.int32)

    # last firing pair wins each row: max-scatter of 1-based pair index
    prio = jnp.where(fire, jnp.arange(npairs, dtype=jnp.int32) + 1, 0)
    row_a = jnp.where(fire, pair_a, cap)
    row_b = jnp.where(fire, pair_b, cap)
    winner = jnp.zeros(cap + 1, dtype=jnp.int32)
    winner = winner.at[row_a].max(prio, mode="drop")
    winner = winner.at[row_b].max(prio, mode="drop")
    write_a = fire & (winner[pair_a] == prio)
    write_b = fire & (winner[pair_b] == prio)

    col = jnp.arange(g, dtype=jnp.int32)[None, :]

    def _swap(rows_keep, rows_tail, cut_keep, cut_tail, len_tail):
        """head of `rows_keep` up to its cut + tail of `rows_tail` from
        its cut, gathered in one pass."""
        from_head = col < cut_keep[:, None]
        src_row = jnp.where(from_head, rows_keep[:, None], rows_tail[:, None])
        src_col = jnp.where(
            from_head, col, col - cut_keep[:, None] + cut_tail[:, None]
        )
        out = tokens[
            jnp.clip(src_row, 0, cap - 1), jnp.clip(src_col, 0, g - 1)
        ]
        new_len = jnp.clip(cut_keep + (len_tail - cut_tail), 0, g)
        out = jnp.where(col < new_len[:, None], out, np.int8(PAD))
        return out, new_len

    new_a, len_a = _swap(pair_a, pair_b, cut_a, cut_b, lb)
    new_b, len_b = _swap(pair_b, pair_a, cut_b, cut_a, la)

    out_tokens = tokens.at[jnp.where(write_a, pair_a, cap), :].set(
        new_a, mode="drop"
    )
    out_tokens = out_tokens.at[jnp.where(write_b, pair_b, cap), :].set(
        new_b, mode="drop"
    )
    out_lengths = lengths.at[jnp.where(write_a, pair_a, cap)].set(
        len_a, mode="drop"
    )
    out_lengths = out_lengths.at[jnp.where(write_b, pair_b, cap)].set(
        len_b, mode="drop"
    )
    changed = jnp.zeros(tokens.shape[0], dtype=bool)
    changed = changed.at[row_a].set(True, mode="drop")
    changed = changed.at[row_b].set(True, mode="drop")
    return out_tokens, out_lengths, changed


@jax.jit
def _set_rows_program(tokens, lengths, idxs, rows, lens):
    """Scatter encoded rows into slots (OOB-padded idxs drop)."""
    tokens = tokens.at[idxs, :].set(rows, mode="drop")
    lengths = lengths.at[idxs].set(lens, mode="drop")
    return tokens, lengths


@jax.jit
def _copy_rows_program(tokens, lengths, src, dst):
    """Parent -> child row copies (division inheritance, zero decode)."""
    tokens = tokens.at[dst, :].set(tokens[src.clip(0)], mode="drop")
    lengths = lengths.at[dst].set(lengths[src.clip(0)], mode="drop")
    return tokens, lengths


@jax.jit
def _permute_program(tokens, lengths, perm, n_keep):
    """Apply a compaction permutation and PAD rows past ``n_keep``."""
    tokens = tokens[perm]
    lengths = lengths[perm]
    row = jnp.arange(tokens.shape[0], dtype=jnp.int32)
    tokens = jnp.where((row < n_keep)[:, None], tokens, np.int8(PAD))
    lengths = jnp.where(row < n_keep, lengths, 0)
    return tokens, lengths


def _as_key(seed: int | None) -> jax.Array:
    if seed is None:
        import random as _random

        seed = _random.SystemRandom().randrange(2**63)  # graftlint: disable=GL004 entropy only when the caller passed no seed
    return jax.random.PRNGKey(int(seed) & 0x7FFFFFFFFFFFFFFF)


def point_mutations_tokens(
    tokens,
    lengths,
    *,
    p: float = 1e-6,
    p_indel: float = 0.4,
    p_del: float = 0.66,
    seed: int | None = None,
    live=None,
    det: bool = False,
):
    """Jitted point mutations over a token array.  Returns
    ``(tokens, lengths, changed)`` — full new arrays plus a ``(cap,)``
    changed-row mask.  Rates arrive as traced scalars so sweeping them
    never recompiles."""
    if live is None:
        live = jnp.ones(tokens.shape[0], dtype=bool)
    elif not isinstance(live, jax.Array):
        # host mask (callers hand in a bool ndarray) -> explicit upload
        live = _upload(live, tokens)
    return _point_mutations_program(
        tokens,
        lengths,
        live,
        _as_key(seed),
        _upload(np.float32(p), tokens),
        _upload(np.float32(p_indel), tokens),
        _upload(np.float32(p_del), tokens),
        det=det,
    )


def recombinations_tokens(
    tokens,
    lengths,
    pairs,
    *,
    p: float = 1e-7,
    seed: int | None = None,
    det: bool = False,
):
    """Jitted pairwise recombination over a token array.  ``pairs`` is a
    host ``(n, 2)`` row-index array (e.g. :func:`util.moore_pairs`
    output); it is padded to an ``IDX_BLOCK`` multiple so pair-count
    jitter between calls does not recompile.  Returns
    ``(tokens, lengths, changed)``."""
    pairs = np.asarray(pairs, dtype=np.int32).reshape(-1, 2)
    cap = tokens.shape[0]
    n = len(pairs)
    a = pad_idxs(pairs[:, 0], oob=cap)
    b = pad_idxs(pairs[:, 1], oob=cap)
    valid = np.zeros(len(a), dtype=bool)
    valid[:n] = True
    # log1p in float64 on host: the per-pair miss probability is then a
    # single device exp of `total * log(1-p)`
    log1mp = np.float32(np.log1p(-min(float(p), 1.0 - 1e-12)))
    return _recombinations_program(
        tokens,
        lengths,
        _upload(a, tokens),
        _upload(b, tokens),
        _upload(valid, tokens),
        _as_key(seed),
        _upload(log1mp, tokens),
        det=det,
    )


# ------------------------------------------------------------------ #
# string-replay wrappers (engine-shaped API over the same kernels)   #
# ------------------------------------------------------------------ #


def _encode_at_shape(
    seqs: list[str], cap: int | None, length_cap: int | None
) -> tuple[np.ndarray, np.ndarray]:
    """Encode ``seqs`` padded to an explicit ``(cap, G)`` shape.  The
    kernels' PRNG draw shapes ARE ``(cap, G)`` — a string-side replay
    only reproduces a token world's kernel call bit-for-bit when it runs
    at the token world's exact array shape, so equivalence harnesses
    pass the world's slot capacity and length cap here."""
    tokens, lengths = encode_genomes(seqs, length_cap=length_cap)
    if cap is not None and cap > len(seqs):
        tokens = np.pad(
            tokens,
            ((0, cap - len(seqs)), (0, 0)),
            constant_values=PAD,
        )
        lengths = np.pad(lengths, (0, cap - len(seqs)))
    return tokens, lengths


def point_mutations_strings(
    seqs: list[str],
    p: float = 1e-6,
    p_indel: float = 0.4,
    p_del: float = 0.66,
    seed: int | None = None,
    *,
    cap: int | None = None,
    length_cap: int | None = None,
    det: bool = False,
) -> list[tuple[str, int]]:
    """:func:`mutations.point_mutations`-shaped wrapper over the token
    kernel: encode, run the SAME jitted program, decode changed rows.
    With ``cap``/``length_cap`` matching a token world's store shape, a
    string-backed world replaying this sees bit-identical outcomes to
    the token-backed world running the kernel directly — the
    equivalence pin for the ``--genome`` smoke."""
    if not seqs:
        return []
    tokens, lengths = _encode_at_shape(seqs, cap, length_cap)
    live = np.zeros(tokens.shape[0], dtype=bool)
    live[: len(seqs)] = True
    out_t, out_l, changed = point_mutations_tokens(
        tokens,
        lengths,
        p=p,
        p_indel=p_indel,
        p_del=p_del,
        seed=seed,
        live=jnp.asarray(live),
        det=det,
    )
    from magicsoup_tpu.util import fetch_host

    changed, host_t, host_l = (
        np.asarray(a) for a in fetch_host((changed, out_t, out_l))
    )
    idxs = np.nonzero(changed[: len(seqs)])[0]
    if not len(idxs):
        return []
    return [
        (
            bytes(_DEC[host_t[i, : host_l[i]].astype(np.uint8)]).decode(),
            int(i),
        )
        for i in idxs
    ]


def recombinations_indexed_strings(
    seqs: list[str],
    pairs,
    p: float = 1e-7,
    seed: int | None = None,
    *,
    cap: int | None = None,
    length_cap: int | None = None,
    det: bool = False,
) -> list[tuple[str, str, int]]:
    """``engine.recombinations_indexed``-shaped wrapper over the token
    kernel.  Same return shape — ``(genome_a, genome_b, pair_index)``
    per pair touching a changed row; every entry for a given row carries
    the kernel's FINAL row content, so applying them in any order
    converges to the kernel state."""
    pairs = np.asarray(pairs, dtype=np.int64).reshape(-1, 2)
    if not len(seqs) or not len(pairs):
        return []
    tokens, lengths = _encode_at_shape(seqs, cap, length_cap)
    out_t, out_l, changed = recombinations_tokens(
        tokens, lengths, pairs, p=p, seed=seed, det=det
    )
    from magicsoup_tpu.util import fetch_host

    changed, host_t, host_l = (
        np.asarray(a) for a in fetch_host((changed, out_t, out_l))
    )

    def _row(i: int) -> str:
        return bytes(_DEC[host_t[i, : host_l[i]].astype(np.uint8)]).decode()

    out = []
    for k, (a, b) in enumerate(pairs):
        if changed[a] or changed[b]:
            out.append((_row(int(a)), _row(int(b)), int(k)))
    return out


# ------------------------------------------------------------------ #
# the device store                                                   #
# ------------------------------------------------------------------ #


class GenomeStore:
    """Device-resident packed genomes for one World.

    Owns the ``(cap, G)`` token tensor and ``(cap,)`` length vector.
    Every mutator is functional — it replaces the arrays (placed through
    the world's cell sharding, so mesh worlds keep genomes cell-sharded
    like ``CellParams``) and bumps ``version``; the decoded string view,
    the host token snapshot, and per-row hashes are caches keyed by that
    version, so steady-state device evolution never decodes and a
    repeated export decodes once.
    """

    def __init__(
        self,
        capacity: int,
        length_cap: int = _MIN_G,
        place=None,
    ):
        self.capacity = int(capacity)
        self.length_cap = length_capacity(length_cap)
        self._place = place if place is not None else jnp.asarray
        self.tokens = self._place(
            np.full((self.capacity, self.length_cap), PAD, dtype=np.int8)
        )
        self.lengths = self._place(np.zeros(self.capacity, dtype=np.int32))
        self.version = 0
        self._decoded: tuple[int, list[str]] | None = None
        self._host: tuple[int, np.ndarray, np.ndarray] | None = None

    # -- placement / pickling ---------------------------------------- #

    def place(self, place) -> None:
        """(Re)bind the device placement callback and re-place the
        arrays (used after unpickling and on mesh re-placement)."""
        from magicsoup_tpu.util import fetch_host

        self._place = place
        tok, lens = fetch_host((self.tokens, self.lengths))
        self.tokens = self._place(np.asarray(tok))
        self.lengths = self._place(np.asarray(lens))

    def __getstate__(self) -> dict:
        from magicsoup_tpu.util import fetch_host

        state = self.__dict__.copy()
        state["tokens"] = np.asarray(fetch_host(self.tokens))
        state["lengths"] = np.asarray(fetch_host(self.lengths))
        state["_place"] = None
        state["_decoded"] = None
        state["_host"] = None
        return state

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(state)
        self._place = jnp.asarray
        self.tokens = jnp.asarray(state["tokens"])
        self.lengths = jnp.asarray(state["lengths"])

    def clone(self) -> "GenomeStore":
        """Array-SHARING copy (cheap: no device work).  Safe because
        every mutator is functional — it replaces the arrays, never
        writes in place — so the clone and the original diverge on first
        write.  The stepper checks out a world's genomes this way:
        attach performs zero decode/copy."""
        new = GenomeStore.__new__(GenomeStore)
        new.capacity = self.capacity
        new.length_cap = self.length_cap
        new._place = self._place
        new.tokens = self.tokens
        new.lengths = self.lengths
        new.version = 0
        new._decoded = None
        new._host = None
        return new

    # -- cached host views ------------------------------------------- #

    def _bump(self) -> None:
        self.version += 1
        self._decoded = None
        self._host = None

    def host_arrays(self) -> tuple[np.ndarray, np.ndarray]:
        """Host snapshot ``(tokens, lengths)`` (cached per version)."""
        if self._host is None or self._host[0] != self.version:
            from magicsoup_tpu.util import fetch_host

            self._host = (
                self.version,
                np.asarray(fetch_host(self.tokens)),
                np.asarray(fetch_host(self.lengths)),
            )
        return self._host[1], self._host[2]

    def decoded(self, n: int) -> list[str]:
        """The first ``n`` rows as genome strings (cached per version;
        the export boundary — steady-state device paths never call it)."""
        cached = self._decoded
        if cached is not None and cached[0] == self.version and len(
            cached[1]
        ) == n:
            return cached[1]
        tok, lens = self.host_arrays()
        out = decode_tokens(tok[:n], lens[:n])
        _note_decode(n)
        self._decoded = (self.version, out)
        return out

    def decode_row(self, i: int) -> str:
        """One row as a genome string (per-cell inspection without the
        whole-population export)."""
        tok, lens = self.host_arrays()
        _note_decode(1)
        return decode_tokens(tok[i : i + 1], lens[i : i + 1])[0]

    def hashes(self, idxs) -> list[bytes]:
        """Content hashes for the given rows (phenotype-cache keys)."""
        tok, lens = self.host_arrays()
        return token_hashes(tok, lens, idxs)

    def max_length(self) -> int:
        _, lens = self.host_arrays()
        return int(lens.max()) if len(lens) else 0

    # -- mutators ------------------------------------------------------ #

    def adopt(self, tokens, lengths) -> None:
        """Replace the arrays wholesale (stepper flush hand-back)."""
        self.capacity = int(tokens.shape[0])
        self.length_cap = int(tokens.shape[1])
        self.tokens = tokens
        self.lengths = lengths
        self._bump()

    def set_all(self, genomes: list[str]) -> None:
        """Reset the store to exactly these genomes (property setter)."""
        n = len(genomes)
        if n > self.capacity:
            raise ValueError(
                f"{n} genomes exceed the store capacity {self.capacity};"
                " grow the world first"
            )
        rows, lens = encode_genomes(genomes) if n else (
            np.zeros((0, self.length_cap), dtype=np.int8),
            np.zeros(0, dtype=np.int32),
        )
        self.ensure_length_cap(rows.shape[1])
        tokens = np.full(
            (self.capacity, self.length_cap), PAD, dtype=np.int8
        )
        tokens[:n, : rows.shape[1]] = rows
        lengths = np.zeros(self.capacity, dtype=np.int32)
        lengths[:n] = lens
        self.tokens = self._place(tokens)
        self.lengths = self._place(lengths)
        self._bump()

    def set_rows(self, idxs, genomes: list[str]) -> tuple[np.ndarray, np.ndarray]:
        """Encode + scatter genomes into slots; returns the encoded
        ``(rows, lens)`` so callers can hash/translate without a device
        round trip (the string import boundary)."""
        rows, lens = encode_genomes(genomes)
        self.ensure_length_cap(rows.shape[1])
        if rows.shape[1] < self.length_cap:
            rows = np.pad(
                rows,
                ((0, 0), (0, self.length_cap - rows.shape[1])),
                constant_values=PAD,
            )
        idxs_pad = pad_idxs(np.asarray(idxs, dtype=np.int64), oob=self.capacity)
        b = len(idxs_pad)
        rows_pad = np.full(
            (b, self.length_cap), PAD, dtype=np.int8
        )
        rows_pad[: len(genomes)] = rows
        lens_pad = np.zeros(b, dtype=np.int32)
        lens_pad[: len(genomes)] = lens
        self.tokens, self.lengths = _set_rows_program(
            self.tokens,
            self.lengths,
            _upload(idxs_pad, self.tokens),
            _upload(rows_pad, self.tokens),
            _upload(lens_pad, self.tokens),
        )
        self._repin()
        return rows, lens

    def copy_rows(self, src, dst) -> None:
        """Device parent->child copies (division; zero host work)."""
        src_pad = pad_idxs(np.asarray(src, dtype=np.int64), oob=self.capacity)
        dst_pad = pad_idxs(np.asarray(dst, dtype=np.int64), oob=self.capacity)
        self.tokens, self.lengths = _copy_rows_program(
            self.tokens,
            self.lengths,
            _upload(src_pad, self.tokens),
            _upload(dst_pad, self.tokens),
        )
        self._repin()

    def permute(self, perm, n_keep: int) -> None:
        """Device compaction (kill path; zero host work)."""
        self.tokens, self.lengths = _permute_program(
            self.tokens,
            self.lengths,
            _upload(np.asarray(perm, dtype=np.int32), self.tokens),
            _upload(np.int32(n_keep), self.tokens),
        )
        self._repin()

    def apply(self, tokens, lengths) -> None:
        """Install kernel outputs (mutation/recombination results)."""
        self.tokens = tokens
        self.lengths = lengths
        self._repin()

    def _repin(self) -> None:
        """Keep mesh placement pinned after a jitted update (the
        kernels' inferred out-shardings may differ) and invalidate the
        per-version caches."""
        if self._place is not jnp.asarray:
            self.tokens = self._place(self.tokens)
            self.lengths = self._place(self.lengths)
        self._bump()

    # -- capacity ------------------------------------------------------ #

    def grow_capacity(self, capacity: int) -> None:
        """Grow the slot axis to ``capacity`` (world capacity growth)."""
        if capacity <= self.capacity:
            return
        tok, lens = self.host_arrays()
        tokens = np.full(
            (capacity, self.length_cap), PAD, dtype=np.int8
        )
        tokens[: self.capacity] = tok
        lengths = np.zeros(capacity, dtype=np.int32)
        lengths[: self.capacity] = lens
        self.capacity = capacity
        self.tokens = self._place(tokens)
        self.lengths = self._place(lengths)
        self._bump()

    def ensure_length_cap(self, g: int) -> None:
        """Grow the per-genome length axis to a pow2 >= ``g``.  Indel
        drift regrows G BEFORE the live region reaches it (callers check
        ``max_length()`` against ``length_cap - _G_SLACK``), so the
        kernels' capacity truncation stays a never-hit backstop."""
        if g <= self.length_cap:
            return
        new_g = length_capacity(g)
        tok, lens = self.host_arrays()
        tokens = np.full((self.capacity, new_g), PAD, dtype=np.int8)
        tokens[:, : self.length_cap] = tok
        self.length_cap = new_g
        self.tokens = self._place(tokens)
        self.lengths = self._place(lens)
        self._bump()

    def maybe_regrow(self) -> None:
        """Regrow G when insertions drift the longest genome into the
        slack band (one host scalar read per call — the caches make it
        free when nothing changed)."""
        if self.max_length() > self.length_cap - _G_SLACK:
            self.ensure_length_cap(self.length_cap + _G_SLACK + 1)
