"""
Pure-stdlib summarization and validation of graftscope JSONL telemetry.

Kept free of jax/numpy imports ON PURPOSE: ``scripts/summarize_capture.py``
loads this file directly (``spec_from_file_location``) to fold a
capture's ``telemetry.jsonl`` into ``BASELINE.json`` without initializing
a backend, and the ``python -m magicsoup_tpu.telemetry`` CLI reuses the
same functions so the two consumers cannot drift.

Row schema (one JSON object per line; ``type`` discriminates):

- ``meta``     — one per attach: ``{"version": 1, "wall": <epoch s>}``.
- ``counters`` — process-total runtime counters (compiles, persistent
  cache, phenotype cache, D2H fetches) at attach / flush boundaries.
- ``step``     — one per simulation step, built from the on-device
  metric lanes of the packed step record plus host replay bookkeeping:
  ``step``, ``alive``, ``rows``, ``occupied``, ``mm_mass``, ``cm_mass``,
  per-step ``kills``/``divisions``/``spawned``, genome-length stats,
  and cumulative ``total_*`` counters (monotone by contract).
- ``dispatch`` — one per host dispatch: ``k`` (megastep), queue depth,
  cold/compact flags, and ``phases`` mapping phase name -> milliseconds
  spent since the previous dispatch row.
- ``accounting`` — one per tenant from the graftserve ledger
  (``serve.accounting.TenantAccount.row``): ``tenant``, ``world``, and
  the non-negative usage counters in ``ACCOUNTING_COUNTER_KEYS``
  (steps/megasteps/dispatches, fetch bytes, device microseconds, and
  health trips).

Mesh-placed runs add optional keys: step rows carry ``tile_occupancy``
(per-map-row-tile occupied pixel counts, one int per mesh tile, summing
to ``occupied`` — computed on device from the sharded occupancy map) and
dispatch rows carry ``tiles``/``mesh_axis``.  Single-device rows omit
them, so the schema is backward compatible.

Fleet runs annotate dispatch rows with ``fleet_slot``/``fleet_size``;
cross-rung FUSED dispatches additionally carry ``fused_groups`` (how
many rung groups shared the one program launch, a positive int) and
``envelope`` (``[k_env, rec_env]`` — the grow-only record envelope the
shared fetch buffer was padded to).  Both are optional; when
``envelope`` is present ``fused_groups`` must be too.
"""
from __future__ import annotations

import json
import math
from pathlib import Path

# keys every step row must carry (the on-device metric lanes)
REQUIRED_STEP_KEYS = (
    "step",
    "alive",
    "rows",
    "occupied",
    "mm_mass",
    "cm_mass",
)
# cumulative counters that must never decrease across step rows
MONOTONE_STEP_KEYS = (
    "step",
    "total_kills",
    "total_divisions",
    "total_spawned",
    "total_mutations",
)
# per-tenant usage counters every accounting row must carry
# (serve.accounting._COUNTER_FIELDS — pinned here so the stdlib-pure
# validator and the ledger cannot drift without a test noticing)
ACCOUNTING_COUNTER_KEYS = (
    "steps",
    "megasteps",
    "dispatches",
    "fetch_bytes",
    "device_us",
    "sentinel_trips",
    "invariant_trips",
)


def read_jsonl(path) -> list[dict]:
    """Parse a JSONL telemetry file into row dicts (blank lines skipped).

    Raises ``ValueError`` naming the offending line number on malformed
    JSON — a truncated final line from a crashed run is the common case,
    and the line number makes it obvious.
    """
    rows: list[dict] = []
    with open(Path(path), "r", encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path}:{lineno}: malformed JSONL row: {e}"
                ) from e
    return rows


def percentile(values, q: float) -> float:
    """Nearest-rank-with-interpolation percentile (q in [0, 100]).

    Matches numpy's default 'linear' method so the published p50/p95
    stay comparable if a future consumer recomputes them with numpy.
    """
    vals = sorted(float(v) for v in values)
    if not vals:
        return math.nan
    if len(vals) == 1:
        return vals[0]
    pos = (len(vals) - 1) * (q / 100.0)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(vals) - 1)
    frac = pos - lo
    return vals[lo] * (1.0 - frac) + vals[hi] * frac


def phase_quantiles(rows: list[dict]) -> dict[str, dict]:
    """Per-phase timing stats from the ``dispatch`` rows' ``phases``."""
    samples: dict[str, list[float]] = {}
    for row in rows:
        if row.get("type") != "dispatch":
            continue
        for name, ms in (row.get("phases") or {}).items():
            samples.setdefault(name, []).append(float(ms))
    out: dict[str, dict] = {}
    for name in sorted(samples):
        vals = samples[name]
        out[name] = {
            "n": len(vals),
            "p50_ms": round(percentile(vals, 50), 4),
            "p95_ms": round(percentile(vals, 95), 4),
            "max_ms": round(max(vals), 4),
            "total_ms": round(sum(vals), 4),
        }
    return out


def counter_deltas(rows: list[dict]) -> dict[str, dict]:
    """first/last/delta for every counter across the ``counters`` rows."""
    first: dict[str, float] = {}
    last: dict[str, float] = {}
    for row in rows:
        if row.get("type") != "counters":
            continue
        for name, val in (row.get("counters") or {}).items():
            first.setdefault(name, val)
            last[name] = val
    return {
        name: {
            "first": first[name],
            "last": last[name],
            "delta": last[name] - first[name],
        }
        for name in sorted(first)
    }


def validate_rows(rows: list[dict]) -> list[str]:
    """Schema check; returns human-readable problems (empty == valid).

    The gate the CI smoke runs: required keys on every step row, the
    ``step`` index strictly increasing, cumulative counters monotone,
    and dispatch phase timings well-formed non-negative numbers.
    """
    problems: list[str] = []
    prev_step: dict[str, float] = {}
    prev_index: float | None = None
    for i, row in enumerate(rows):
        where = f"row {i}"
        if not isinstance(row, dict) or "type" not in row:
            problems.append(f"{where}: not an object with a 'type' key")
            continue
        kind = row["type"]
        if kind == "step":
            missing = [k for k in REQUIRED_STEP_KEYS if k not in row]
            if missing:
                problems.append(f"{where}: step row missing {missing}")
                continue
            if prev_index is not None and row["step"] <= prev_index:
                problems.append(
                    f"{where}: step index {row['step']} not increasing "
                    f"(previous {prev_index})"
                )
            prev_index = row["step"]
            for key in MONOTONE_STEP_KEYS:
                if key not in row:
                    continue
                if key in prev_step and row[key] < prev_step[key]:
                    problems.append(
                        f"{where}: {key} decreased "
                        f"({prev_step[key]} -> {row[key]})"
                    )
                prev_step[key] = row[key]
            tiles = row.get("tile_occupancy")
            if tiles is not None:
                if not isinstance(tiles, list) or any(
                    not isinstance(v, int) or v < 0 for v in tiles
                ):
                    problems.append(
                        f"{where}: tile_occupancy must be a list of"
                        f" non-negative ints, got {tiles!r}"
                    )
                elif sum(tiles) != row["occupied"]:
                    problems.append(
                        f"{where}: tile_occupancy sums to {sum(tiles)}"
                        f" but occupied={row['occupied']}"
                    )
        elif kind == "dispatch":
            phases = row.get("phases")
            if not isinstance(phases, dict):
                problems.append(f"{where}: dispatch row missing 'phases'")
                continue
            for name, ms in phases.items():
                if not isinstance(ms, (int, float)) or ms < 0:
                    problems.append(
                        f"{where}: phase {name!r} timing {ms!r} invalid"
                    )
            # cross-rung fused dispatch tags (fleet.scheduler
            # _dispatch_fused): how many rung groups shared this one
            # program launch, and the grow-only [k_env, rec_env] record
            # envelope its fetch buffer was padded to
            fused = row.get("fused_groups")
            if fused is not None and (
                not isinstance(fused, int) or fused < 1
            ):
                problems.append(
                    f"{where}: fused_groups must be a positive int, "
                    f"got {fused!r}"
                )
            env = row.get("envelope")
            if env is not None:
                if (
                    not isinstance(env, list)
                    or len(env) != 2
                    or any(not isinstance(v, int) or v < 1 for v in env)
                ):
                    problems.append(
                        f"{where}: envelope must be [k_env, rec_env] "
                        f"positive ints, got {env!r}"
                    )
                elif fused is None:
                    problems.append(
                        f"{where}: envelope without fused_groups — "
                        "fused tags must travel together"
                    )
        elif kind == "counters":
            if not isinstance(row.get("counters"), dict):
                problems.append(f"{where}: counters row missing 'counters'")
        elif kind == "sentinel":
            # graftguard health-sentinel trip (stepper._handle_sentinel)
            if not isinstance(row.get("flags"), int) or "step" not in row:
                problems.append(
                    f"{where}: sentinel row missing 'flags'/'step'"
                )
        elif kind == "invariant":
            # graftcheck invariant-lane trip (stepper._handle_invariant)
            if not isinstance(row.get("flags"), int) or "step" not in row:
                problems.append(
                    f"{where}: invariant row missing 'flags'/'step'"
                )
        elif kind == "accounting":
            # graftserve per-tenant usage ledger (serve.accounting)
            if not isinstance(row.get("tenant"), str):
                problems.append(f"{where}: accounting row missing 'tenant'")
                continue
            if not isinstance(row.get("world"), int):
                problems.append(f"{where}: accounting row missing 'world'")
            for key in ACCOUNTING_COUNTER_KEYS:
                val = row.get(key)
                if not isinstance(val, int) or val < 0:
                    problems.append(
                        f"{where}: accounting counter {key!r} must be a"
                        f" non-negative int, got {val!r}"
                    )
        elif kind == "warden":
            # graftwarden world-level event (quarantine / heal /
            # heal_failed / circuit_break / save_degraded /
            # save_recovered — fleet.warden.FleetWarden)
            if not isinstance(row.get("event"), str) or "step" not in row:
                problems.append(
                    f"{where}: warden row missing 'event'/'step'"
                )
        elif kind == "chaos":
            # graftchaos fault firing (guard.chaos.site) — drained from
            # the chaos event ring at counter-emit boundaries
            if not isinstance(row.get("site"), str) or not isinstance(
                row.get("kind"), str
            ):
                problems.append(f"{where}: chaos row missing 'site'/'kind'")
        elif kind == "degraded":
            # graceful-degradation transition (guard.chaos.note_degraded
            # / clear_degraded)
            if not isinstance(row.get("subsystem"), str) or row.get(
                "state"
            ) not in ("degraded", "recovered"):
                problems.append(
                    f"{where}: degraded row needs 'subsystem' and a"
                    " 'state' of degraded|recovered"
                )
        elif kind != "meta":
            problems.append(f"{where}: unknown row type {kind!r}")
    return problems


def summarize_rows(rows: list[dict]) -> dict:
    """The aggregate the CLI prints and ``summarize_capture`` publishes."""
    steps = [r for r in rows if r.get("type") == "step"]
    dispatches = [r for r in rows if r.get("type") == "dispatch"]
    final = {}
    if steps:
        final = {k: steps[-1].get(k) for k in REQUIRED_STEP_KEYS}
        final["total_kills"] = steps[-1].get("total_kills")
        final["total_divisions"] = steps[-1].get("total_divisions")
        final["total_spawned"] = steps[-1].get("total_spawned")
        final["total_mutations"] = steps[-1].get("total_mutations")
        if steps[-1].get("tile_occupancy") is not None:
            final["tile_occupancy"] = steps[-1]["tile_occupancy"]
    out = {
        "rows": len(rows),
        "steps": len(steps),
        "dispatches": len(dispatches),
        "phases": phase_quantiles(rows),
        "counters": counter_deltas(rows),
        "final": final,
    }
    tiles = [r["tiles"] for r in dispatches if "tiles" in r]
    if tiles:
        out["tiles"] = max(tiles)
    return out


def format_summary(summary: dict) -> str:
    """Render :func:`summarize_rows` output as an aligned text report."""
    lines = [
        f"rows={summary['rows']} steps={summary['steps']} "
        f"dispatches={summary['dispatches']}"
    ]
    if summary["phases"]:
        lines.append("phase timings (ms):")
        width = max(len(n) for n in summary["phases"])
        lines.append(
            f"  {'phase':<{width}}  {'n':>6}  {'p50':>9}  {'p95':>9}"
            f"  {'max':>9}  {'total':>10}"
        )
        for name, st in summary["phases"].items():
            lines.append(
                f"  {name:<{width}}  {st['n']:>6}  {st['p50_ms']:>9.3f}"
                f"  {st['p95_ms']:>9.3f}  {st['max_ms']:>9.3f}"
                f"  {st['total_ms']:>10.3f}"
            )
    if summary["counters"]:
        lines.append("counter deltas:")
        width = max(len(n) for n in summary["counters"])
        for name, st in summary["counters"].items():
            lines.append(
                f"  {name:<{width}}  {st['first']} -> {st['last']}"
                f"  (+{st['delta']})"
            )
    if summary["final"]:
        fin = summary["final"]
        lines.append(
            f"final step: step={fin.get('step')} alive={fin.get('alive')} "
            f"occupied={fin.get('occupied')} "
            f"mm_mass={fin.get('mm_mass')} cm_mass={fin.get('cm_mass')}"
        )
    return "\n".join(lines)
