"""
graftscope — zero-sync telemetry for the magicsoup_tpu step loop.

Three layers, documented in their modules:

- :mod:`.recorder` — :class:`TelemetryRecorder` (host-side phase spans
  + buffered JSONL emission), :class:`TelemetrySnapshot` (unified
  runtime-counter view), :func:`trace_window` (jax.profiler capture of
  a steady-state window), and the ``note_fetch``/``fetch_stats`` D2H
  accounting fed by ``util.fetch_host``.
- :mod:`.summary` — stdlib-pure JSONL parsing/validation/aggregation
  (shared by the CLI and ``scripts/summarize_capture.py``).
- :mod:`.metrics` — graftpulse: the stdlib-pure live metrics registry
  (Prometheus text exposition for ``GET /metrics``) and the
  ``note_device_time``/``device_time_stats`` device-time census the
  serve ledger bills per-tenant ``device_us`` from.
- :mod:`.trace` — recorder JSONL -> Chrome trace-event JSON
  (``python -m magicsoup_tpu.telemetry trace in.jsonl out.json``).
- ``python -m magicsoup_tpu.telemetry summarize run.jsonl`` — per-phase
  p50/p95 and counter deltas from a recorded run.

The on-device half lives in ``stepper._step_body``: per-step metric
lanes (alive/occupancy/mass totals) are packed into the step record
unconditionally, so attaching a recorder changes nothing on device —
det-mode trajectories are bit-identical telemetry on vs off.
"""
from magicsoup_tpu.telemetry.recorder import (
    TelemetryRecorder,
    TelemetrySnapshot,
    fetch_stats,
    note_fetch,
    runtime_counters,
    trace_window,
)
from magicsoup_tpu.telemetry.metrics import (
    MetricsRegistry,
    device_time_stats,
    note_device_time,
    parse_exposition,
)
from magicsoup_tpu.telemetry.summary import (
    read_jsonl,
    summarize_rows,
    validate_rows,
)
from magicsoup_tpu.telemetry.trace import rows_to_trace

__all__ = [
    "MetricsRegistry",
    "TelemetryRecorder",
    "TelemetrySnapshot",
    "device_time_stats",
    "fetch_stats",
    "note_device_time",
    "note_fetch",
    "parse_exposition",
    "rows_to_trace",
    "runtime_counters",
    "trace_window",
    "read_jsonl",
    "summarize_rows",
    "validate_rows",
]
