"""
CLI for graftscope telemetry files::

    python -m magicsoup_tpu.telemetry summarize run.jsonl [--json]
    python -m magicsoup_tpu.telemetry validate run.jsonl
    python -m magicsoup_tpu.telemetry trace run.jsonl run.trace.json

``summarize`` prints per-phase p50/p95 timings and counter deltas
(``--json`` for the machine-readable aggregate); ``validate`` exits
nonzero listing every schema problem; ``trace`` converts recorder span
rows to Chrome trace-event JSON (load in ``chrome://tracing`` or
Perfetto — lanes follow the graftrace ownership roles, timeline is
synthetic; see :mod:`.trace`).  All three run schema validation, so
the CI smoke can gate on exit codes alone.

Imports stay stdlib-only (``summary``/``trace`` modules): processing a
capture never initializes a jax backend.
"""
import argparse
import json
import sys

from magicsoup_tpu.telemetry.summary import (
    format_summary,
    read_jsonl,
    summarize_rows,
    validate_rows,
)
from magicsoup_tpu.telemetry.trace import rows_to_trace


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m magicsoup_tpu.telemetry")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_sum = sub.add_parser("summarize", help="per-phase p50/p95 + deltas")
    p_sum.add_argument("path")
    p_sum.add_argument("--json", action="store_true", dest="as_json")
    p_val = sub.add_parser("validate", help="schema-check a JSONL file")
    p_val.add_argument("path")
    p_tr = sub.add_parser(
        "trace", help="convert spans to Chrome trace-event JSON"
    )
    p_tr.add_argument("path")
    p_tr.add_argument("out")
    args = ap.parse_args(argv)

    try:
        rows = read_jsonl(args.path)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    problems = validate_rows(rows)
    if problems:
        for p in problems:
            print(f"invalid: {p}", file=sys.stderr)
        return 1
    if args.cmd == "validate":
        print(f"{args.path}: {len(rows)} rows, schema OK")
        return 0
    if args.cmd == "trace":
        doc = rows_to_trace(rows)
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
            fh.write("\n")
        print(
            f"{args.out}: {len(doc['traceEvents'])} events from "
            f"{doc['otherData']['dispatches']} dispatches"
        )
        return 0
    summary = summarize_rows(rows)
    if args.as_json:
        print(json.dumps(summary, indent=2, sort_keys=True))
    else:
        print(format_summary(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
