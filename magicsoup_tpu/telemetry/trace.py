"""
graftpulse trace export: recorder JSONL -> Chrome trace-event JSON.

``python -m magicsoup_tpu.telemetry trace run.jsonl run.trace.json``
converts a graftscope capture into the Trace Event Format that
``chrome://tracing`` / Perfetto load directly.  Thread lanes follow the
graftrace ownership roles (:mod:`magicsoup_tpu.analysis.ownership`):
the ``scheduler-loop`` lane carries the host dispatch phases, the
``stepper-worker`` lane the fetch/device spans, and the
``telemetry-writer`` lane the instant events (chaos fault firings,
degradation transitions, warden/sentinel/invariant trips).

**The timeline is synthetic.**  Dispatch rows record per-phase
DURATIONS (milliseconds since the previous dispatch row), not absolute
timestamps, so the exporter lays dispatches out sequentially: each
dispatch's phases start where the previous dispatch ended, and the
phases within one lane are laid end to end in a canonical order.
Durations, ordering, and per-phase proportions are faithful; absolute
concurrency between lanes is not (the live alternative is
:func:`magicsoup_tpu.telemetry.trace_window`, which wraps
``jax.profiler`` around a steady-state window for a REAL timeline).

Stdlib-pure by the same contract as :mod:`.summary` — the CLI path
never initializes a jax backend.
"""
from __future__ import annotations

__all__ = ["rows_to_trace"]

#: host dispatch phases, in the order they are laid out within one
#: dispatch's scheduler-loop span (the order _prepare_dispatch ->
#: _finalize_inputs -> dispatch -> replay actually runs them)
_LOOP_PHASES = (
    "spawn",
    "param_assembly",
    "push",
    "dispatch",
    "dispatch_retry",
    "replay",
)
#: phases that resolve on the fetch worker (graftrace stepper-worker):
#: the D2H fetch span and the commit-to-fetch-ready device span
_WORKER_PHASES = ("device", "fetch")

_TIDS = {"scheduler-loop": 1, "stepper-worker": 2, "telemetry-writer": 3}
_PID = 1

#: instant-event row types relayed to the telemetry-writer lane, with
#: the row keys folded into the event args
_INSTANT_TYPES = ("chaos", "degraded", "warden", "sentinel", "invariant")


def _meta_events() -> list[dict]:
    events = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": _PID,
            "args": {"name": "magicsoup_tpu"},
        }
    ]
    for role, tid in sorted(_TIDS.items(), key=lambda kv: kv[1]):
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": _PID,
                "tid": tid,
                "args": {"name": role},
            }
        )
    return events


def _complete(name: str, tid: int, ts_us: float, dur_us: float, args=None):
    ev = {
        "name": name,
        "ph": "X",
        "pid": _PID,
        "tid": tid,
        "ts": round(ts_us, 3),
        "dur": round(dur_us, 3),
        "cat": "phase",
    }
    if args:
        ev["args"] = args
    return ev


def rows_to_trace(rows: list[dict]) -> dict:
    """Convert validated recorder rows to a trace-event document."""
    events = _meta_events()
    cursor = 0.0  # synthetic timeline, microseconds
    dispatch_index = 0
    for row in rows:
        kind = row.get("type")
        if kind == "dispatch":
            phases = row.get("phases") or {}
            args = {
                k: row[k]
                for k in (
                    "k",
                    "q",
                    "rows",
                    "cold",
                    "compact",
                    "fleet_slot",
                    "fleet_size",
                    "fused_groups",
                    "envelope",
                )
                if k in row
            }
            args["dispatch_index"] = dispatch_index
            lane_end = cursor
            t = cursor
            for name in _LOOP_PHASES:
                if name not in phases:
                    continue
                dur = max(0.0, float(phases[name])) * 1e3
                events.append(
                    _complete(name, _TIDS["scheduler-loop"], t, dur, args)
                )
                t += dur
            lane_end = max(lane_end, t)
            t = cursor
            for name in _WORKER_PHASES:
                if name not in phases:
                    continue
                dur = max(0.0, float(phases[name])) * 1e3
                events.append(
                    _complete(name, _TIDS["stepper-worker"], t, dur, args)
                )
                t += dur
            lane_end = max(lane_end, t)
            # unknown phases (future recorder additions) still render
            for name in sorted(phases):
                if name in _LOOP_PHASES or name in _WORKER_PHASES:
                    continue
                dur = max(0.0, float(phases[name])) * 1e3
                events.append(
                    _complete(name, _TIDS["scheduler-loop"], lane_end, dur, args)
                )
                lane_end += dur
            cursor = lane_end + 1.0  # 1 µs gap keeps dispatches distinct
            dispatch_index += 1
        elif kind == "step":
            events.append(
                {
                    "name": "population",
                    "ph": "C",
                    "pid": _PID,
                    "ts": round(cursor, 3),
                    "args": {
                        "alive": row.get("alive", 0),
                        "occupied": row.get("occupied", 0),
                    },
                }
            )
        elif kind in _INSTANT_TYPES:
            args = {
                k: v
                for k, v in row.items()
                if k != "type" and isinstance(v, (str, int, float, bool))
            }
            events.append(
                {
                    "name": kind,
                    "ph": "i",
                    "s": "t",
                    "pid": _PID,
                    "tid": _TIDS["telemetry-writer"],
                    "ts": round(cursor, 3),
                    "cat": "event",
                    "args": args,
                }
            )
        # meta / counters / accounting rows carry no timeline content
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "magicsoup_tpu.telemetry trace",
            "synthetic_timeline": True,
            "dispatches": dispatch_index,
        },
    }
