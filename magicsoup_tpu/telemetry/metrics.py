"""
graftpulse: the live metrics plane — a stdlib-pure, thread-safe
registry of counters/gauges/histograms rendered as Prometheus text
exposition (version 0.0.4), plus the process-wide DEVICE-TIME census
the serve accounting layer bills per-tenant ``device_us`` from.

Design constraints (mirroring :mod:`.summary`):

- **Stdlib-pure.**  ``scripts/summarize_capture.py`` loads this file
  directly (``spec_from_file_location``) to fold a capture's final
  ``/metrics`` scrape into ``summary["metrics"]`` without initializing
  a jax backend, so nothing here may import jax, numpy, or any other
  magicsoup_tpu module.
- **Zero device sync.**  :func:`note_device_time` is fed from the
  fetch-ready callback the stepper/fleet fetch plumbing fires when the
  ONE sanctioned per-megastep D2H fetch resolves — device time is the
  commit-to-fetch-ready wall span the pipeline already pays for, never
  a new ``block_until_ready`` or extra transfer.
- **Exact conservation.**  Device time accumulates as INTEGER
  microseconds so the serve ledger's even split (divmod, remainder to
  the first tenant in sorted order — the fetch_bytes discipline) makes
  per-tenant ``device_us`` sum EXACTLY to the process total.

The registry is deliberately small: fixed metric families registered
up front, label values escaped per the exposition spec, one coarse
lock (scrape frequency is ~1/s; the hot loop only ever touches the
separate device-time lock below).
"""
from __future__ import annotations

import threading

__all__ = [
    "CONTENT_TYPE",
    "MetricsRegistry",
    "device_time_stats",
    "note_device_time",
    "parse_exposition",
    "reset_device_time",
]

#: the Prometheus text exposition content type (/metrics responses)
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# ----------------------------------------------------------------- #
# process-wide device-time census                                   #
# ----------------------------------------------------------------- #
# mirrors recorder.py's note_fetch/fetch_stats: one lock-guarded pair
# of process accumulators, fed once per PHYSICAL dispatch (a fused
# fleet launch counts once, however many lanes rode it)
_device_lock = threading.Lock()
_device_time_us = 0
_device_dispatches = 0


def note_device_time(seconds: float) -> None:
    """Count one dispatch's commit-to-fetch-ready span (whole-µs).

    Called from the fetch worker's ready callback — once per physical
    device dispatch, before any consumer's ``result()`` returns, so a
    drained scheduler always has a settled census."""
    global _device_time_us, _device_dispatches
    us = max(0, int(round(float(seconds) * 1e6)))
    with _device_lock:
        _device_time_us += us
        _device_dispatches += 1


def device_time_stats() -> dict[str, int]:
    """Process-total measured device time (µs) and dispatches timed."""
    with _device_lock:
        return {
            "device_time_us": _device_time_us,
            "device_dispatches": _device_dispatches,
        }


def reset_device_time() -> None:
    """Zero the census (test isolation; see ``runtime.reset_counters``)."""
    global _device_time_us, _device_dispatches
    with _device_lock:
        _device_time_us = 0
        _device_dispatches = 0


# ----------------------------------------------------------------- #
# exposition format                                                 #
# ----------------------------------------------------------------- #

def escape_label_value(value: str) -> str:
    """Escape a label value per the exposition spec: backslash, quote,
    and newline (in that order — backslash first or the others double)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _format_value(value) -> str:
    # integers render bare (no trailing .0) so counter lines are stable
    # byte-for-byte across scrapes that land on whole numbers
    if isinstance(value, bool):
        return str(int(value))
    if isinstance(value, int):
        return str(value)
    f = float(value)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels_key(label_names, labels: dict) -> tuple:
    extra = set(labels) - set(label_names)
    if extra:
        raise ValueError(
            f"unknown label(s) {sorted(extra)}; declared {list(label_names)}"
        )
    return tuple(str(labels.get(name, "")) for name in label_names)


def _render_labels(label_names, key: tuple) -> str:
    if not label_names:
        return ""
    inner = ",".join(
        f'{name}="{escape_label_value(val)}"'
        for name, val in zip(label_names, key)
    )
    return "{" + inner + "}"


class _Family:
    __slots__ = ("name", "help", "kind", "label_names", "samples", "buckets")

    def __init__(self, name, help_text, kind, label_names, buckets=None):
        self.name = name
        self.help = help_text
        self.kind = kind
        self.label_names = tuple(label_names)
        # labels-key -> value (counter/gauge) or
        # labels-key -> [bucket_counts..., sum, count] (histogram)
        self.samples: dict[tuple, object] = {}
        self.buckets = None if buckets is None else tuple(buckets)


class MetricsRegistry:
    """Fixed-family metrics with Prometheus text rendering.

    Families are declared once (:meth:`counter` / :meth:`gauge` /
    :meth:`histogram`) and fed by ``inc``/``set``/``observe``.
    Counters fed from already-cumulative process totals (the runtime
    snapshot, the accounting ledger) use :meth:`set` — the registry
    pins that the stored value never decreases, so the rendered series
    keeps the counter contract whichever way it is fed.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    # -------------------------------------------------- declaration
    def _declare(self, name, help_text, kind, label_names, buckets=None):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.label_names != tuple(label_names):
                    raise ValueError(
                        f"metric {name!r} re-declared with a different "
                        "type or label set"
                    )
                return fam
            fam = _Family(name, help_text, kind, label_names, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name, help_text, label_names=()):
        self._declare(name, help_text, "counter", label_names)
        return self

    def gauge(self, name, help_text, label_names=()):
        self._declare(name, help_text, "gauge", label_names)
        return self

    def histogram(self, name, help_text, buckets, label_names=()):
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError(f"histogram {name!r} needs at least one bucket")
        self._declare(name, help_text, "histogram", label_names, bounds)
        return self

    # ------------------------------------------------------ feeding
    def _family(self, name, kinds):
        fam = self._families.get(name)
        if fam is None:
            raise KeyError(f"metric {name!r} was never declared")
        if fam.kind not in kinds:
            raise ValueError(
                f"metric {name!r} is a {fam.kind}, not {'/'.join(kinds)}"
            )
        return fam

    def inc(self, name, amount=1, **labels):
        """Add ``amount`` (>= 0) to a counter series."""
        if amount < 0:
            raise ValueError(f"counter {name!r} increment must be >= 0")
        with self._lock:
            fam = self._family(name, ("counter",))
            key = _labels_key(fam.label_names, labels)
            fam.samples[key] = fam.samples.get(key, 0) + amount

    def set(self, name, value, **labels):
        """Set a gauge, or pin a counter to a process-cumulative total
        (monotone: a counter silently keeps its high-water mark)."""
        with self._lock:
            fam = self._family(name, ("counter", "gauge"))
            key = _labels_key(fam.label_names, labels)
            if fam.kind == "counter":
                prev = fam.samples.get(key, 0)
                value = value if value > prev else prev
            fam.samples[key] = value

    def observe(self, name, value, **labels):
        """Record one histogram observation."""
        with self._lock:
            fam = self._family(name, ("histogram",))
            key = _labels_key(fam.label_names, labels)
            state = fam.samples.get(key)
            if state is None:
                state = fam.samples[key] = [0] * len(fam.buckets) + [0.0, 0]
            value = float(value)
            for i, bound in enumerate(fam.buckets):
                if value <= bound:
                    state[i] += 1
            state[-2] += value
            state[-1] += 1

    # ---------------------------------------------------- rendering
    def render(self) -> str:
        """The full exposition document (families in declaration
        order, series in label-sorted order — stable across scrapes)."""
        with self._lock:
            lines: list[str] = []
            for fam in self._families.values():
                lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
                lines.append(f"# TYPE {fam.name} {fam.kind}")
                if fam.kind == "histogram":
                    self._render_histogram(fam, lines)
                    continue
                for key in sorted(fam.samples):
                    labels = _render_labels(fam.label_names, key)
                    value = _format_value(fam.samples[key])
                    lines.append(f"{fam.name}{labels} {value}")
            return "\n".join(lines) + "\n"

    def _render_histogram(self, fam: _Family, lines: list) -> None:
        for key in sorted(fam.samples):
            state = fam.samples[key]
            # bucket counts are stored cumulative-by-le (observe bumps
            # every bucket whose bound covers the value)
            for bound, n in zip(fam.buckets, state[:-2]):
                le = _format_value(bound)
                names = fam.label_names + ("le",)
                labels = _render_labels(names, key + (le,))
                lines.append(f"{fam.name}_bucket{labels} {n}")
            inf_labels = _render_labels(
                fam.label_names + ("le",), key + ("+Inf",)
            )
            lines.append(f"{fam.name}_bucket{inf_labels} {state[-1]}")
            base = _render_labels(fam.label_names, key)
            lines.append(f"{fam.name}_sum{base} {_format_value(state[-2])}")
            lines.append(f"{fam.name}_count{base} {state[-1]}")


# ----------------------------------------------------------------- #
# parsing (tests / smoke / capture folding)                         #
# ----------------------------------------------------------------- #

def _unescape_label_value(value: str) -> str:
    out = []
    i = 0
    while i < len(value):
        ch = value[i]
        if ch == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            out.append({"n": "\n", '"': '"', "\\": "\\"}.get(nxt, nxt))
            i += 2
            continue
        out.append(ch)
        i += 1
    return "".join(out)


def _parse_labels(blob: str) -> dict:
    labels: dict = {}
    i = 0
    while i < len(blob):
        eq = blob.index("=", i)
        name = blob[i:eq].strip().lstrip(",").strip()
        assert blob[eq + 1] == '"', f"malformed label at {blob[i:]!r}"
        j = eq + 2
        raw = []
        while blob[j] != '"':
            if blob[j] == "\\":
                raw.append(blob[j : j + 2])
                j += 2
                continue
            raw.append(blob[j])
            j += 1
        labels[name] = _unescape_label_value("".join(raw))
        i = j + 1
    return labels


def parse_exposition(text: str) -> dict:
    """Parse an exposition document back into
    ``{"types": {name: kind}, "helps": {name: text},
    "samples": [{"name", "labels", "value"}, ...]}``.

    A deliberately strict inverse of :meth:`MetricsRegistry.render`
    for the test/smoke/capture consumers — not a general scraper."""
    types: dict = {}
    helps: dict = {}
    samples: list = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            name, _, rest = line[len("# HELP ") :].partition(" ")
            helps[name] = rest
            continue
        if line.startswith("# TYPE "):
            name, _, kind = line[len("# TYPE ") :].partition(" ")
            types[name] = kind.strip()
            continue
        if line.startswith("#"):
            continue
        head, _, value = line.rpartition(" ")
        if "{" in head:
            name, _, blob = head.partition("{")
            labels = _parse_labels(blob.rstrip("}"))
        else:
            name, labels = head, {}
        samples.append(
            {"name": name, "labels": labels, "value": float(value)}
        )
    return {"types": types, "helps": helps, "samples": samples}


def sample_value(parsed: dict, name: str, **labels) -> float | None:
    """The value of one series in a :func:`parse_exposition` result
    (``None`` when absent) — label match is exact."""
    for s in parsed["samples"]:
        if s["name"] == name and s["labels"] == labels:
            return s["value"]
    return None
