"""
graftscope: the zero-sync telemetry recorder.

Design constraints (the whole point of this module):

- **Zero extra D2H.**  Per-step simulation metrics (alive count, grid
  occupancy, kill/divide/spawn counts, molecule-mass totals) are packed
  into the step record *on device* by ``stepper._step_body`` and ride
  the one sanctioned ``util.fetch_host`` transfer the pipeline already
  performs.  The recorder only ever sees host-side Python scalars.
- **Zero retraces.**  Nothing here is called from inside a jitted body;
  all timing is host-side ``time.perf_counter`` spans around dispatch
  phases.  graftlint rule GL008 enforces the inverse direction: no
  ``io_callback``/host work may be planted inside jitted hot bodies in
  the name of telemetry.
- **Bit-identity.**  The metric lanes are computed unconditionally (the
  device program is identical whether a recorder is attached or not),
  so det-mode trajectories cannot differ telemetry-on vs -off.
- **Bounded memory.**  Per-phase timing keeps exact count/total/max
  plus a bounded ring of recent samples for percentiles; the JSONL
  buffer flushes every ``flush_every`` rows.

Usage::

    world = World(..., telemetry="run.jsonl")     # or:
    world.telemetry.attach("run.jsonl")
    ... step ...
    stepper.flush()                               # drains + flushes rows
    print(world.telemetry.snapshot().to_dict())

then ``python -m magicsoup_tpu.telemetry summarize run.jsonl``.
"""
from __future__ import annotations

import contextlib
import dataclasses
import json
import threading
import time
import warnings
import weakref

from magicsoup_tpu.analysis import ownership
from magicsoup_tpu.guard import chaos as _chaos
from magicsoup_tpu.telemetry.summary import percentile

# per-phase sample rings are trimmed at this size (same bound as the
# stepper's trace ring): percentiles come from recent samples, totals
# and maxima stay exact over the full run
_RING = 4096
_TRIM = _RING // 2

# process-wide D2H fetch accounting, fed by util.fetch_host
_fetch_lock = threading.Lock()
_fetch_count = 0
_fetch_bytes = 0


def note_fetch(nbytes: int) -> None:
    """Count one sanctioned device->host fetch (called by fetch_host)."""
    global _fetch_count, _fetch_bytes
    with _fetch_lock:
        _fetch_count += 1
        _fetch_bytes += int(nbytes)


def fetch_stats() -> dict[str, int]:
    """Process-total sanctioned D2H fetches and bytes moved."""
    with _fetch_lock:
        return {"fetches": _fetch_count, "fetch_bytes": _fetch_bytes}


def runtime_counters() -> dict[str, int]:
    """One flat dict of every process-global counter: compiles,
    persistent-cache and phenotype-cache outcomes (from
    ``analysis.runtime.snapshot``) plus the fetch accounting above.
    Imported lazily so stdlib-only consumers of this module's sibling
    ``summary`` never pull jax in."""
    from magicsoup_tpu.analysis import runtime as _rt

    out = dict(_rt.snapshot())
    out.update(fetch_stats())
    return out


@dataclasses.dataclass(frozen=True)
class TelemetrySnapshot:
    """Point-in-time union of runtime counters and phase timings."""

    counters: dict
    phases: dict
    rows_emitted: int
    path: str | None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _close_handle(fh, buffered: list[str], lock) -> None:
    # weakref.finalize target: flush whatever the recorder still holds
    # buffered if it is garbage-collected while attached.  The finalizer
    # can fire at interpreter exit while a live emit() holds the buffer,
    # so it must take the same lock the recorder's writers hold.
    try:
        with lock:
            if buffered:
                fh.write("\n".join(buffered) + "\n")
            fh.close()
    except Exception:  # graftlint: disable=GL013 gc-time finalizer; nothing above it can react
        pass


class TelemetryRecorder:
    """Host-side span timing + buffered JSONL emission.

    Always constructible and always cheap: an unattached recorder still
    accumulates phase timings (``span``/``note``/``phase_stats``) so the
    performance harnesses can share this implementation, but ``emit`` is
    a no-op until :meth:`attach` opens a JSONL sink.
    """

    def __init__(self, path=None, *, flush_every: int = 256) -> None:
        self._lock = threading.Lock()
        # phase -> [count, total_s, max_s, ring-of-recent-samples]
        self._phases: dict[str, list] = {}
        # phase -> seconds since last take_dispatch() (per-dispatch rows)
        self._window: dict[str, float] = {}
        self._buffer: list[str] = []
        self._fh = None
        self._finalizer = None
        self.path: str | None = None
        self.flush_every = max(1, int(flush_every))
        self.rows_emitted = 0
        # graceful degradation: an I/O failure on the sink disarms the
        # stream into this COUNTED state instead of raising through (or
        # silently losing) a simulation step
        self.degraded = False
        self.degraded_reason: str | None = None
        self.rows_dropped = 0
        # chaos/degraded transitions are PULLED from guard.chaos's event
        # ring at counter-emit boundaries (push would deadlock: the
        # telemetry.emit fault fires inside our own flush).  Start the
        # cursor at "now" so this stream only carries transitions from
        # its own lifetime.
        self._chaos_cursor = _chaos.events_since(0)[0]
        if path is not None:
            self.attach(path)

    # ------------------------------------------------------- lifecycle
    @classmethod
    def coerce(cls, value) -> "TelemetryRecorder":
        """Normalize ``World(telemetry=...)``: None -> fresh detached
        recorder, str/PathLike -> recorder attached to that path, an
        existing recorder passes through (shared across worlds)."""
        if value is None:
            return cls()
        if isinstance(value, cls):
            return value
        return cls(path=value)

    @property
    def attached(self) -> bool:
        return self._fh is not None

    def attach(self, path) -> "TelemetryRecorder":
        """Open ``path`` for append and start emitting JSONL rows."""
        # the attaching thread owns the sink lifecycle (flush/detach);
        # concurrent emit() is fine — it only touches the locked buffer
        ownership.bind(self, "telemetry-writer")
        with self._lock:
            if self._fh is not None:
                raise ValueError(
                    f"already attached to {self.path}; detach() first"
                )
            self.path = str(path)
            self._fh = open(self.path, "a", encoding="utf-8")
            if self.degraded:
                # an explicit re-attach is the recovery path out of the
                # degraded state (rows_dropped stays — it is history)
                self.degraded = False
                self.degraded_reason = None
                _chaos.clear_degraded("telemetry.emit")
            self._finalizer = weakref.finalize(
                self, _close_handle, self._fh, self._buffer, self._lock
            )
        self.emit(
            {
                "type": "meta",
                "version": 1,
                # wall-clock on purpose: correlates the run with external
                # logs; never used for measurement (spans use perf_counter)
                "wall": time.time(),  # graftlint: disable=GL004 telemetry timestamp, not simulation state
            }
        )
        self.emit_counters()
        self.flush()
        return self

    def detach(self) -> None:
        """Emit a final counters row, flush, and close the sink."""
        if self._fh is None:
            return
        ownership.assert_owner(
            self, "telemetry-writer", attribute="TelemetryRecorder._fh"
        )
        self.emit_counters()
        with self._lock:
            fh = self._fh
            if fh is None:
                return
            self._flush_locked()
            self._fh = None
            if self._finalizer is not None:
                self._finalizer.detach()
                self._finalizer = None
        fh.close()

    def __getstate__(self):
        # recorders ride on pickled Worlds; the file handle and lock do
        # not survive — the unpickled twin starts detached
        return {"flush_every": self.flush_every}

    def __setstate__(self, state):
        self.__init__(flush_every=state.get("flush_every", 256))

    # ---------------------------------------------------- span timing
    @contextlib.contextmanager
    def span(self, phase: str):
        """Time a host-side dispatch phase with ``perf_counter``."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.note(phase, time.perf_counter() - t0)

    def note(self, phase: str, seconds: float) -> None:
        """Record an externally measured duration under ``phase``."""
        with self._lock:
            rec = self._phases.get(phase)
            if rec is None:
                rec = self._phases[phase] = [0, 0.0, 0.0, []]
            rec[0] += 1
            rec[1] += seconds
            if seconds > rec[2]:
                rec[2] = seconds
            ring = rec[3]
            ring.append(seconds)
            if len(ring) > _RING:
                del ring[:_TRIM]
            self._window[phase] = self._window.get(phase, 0.0) + seconds

    def take_dispatch(self) -> dict[str, float]:
        """Milliseconds per phase since the previous call (and reset).

        The stepper calls this once per dispatch to build the
        ``dispatch`` JSONL row, so phase costs attribute to the dispatch
        that paid them."""
        with self._lock:
            out = {k: round(v * 1e3, 6) for k, v in self._window.items()}
            self._window.clear()
        return out

    def phase_stats(self) -> dict[str, dict]:
        """Aggregate per-phase stats (count/mean/p50/p95/max/total ms).

        count/total/max are exact over the recorder's lifetime; the
        percentiles come from the bounded recent-sample ring."""
        with self._lock:
            items = {
                name: (rec[0], rec[1], rec[2], list(rec[3]))
                for name, rec in self._phases.items()
            }
        out: dict[str, dict] = {}
        for name in sorted(items):
            n, total, mx, ring = items[name]
            out[name] = {
                "n": n,
                "mean_ms": round(total / n * 1e3, 4) if n else 0.0,
                "p50_ms": round(percentile(ring, 50) * 1e3, 4),
                "p95_ms": round(percentile(ring, 95) * 1e3, 4),
                "max_ms": round(mx * 1e3, 4),
                "total_ms": round(total * 1e3, 4),
            }
        return out

    # ------------------------------------------------------- emission
    def emit(self, row: dict) -> None:
        """Buffer one JSONL row (no-op when detached); auto-flushes
        every ``flush_every`` rows."""
        if self._fh is None:
            if self.degraded:
                with self._lock:
                    self.rows_dropped += 1
            return
        line = json.dumps(row, separators=(",", ":"))
        with self._lock:
            if self._fh is None:
                if self.degraded:
                    self.rows_dropped += 1
                return
            self._buffer.append(line)
            self.rows_emitted += 1
            if len(self._buffer) >= self.flush_every:
                self._flush_locked()

    def emit_counters(self) -> None:
        """Emit a ``counters`` row (attach/flush boundaries call this,
        giving the summarizer first/last values for delta reporting),
        preceded by any ``chaos``/``degraded`` transition rows recorded
        since the last drain."""
        if self._fh is None:
            return
        cursor, events = _chaos.events_since(self._chaos_cursor)
        self._chaos_cursor = cursor
        for row in events:
            self.emit(row)
        self.emit({"type": "counters", "counters": runtime_counters()})

    def flush(self, sync: bool = False) -> None:
        """Write buffered rows through to disk.  Idempotent and safe
        whether attached or not — shutdown paths (graceful-preemption
        handlers, the weakref finalizer) call it unconditionally.

        ``sync=True`` additionally fsyncs the file so the rows survive
        a power cut / SIGKILL that lands right after — the graceful
        SIGTERM drain uses this for its final telemetry flush.
        """
        if self._fh is not None:
            ownership.assert_owner(
                self, "telemetry-writer", attribute="TelemetryRecorder._fh"
            )
        with self._lock:
            self._flush_locked()
            if sync and self._fh is not None:
                import os

                try:
                    os.fsync(self._fh.fileno())
                except ValueError:
                    # not a real file (tests pass StringIO) or already
                    # closed — durability is best-effort on teardown.
                    # io.UnsupportedOperation subclasses ValueError, so
                    # this arm keeps absorbing the StringIO case while a
                    # REAL fsync failure falls through to degrade below
                    pass
                except OSError as exc:
                    self._degrade_locked(exc)

    def _flush_locked(self) -> None:
        if self._fh is None or not self._buffer:
            return
        try:
            fault = _chaos.site("telemetry.emit")
            if fault is not None:
                raise fault.as_oserror()
            self._fh.write("\n".join(self._buffer) + "\n")
            self._buffer.clear()
            self._fh.flush()
        except OSError as exc:
            self._degrade_locked(exc)

    def _degrade_locked(self, exc: OSError) -> None:
        # the telemetry degradation contract: a failed sink NEVER raises
        # into the stepper's dispatch loop and NEVER silently vanishes —
        # the stream disarms, the loss is counted (here + the process-
        # wide chaos registry), and exactly one warning names the cause
        dropped = len(self._buffer)
        self._buffer.clear()
        fh, self._fh = self._fh, None
        if self._finalizer is not None:
            self._finalizer.detach()
            self._finalizer = None
        self.degraded = True
        self.degraded_reason = f"{type(exc).__name__}: {exc}"
        self.rows_dropped += dropped
        if dropped:
            _chaos.note_counter("telemetry_rows_dropped", dropped)
        _chaos.note_degraded("telemetry.emit", self.degraded_reason)
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass  # graftlint: disable=GL013 sink is already dead; close failure adds nothing
        warnings.warn(
            f"telemetry stream to {self.path} degraded after an I/O "
            f"failure ({self.degraded_reason}); {dropped} buffered rows "
            "dropped, further rows are counted and discarded until "
            "re-attach"
        )

    # ------------------------------------------------------- snapshot
    def snapshot(self) -> TelemetrySnapshot:
        """Unified point-in-time view: process counters + phase stats."""
        return TelemetrySnapshot(
            counters=runtime_counters(),
            phases=self.phase_stats(),
            rows_emitted=self.rows_emitted,
            path=self.path,
        )


@contextlib.contextmanager
def trace_window(trace_dir: str):
    """Capture a ``jax.profiler`` trace of the wrapped window.

    Wrap N *steady-state* steps (after warmup, after ``drain()``) so the
    trace shows the repeating dispatch pattern rather than compile
    noise; the ``jax.named_scope`` phase tags the stepper plants
    (``ms:activity``, ``ms:physics``, ``ms:divide``, ...) make the XLA
    ops attributable to simulation phases in the viewer::

        with telemetry.trace_window("/tmp/msoup-trace"):
            for _ in range(20):
                stepper.step()
            stepper.drain()
    """
    import jax

    with jax.profiler.trace(str(trace_dir)):
        yield
