"""
The World: the main API object holding all simulation state and the methods
advancing it.

Parity reference: `python/magicsoup/world.py:36-1004` — same surface
(spawn/add/divide/update/kill/move/reposition cells, enzymatic_activity,
diffuse/degrade_molecules, increment_cell_lifetimes, mutate/recombinate,
get_cell/get_neighbors, save/load + light state checkpoints) and the same
index semantics: cells are dense indices 0..n_cells-1, kill compacts and
shifts indices, molecules are ordered as in :class:`Chemistry`.

TPU-first architecture (SURVEY.md §7):

- **capacity pools, not concatenation**: device tensors are allocated at a
  power-of-two slot capacity and grown amortized; kill is a jitted
  permutation-gather (stable compaction), divide/spawn are masked scatters.
  XLA never sees a shape change except on capacity growth.
- **host/device split**: genome strings, labels, positions, the boolean
  cell map, lifetimes and divisions live host-side (numpy / lists);
  the molecule map, intracellular molecules and all kinetic parameter
  tensors live on device (HBM).  Per-step device work is a handful of
  fused jit programs; per-event bookkeeping is cheap vectorized numpy.
- **explicit seeding**: one ``seed`` drives placement, token maps and
  mutations (the reference draws everything from process-global RNGs).
"""
import functools
import pickle
import random
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from magicsoup_tpu.analysis import runtime as _runtime
from magicsoup_tpu.containers import Cell, Chemistry
from magicsoup_tpu.genetics import Genetics, PhenotypeCache
from magicsoup_tpu.kinetics import Kinetics
from magicsoup_tpu.native import engine as _engine
from magicsoup_tpu.ops import diffusion as _diff
from magicsoup_tpu.ops.integrate import (
    CellParams,
    default_deterministic,
)
from magicsoup_tpu.ops.params import (
    compact_rows,
    copy_params,
    next_rung,
    pad_idxs,
    pad_pow2,
    permute_params,
    quantize_rows,
)
from magicsoup_tpu.util import (
    WarmScheduler,
    async_workers_enabled as _async_workers_enabled,
    fetch_host as _fetch_host,
    randstr,
)

_MIN_CAPACITY = 64

# --------------------------------------------------------------------- #
# jitted state-update kernels (slot-capacity shapes, OOB idxs dropped)   #
# --------------------------------------------------------------------- #


def _make_enzymatic_activity(integrator):
    """Build the jitted activity step around a signal integrator
    (the XLA one, or the Pallas kernel in interpret/compiled mode)."""

    @functools.partial(jax.jit, static_argnames=("q",))
    def _enzymatic_activity(
        molecule_map: jax.Array,  # (mols, m, m)
        cell_molecules: jax.Array,  # (cap, mols)
        positions: jax.Array,  # (cap, 2) int32; dead slots at (0, 0)
        n_cells: jax.Array,  # scalar int
        params,  # CellParams
        q: int | None = None,  # live-row prefix (static); None = cap
    ) -> tuple[jax.Array, jax.Array]:
        """Gather signals, run the MM integrator over the live-row
        prefix, scatter back deltas (reference world.py:610-625)."""
        cap = cell_molecules.shape[0]
        if q is None or q >= cap:
            q = cap
        cm_q = cell_molecules[:q]
        params_q = jax.tree_util.tree_map(lambda t: t[:q], params)
        alive = (jnp.arange(q) < n_cells)[:, None]  # (q, 1)
        xs, ys = positions[:q, 0], positions[:q, 1]
        ext = molecule_map[:, xs, ys].T  # (q, mols)
        X0 = jnp.concatenate([cm_q, ext], axis=1)
        X1 = integrator(X0, params_q)
        n_mols = cell_molecules.shape[1]
        new_cm_q = jnp.where(alive, X1[:, :n_mols], cm_q)
        new_cm = jax.lax.dynamic_update_slice_in_dim(
            cell_molecules, new_cm_q, 0, axis=0
        )
        delta_ext = jnp.where(alive, X1[:, n_mols:] - ext, 0.0)
        new_map = molecule_map.at[:, xs, ys].add(delta_ext.T)
        return new_map, new_cm

    return _enzymatic_activity


_activity_fns: dict = {}  # keyed by integrator backend name; built lazily
_activity_col_fns: dict = {}  # same keys; activity + column slice fused


def _get_activity_fn(integrator: str):
    """The jitted activity program around one registered integrator
    backend (``ops.backends`` is the only selection path — the backend
    name fully determines the traced integrator body)."""
    if integrator not in _activity_fns:
        from magicsoup_tpu.ops import backends as _backends

        _activity_fns[integrator] = _make_enzymatic_activity(
            _backends.integrator_fn(integrator)
        )
    return _activity_fns[integrator]


def _get_activity_col_fn(integrator: str):
    """The activity step with one molecule column sliced out in the SAME
    program (traced column index, so one compile covers all columns) —
    saves the separate slice dispatch when a selection threshold will be
    fetched right after the step."""
    key = integrator
    if key not in _activity_col_fns:
        activity = _get_activity_fn(integrator)

        @functools.partial(jax.jit, static_argnames=("q",))
        def fn(
            molecule_map, cell_molecules, positions, n_cells, params, col,
            q=None,
        ):
            new_map, new_cm = activity(
                molecule_map, cell_molecules, positions, n_cells, params,
                q=q,
            )
            column = jax.lax.dynamic_index_in_dim(
                new_cm, col, axis=1, keepdims=False
            )
            return new_map, new_cm, column

        _activity_col_fns[key] = fn
    return _activity_col_fns[key]


@functools.partial(jax.jit, static_argnames=("det",))
def _diffuse_and_permeate(
    molecule_map: jax.Array,
    cell_molecules: jax.Array,
    positions: jax.Array,
    n_cells: jax.Array,
    kernels: jax.Array,
    perm_factors: jax.Array,
    det: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Map diffusion + membrane permeation (reference world.py:627-665)"""
    new_map = _diff.diffuse(molecule_map, kernels, det=det)
    cap = cell_molecules.shape[0]
    alive = (jnp.arange(cap) < n_cells)[:, None]
    xs, ys = positions[:, 0], positions[:, 1]
    ext = new_map[:, xs, ys].T
    new_cm, new_ext = _diff.permeate(cell_molecules, ext, perm_factors, det=det)
    new_cm = jnp.where(alive, new_cm, cell_molecules)
    delta_ext = jnp.where(alive, new_ext - ext, 0.0)
    new_map = new_map.at[:, xs, ys].add(delta_ext.T)
    return new_map, new_cm


@jax.jit
def _pickup_molecules(
    molecule_map: jax.Array,
    cell_molecules: jax.Array,
    new_pos: jax.Array,  # (b_pad, 2); padding at (0, 0)
    new_idxs: jax.Array,  # (b_pad,); padding OOB
    valid: jax.Array,  # (b_pad,) bool
) -> tuple[jax.Array, jax.Array]:
    """New cells pick up half the molecules of their pixel
    (reference world.py:336-338)."""
    xs, ys = new_pos[:, 0], new_pos[:, 1]
    pickup = molecule_map[:, xs, ys] * 0.5 * valid[None, :]  # (mols, b)
    new_map = molecule_map.at[:, xs, ys].add(-pickup)
    new_cm = cell_molecules.at[new_idxs].add(pickup.T, mode="drop")
    return new_map, new_cm


@functools.partial(jax.jit, static_argnames=("det",))
def _degrade_diffuse_permeate(
    molecule_map: jax.Array,
    cell_molecules: jax.Array,
    positions: jax.Array,
    n_cells: jax.Array,
    degrad_factors: jax.Array,
    kernels: jax.Array,
    perm_factors: jax.Array,
    det: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Degradation + diffusion + permeation fused into one program (the
    jitted callees inline); same order as the separate methods."""
    molecule_map, cell_molecules = _diff.degrade(
        molecule_map, cell_molecules, degrad_factors
    )
    return _diffuse_and_permeate(
        molecule_map,
        cell_molecules,
        positions,
        n_cells,
        kernels,
        perm_factors,
        det=det,
    )


# graftlint: disable=GL006 params is read-only in the step burst; the (map, molecules) successors ARE donated below
@functools.partial(
    jax.jit,
    static_argnames=("det", "integrator", "n_steps", "q"),
    # the burst consumes (molecule_map, cell_molecules) and returns their
    # successors; donation lets XLA update them in place instead of
    # holding two copies of the largest world tensors for n_steps.
    # Donated on CPU too, unlike the stepper's step programs (see
    # stepper._pipeline_step_retained): this conv/elementwise program has
    # no scatter-placement loop, and its CPU donation is exercised green
    # by tests/fast/test_megastep.py (deletion + det-mode bit-identity)
    donate_argnums=(0, 1),
)
def _step_many(
    molecule_map: jax.Array,
    cell_molecules: jax.Array,
    positions: jax.Array,
    n_cells: jax.Array,
    params: CellParams,
    degrad_factors: jax.Array,
    kernels: jax.Array,
    perm_factors: jax.Array,
    *,
    det: bool,
    integrator: str,
    n_steps: int,
    q: int | None = None,
) -> tuple[jax.Array, jax.Array]:
    """``n_steps`` fused chemistry steps (activity -> degrade + diffuse +
    permeate) as ONE ``lax.scan``-driven device program — the classic
    loop's :func:`World.step_many` megastep counterpart.  The math and
    order per iteration are exactly ``enzymatic_activity()`` followed by
    ``degrade_and_diffuse_molecules()``."""
    activity = _get_activity_fn(integrator)

    def body(carry, _):
        # named_scope: profiler-trace phase labels only, no lowering
        # change (same tags as the pipelined stepper's _step_body)
        mm, cm = carry
        with jax.named_scope("ms:activity"):
            mm, cm = activity(mm, cm, positions, n_cells, params, q=q)
        with jax.named_scope("ms:physics"):
            mm, cm = _degrade_diffuse_permeate(
                mm, cm, positions, n_cells,
                degrad_factors, kernels, perm_factors, det=det,
            )
        return (mm, cm), None

    (molecule_map, cell_molecules), _ = jax.lax.scan(
        body, (molecule_map, cell_molecules), None, length=n_steps
    )
    return molecule_map, cell_molecules


@jax.jit
def _set_rows(
    cell_molecules: jax.Array,
    idxs: jax.Array,  # (b_pad,); padding OOB
    values: jax.Array,  # (b_pad, mols)
) -> jax.Array:
    return cell_molecules.at[idxs].set(values, mode="drop")


@jax.jit
def _add_at(
    cell_molecules: jax.Array,
    idxs: jax.Array,  # (b_pad,); padding OOB
    col: jax.Array,  # scalar int — molecule column
    delta: jax.Array,  # scalar float
) -> jax.Array:
    return cell_molecules.at[idxs, col].add(delta, mode="drop")


# graftlint: disable=GL006 compaction gather cannot alias in place; fires on kill events, not per step
@jax.jit
def _kill_update(
    molecule_map: jax.Array,
    cell_molecules: jax.Array,
    params: CellParams,
    positions: jax.Array,
    idxs: jax.Array,  # (b_pad,); padding OOB
    valid: jax.Array,  # (b_pad,) bool
    perm: jax.Array,  # (cap,) stable compaction permutation
    n_keep: jax.Array,  # scalar int
) -> tuple[jax.Array, jax.Array, CellParams, jax.Array]:
    """Fused kill step: killed cells dump their contents onto their pixel
    (reference world.py:520-525), then cell rows, all kinetic parameter
    tensors and the device position mirror are compacted by one
    permutation.  One dispatch — a remote accelerator pays per-call
    latency, so the four updates ride together.
    """
    pos = positions[idxs]  # OOB clamps; masked below
    spill = cell_molecules[idxs] * valid[:, None]  # (b, mols)
    new_map = molecule_map.at[:, pos[:, 0], pos[:, 1]].add(spill.T)
    new_cm = compact_rows(cell_molecules, perm, n_keep)
    new_pos = compact_rows(positions, perm, n_keep)
    return new_map, new_cm, permute_params(params, perm, n_keep), new_pos


# graftlint: disable=GL006 self-referencing parent->child copies cannot alias in place; fires on divide events only
@jax.jit
def _divide_update(
    cell_molecules: jax.Array,
    params: CellParams,
    positions: jax.Array,  # (cap, 2) int32
    parent_idxs: jax.Array,  # (b_pad,); padding OOB
    child_idxs: jax.Array,  # (b_pad,); padding OOB
    child_pos: jax.Array,  # (b_pad, 2) int32; padding rows ignored
) -> tuple[jax.Array, CellParams, jax.Array]:
    """Fused divide step: molecules are shared evenly among both
    descendants (reference world.py:467-470), the children inherit the
    parents' kinetic parameter rows, and the device position mirror gets
    the child pixels — one dispatch."""
    half = cell_molecules[parent_idxs] * 0.5
    cm = cell_molecules.at[parent_idxs].set(half, mode="drop")
    cm = cm.at[child_idxs].set(half, mode="drop")
    new_pos = positions.at[child_idxs].set(child_pos, mode="drop")
    return cm, copy_params(params, parent_idxs, child_idxs), new_pos


@jax.jit
def _set_prefix(
    cell_molecules: jax.Array,  # (cap, mols)
    values: jax.Array,  # (cap, mols) — rows >= n ignored
    n: jax.Array,  # scalar int
) -> jax.Array:
    """Overwrite rows 0..n-1 with static shapes (no per-n recompiles)"""
    keep = (jnp.arange(cell_molecules.shape[0]) < n)[:, None]
    return jnp.where(keep, values, cell_molecules)


def _resolve_device(spec) -> "jax.Device | None":
    """``None`` | ``"tpu"`` | ``"cpu:1"`` | a ``jax.Device`` -> a concrete
    device, or None for backend-default placement."""
    if spec is None:
        return None
    if isinstance(spec, jax.Device):
        return spec
    platform, _, idx = str(spec).partition(":")
    try:
        devices = jax.devices(platform)
    except RuntimeError as err:
        raise ValueError(
            f"device={spec!r}: no {platform!r} backend available ({err})"
        ) from None
    try:
        i = int(idx) if idx else 0
    except ValueError:
        raise ValueError(
            f"device={spec!r}: index {idx!r} is not an integer"
        ) from None
    if i < 0 or i >= len(devices):
        raise ValueError(
            f"device={spec!r}: only {len(devices)} {platform!r} device(s)"
        )
    return devices[i]


class World:
    """
    Main API for running the simulation; holds the state and offers methods
    to advance it.

    Parameters:
        chemistry: :class:`Chemistry` with molecules and reactions.
        map_size: Number of pixels in x and y direction of the world torus.
        abs_temp: Absolute temperature (K); influences reaction equilibria.
        mol_map_init: Initial molecule map concentrations — ``"randn"``
            (|N(10, 1)|) or ``"zeros"``.
        start_codons: Codons starting a coding sequence.
        stop_codons: Codons stopping a coding sequence.
        device: Where the device-side state lives: ``None`` (backend
            default — TPU when available), a platform string like
            ``"cpu"`` / ``"tpu"`` / ``"tpu:1"``, or a ``jax.Device``.
            Unknown backends raise (the reference silently fell back to
            CPU, world.py:158-159 — a documented quirk, not copied).
            Mutually exclusive with ``mesh``.
        batch_size: Optional chunk size when updating cell parameters
            (bounds memory peaks of spawn/update at many cells).
        seed: Seed driving all randomness (placement, token maps,
            mutations).  ``None`` draws a random seed.
        phenotype_cache_size: Max entries of the genome->phenotype LRU
            cache (``World.phenotypes``); ``0`` disables cross-call
            caching.  Cached and uncached paths are bit-identical.
        telemetry: graftscope sink — ``None`` (default) keeps a detached
            :class:`~magicsoup_tpu.telemetry.TelemetryRecorder` (phase
            timing only), a path opens a JSONL sink, or pass a recorder
            to share one stream across worlds.  Attaching a recorder
            never changes simulation results (README "Telemetry").

    State is exposed with the reference's names — ``cell_genomes``,
    ``cell_labels``, ``cell_map``, ``cell_positions``, ``cell_lifetimes``,
    ``cell_divisions``, ``cell_molecules``, ``molecule_map`` — with cells
    always indexed 0..n_cells-1 (kill compacts indices, like the
    reference).  Device-backed attributes are jax Arrays; assign through
    the provided setters (jax arrays are immutable).
    """

    def __init__(
        self,
        chemistry: Chemistry,
        map_size: int = 128,
        abs_temp: float = 310.0,
        mol_map_init: str = "randn",
        start_codons: tuple[str, ...] = ("TTG", "GTG", "ATG"),
        stop_codons: tuple[str, ...] = ("TGA", "TAG", "TAA"),
        device: str | None = None,
        batch_size: int | None = None,
        seed: int | None = None,
        mesh: "jax.sharding.Mesh | None" = None,
        integrator: str | None = None,
        use_pallas: bool | None = None,
        phenotype_cache_size: int = 16384,
        telemetry=None,
        genome_backend: str = "string",
    ):
        if seed is None:
            seed = random.SystemRandom().randrange(2**63)  # graftlint: disable=GL004 entropy only when the caller passed no seed
        self.seed = seed
        self._rng = random.Random(seed)
        self._nprng = np.random.default_rng(seed)

        # graftscope recorder (magicsoup_tpu.telemetry): None -> detached
        # recorder (phase timing only, no emission), a path -> JSONL sink
        # opened now, an existing TelemetryRecorder -> shared.  Steppers
        # built on this world pick it up; attach later any time with
        # ``world.telemetry.attach(path)``.
        from magicsoup_tpu.telemetry import TelemetryRecorder

        self.telemetry = TelemetryRecorder.coerce(telemetry)

        if device is not None and mesh is not None:
            raise ValueError(
                "device and mesh are mutually exclusive: a mesh-placed"
                " world is sharded over the mesh's devices"
            )
        self.device = device
        self._device = _resolve_device(device)
        # resolved ONCE against the platform this world's arrays live on
        # (the background-worker hazard is per-client, not per-process)
        self._async_workers = _async_workers_enabled(
            self._device.platform if self._device is not None else None
        )
        self.batch_size = batch_size
        self.map_size = map_size
        self.abs_temp = abs_temp
        self.chemistry = chemistry

        # multi-chip: place all device state sharded over the mesh (map by
        # rows, cell-axis tensors by slots).  Every jitted step then runs
        # SPMD — GSPMD inserts the collectives for the cell<->map signal
        # exchange, and host bookkeeping stays global, so divide /
        # recombination across tile boundaries need no special casing.
        self._mesh = mesh
        self._map_sharding = None
        self._cell_sharding = None
        if mesh is not None:
            from magicsoup_tpu.parallel import tiled

            # rows shard along the FIRST mesh axis only (tiled.map_sharding)
            n_tiles = int(mesh.shape[mesh.axis_names[0]])
            if map_size % n_tiles != 0:
                raise ValueError(
                    f"map_size={map_size} must be divisible by the first"
                    f" mesh axis size {n_tiles} for row sharding"
                )
            self._map_sharding = tiled.map_sharding(mesh)
            self._cell_sharding = tiled.cell_sharding(mesh)

        # Integrator backend: the ops.backends registry is the ONLY
        # selection path — explicit ``integrator=`` name, the env vars,
        # or the legacy ``use_pallas`` flag all resolve there, with the
        # capability flags (mesh-able, det-able) enforced by the
        # registry instead of scattered raises here.
        from magicsoup_tpu.ops import backends as _backends

        # numeric mode, fixed per instance at construction (README
        # "Numeric modes"): deterministic = bit-reproducible across
        # backends, fast = backend-native lowerings
        self.deterministic = default_deterministic()
        choice, pinned = _backends.resolve(
            integrator,
            use_pallas=use_pallas,
            deterministic=self.deterministic,
            mesh=mesh,
        )
        # unpinned = derived from the numeric mode only; the
        # ``integrator`` property keeps following ``deterministic`` then
        self._integrator_choice = choice if pinned else None

        self.genetics = Genetics(
            start_codons=start_codons,
            stop_codons=stop_codons,
            seed=self._rng.randrange(2**63),
        )
        # genome -> phenotype LRU (no RNG draw: construction here must not
        # shift the seed-derived stream feeding Kinetics below)
        self.phenotypes = PhenotypeCache(
            self.genetics, maxsize=phenotype_cache_size
        )
        self.kinetics = Kinetics(
            chemistry=chemistry,
            abs_temp=abs_temp,
            scalar_enc_size=max(self.genetics.one_codon_map.values()),
            vector_enc_size=max(self.genetics.two_codon_map.values()),
            seed=self._rng.randrange(2**63),
        )
        self.kinetics.cell_sharding = self._cell_sharding

        mols = chemistry.molecules
        self.n_molecules = len(mols)
        self._diff_kernels = jnp.asarray(
            _diff.diffusion_kernels([d.diffusivity for d in mols])
        )
        self._perm_factors = jnp.asarray(
            _diff.permeation_factors([d.permeability for d in mols])
        )
        self._degrad_factors = jnp.asarray(
            _diff.degradation_factors([d.half_life for d in mols])
        )

        # genome storage backend: "string" keeps the reference host list
        # of genome strings; "token" keeps genomes device-resident as a
        # packed (cap, G) int8 token tensor + length vector (GenomeStore),
        # mutated by jitted kernels — strings then exist only at the
        # import/export boundary (spawn/save/get_cell)
        if genome_backend not in ("string", "token"):
            raise ValueError(
                f"genome_backend must be 'string' or 'token',"
                f" got {genome_backend!r}"
            )
        self.genome_backend = genome_backend
        self._genome_store = None

        # host-side state
        self.n_cells = 0
        self._genomes_list: list[str] = []
        self.cell_labels: list[str] = []
        self._capacity = 0
        self._np_cell_map = np.zeros((map_size, map_size), dtype=bool)
        self._np_positions = np.zeros((0, 2), dtype=np.int32)
        self._np_lifetimes = np.zeros(0, dtype=np.int32)
        self._np_divisions = np.zeros(0, dtype=np.int32)
        # mutation marker for the few IN-PLACE host mutators (lifetimes /
        # divisions writes that replace no array object): every other
        # mutator replaces an array or list, which the stepper's
        # flush-token identity check already observes.  Together they let
        # a re-attach after flush prove "nothing touched this World" and
        # skip the serial per-world host replay rebuild.
        self._host_epoch = 0

        # device-side state (+ identity-keyed host snapshot caches)
        self._cell_molecules = jnp.zeros((0, self.n_molecules), dtype=jnp.float32)
        self._positions_dev = jnp.zeros((0, 2), dtype=jnp.int32)
        self._molecule_map = self._init_molecule_map(mol_map_init)
        self._mm_cache: tuple | None = None
        self._cm_cache: tuple | None = None

        # activity-program variant bookkeeping (see enzymatic_activity);
        # keys include the kinetics token capacities the shapes depend on
        self._warm_sched = WarmScheduler()

        self._ensure_capacity(_MIN_CAPACITY)

    # ------------------------------------------------------------------ #
    # state views                                                        #
    # ------------------------------------------------------------------ #

    @property
    def cell_genomes(self) -> list[str]:
        """Genome strings of all living cells.

        String backend: the actual mutable host list.  Token backend: a
        decoded EXPORT VIEW of the device token store, cached per store
        version — cheap to re-read, but treat it as read-only (mutations
        of the returned list are not written back; assign a full list or
        use the ``update_cells``/``spawn_cells`` APIs instead).
        """
        if self._genome_store is not None:
            return self._genome_store.decoded(self.n_cells)
        return self._genomes_list

    @cell_genomes.setter
    def cell_genomes(self, value):
        if self._genome_store is not None:
            self._genome_store.set_all(list(value))
        else:
            self._genomes_list = list(value)

    @property
    def genome_store(self):
        """The device :class:`~magicsoup_tpu.genomes.GenomeStore`
        (token backend only; ``None`` on the string backend)."""
        return self._genome_store

    @property
    def molecule_map(self) -> jax.Array:
        """(n_mols, m, m) float32 molecule concentrations on the map"""
        return self._molecule_map

    @molecule_map.setter
    def molecule_map(self, value):
        if not isinstance(value, jax.Array):
            value = np.asarray(value, dtype=np.float32)
        if tuple(value.shape) != self._molecule_map.shape:
            raise ValueError(f"molecule_map must have shape {self._molecule_map.shape}")
        if isinstance(value, jax.Array):
            # already on device: device_put reshards without a host trip
            value = value.astype(jnp.float32)
            self._molecule_map = (
                jax.device_put(value, self._map_sharding)
                if self._map_sharding is not None
                else value
            )
        else:
            self._molecule_map = self._place_map(value)

    def _host_molecule_map(self) -> np.ndarray:
        """Cached host snapshot of the molecule map.  Valid exactly while
        the device array object is unchanged (jax arrays are immutable, so
        identity comparison is an exact invalidation test)."""
        cache = self._mm_cache
        if cache is None or cache[0] is not self._molecule_map:
            cache = (self._molecule_map, _fetch_host(self._molecule_map))
            self._mm_cache = cache
        return cache[1]

    def _host_cell_molecules(self) -> np.ndarray:
        """Cached host snapshot of the full-capacity cell molecule buffer"""
        cache = self._cm_cache
        if cache is None or cache[0] is not self._cell_molecules:
            cache = (self._cell_molecules, _fetch_host(self._cell_molecules))
            self._cm_cache = cache
        return cache[1]

    @property
    def cell_molecules(self) -> np.ndarray:
        """
        (n_cells, n_mols) float32 intracellular concentrations as a
        READ-ONLY host numpy view.  In-place writes raise — copy, modify,
        and assign back instead (``cm = world.cell_molecules.copy();
        ...; world.cell_molecules = cm``).  The full-capacity device
        buffer is ``world._cell_molecules``.

        Returned host-side on purpose: slicing the device buffer to the
        current (dynamic) cell count would compile a fresh XLA program for
        every population size.
        """
        return self._host_cell_molecules()[: self.n_cells]

    def _record_col_prefetch(self, mol_idx: int, col: jax.Array):
        """Start the device→host copy of an in-flight column slice and
        remember it for :meth:`cell_molecule_column` pickup."""
        if not getattr(col, "is_fully_addressable", True):
            # multi-host: the local-shard copy would be discarded by the
            # process_allgather fetch anyway — skip the dead transfer
            return
        try:
            col.copy_to_host_async()
        except AttributeError:  # non-jax array stand-ins in tests
            pass
        self._col_prefetch = (self._cell_molecules, mol_idx, col)

    def prefetch_cell_molecule_column(self, mol_idx: int):
        """
        Start an async device→host copy of one molecule column.  Call
        right after dispatching the device work that produces it (e.g.
        ``enzymatic_activity``) so the transfer overlaps the computation
        and — on remote accelerators — the request's network round trip.
        A later :meth:`cell_molecule_column` for the same state picks up
        the in-flight copy instead of starting a fresh one.
        """
        self._record_col_prefetch(mol_idx, self._cell_molecules[:, mol_idx])

    def cell_molecule_column(self, mol_idx: int) -> np.ndarray:
        """
        (n_cells,) float32 host copy of ONE molecule's intracellular
        concentrations.  ~n_mols× less device→host traffic than the full
        ``cell_molecules`` matrix — use for per-step selection thresholds
        (the canonical workload only ever looks at ATP).

        The slice is taken at the full (static) slot capacity so XLA
        compiles it once, not once per population size.
        """
        pf = getattr(self, "_col_prefetch", None)
        if (
            pf is not None
            and pf[0] is self._cell_molecules
            and pf[1] == mol_idx
        ):
            col = pf[2]
        else:
            col = self._cell_molecules[:, mol_idx]
        self._col_prefetch = None
        return _fetch_host(col)[: self.n_cells]

    def add_cell_molecules(self, cell_idxs: list[int], mol_idx: int, delta: float):
        """Add ``delta`` to one molecule of the given cells on device —
        avoids a full fetch-modify-push round trip of ``cell_molecules``."""
        if len(cell_idxs) == 0:
            return
        idxs_pad = pad_idxs(np.asarray(cell_idxs, dtype=np.int32), oob=self._capacity)
        self._cell_molecules = _add_at(
            self._cell_molecules,
            jnp.asarray(idxs_pad),
            jnp.asarray(mol_idx, dtype=jnp.int32),
            jnp.asarray(delta, dtype=jnp.float32),
        )

    @cell_molecules.setter
    def cell_molecules(self, value):
        value = np.asarray(value, dtype=np.float32)
        if value.shape != (self.n_cells, self.n_molecules):
            raise ValueError(
                f"cell_molecules must have shape {(self.n_cells, self.n_molecules)}"
            )
        vals = np.zeros((self._capacity, self.n_molecules), dtype=np.float32)
        vals[: self.n_cells] = value
        self._cell_molecules = _set_prefix(
            self._cell_molecules, self._place_cells(vals), self._n_cells_dev()
        )

    @property
    def cell_map(self) -> np.ndarray:
        """(m, m) bool — which pixels are occupied by a cell (host numpy)"""
        return self._np_cell_map

    @property
    def cell_positions(self) -> np.ndarray:
        """(n_cells, 2) int32 cell positions (host numpy)"""
        return self._np_positions[: self.n_cells]

    @property
    def cell_lifetimes(self) -> np.ndarray:
        """(n_cells,) int32 — steps alive since spawn or last division"""
        return self._np_lifetimes[: self.n_cells]

    @cell_lifetimes.setter
    def cell_lifetimes(self, value):
        self._host_epoch += 1
        self._np_lifetimes[: self.n_cells] = np.asarray(value, dtype=np.int32)

    @property
    def cell_divisions(self) -> np.ndarray:
        """(n_cells,) int32 — number of ancestor divisions"""
        return self._np_divisions[: self.n_cells]

    @cell_divisions.setter
    def cell_divisions(self, value):
        self._host_epoch += 1
        self._np_divisions[: self.n_cells] = np.asarray(value, dtype=np.int32)

    # ------------------------------------------------------------------ #
    # capacity                                                           #
    # ------------------------------------------------------------------ #

    def _ensure_capacity(self, n: int):
        if n <= self._capacity:
            return
        cap = pad_pow2(n, minimum=_MIN_CAPACITY)
        if self._mesh is not None:
            # the cell axis is sharded: capacity must split evenly across
            # tiles (pow2 caps with a pow2 tile count already do; this
            # covers meshes of e.g. 3 or 6 devices)
            n_tiles = int(self._mesh.shape[self._mesh.axis_names[0]])
            cap = -(-cap // n_tiles) * n_tiles
        grow = cap - self._capacity
        self._np_positions = np.concatenate(
            [self._np_positions, np.zeros((grow, 2), dtype=np.int32)]
        )
        self._np_lifetimes = np.concatenate(
            [self._np_lifetimes, np.zeros(grow, dtype=np.int32)]
        )
        self._np_divisions = np.concatenate(
            [self._np_divisions, np.zeros(grow, dtype=np.int32)]
        )
        cm = np.zeros((cap, self.n_molecules), dtype=np.float32)
        cm[: self._capacity] = _fetch_host(self._cell_molecules)
        self._cell_molecules = self._place_cells(cm)
        self._capacity = cap
        if self.genome_backend == "token":
            if self._genome_store is None:
                from magicsoup_tpu.genomes import GenomeStore

                self._genome_store = GenomeStore(
                    cap, place=self._place_cells
                )
            else:
                self._genome_store.grow_capacity(cap)
        self._sync_positions()
        self.kinetics.ensure_capacity(n_cells=cap)
        # capacity growth changes the activity program's shapes: the
        # compiled-variant bookkeeping starts over
        self._warm_sched.reset()

    def _place_map(self, arr) -> jax.Array:
        """Host array -> device: sharded over the mesh when one is set,
        committed to the selected device when one was requested"""
        if self._map_sharding is not None:
            return jax.device_put(arr, self._map_sharding)
        if self._device is not None:
            return jax.device_put(arr, self._device)
        return jnp.asarray(arr)

    def _place_cells(self, arr) -> jax.Array:
        if self._cell_sharding is not None:
            return jax.device_put(arr, self._cell_sharding)
        if self._device is not None:
            return jax.device_put(arr, self._device)
        return jnp.asarray(arr)

    def _sync_positions(self):
        self._positions_dev = self._place_cells(self._np_positions)

    def _n_cells_dev(self) -> jax.Array:
        return jnp.asarray(self.n_cells, dtype=jnp.int32)

    def _init_molecule_map(self, init: str) -> jax.Array:
        shape = (self.n_molecules, self.map_size, self.map_size)
        if init == "zeros":
            return self._place_map(np.zeros(shape, dtype=np.float32))
        if init == "randn":
            arr = np.abs(
                self._nprng.standard_normal(shape, dtype=np.float32) + 10.0
            )
            return self._place_map(arr)
        raise ValueError(
            f"Didnt recognize mol_map_init={init}. Should be one of: 'zeros', 'randn'."
        )

    # ------------------------------------------------------------------ #
    # interpretation                                                     #
    # ------------------------------------------------------------------ #

    def get_cell(
        self,
        by_idx: int | None = None,
        by_position: tuple[int, int] | None = None,
    ) -> Cell:
        """Get a :class:`Cell` view of one cell (analysis helper)"""
        idx = -1
        if by_idx is not None:
            idx = by_idx
        if by_position is not None:
            pos = np.asarray(by_position, dtype=np.int32)
            hits = np.nonzero((self.cell_positions == pos).all(axis=1))[0]
            if len(hits) == 0:
                raise ValueError(f"Cell at {by_position} not found")
            idx = int(hits[0])

        return Cell(
            world=self,
            idx=idx,
            # token backend: defer to Cell.genome, which decodes ONE row
            # instead of exporting the whole population
            genome=(
                None
                if self._genome_store is not None
                else self._genomes_list[idx]
            ),
            position=tuple(self._np_positions[idx].tolist()),  # type: ignore
            label=self.cell_labels[idx],
            n_steps_alive=int(self._np_lifetimes[idx]),
            n_divisions=int(self._np_divisions[idx]),
        )

    def genome_of(self, idx: int) -> str:
        """One cell's genome string (token backend: decodes just that
        row; string backend: a list index)."""
        if self._genome_store is not None:
            return self._genome_store.decode_row(idx)
        return self._genomes_list[idx]

    def get_neighbors(
        self, cell_idxs: list[int], nghbr_idxs: list[int] | None = None
    ) -> list[tuple[int, int]]:
        """
        Unique Moore-neighborhood pairs among cells (smaller index first).
        With ``nghbr_idxs`` given, pairs are restricted to partners from
        that list (reference world.py:247-285; vectorized via an occupancy
        grid instead of pairwise distances).
        """
        pairs = self._neighbor_pairs(cell_idxs, nghbr_idxs)
        return list(zip(pairs[:, 0].tolist(), pairs[:, 1].tolist()))

    def _neighbor_pairs(
        self,
        cell_idxs: list[int] | None,
        nghbr_idxs: list[int] | None = None,
    ) -> np.ndarray:
        """:meth:`get_neighbors` as a (k, 2) int64 array, smaller index
        first, sorted; ``cell_idxs=None`` means the whole population"""
        n = self.n_cells
        if cell_idxs is None and nghbr_idxs is None:
            # whole-population fast path: one shared implementation with
            # the pipelined stepper's recombination replay
            from magicsoup_tpu.util import moore_pairs

            return moore_pairs(self._np_positions[:n], self.map_size)
        if cell_idxs is None:
            from_idxs = np.arange(n, dtype=np.int64)
        else:
            if len(cell_idxs) == 0:
                return np.zeros((0, 2), dtype=np.int64)
            from_idxs = np.array(sorted(set(cell_idxs)), dtype=np.int64)
        if nghbr_idxs is None:
            to_member = None if cell_idxs is None else np.zeros(n, dtype=bool)
            if to_member is not None:
                to_member[from_idxs] = True
        else:
            if len(nghbr_idxs) == 0:
                return np.zeros((0, 2), dtype=np.int64)
            to_member = np.zeros(n, dtype=bool)
            to_member[list(set(nghbr_idxs))] = True

        m = self.map_size
        grid = np.full((m, m), -1, dtype=np.int64)
        pos = self._np_positions[:n]
        grid[pos[:, 0], pos[:, 1]] = np.arange(n)

        fp = pos[from_idxs]  # (k, 2)
        dx = np.array([-1, -1, -1, 0, 0, 1, 1, 1])
        dy = np.array([-1, 0, 1, -1, 1, -1, 0, 1])
        nx = (fp[:, 0][:, None] + dx[None, :]) % m
        ny = (fp[:, 1][:, None] + dy[None, :]) % m
        cand = grid[nx, ny]  # (k, 8)
        src = np.broadcast_to(from_idxs[:, None], cand.shape)
        # cand != src guards degenerate torus wraps (map_size <= 2), where
        # a Moore offset can land back on the cell's own pixel
        valid = (cand >= 0) & (cand != src)
        if to_member is not None:
            valid &= to_member[np.clip(cand, 0, None)]
        a = src[valid]
        b = cand[valid]
        lo = np.minimum(a, b)
        hi = np.maximum(a, b)
        # 1D-encoded unique (np.unique(axis=0) goes through a slow
        # void-dtype view; this is ~100x faster at 10k cells)
        enc = np.unique(lo * np.int64(n) + hi)
        return np.stack([enc // n, enc % n], axis=1)

    # ------------------------------------------------------------------ #
    # cell lifecycle                                                     #
    # ------------------------------------------------------------------ #

    def _find_free_random_positions(self, n_cells: int) -> np.ndarray:
        free = np.argwhere(~self._np_cell_map)
        if n_cells > len(free):
            n_cells = len(free)
        chosen = self._nprng.choice(len(free), size=n_cells, replace=False)
        return free[chosen].astype(np.int32)

    def spawn_cells(self, genomes: list[str]) -> list[int]:
        """
        Create new cells from genome strings and place them on random free
        pixels.  Each new cell picks up half the molecules of its pixel,
        gets lifetime 0, 0 divisions, and a random label.  Returns the new
        cell indexes.
        """
        n_new = len(genomes)
        if n_new == 0:
            return []
        free_pos = self._find_free_random_positions(n_cells=n_new)
        if len(free_pos) == 0:
            return []
        if len(free_pos) < n_new:
            n_new = len(free_pos)
            genomes = list(genomes)
            self._rng.shuffle(genomes)
            genomes = genomes[:n_new]

        new_idxs = list(range(self.n_cells, self.n_cells + n_new))
        self._ensure_capacity(self.n_cells + n_new)
        self.n_cells += n_new
        if self._genome_store is not None:
            # string import boundary: encode once; the encoded rows feed
            # both the device scatter and the hash-keyed translation below
            g_rows, g_lens = self._genome_store.set_rows(new_idxs, genomes)
        else:
            self._genomes_list.extend(genomes)
        self.cell_labels.extend(randstr(n=12, rng=self._rng) for _ in range(n_new))

        self._np_cell_map[free_pos[:, 0], free_pos[:, 1]] = True
        self._np_positions[new_idxs] = free_pos
        self._np_lifetimes[new_idxs] = 0
        self._np_divisions[new_idxs] = 0
        self._sync_positions()

        idxs_pad = pad_idxs(np.asarray(new_idxs), oob=self._capacity)
        b_pad = len(idxs_pad)
        pos_pad = np.zeros((b_pad, 2), dtype=np.int32)
        pos_pad[:n_new] = free_pos
        valid = np.zeros(b_pad, dtype=bool)
        valid[:n_new] = True
        self._molecule_map, self._cell_molecules = _pickup_molecules(
            self._molecule_map,
            self._cell_molecules,
            jnp.asarray(pos_pad),
            jnp.asarray(idxs_pad),
            jnp.asarray(valid),
        )

        if self._genome_store is not None:
            self._update_cell_params_rows(new_idxs, g_rows, g_lens)
        else:
            self._update_cell_params(genomes=genomes, idxs=new_idxs)
        return new_idxs

    def add_cells(self, cells: list[Cell]) -> list[int]:
        """
        Place :class:`Cell` objects on random free pixels, keeping their
        genomes, molecules, lifetimes, divisions and labels.  Returns the
        new cell indexes.
        """
        n_new = len(cells)
        if n_new == 0:
            return []
        free_pos = self._find_free_random_positions(n_cells=n_new)
        if len(free_pos) == 0:
            return []
        if len(free_pos) < n_new:
            n_new = len(free_pos)
            cells = list(cells)
            self._rng.shuffle(cells)
            cells = cells[:n_new]

        new_idxs = list(range(self.n_cells, self.n_cells + n_new))
        self._ensure_capacity(self.n_cells + n_new)
        self.n_cells += n_new
        if self._genome_store is not None:
            g_rows, g_lens = self._genome_store.set_rows(
                new_idxs, [d.genome for d in cells]
            )
        else:
            self._genomes_list.extend(d.genome for d in cells)
        for cell in cells:
            self.cell_labels.append(cell.label)

        self._np_cell_map[free_pos[:, 0], free_pos[:, 1]] = True
        self._np_positions[new_idxs] = free_pos
        self._np_lifetimes[new_idxs] = [d.n_steps_alive for d in cells]
        self._np_divisions[new_idxs] = [d.n_divisions for d in cells]
        self._sync_positions()

        idxs_pad = pad_idxs(np.asarray(new_idxs), oob=self._capacity)
        vals = np.zeros((len(idxs_pad), self.n_molecules), dtype=np.float32)
        vals[:n_new] = np.stack([np.asarray(d.int_molecules) for d in cells])
        self._cell_molecules = _set_rows(
            self._cell_molecules, jnp.asarray(idxs_pad), jnp.asarray(vals)
        )

        if self._genome_store is not None:
            self._update_cell_params_rows(new_idxs, g_rows, g_lens)
        else:
            self._update_cell_params(
                genomes=[d.genome for d in cells], idxs=new_idxs
            )
        return new_idxs

    _MOORE_DX = np.array([-1, -1, -1, 0, 0, 1, 1, 1], dtype=np.int64)
    _MOORE_DY = np.array([-1, 0, 1, -1, 1, -1, 0, 1], dtype=np.int64)

    def _place_in_neighborhood(
        self, idxs: np.ndarray, vacate: bool
    ) -> list[tuple[int, tuple[int, int]]]:
        """
        Place one pixel per cell in its free Moore neighborhood, no two on
        the same pixel (reference rust/world.rs:59-146, which scans
        candidates in parallel then resolves conflicts sequentially).

        Vectorized round-based resolution instead of a per-cell loop: each
        round every pending cell draws a uniformly random free neighbor
        against the current map; when several cells draw the same pixel
        the lowest-index cell wins and the rest retry next round against
        the updated map — so earlier cells constrain later ones exactly as
        in a sequential pass.  With ``vacate`` (move), a winner frees its
        old pixel, which becomes available from the next round on; without
        (divide), the new pixel stays occupied by the child.  Updates
        ``_np_cell_map`` (+ ``_np_positions`` when vacating) in place and
        returns ``(cell_idx, (x, y))`` for each placed cell, by cell idx.
        """
        m = self.map_size
        cmap = self._np_cell_map
        pos = self._np_positions
        dx, dy = self._MOORE_DX, self._MOORE_DY
        pending = idxs
        placed: list[tuple[int, tuple[int, int]]] = []
        while len(pending) > 0:
            p = pos[pending]
            nx = (p[:, 0:1] + dx[None, :]) % m  # (k, 8)
            ny = (p[:, 1:2] + dy[None, :]) % m
            free = ~cmap[nx, ny]
            has_opts = free.sum(axis=1) > 0
            if not vacate:
                # divide: pixels only fill up, so no options is terminal —
                # drop blocked cells from pending AND the candidate arrays
                # together (mis-aligned rows once let a blocked cell's
                # all-occupied neighborhood win a placement, stacking two
                # cells on one pixel); move: blocked cells retry, a later
                # round may vacate a pixel
                pending = pending[has_opts]
                nx, ny, free = nx[has_opts], ny[has_opts], free[has_opts]
                active = np.arange(len(pending))
            else:
                active = np.nonzero(has_opts)[0]
            if len(active) == 0:
                break
            nx, ny, free = nx[active], ny[active], free[active]
            n_free = free.sum(axis=1)

            # rank-r free option per cell, r uniform in [0, n_free)
            rank = (self._nprng.random(len(active)) * n_free).astype(np.int64)
            opt_rank = np.cumsum(free, axis=1) - 1
            sel = np.argmax(free & (opt_rank == rank[:, None]), axis=1)
            rows = np.arange(len(active))
            tx = nx[rows, sel]
            ty = ny[rows, sel]

            # same-target conflicts: lowest cell idx wins (pending is sorted)
            target = tx * m + ty
            order = np.argsort(target, kind="stable")
            win = np.ones(len(active), dtype=bool)
            srt = target[order]
            win[order[1:]] = srt[1:] != srt[:-1]

            w_idx = pending[active[win]]
            w_x, w_y = tx[win], ty[win]
            cmap[w_x, w_y] = True
            if vacate:
                old = pos[w_idx]
                cmap[old[:, 0], old[:, 1]] = False
                pos[w_idx, 0] = w_x
                pos[w_idx, 1] = w_y
            placed.extend(
                (int(i), (int(x), int(y)))
                for i, x, y in zip(w_idx, w_x, w_y)
            )
            drop = np.zeros(len(pending), dtype=bool)
            drop[active[win]] = True
            pending = pending[~drop]
        placed.sort(key=lambda t: t[0])
        return placed

    def divide_cells(self, cell_idxs: list[int]) -> list[tuple[int, int]]:
        """
        Divide cells that have at least one free Moore-neighborhood pixel;
        the clone lands there.  Descendants share molecules evenly, get
        divisions + 1 and lifetime 0.  Returns ``(parent_idx, child_idx)``
        tuples of successful divisions.
        """
        if len(cell_idxs) == 0:
            return []
        cell_idxs = sorted(set(cell_idxs))

        # conflict-free child placement (reference rust/world.rs:59-97),
        # vectorized: no per-cell Python loop
        placed = self._place_in_neighborhood(
            np.asarray(cell_idxs, dtype=np.int64), vacate=False
        )
        parent_idxs = [int(i) for i, _ in placed]
        child_pos = [p for _, p in placed]

        n_new = len(parent_idxs)
        if n_new == 0:
            return []
        child_idxs = list(range(self.n_cells, self.n_cells + n_new))
        self._ensure_capacity(self.n_cells + n_new)
        self.n_cells += n_new

        if self._genome_store is not None:
            # parent->child copies stay on device: zero host string work
            self._genome_store.copy_rows(parent_idxs, child_idxs)
        else:
            self._genomes_list.extend(
                [self._genomes_list[d] for d in parent_idxs]
            )
        self.cell_labels.extend([self.cell_labels[d] for d in parent_idxs])

        child_pos_arr = np.array(child_pos, dtype=np.int32)
        self._np_positions[child_idxs] = child_pos_arr
        descendant_idxs = parent_idxs + child_idxs
        self._np_divisions[child_idxs] = self._np_divisions[parent_idxs]
        self._np_divisions[descendant_idxs] += 1
        self._np_lifetimes[descendant_idxs] = 0

        p_pad = pad_idxs(np.asarray(parent_idxs), oob=self._capacity)
        c_pad = pad_idxs(np.asarray(child_idxs), oob=self._capacity)
        pos_pad = np.zeros((len(c_pad), 2), dtype=np.int32)
        pos_pad[: len(child_idxs)] = child_pos_arr
        (
            self._cell_molecules,
            self.kinetics.params,
            self._positions_dev,
        ) = _divide_update(
            self._cell_molecules,
            self.kinetics.params,
            self._positions_dev,
            jnp.asarray(p_pad),
            jnp.asarray(c_pad),
            jnp.asarray(pos_pad),
        )
        # keep the device mirror pinned to the mesh placement (the jitted
        # kernel's inferred out-sharding may differ)
        self._positions_dev = self._place_cells(self._positions_dev)

        return list(zip(parent_idxs, child_idxs))

    def update_cells(self, genome_idx_pairs: list[tuple[str, int]]):
        """Update existing cells with new genomes and re-derive their
        proteomes."""
        if len(genome_idx_pairs) == 0:
            return
        if self._genome_store is not None:
            genomes = [g for g, _ in genome_idx_pairs]
            idxs_arr = np.asarray(
                [i for _, i in genome_idx_pairs], dtype=np.int32
            )
            if len(np.unique(idxs_arr)) != len(idxs_arr):
                # duplicate target slots must resolve last-wins BEFORE
                # the device scatter (duplicate indices in one scatter
                # have no defined order)
                _, keep = np.unique(idxs_arr[::-1], return_index=True)
                keep = np.sort(len(idxs_arr) - 1 - keep)
                idxs_arr = idxs_arr[keep]
                genomes = [genomes[i] for i in keep]
            g_rows, g_lens = self._genome_store.set_rows(
                idxs_arr.tolist(), genomes
            )
            self._update_cell_params_rows(idxs_arr, g_rows, g_lens)
            return
        for genome, idx in genome_idx_pairs:
            self._genomes_list[idx] = genome
        genomes, idxs = map(list, zip(*genome_idx_pairs))
        self._update_cell_params(genomes=genomes, idxs=idxs)  # type: ignore

    def kill_cells(self, cell_idxs: list[int] | None = None):
        """
        Remove cells; their molecule contents spill onto their pixel.
        Cells are compacted, so surviving cells' indexes shift down
        (reference world.py:495-540).
        """
        if cell_idxs is None:
            cell_idxs = list(range(self.n_cells))
        if len(cell_idxs) == 0:
            return
        kill = np.array(sorted(set(cell_idxs)), dtype=np.int32)

        # spill contents, free pixels
        idxs_pad = pad_idxs(kill, oob=self._capacity)
        valid = np.zeros(len(idxs_pad), dtype=bool)
        valid[: len(kill)] = True
        pos = self._np_positions[kill]
        self._np_cell_map[pos[:, 0], pos[:, 1]] = False

        # stable compaction permutation over the full capacity
        keep_mask = np.ones(self._capacity, dtype=bool)
        keep_mask[kill] = False
        keep_mask[self.n_cells :] = False
        perm = np.concatenate(
            [np.nonzero(keep_mask)[0], np.nonzero(~keep_mask)[0]]
        ).astype(np.int32)
        n_keep = int(keep_mask.sum())

        (
            self._molecule_map,
            self._cell_molecules,
            self.kinetics.params,
            self._positions_dev,
        ) = _kill_update(
            self._molecule_map,
            self._cell_molecules,
            self.kinetics.params,
            self._positions_dev,
            jnp.asarray(idxs_pad),
            jnp.asarray(valid),
            jnp.asarray(perm),
            jnp.asarray(n_keep),
        )
        # keep the device mirror pinned to the mesh placement (the jitted
        # kernel's inferred out-sharding may differ)
        self._positions_dev = self._place_cells(self._positions_dev)
        self._np_positions = self._np_positions[perm]
        self._np_positions[n_keep:] = 0
        self._np_lifetimes = self._np_lifetimes[perm]
        self._np_lifetimes[n_keep:] = 0
        self._np_divisions = self._np_divisions[perm]
        self._np_divisions[n_keep:] = 0

        kill_set = set(kill.tolist())
        if self._genome_store is not None:
            # same compaction permutation as every other cell tensor,
            # applied on device
            self._genome_store.permute(perm, n_keep)
        else:
            self._genomes_list = [
                g
                for i, g in enumerate(self._genomes_list)
                if i not in kill_set
            ]
        self.cell_labels = [
            l for i, l in enumerate(self.cell_labels) if i not in kill_set
        ]
        self.n_cells -= len(kill)

    def move_cells(self, cell_idxs: list[int] | None = None):
        """
        Move cells to a random free pixel in their Moore neighborhood
        (cells with no free neighbor stay).  Processed sequentially so a
        pixel vacated earlier can be taken by a later cell
        (reference rust/world.rs:102-146).
        """
        if cell_idxs is None:
            cell_idxs = list(range(self.n_cells))
        if len(cell_idxs) == 0:
            return
        cell_idxs = sorted(set(cell_idxs))
        self._place_in_neighborhood(
            np.asarray(cell_idxs, dtype=np.int64), vacate=True
        )
        self._sync_positions()

    def reposition_cells(self, cell_idxs: list[int] | None = None):
        """Teleport cells to random free pixels without changing them"""
        if cell_idxs is None:
            cell_idxs = list(range(self.n_cells))
        if len(cell_idxs) == 0:
            return
        cell_idxs = sorted(set(cell_idxs))
        old = self._np_positions[cell_idxs]
        self._np_cell_map[old[:, 0], old[:, 1]] = False
        new_pos = self._find_free_random_positions(n_cells=len(cell_idxs))
        self._np_cell_map[new_pos[:, 0], new_pos[:, 1]] = True
        self._np_positions[cell_idxs] = new_pos
        self._sync_positions()

    # ------------------------------------------------------------------ #
    # physics                                                            #
    # ------------------------------------------------------------------ #

    @property
    def integrator(self) -> str:
        """The resolved integrator backend name (``ops.backends``).

        Pinned per instance when selected explicitly (``integrator=``,
        ``use_pallas=True``, or an env var); otherwise it follows the
        numeric mode (``xla-det`` when :attr:`deterministic`, else
        ``xla-fast``) so post-construction mode flips stay coherent."""
        choice = self.__dict__.get("_integrator_choice")
        if choice is not None:
            return choice
        return "xla-det" if self.deterministic else "xla-fast"

    @property
    def use_pallas(self) -> bool:
        """Legacy spelling of ``integrator == "pallas"`` (read-only)."""
        return self.integrator == "pallas"

    def _activity_fn(self):
        return _get_activity_fn(self.integrator)

    def enzymatic_activity(self, prefetch_column: int | None = None):
        """Catalyze reactions and transport for one time step; updates
        ``molecule_map`` and ``cell_molecules``.

        With ``prefetch_column``, that molecule's intracellular column is
        sliced inside the same program and its device→host copy starts
        immediately (one dispatch instead of activity + slice) — a later
        :meth:`cell_molecule_column` for it picks up the in-flight copy.
        """
        if self.n_cells == 0:
            return
        # live-row prefix for the integrator (dead-slot tax); sharded
        # worlds skip it — a slice off the sharded cell axis would insert
        # resharding collectives
        q = (
            None
            if self._cell_sharding is not None
            else quantize_rows(self.n_cells, self._capacity)
        )
        if prefetch_column is None:
            self._molecule_map, self._cell_molecules = self._activity_fn()(
                self._molecule_map,
                self._cell_molecules,
                self._positions_dev,
                self._n_cells_dev(),
                self.kinetics.params,
                q=q,
            )
            _runtime.note_integrator_dispatch(self.integrator)
            self._note_activity_warm(q, has_col=False)
            return
        fn = _get_activity_col_fn(self.integrator)
        self._molecule_map, self._cell_molecules, col = fn(
            self._molecule_map,
            self._cell_molecules,
            self._positions_dev,
            self._n_cells_dev(),
            self.kinetics.params,
            jnp.asarray(prefetch_column, dtype=jnp.int32),
            q=q,
        )
        self._record_col_prefetch(prefetch_column, col)
        _runtime.note_integrator_dispatch(self.integrator)
        self._note_activity_warm(q, has_col=True)

    def prewarm_activity(
        self, *, q: int | None = None, has_col: bool = False
    ) -> None:
        """Compile (and persistently cache) the activity program's
        live-row-prefix variant WITHOUT touching state: the program is
        pure, so calling it on the current state and discarding the
        results is a compile warmer.  ``q`` defaults to the NEXT rung of
        the row ladder above the current population.  Steps schedule
        this automatically one rung ahead in a background thread; call
        it (plus :meth:`wait_warm`) before a timing window so population
        growth cannot meet a multi-second remote compile mid-window."""
        if self._cell_sharding is not None or self.n_cells == 0:
            return
        if q is None:
            # warm the rung the current population uses AND the one above
            # it: before the first step nothing is compiled yet, so
            # 'current' is only a no-op when a step already ran
            cur = quantize_rows(self.n_cells, self._capacity)
            self.prewarm_activity(q=cur, has_col=has_col)
            if (nxt := next_rung(cur, self._capacity)) != cur:
                self.prewarm_activity(q=nxt, has_col=has_col)
            return
        args = (
            self._molecule_map,
            self._cell_molecules,
            self._positions_dev,
            self._n_cells_dev(),
            self.kinetics.params,
        )
        if has_col:
            fn = _get_activity_col_fn(self.integrator)
            fn(*args, jnp.asarray(0, dtype=jnp.int32), q=q)
        else:
            self._activity_fn()(*args, q=q)

    def _activity_variant_key(self, q: int, has_col: bool) -> tuple:
        # token capacities are in the key: growing them reshapes
        # kinetics.params, invalidating every compiled activity variant
        return (q, has_col, self.kinetics.max_proteins, self.kinetics.max_doms)

    def _note_activity_warm(self, q: int | None, has_col: bool) -> None:
        """Record a just-used activity variant and keep the row ladder
        warm one rung ahead in a background thread (remote-compile
        backends only; on CPU first use compiles synchronously, which is
        cheap and the only thread-safe option — see
        util.async_workers_enabled)."""
        if q is None:
            return
        self._warm_sched.mark(self._activity_variant_key(q, has_col))
        if not self._async_workers:
            return
        nxt = next_rung(q, self._capacity)
        self._warm_sched.schedule(
            [self._activity_variant_key(nxt, has_col)],
            lambda k: self.prewarm_activity(q=k[0], has_col=k[1]),
        )

    def wait_warm(self, timeout: float | None = None) -> None:
        """Block until any in-flight background compile warmer finishes."""
        self._warm_sched.wait(timeout)

    def diffuse_molecules(self):
        """Let molecules diffuse over the map and permeate membranes for
        one time step."""
        if self.n_cells == 0:
            self._molecule_map = _diff.diffuse(
                self._molecule_map, self._diff_kernels, det=self.deterministic
            )
            return
        self._molecule_map, self._cell_molecules = _diffuse_and_permeate(
            self._molecule_map,
            self._cell_molecules,
            self._positions_dev,
            self._n_cells_dev(),
            self._diff_kernels,
            self._perm_factors,
            det=self.deterministic,
        )

    def degrade_molecules(self):
        """Degrade molecules everywhere by one time step"""
        self._molecule_map, self._cell_molecules = _diff.degrade(
            self._molecule_map, self._cell_molecules, self._degrad_factors
        )

    def degrade_and_diffuse_molecules(self):
        """:meth:`degrade_molecules` followed by :meth:`diffuse_molecules`
        as ONE device program — identical math and order, one dispatch
        instead of two (per-dispatch latency matters on remote
        accelerators).  Convenience for per-step loops."""
        if self.n_cells == 0:
            self.degrade_molecules()
            self.diffuse_molecules()
            return
        self._molecule_map, self._cell_molecules = _degrade_diffuse_permeate(
            self._molecule_map,
            self._cell_molecules,
            self._positions_dev,
            self._n_cells_dev(),
            self._degrad_factors,
            self._diff_kernels,
            self._perm_factors,
            det=self.deterministic,
        )

    def step_many(self, n_steps: int):
        """Run ``n_steps`` chemistry steps — each exactly
        :meth:`enzymatic_activity` followed by
        :meth:`degrade_and_diffuse_molecules` — as ONE fused device
        program (``lax.scan`` over the per-step body), plus the matching
        :meth:`increment_cell_lifetimes` bookkeeping on the host.

        One dispatch instead of ``2 * n_steps``: for loops that run many
        chemistry steps between selection decisions this removes the
        per-step dispatch latency entirely and lets XLA fuse across step
        boundaries (in det mode the trajectory is bit-identical to the
        serial calls).  ``n_steps`` is a static shape axis — vary it
        sparingly (each distinct value compiles its own program).

        The program DONATES the molecule buffers: any reference to the
        previous ``world.molecule_map`` / ``world._cell_molecules``
        arrays a caller holds across this call is deleted (re-read the
        properties afterwards instead).
        """
        n_steps = int(n_steps)
        if n_steps < 1:
            raise ValueError("n_steps must be >= 1")
        if self.n_cells == 0:
            # the fused program's activity phase assumes cells exist;
            # the map-only serial path is cheap and rare
            for _ in range(n_steps):
                self.degrade_and_diffuse_molecules()
            return
        q = (
            None
            if self._cell_sharding is not None
            else quantize_rows(self.n_cells, self._capacity)
        )
        self._molecule_map, self._cell_molecules = _step_many(
            self._molecule_map,
            self._cell_molecules,
            self._positions_dev,
            self._n_cells_dev(),
            self.kinetics.params,
            self._degrad_factors,
            self._diff_kernels,
            self._perm_factors,
            det=self.deterministic,
            integrator=self.integrator,
            n_steps=n_steps,
            q=q,
        )
        _runtime.note_integrator_dispatch(self.integrator)
        self._np_lifetimes[: self.n_cells] += n_steps

    def increment_cell_lifetimes(self):
        """Increment ``cell_lifetimes`` by 1"""
        self._host_epoch += 1
        self._np_lifetimes[: self.n_cells] += 1

    # ------------------------------------------------------------------ #
    # evolution                                                          #
    # ------------------------------------------------------------------ #

    def mutate_cells(
        self,
        cell_idxs: list[int] | None = None,
        p: float = 1e-6,
        p_indel: float = 0.4,
        p_del: float = 0.66,
    ):
        """Point-mutate cell genomes, then update changed cells"""
        seed = int(self._nprng.integers(2**63))
        if self._genome_store is not None:
            from magicsoup_tpu import genomes as _genomes

            store = self._genome_store
            # regrow G before the live region reaches it, so the kernel's
            # capacity truncation stays a never-hit backstop
            store.maybe_regrow()
            live = np.zeros(store.capacity, dtype=bool)
            if cell_idxs is None:
                live[: self.n_cells] = True
            else:
                live[np.asarray(cell_idxs, dtype=np.int64)] = True
            tokens, lengths, changed = _genomes.point_mutations_tokens(
                store.tokens,
                store.lengths,
                p=p,
                p_indel=p_indel,
                p_del=p_del,
                seed=seed,
                live=live,
                det=self.deterministic,
            )
            store.apply(tokens, lengths)
            changed_idx = np.nonzero(
                _fetch_host(changed)[: self.n_cells]
            )[0]
            self._update_cell_params_tokens(changed_idx)
            return
        if cell_idxs is None:
            seqs = self.cell_genomes
            mutated = _engine.point_mutations(
                seqs, p=p, p_indel=p_indel, p_del=p_del, seed=seed
            )
            self.update_cells(genome_idx_pairs=mutated)
        else:
            seqs = [self.cell_genomes[d] for d in cell_idxs]
            mutated = _engine.point_mutations(
                seqs, p=p, p_indel=p_indel, p_del=p_del, seed=seed
            )
            pairs = [(d, cell_idxs[i]) for d, i in mutated]
            self.update_cells(genome_idx_pairs=pairs)

    def recombinate_cells(self, cell_idxs: list[int] | None = None, p: float = 1e-7):
        """Recombinate genomes of neighboring cells, then update changed
        cells."""
        pair_arr = self._neighbor_pairs(cell_idxs=cell_idxs)
        seed = int(self._nprng.integers(2**63))
        if self._genome_store is not None:
            from magicsoup_tpu import genomes as _genomes

            store = self._genome_store
            if len(pair_arr) == 0:
                return
            # a tail exchange can at most double a genome: pre-grow G so
            # the kernel's capacity clamp stays a never-hit backstop
            store.ensure_length_cap(
                _genomes.length_capacity(2 * store.max_length())
            )
            tokens, lengths, changed = _genomes.recombinations_tokens(
                store.tokens,
                store.lengths,
                pair_arr,
                p=p,
                seed=seed,
                det=self.deterministic,
            )
            store.apply(tokens, lengths)
            changed_idx = np.nonzero(
                _fetch_host(changed)[: self.n_cells]
            )[0]
            self._update_cell_params_tokens(changed_idx)
            return
        mutated = _engine.recombinations_indexed(
            self.cell_genomes, pair_arr, p=p, seed=seed
        )
        genome_idx_pairs = []
        for c0, c1, idx in mutated:
            c0_i, c1_i = pair_arr[idx]
            genome_idx_pairs.append((c0, int(c0_i)))
            genome_idx_pairs.append((c1, int(c1_i)))
        self.update_cells(genome_idx_pairs=genome_idx_pairs)

    # ------------------------------------------------------------------ #
    # parameter updates                                                  #
    # ------------------------------------------------------------------ #

    # graftlint: hot
    def _update_cell_params(self, genomes: list[str], idxs: list[int]):
        """Translate genomes — through the phenotype cache, so repeated
        genomes translate/pack once — and write kinetic parameters for
        these cells (reference world.py:880-908)."""
        idxs_arr = np.asarray(idxs, dtype=np.int32)
        if len(idxs_arr) == 0:
            return
        if len(np.unique(idxs_arr)) != len(idxs_arr):
            # duplicate target slots (e.g. repeated update pairs): pin
            # last-wins — rung grouping reorders the scatters, so earlier
            # duplicates are dropped up front
            _, keep = np.unique(idxs_arr[::-1], return_index=True)
            keep = np.sort(len(idxs_arr) - 1 - keep)
            idxs_arr = idxs_arr[keep]
            genomes = [genomes[i] for i in keep]
        entries = self.phenotypes.lookup(genomes)
        self._apply_phenotype_entries(idxs_arr, entries)

    # graftlint: hot
    def _update_cell_params_tokens(self, idxs):
        """Param update for token-store rows already ON DEVICE (mutation
        kernels' changed rows): one cached host fetch of the store, then
        hash-keyed translation — no per-cell string appears unless a row
        is a cache miss."""
        idxs_arr = np.unique(np.asarray(idxs, dtype=np.int32))
        if len(idxs_arr) == 0:
            return
        tokens, lengths = self._genome_store.host_arrays()
        entries = self.phenotypes.lookup_tokens(
            tokens, lengths, idxs_arr.tolist()
        )
        self._apply_phenotype_entries(idxs_arr, entries)

    # graftlint: hot
    def _update_cell_params_rows(self, idxs, rows, lens):
        """Param update from freshly ENCODED host rows (the string import
        boundary: spawn/add/update): hashes come straight from the
        encoded rows, no device round trip."""
        idxs_arr = np.asarray(idxs, dtype=np.int32)
        if len(idxs_arr) == 0:
            return
        entries = self.phenotypes.lookup_tokens(rows, lens)
        self._apply_phenotype_entries(idxs_arr, entries)

    # graftlint: hot
    def _apply_phenotype_entries(self, idxs_arr, entries):
        """Shared tail of the param-update paths: unset empty proteomes,
        grow token limits for the whole dispatch, chunked packing."""
        has_prots = np.fromiter(
            (e.n_prots > 0 for e in entries),
            dtype=bool,
            count=len(entries),
        )
        self.kinetics.unset_cell_params(idxs_arr[~has_prots])
        set_idxs = idxs_arr[has_prots]
        if len(set_idxs) == 0:
            return
        set_entries = [e for e, h in zip(entries, has_prots) if h]
        # capacity rule: grow for the WHOLE dispatch before packing any
        # batch of it, so no batch's growth invalidates another's rows
        self.kinetics.ensure_token_limits(
            max(e.n_prots for e in set_entries),
            max(e.max_doms for e in set_entries),
        )
        batch = self.batch_size or len(set_idxs)
        # chunk over cells to bound assembly memory peaks
        for a in range(0, len(set_idxs), batch):
            b = min(a + batch, len(set_idxs))
            self.kinetics.set_cell_params_cached(
                set_idxs[a:b], set_entries[a:b], self.phenotypes
            )

    # ------------------------------------------------------------------ #
    # persistence                                                        #
    # ------------------------------------------------------------------ #

    def convert_genome_backend(self, backend: str) -> None:
        """Switch genome storage in place.  ``'token'`` packs the host
        string list into a device :class:`~magicsoup_tpu.genomes.GenomeStore`
        (the checkpoint-migration path for string-era saves); ``'string'``
        decodes back to the host list.  Phenotypes/kinetics are untouched —
        both backends derive identical parameters from identical genomes."""
        if backend not in ("string", "token"):
            raise ValueError(
                f"genome_backend must be 'string' or 'token',"
                f" got {backend!r}"
            )
        if backend == self.genome_backend:
            return
        if backend == "token":
            from magicsoup_tpu.genomes import GenomeStore

            store = GenomeStore(
                max(self._capacity, _MIN_CAPACITY),
                place=self._place_cells,
            )
            store.set_all(self._genomes_list)
            self._genome_store = store
            self._genomes_list = []
        else:
            self._genomes_list = list(
                self._genome_store.decoded(self.n_cells)
            )
            self._genome_store = None
        self.genome_backend = backend
        self._host_epoch += 1

    def __getstate__(self) -> dict:
        state = self.__dict__.copy()
        # device arrays -> numpy for portable pickles
        state["_cell_molecules"] = _fetch_host(self._cell_molecules)
        state["_molecule_map"] = _fetch_host(self._molecule_map)
        state["_diff_kernels"] = _fetch_host(self._diff_kernels)
        state["_perm_factors"] = _fetch_host(self._perm_factors)
        state["_degrad_factors"] = _fetch_host(self._degrad_factors)
        state.pop("_positions_dev")
        state.pop("_col_prefetch", None)
        state["_mm_cache"] = None
        state["_cm_cache"] = None
        # the phenotype cache pickles ITSELF entry-free (cached rows
        # would bloat saves) and counts the dropped entries into
        # analysis.runtime, so a restored process's first-step miss storm
        # shows up as pickle_drops instead of looking unexplained; the
        # genome store (token backend) likewise pickles its own device
        # arrays as numpy
        # WarmScheduler pickles itself empty (thread handles are not
        # picklable; warm state is runtime-local)
        # meshes/shardings/devices are bound to live runtimes — a restored
        # world re-resolves its device string; pass mesh= again (or
        # device_put) to re-shard
        state["_mesh"] = None
        state["_map_sharding"] = None
        state["_cell_sharding"] = None
        state["_device"] = None
        # a jax.Device object is not picklable — persist the request as
        # its portable string form
        if isinstance(state.get("device"), jax.Device):
            dev = state["device"]
            state["device"] = f"{dev.platform}:{dev.id}"
        return state

    def __setstate__(self, state: dict):
        # legacy pickles stored the genome list under the name that is
        # now a property — route it to the backing attribute
        legacy_genomes = state.pop("cell_genomes", None)
        self.__dict__.update(state)
        # compat defaults for pickles from before these attributes existed
        self.__dict__.setdefault("genome_backend", "string")
        self.__dict__.setdefault("_genome_store", None)
        self.__dict__.setdefault("_genomes_list", [])
        if legacy_genomes is not None:
            self._genomes_list = list(legacy_genomes)
        # integrator plane migration: ``use_pallas`` is a read-only
        # property now — route a legacy pickle's stored bool into the
        # backend-choice attribute the property derives from
        legacy_pallas = self.__dict__.pop("use_pallas", False)
        self.__dict__.setdefault(
            "_integrator_choice", "pallas" if legacy_pallas else None
        )
        self.__dict__.setdefault("deterministic", default_deterministic())
        self.__dict__.setdefault("_host_epoch", 0)
        if self._integrator_choice == "pallas" and self.deterministic:
            # same incompatibility __init__ rejects; a restored world must
            # not silently break the bit-reproducibility contract, and the
            # numeric mode is the stronger promise — drop the kernel
            import warnings

            warnings.warn(
                "restored world had use_pallas=True but deterministic mode"
                " is on; the kernel has no bit-reproducible variant, so"
                " use_pallas is disabled"
            )
            self._integrator_choice = None
        self.__dict__.setdefault("_mm_cache", None)
        self.__dict__.setdefault("_cm_cache", None)
        _pheno_size = self.__dict__.pop("_phenotype_cache_size", 16384)
        if self.__dict__.get("phenotypes") is None:
            self.phenotypes = PhenotypeCache(
                self.genetics, maxsize=_pheno_size
            )
        # recorders pickle themselves detached (no file handle survives a
        # save); pre-telemetry pickles get a fresh detached one
        if self.__dict__.get("telemetry") is None:
            from magicsoup_tpu.telemetry import TelemetryRecorder

            self.telemetry = TelemetryRecorder()
        if "_warm_sched" not in self.__dict__:
            self._warm_sched = WarmScheduler()
        self.__dict__.setdefault("_mesh", None)
        self.__dict__.setdefault("_map_sharding", None)
        self.__dict__.setdefault("_cell_sharding", None)
        self.__dict__.setdefault("device", None)
        try:
            self._device = _resolve_device(self.device)
        except ValueError:
            # restored on a machine without that backend: fall back to
            # the default placement rather than failing the load
            import warnings

            warnings.warn(
                f"restored world requested device={self.device!r} which"
                " is unavailable here; using the default device"
            )
            self.device = None
            self._device = None
        self._async_workers = _async_workers_enabled(
            self._device.platform if self._device is not None else None
        )
        self._cell_molecules = self._place_cells(state["_cell_molecules"])
        self._molecule_map = self._place_map(state["_molecule_map"])
        self._diff_kernels = jnp.asarray(state["_diff_kernels"])
        self._perm_factors = jnp.asarray(state["_perm_factors"])
        self._degrad_factors = jnp.asarray(state["_degrad_factors"])
        if self._genome_store is not None:
            self._genome_store.place(self._place_cells)
        self._sync_positions()

    def save(self, rundir: Path, name: str = "world.pkl"):
        """
        Write the whole world object (chemistry, genetics, kinetics, state)
        to a pickle file; restore with :meth:`from_file`.  For small
        per-step snapshots use :meth:`save_state`.

        The write is atomic (temp file + fsync + ``os.replace``, see
        :mod:`magicsoup_tpu.guard.io`): a crash mid-save leaves the
        previous ``world.pkl`` intact instead of a truncated ruin.  For
        verified, retained, resume-complete checkpoints use
        :func:`magicsoup_tpu.guard.save_run`.
        """
        from magicsoup_tpu.guard.io import atomic_write_bytes

        atomic_write_bytes(Path(rundir) / name, pickle.dumps(self))

    @classmethod
    def from_file(
        cls,
        rundir: Path,
        name: str = "world.pkl",
        device: str | None = None,
    ) -> "World":
        """Restore a world saved with :meth:`save`; ``device`` re-places
        the restored state (same semantics as the constructor kwarg)."""
        import warnings

        path = Path(rundir) / name
        try:
            with open(path, "rb") as fh:
                if device is None:
                    obj: "World" = pickle.load(fh)
                else:
                    # the caller overrides the placement anyway — the saved
                    # device being unavailable here is expected, not warning-
                    # worthy (the duplicate placement below is one-time load
                    # cost)
                    with warnings.catch_warnings():
                        warnings.filterwarnings(
                            "ignore", message="restored world requested device"
                        )
                        obj = pickle.load(fh)
        except (EOFError, pickle.UnpicklingError) as exc:
            # a truncated/garbled pickle (pre-atomic saves could leave one
            # after a crash) surfaces as the typed guard error, not a bare
            # EOFError deep inside pickle
            from magicsoup_tpu.guard.errors import CheckpointError

            raise CheckpointError(
                f"world pickle {path} is truncated or corrupt ({exc}); "
                "recover from an older snapshot or a guard checkpoint",
                check="truncated",
                path=path,
            ) from exc
        if device is not None:
            obj.device = device
            obj._device = _resolve_device(device)
            # the async-worker policy is per-client: follow the override
            obj._async_workers = _async_workers_enabled(
                obj._device.platform if obj._device is not None else None
            )
            obj._molecule_map = obj._place_map(obj._molecule_map)
            obj._cell_molecules = obj._place_cells(obj._cell_molecules)
            if obj._genome_store is not None:
                obj._genome_store.place(obj._place_cells)
            obj._sync_positions()
            obj._mm_cache = None
            obj._cm_cache = None
        return obj

    def save_state(self, statedir: Path):
        """
        Lightweight per-step checkpoint: the mutable tensors as ``.npy``
        files plus a FASTA of genomes/labels (reference world.py:795-822).
        """
        import io as _io

        from magicsoup_tpu.guard.io import atomic_write_bytes, atomic_write_text

        def _atomic_np_save(path: Path, arr: np.ndarray) -> None:
            buf = _io.BytesIO()
            np.save(buf, arr)
            atomic_write_bytes(path, buf.getvalue())

        statedir = Path(statedir)
        statedir.mkdir(parents=True, exist_ok=True)
        n = self.n_cells
        _atomic_np_save(
            statedir / "cell_molecules.npy", _fetch_host(self._cell_molecules)[:n]
        )
        _atomic_np_save(statedir / "cell_map.npy", self._np_cell_map)
        _atomic_np_save(
            statedir / "molecule_map.npy", _fetch_host(self._molecule_map)
        )
        _atomic_np_save(statedir / "cell_lifetimes.npy", self._np_lifetimes[:n])
        _atomic_np_save(statedir / "cell_positions.npy", self._np_positions[:n])
        _atomic_np_save(statedir / "cell_divisions.npy", self._np_divisions[:n])

        lines = [
            f">{idx} {label}\n{genome}"
            for idx, (genome, label) in enumerate(
                zip(self.cell_genomes, self.cell_labels)
            )
        ]
        atomic_write_text(statedir / "cells.fasta", "\n".join(lines))

    def load_state(self, statedir: Path, ignore_cell_params: bool = False):
        """
        Restore a state saved with :meth:`save_state`.  Unless
        ``ignore_cell_params`` is set, all genomes are re-translated (a
        full parameter-update pass, reference world.py:824-878).
        """
        statedir = Path(statedir)
        if not ignore_cell_params:
            self.kill_cells(cell_idxs=list(range(self.n_cells)))

        cm = np.load(statedir / "cell_molecules.npy")
        self._np_cell_map = np.load(statedir / "cell_map.npy")
        self._molecule_map = self._place_map(np.load(statedir / "molecule_map.npy"))
        lifetimes = np.load(statedir / "cell_lifetimes.npy")
        positions = np.load(statedir / "cell_positions.npy")
        divisions = np.load(statedir / "cell_divisions.npy")

        with open(statedir / "cells.fasta", "r", encoding="utf-8") as fh:
            entries = [d.strip() for d in fh.read().split(">") if len(d.strip()) > 0]

        genomes: list[str] = []
        labels: list[str] = []
        genome_idx_pairs: list[tuple[str, int]] = []
        for idx, entry in enumerate(entries):
            parts = entry.split("\n")
            descr = parts[0]
            seq = "" if len(parts) < 2 else parts[1]
            names = descr.split()
            label = names[1].strip() if len(names) > 1 else ""
            genomes.append(seq)
            labels.append(label)
            genome_idx_pairs.append((seq, idx))
        self.cell_labels = labels

        n = len(genome_idx_pairs)
        self.n_cells = 0
        self._ensure_capacity(n)
        # assign genomes AFTER the capacity grow: the token backend's
        # setter scatters into store slots that must already exist
        self.cell_genomes = genomes
        self.n_cells = n
        self._np_positions[:n] = positions
        self._np_positions[n:] = 0
        self._np_lifetimes[:n] = lifetimes
        self._np_lifetimes[n:] = 0
        self._np_divisions[:n] = divisions
        self._np_divisions[n:] = 0
        self._sync_positions()
        full_cm = np.zeros((self._capacity, self.n_molecules), dtype=np.float32)
        full_cm[:n] = cm
        self._cell_molecules = self._place_cells(full_cm)

        if not ignore_cell_params:
            self.update_cells(genome_idx_pairs=genome_idx_pairs)

    def __repr__(self) -> str:
        kwargs = {
            "map_size": self.map_size,
            "abs_temp": self.abs_temp,
            "device": self.device,
        }
        args = [f"{k}:{repr(d)}" for k, d in kwargs.items()]
        return f"{type(self).__name__}({','.join(args)})"
