"""
graftrace: a static thread-role model over the linted file set, plus the
three concurrency rules built on it (GL015-GL017).

The serving stack is deliberately concurrent — FleetService's scheduler
loop, HTTP handler threads, the stepper's worker thread, weakref/atexit
finalizers, and signal handlers all share mutable objects — and its
correctness rests on a single-writer discipline ("handler threads never
touch fleet state") that used to live only in review comments.  This
module makes the discipline machine-checked:

1. **Entry points per role.**  ``threading.Thread(target=...)`` calls
   (role = the thread's constant ``name=``, or the target's ``owner=``
   declaration), ``do_GET``/``do_POST``-style HTTP handler methods
   (role ``http-handler``), ``weakref.finalize``/``atexit.register``
   targets (role ``finalizer``), ``signal.signal`` handlers (role
   ``signal-handler``), and any def carrying an explicit
   ``# graftlint: owner=<role>`` declaration.
2. **Role propagation.**  Roles flow along the call graph; a function
   with an explicit ``owner=`` keeps exactly that role.  Functions that
   are reachable only from ``__init__``-like constructors are
   *init-only* (construction happens-before publication) and carry no
   role; everything else un-roled is *ambient* — callable from any
   thread, the main thread included.
3. **Attribute write/read sites per role, with lock tracking.**  Lock
   scopes come from ``with self._lock:`` blocks (attribute locks typed
   by their ``threading.Lock()``-style constructor) and module-level
   lock names.  Private helpers inherit the intersection of the locks
   their call sites hold, so a ``_flush_locked`` convention is credited
   statically.  Objects handed to a finalizer as extra args are write
   sites from the ``finalizer`` role — and a Lock-typed extra arg
   *grants* that lock to the finalizer's writes, which is exactly the
   safe registration shape.

The rules on top:

- **GL015 cross-thread-write** — a mutable attribute written from two
  or more roles with no common lock statically held by every writer.
- **GL016 lock-order-inversion** — two locks acquired in opposite
  nesting orders anywhere in the linted set.
- **GL017 queue-bypass** — serve-scoped handler-role code mutating
  scheduler/warden/lane state directly instead of submitting a command
  through the service queue (the single-writer serve contract).

Sanctioned sharing is declared, not waived silently: a
``# graftlint: owner=<role>`` on an attribute assignment names the one
role allowed to write it, and the runtime half (`analysis.ownership`)
asserts the same roles under ``MAGICSOUP_DEBUG_OWNERSHIP=1``.

Pure stdlib (ast only), like the rest of graftlint.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from magicsoup_tpu.analysis.engine import Finding

AMBIENT = "ambient"

# constructors reached only before the object is published
INIT_NAMES = {"__init__", "__new__", "__post_init__", "__setstate__"}

# attribute types that synchronize internally — writes through them are
# exempt from GL015 (that is their whole job)
THREAD_SAFE_CTORS = {
    "Event",
    "Lock",
    "RLock",
    "Condition",
    "Semaphore",
    "BoundedSemaphore",
    "Barrier",
    "Queue",
    "SimpleQueue",
    "LifoQueue",
    "PriorityQueue",
}
# the subset usable as a `with` lock scope
LOCK_CTORS = {"Lock", "RLock", "Condition"}

# method names that mutate their receiver in place
MUTATORS = {
    "append",
    "appendleft",
    "add",
    "clear",
    "close",
    "discard",
    "extend",
    "flush",
    "insert",
    "pop",
    "popitem",
    "popleft",
    "put",
    "put_nowait",
    "remove",
    "reverse",
    "set",
    "set_exception",
    "set_result",
    "setdefault",
    "sort",
    "truncate",
    "update",
    "write",
    "writelines",
}

# fleet-state attribute segments the serve handlers must never touch
# directly — commands go through FleetService.submit() (PR-12 contract)
SERVE_STATE = {"scheduler", "warden", "lane", "lanes"}

HANDLER_DEF_RE = re.compile(r"^do_[A-Z]+$")

RULE_INFO = {
    "GL015": (
        "cross-thread-write",
        "a mutable attribute written from two or more thread roles "
        "with no common lock statically held by every writer — the "
        "interleaving is a data race even when each write looks atomic "
        "on its own line",
    ),
    "GL016": (
        "lock-order-inversion",
        "two locks acquired in opposite nesting orders in the linted "
        "set — two threads taking the two paths concurrently deadlock, "
        "and nothing times out because both sides are 'about to' "
        "release",
    ),
    "GL017": (
        "queue-bypass",
        "serve-scoped handler-role code reaching into scheduler/"
        "warden/lane state directly — the serving layer is "
        "single-writer by contract; every mutation must be submitted "
        "as a command and applied by the scheduler loop",
    ),
}


def _chain_parts(node: ast.expr) -> list[str]:
    """``self.service.scheduler.admit`` -> ["self","service","scheduler",
    "admit"]; empty when the chain is not rooted at a plain Name."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return []
    parts.append(node.id)
    return list(reversed(parts))


def _self_attr(node: ast.expr) -> str | None:
    """First attribute off ``self`` in a chain, else None."""
    parts = _chain_parts(node)
    if len(parts) >= 2 and parts[0] == "self":
        return parts[1]
    return None


def _ctor_name(value: ast.expr) -> str | None:
    """``threading.Lock()`` / ``Queue()`` -> the constructor leaf name."""
    if not isinstance(value, ast.Call):
        return None
    func = value.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


@dataclass(frozen=True)
class WriteSite:
    rel: str
    cls: str
    attr: str
    roles: frozenset
    held: frozenset
    line: int
    col: int


@dataclass(frozen=True)
class ReadSite:
    rel: str
    cls: str
    attr: str
    roles: frozenset
    line: int


@dataclass
class _RawEvent:
    func: tuple
    cls: str
    attr: str
    held: frozenset
    line: int
    col: int
    escape_role: str | None = None  # set for finalizer-escaped objects
    role_override: str | None = None  # writes inside a nested handler class


class ThreadModel:
    """Thread roles, per-role attribute access sites, and lock orders
    for one linted file set.  Built once per analyze() and shared by
    the GL015/GL016/GL017 checkers via ``Context.model``."""

    def __init__(self, files: list, graph):
        self.files = list(files)
        self.graph = graph
        # role machinery
        self.entries: dict[tuple, set[str]] = {}
        self.explicit: dict[tuple, str] = {}
        self.roles: dict[tuple, frozenset] = {}
        self.init_only: set[tuple] = set()
        # per-class attribute facts
        self.attr_ctors: dict[tuple, set[str]] = {}  # (rel,cls,attr)->ctors
        self.declared: dict[tuple, str] = {}  # (rel,cls,attr)->owner role
        self.module_locks: dict[str, set[str]] = {}  # rel -> lock names
        # access sites (materialized after role/lock resolution)
        self.writes: list[WriteSite] = []
        self.reads: list[ReadSite] = []
        self.init_writes: list[_RawEvent] = []
        # lock-order facts: (held, acquired) -> first (rel, line, col)
        self.lock_pairs: dict[tuple, tuple] = {}
        # scratch collected by the body scan
        self._raw_writes: list[_RawEvent] = []
        self._raw_reads: list[tuple] = []
        self._raw_acqs: list[tuple] = []  # (func, held, lock, rel, ln, col)
        self._call_sites: dict[tuple, list[tuple]] = {}
        self._eff: dict[tuple, frozenset] = {}

        self._scan_attr_types()
        self._scan_bodies()
        self._propagate_roles()
        self._compute_init_only()
        self._compute_effective_locks()
        self._materialize()

    # ---------------------------------------------------- type facts
    def _scan_attr_types(self) -> None:
        for f in self.files:
            locks: set[str] = set()
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    ctor = _ctor_name(node.value)
                    if ctor in LOCK_CTORS:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                locks.add(tgt.id)
            self.module_locks[f.rel] = locks
        for (rel, qualname), rec in self.graph.functions.items():
            cls = self._cls_of(qualname)
            for node in ast.walk(rec.node):
                if not isinstance(node, ast.Assign):
                    continue
                ctor = _ctor_name(node.value)
                if ctor is None:
                    continue
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is not None:
                        self.attr_ctors.setdefault(
                            (rel, cls, attr), set()
                        ).add(ctor)

    def attr_is_threadsafe(self, rel: str, cls: str, attr: str) -> bool:
        ctors = self.attr_ctors.get((rel, cls, attr), ())
        return bool(THREAD_SAFE_CTORS.intersection(ctors))

    def _attr_is_lock(self, rel: str, cls: str, attr: str) -> bool:
        ctors = self.attr_ctors.get((rel, cls, attr), ())
        return bool(LOCK_CTORS.intersection(ctors))

    @staticmethod
    def _cls_of(qualname: str) -> str:
        if "." in qualname:
            return qualname.rsplit(".", 1)[0]
        return f"<{qualname}>"

    # ---------------------------------------------------- body scan
    def _scan_bodies(self) -> None:
        for key, rec in self.graph.functions.items():
            f = rec.file
            cls = self._cls_of(rec.qualname)
            owner = self._def_owner(f, rec.node)
            if owner is not None:
                self.explicit[key] = owner
            if self._is_handler_record(rec.node):
                self.entries.setdefault(key, set()).add("http-handler")
            body = getattr(rec.node, "body", [])
            self._visit_stmts(key, f, cls, body, frozenset())

    @staticmethod
    def _def_owner(f, node) -> str | None:
        lines = [node.lineno] + [d.lineno for d in node.decorator_list]
        for ln in lines:
            owner = f.owners.get(ln)
            if owner is not None:
                return owner
        return None

    @staticmethod
    def _is_handler_record(node) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if HANDLER_DEF_RE.match(sub.name):
                    return True
        return False

    def _visit_stmts(
        self, key, f, cls, stmts, held: frozenset, override: str | None = None
    ) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def runs later, on its caller's thread, with
                # no locks carried over from the defining scope
                for dec in st.decorator_list:
                    self._visit_expr(key, f, cls, dec, held, st, override)
                self._visit_stmts(key, f, cls, st.body, frozenset(), override)
            elif isinstance(st, ast.ClassDef):
                # `self` inside a nested class belongs to that class's
                # instances, not the enclosing function — when the class
                # is an HTTP handler its methods run on handler threads,
                # whatever thread defined the class
                ov = override
                if self._is_handler_record(st):
                    ov = "http-handler"
                self._visit_stmts(key, f, cls, st.body, held, ov)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                inner = held
                for item in st.items:
                    self._visit_expr(
                        key, f, cls, item.context_expr, inner, st, override
                    )
                    lid = self._lock_id(f, cls, item.context_expr)
                    if lid is not None:
                        self._raw_acqs.append(
                            (
                                key,
                                inner,
                                lid,
                                f.rel,
                                item.context_expr.lineno,
                                item.context_expr.col_offset,
                            )
                        )
                        inner = inner | {lid}
                self._visit_stmts(key, f, cls, st.body, inner, override)
            else:
                self._visit_stmt_events(key, f, cls, st, held, override)
                for name in ("body", "orelse", "finalbody"):
                    sub = getattr(st, name, None)
                    if sub:
                        self._visit_stmts(key, f, cls, sub, held, override)
                for handler in getattr(st, "handlers", []):
                    self._visit_stmts(key, f, cls, handler.body, held, override)

    def _visit_stmt_events(self, key, f, cls, st, held, override=None) -> None:
        # write targets first (Store/Del contexts)
        if isinstance(st, ast.Assign):
            for tgt in st.targets:
                self._record_target(key, f, cls, tgt, held, st, override)
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            self._record_target(key, f, cls, st.target, held, st, override)
        elif isinstance(st, ast.Delete):
            for tgt in st.targets:
                self._record_target(key, f, cls, tgt, held, st, override)
        # then every expression hanging off this statement (skipping
        # nested statement lists, which the caller recurses into)
        for fname, value in ast.iter_fields(st):
            if fname in ("body", "orelse", "finalbody", "handlers"):
                continue
            for expr in self._exprs(value):
                self._visit_expr(key, f, cls, expr, held, st, override)

    @staticmethod
    def _exprs(value):
        if isinstance(value, ast.expr):
            yield value
        elif isinstance(value, list):
            for v in value:
                if isinstance(v, ast.expr):
                    yield v

    def _record_target(self, key, f, cls, tgt, held, st, override=None) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._record_target(key, f, cls, el, held, st, override)
            return
        if isinstance(tgt, (ast.Subscript, ast.Starred)):
            self._record_target_inner(key, f, cls, tgt.value, held, st, override)
            return
        self._record_target_inner(key, f, cls, tgt, held, st, override)

    def _record_target_inner(
        self, key, f, cls, expr, held, st, override=None
    ) -> None:
        attr = _self_attr(expr)
        if attr is None:
            return
        self._raw_writes.append(
            _RawEvent(
                func=key,
                cls=cls,
                attr=attr,
                held=held,
                line=st.lineno,
                col=st.col_offset,
                role_override=override,
            )
        )
        owner = f.owners.get(st.lineno)
        if owner is not None:
            self.declared[(f.rel, cls, attr)] = owner

    def _visit_expr(self, key, f, cls, expr, held, st, override=None) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._record_call(key, f, cls, node, held, override)
            elif isinstance(node, ast.Attribute) and isinstance(
                getattr(node, "ctx", None), ast.Load
            ):
                attr = _self_attr(node)
                if attr is not None:
                    self._raw_reads.append(
                        (key, f.rel, cls, attr, node.lineno)
                    )

    def _record_call(self, key, f, cls, call: ast.Call, held, override=None) -> None:
        rec_cls = cls if not cls.startswith("<") else None
        # call-graph edge with the locks held at this call site, for the
        # effective-lock propagation into private helpers
        tgt = self.graph.resolve(f, rec_cls, call.func)
        if tgt is not None:
            self._call_sites.setdefault(tgt, []).append((key, held))
        # mutator method on a self attribute == write to that attribute
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in MUTATORS
        ):
            attr = _self_attr(call.func.value)
            if attr is not None:
                self._raw_writes.append(
                    _RawEvent(
                        func=key,
                        cls=cls,
                        attr=attr,
                        held=held,
                        line=call.lineno,
                        col=call.col_offset,
                        role_override=override,
                    )
                )
        # thread/finalizer/signal registrations mint role entries
        self._record_registration(key, f, rec_cls, cls, call)

    # ------------------------------------------------- registrations
    def _callee_is(self, f, func, module: str, name: str) -> bool:
        if (
            isinstance(func, ast.Attribute)
            and func.attr == name
            and isinstance(func.value, ast.Name)
            and func.value.id == module
        ):
            return True
        if isinstance(func, ast.Name):
            imported = self.graph._imports.get(f.rel, {}).get(func.id)
            return imported == (module, name)
        return False

    def _record_registration(self, key, f, rec_cls, cls, call) -> None:
        func = call.func
        is_thread = (
            isinstance(func, ast.Attribute) and func.attr == "Thread"
        ) or (isinstance(func, ast.Name) and func.id == "Thread")
        if is_thread:
            target = None
            name_const = None
            for kw in call.keywords:
                if kw.arg == "target":
                    target = self.graph.resolve_ref(f, rec_cls, kw.value)
                elif kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    if isinstance(kw.value.value, str):
                        name_const = kw.value.value
            if target is not None:
                role = self._target_owner(target) or name_const
                role = role or f"thread:{target[1]}"
                self.entries.setdefault(target, set()).add(role)
            return
        escaped = None
        if self._callee_is(f, func, "weakref", "finalize"):
            if len(call.args) >= 2:
                target = self.graph.resolve_ref(f, rec_cls, call.args[1])
                if target is not None:
                    self.entries.setdefault(target, set()).add("finalizer")
                escaped = call.args[2:]
        elif self._callee_is(f, func, "atexit", "register"):
            if call.args:
                target = self.graph.resolve_ref(f, rec_cls, call.args[0])
                if target is not None:
                    self.entries.setdefault(target, set()).add("finalizer")
                escaped = call.args[1:]
        elif self._callee_is(f, func, "signal", "signal"):
            if len(call.args) >= 2:
                target = self.graph.resolve_ref(f, rec_cls, call.args[1])
                if target is not None:
                    self.entries.setdefault(target, set()).add(
                        "signal-handler"
                    )
            return
        if not escaped:
            return
        # extra finalizer args escape to the finalizer thread: each one
        # is a write site from the `finalizer` role.  A Lock-typed arg
        # instead GRANTS that lock to the finalizer's writes — passing
        # the guarding lock alongside the guarded state is the safe
        # registration shape.
        attrs = [a for a in (map(_self_attr, escaped)) if a is not None]
        granted = frozenset(
            f"{f.rel}::{cls}.{a}"
            for a in attrs
            if self._attr_is_lock(f.rel, cls, a)
        )
        for a in attrs:
            if self._attr_is_lock(f.rel, cls, a):
                continue
            self._raw_writes.append(
                _RawEvent(
                    func=key,
                    cls=cls,
                    attr=a,
                    held=granted,
                    line=call.lineno,
                    col=call.col_offset,
                    escape_role="finalizer",
                )
            )

    def _target_owner(self, target) -> str | None:
        rec = self.graph.functions.get(target)
        if rec is None:
            return None
        return self._def_owner(rec.file, rec.node)

    # ------------------------------------------------------- locks
    def _lock_id(self, f, cls, expr) -> str | None:
        attr = _self_attr(expr)
        if attr is not None and self._attr_is_lock(f.rel, cls, attr):
            return f"{f.rel}::{cls}.{attr}"
        if isinstance(expr, ast.Name) and expr.id in self.module_locks.get(
            f.rel, ()
        ):
            return f"{f.rel}::{expr.id}"
        return None

    # ------------------------------------------------------- roles
    def _propagate_roles(self) -> None:
        roles: dict[tuple, set[str]] = {}
        for key, role in self.explicit.items():
            roles[key] = {role}
        for key, rs in self.entries.items():
            if key in self.explicit:
                continue
            roles.setdefault(key, set()).update(rs)
        stack = list(roles)
        while stack:
            key = stack.pop()
            rec = self.graph.functions.get(key)
            if rec is None:
                continue
            for callee in rec.calls:
                if callee in self.explicit or callee == key:
                    continue
                have = roles.setdefault(callee, set())
                if not roles[key] <= have:
                    have.update(roles[key])
                    stack.append(callee)
        self.roles = {k: frozenset(v) for k, v in roles.items() if v}

    def _compute_init_only(self) -> None:
        callers: dict[tuple, set[tuple]] = {}
        for key, rec in self.graph.functions.items():
            for callee in rec.calls:
                callers.setdefault(callee, set()).add(key)

        def leaf(key) -> str:
            return key[1].rsplit(".", 1)[-1]

        # greatest fixpoint: assume every candidate is init-only, then
        # evict anything with a non-init caller (or no callers at all)
        init = {
            key
            for key in self.graph.functions
            if key not in self.roles
            and key not in self.entries
            and (leaf(key) in INIT_NAMES or callers.get(key))
        }
        changed = True
        while changed:
            changed = False
            for key in list(init):
                if leaf(key) in INIT_NAMES:
                    continue
                who = callers.get(key)
                if not who or any(c not in init for c in who):
                    init.discard(key)
                    changed = True
        self.init_only = init

    def role_of(self, key) -> frozenset:
        """Final role set for a function: explicit/propagated roles, or
        {ambient} when callable from anywhere, or empty when the
        function only runs during construction."""
        if key in self.roles:
            return self.roles[key]
        if key in self.init_only:
            return frozenset()
        return frozenset({AMBIENT})

    # --------------------------------------------- effective locks
    def _compute_effective_locks(self) -> None:
        """Locks a private helper can bank on: the intersection over its
        call sites of (locks held there ∪ the caller's own effective
        set).  Monotone-increasing fixpoint from the empty set."""
        def eligible(key) -> bool:
            name = key[1].rsplit(".", 1)[-1]
            return (
                name.startswith("_")
                and not name.startswith("__")
                and key not in self.entries
                and key in self._call_sites
            )

        eff: dict[tuple, frozenset] = {}
        for _ in range(len(self.graph.functions) + 1):
            changed = False
            for key in self._call_sites:
                if not eligible(key):
                    continue
                sets = [
                    held | eff.get(caller, frozenset())
                    for caller, held in self._call_sites[key]
                ]
                new = frozenset.intersection(*sets) if sets else frozenset()
                if eff.get(key, frozenset()) != new:
                    eff[key] = new
                    changed = True
            if not changed:
                break
        self._eff = eff

    # --------------------------------------------------- finalize
    def _materialize(self) -> None:
        for ev in self._raw_writes:
            if ev.escape_role is not None:
                # the finalizer runs later: only explicitly granted
                # locks count, never the registration site's scope
                self.writes.append(
                    WriteSite(
                        rel=ev.func[0],
                        cls=ev.cls,
                        attr=ev.attr,
                        roles=frozenset({ev.escape_role}),
                        held=ev.held,
                        line=ev.line,
                        col=ev.col,
                    )
                )
                continue
            if ev.role_override is not None:
                self.writes.append(
                    WriteSite(
                        rel=ev.func[0],
                        cls=ev.cls,
                        attr=ev.attr,
                        roles=frozenset({ev.role_override}),
                        held=ev.held,
                        line=ev.line,
                        col=ev.col,
                    )
                )
                continue
            if ev.func in self.init_only:
                self.init_writes.append(ev)
                continue
            roles = self.role_of(ev.func)
            if not roles:
                self.init_writes.append(ev)
                continue
            self.writes.append(
                WriteSite(
                    rel=ev.func[0],
                    cls=ev.cls,
                    attr=ev.attr,
                    roles=roles,
                    held=ev.held | self._eff.get(ev.func, frozenset()),
                    line=ev.line,
                    col=ev.col,
                )
            )
        for key, rel, cls, attr, line in self._raw_reads:
            roles = self.role_of(key)
            if roles:
                self.reads.append(
                    ReadSite(
                        rel=rel, cls=cls, attr=attr, roles=roles, line=line
                    )
                )
        for key, held, lock, rel, line, col in self._raw_acqs:
            full = held | self._eff.get(key, frozenset())
            for h in full:
                if h == lock:
                    continue
                site = (rel, line, col)
                prev = self.lock_pairs.get((h, lock))
                if prev is None or site < prev:
                    self.lock_pairs[(h, lock)] = site


def _model(ctx) -> ThreadModel:
    if getattr(ctx, "model", None) is None:
        ctx.model = ThreadModel(ctx.files, ctx.graph)
    return ctx.model


def _short(lock_id: str) -> str:
    return lock_id.rsplit("::", 1)[-1]


# ------------------------------------------------------------- GL015
def check_gl015(ctx):
    """Cross-thread writes.  For every (class, attribute) pair, collect
    the write sites with their roles and statically-held locks; flag
    when two or more roles write with no single lock common to every
    writer.  Attributes backed by internally-synchronized types
    (Event/Lock/Queue/...) are exempt, init-only writes are invisible
    (construction happens-before publication), and a
    ``# graftlint: owner=<role>`` declaration on an assignment narrows
    the check to "no role other than the declared owner writes this"
    (ambient setup writes stay allowed — binding happens at publication).
    """
    model = _model(ctx)
    groups: dict[tuple, list[WriteSite]] = {}
    for w in model.writes:
        groups.setdefault((w.rel, w.cls, w.attr), []).append(w)
    for (rel, cls, attr), sites in sorted(groups.items()):
        if model.attr_is_threadsafe(rel, cls, attr):
            continue
        declared = model.declared.get((rel, cls, attr))
        if declared is not None:
            for w in sorted(sites, key=lambda s: (s.line, s.col)):
                foreign = w.roles - {declared, AMBIENT}
                if foreign:
                    yield Finding(
                        path=rel,
                        line=w.line,
                        col=w.col + 1,
                        rule="GL015",
                        name=RULE_INFO["GL015"][0],
                        message=(
                            f"`{cls}.{attr}` is owned by role "
                            f"`{declared}` but written from "
                            f"{sorted(foreign)}"
                        ),
                        fixit=(
                            "route the mutation through the owning "
                            "thread (e.g. a command queue), or move the "
                            "`# graftlint: owner=` declaration if "
                            "ownership really changed"
                        ),
                    )
            continue
        roles = frozenset().union(*(w.roles for w in sites))
        if len(roles) < 2:
            continue
        common = frozenset.intersection(*(w.held for w in sites))
        if common:
            continue
        keyed = sorted(sites, key=lambda s: (s.line, s.col))
        threaded = [w for w in keyed if w.roles != frozenset({AMBIENT})]
        site = (threaded or keyed)[0]
        yield Finding(
            path=rel,
            line=site.line,
            col=site.col + 1,
            rule="GL015",
            name=RULE_INFO["GL015"][0],
            message=(
                f"`{cls}.{attr}` is written from roles "
                f"{sorted(roles)} with no common lock held at every "
                "write site"
            ),
            fixit=(
                "guard every writer with one shared lock (`with "
                "self._lock:`), route writes through the owning "
                "thread's queue, or declare sanctioned ownership with "
                "`# graftlint: owner=<role>`"
            ),
        )


# ------------------------------------------------------------- GL016
def check_gl016(ctx):
    """Lock-order inversions.  Every ``with`` lock acquisition records
    the ordered pairs (already-held, newly-acquired), with held sets
    including the locks private helpers inherit from their call sites.
    A pair acquired in both directions anywhere in the linted set is a
    deadlock waiting for its first concurrent execution; one finding
    per unordered pair, reported at the later acquisition site."""
    model = _model(ctx)
    seen: set[frozenset] = set()
    for (a, b), site in sorted(model.lock_pairs.items()):
        if a == b:
            continue
        other = model.lock_pairs.get((b, a))
        if other is None:
            continue
        pair = frozenset((a, b))
        if pair in seen:
            continue
        seen.add(pair)
        first, second = sorted([site, other])
        rel, line, col = second
        yield Finding(
            path=rel,
            line=line,
            col=col + 1,
            rule="GL016",
            name=RULE_INFO["GL016"][0],
            message=(
                f"lock `{_short(a)}` and lock `{_short(b)}` are "
                f"acquired in opposite orders (other order at "
                f"{first[0]}:{first[1]}) — concurrent callers deadlock"
            ),
            fixit=(
                "pick one global acquisition order for the two locks "
                "and restructure the later site to follow it (or "
                "collapse them into a single lock)"
            ),
        )


# ------------------------------------------------------------- GL017
def check_gl017(ctx):
    """Queue bypass.  In serve-scoped modules, functions carrying the
    ``http-handler`` role may read health snapshots and submit
    commands, but never call into or assign through
    scheduler/warden/lane state: the scheduler loop is the single
    writer, and a handler-side mutation races every tenant at once."""
    from magicsoup_tpu.analysis import rules as rules_mod

    model = _model(ctx)
    fix = (
        "submit a command through the service queue "
        "(`service.submit(name, payload)`) and let the scheduler loop "
        "apply it; handlers may only read the published health snapshot"
    )
    for key, rec in sorted(ctx.graph.functions.items()):
        roles = model.roles.get(key, frozenset())
        if "http-handler" not in roles:
            continue
        f = rec.file
        if not rules_mod._is_serve_scoped(f):
            continue
        for node in ast.walk(rec.node):
            if isinstance(node, ast.Call):
                parts = _chain_parts(node.func)
                hit = set(parts[1:-1]) & SERVE_STATE
                if len(parts) >= 3 and hit:
                    yield Finding(
                        path=f.rel,
                        line=node.lineno,
                        col=node.col_offset + 1,
                        rule="GL017",
                        name=RULE_INFO["GL017"][0],
                        message=(
                            f"handler-role code calls "
                            f"`{'.'.join(parts)}` — mutating "
                            f"{sorted(hit)[0]} state directly bypasses "
                            "the single-writer command queue"
                        ),
                        fixit=fix,
                    )
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
                targets = (
                    node.targets
                    if isinstance(node, (ast.Assign, ast.Delete))
                    else [node.target]
                )
                for tgt in targets:
                    expr = tgt.value if isinstance(tgt, ast.Subscript) else tgt
                    parts = _chain_parts(expr)
                    hit = set(parts[1:]) & SERVE_STATE
                    if len(parts) >= 2 and hit:
                        yield Finding(
                            path=f.rel,
                            line=node.lineno,
                            col=node.col_offset + 1,
                            rule="GL017",
                            name=RULE_INFO["GL017"][0],
                            message=(
                                f"handler-role code writes through "
                                f"`{'.'.join(parts)}` — "
                                f"{sorted(hit)[0]} state belongs to "
                                "the scheduler loop"
                            ),
                            fixit=fix,
                        )
