"""graftlint command line: `python -m magicsoup_tpu.analysis [--check]`."""
from __future__ import annotations

import argparse
import json
import sys
from dataclasses import asdict
from pathlib import Path

from magicsoup_tpu.analysis import engine
from magicsoup_tpu.analysis.rules import RULE_INFO


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m magicsoup_tpu.analysis",
        description="graftlint: JAX/TPU hot-path static analyzer "
        "(host syncs, recompile churn, dtype drift, nondeterminism, "
        "unsanctioned transfers)",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files/dirs to lint (default: the magicsoup_tpu package)",
    )
    parser.add_argument(
        "--check",
        action="store_true",
        help="exit nonzero when findings are not covered by the baseline "
        "(the CI mode wired into scripts/test.sh)",
    )
    parser.add_argument(
        "--baseline",
        default=None,
        help="baseline JSON path (default: the shipped — empty — "
        "analysis/baseline.json)",
    )
    parser.add_argument(
        "--rules",
        default=None,
        help="comma-separated rule codes to run (default: all)",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="machine-readable report (stable `graftlint/1` schema: "
        "per-rule counts incl. zeros, fresh/baselined totals, finding "
        "rows) — what CI archives",
    )
    parser.add_argument(
        "--sarif",
        default=None,
        metavar="PATH",
        help="also write the (fresh) findings as a SARIF 2.1.0 log at "
        "PATH — the format CI code-scanning ingests",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule table"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for code, (name, desc) in RULE_INFO.items():
            print(f"{code}  {name:24s} {desc}")
        return 0

    paths = args.paths or [engine.default_target()]
    only = args.rules.split(",") if args.rules else None
    timings: dict = {}
    ctx = engine.build_context(paths, timings=timings)
    findings = engine.analyze(paths, rules=only, ctx=ctx, timings=timings)
    baseline = engine.load_baseline(
        Path(args.baseline) if args.baseline else None
    )
    fresh = engine.apply_baseline(findings, baseline)
    iterations = getattr(ctx.dataflow, "iterations", 0)

    if args.sarif:
        from magicsoup_tpu.analysis import sarif

        sarif.write_sarif(args.sarif, fresh, RULE_INFO)

    if args.json:
        counts = {code: 0 for code in sorted(RULE_INFO)}
        for f in fresh:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        report = {
            "schema": "graftlint/1",
            "counts": counts,
            "fresh": len(fresh),
            "baselined": len(findings) - len(fresh),
            "files": len({f.path for f in fresh}),
            "findings": [asdict(f) for f in fresh],
            # full device->host crossing inventory (sanctioned and not):
            # the sync-point certificate downstream perf triage diffs
            # against — a new unsanctioned row is a regression even when
            # no rule fires (it may be waived or outside a hot path)
            "d2h": ctx.dataflow.d2h_inventory(),
            "dataflow_iterations": iterations,
            "timings": {k: round(v, 4) for k, v in timings.items()},
        }
        print(json.dumps(report, indent=2))
    else:
        for f in fresh:
            print(f.format())
        n_files = len({f.path for f in fresh})
        print(
            f"graftlint: {len(fresh)} finding(s) in {n_files} file(s) "
            f"({len(findings) - len(fresh)} baselined)"
        )
        if args.check:
            # --check is the CI gate: surface where the wall time goes
            # and that the taint fixpoint converged (vs hit its cap)
            passes = "  ".join(
                f"{k}={v:.2f}s" for k, v in timings.items()
            )
            print(
                f"graftlint: passes: {passes}  "
                f"(dataflow fixpoint: {iterations} iteration(s))"
            )
    return 1 if (args.check and fresh) else 0


if __name__ == "__main__":
    sys.exit(main())
