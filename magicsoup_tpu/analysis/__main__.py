import sys

from magicsoup_tpu.analysis.cli import main

sys.exit(main())
