"""
graftflow: interprocedural host<->device dataflow analysis.

The shallow taint pass in rules.py answers "is this name device-resident"
for straight-line code inside ONE function.  This module answers it for
the whole linted tree: a DEVICE taint seeded at every ``jnp.*`` / jit
result / ``device_put`` producer is propagated through call arguments,
return values, ``self.X`` attribute stores and loads, and container
packing, to a fixpoint over the call graph.  Fetching through the
sanctioned boundary (``util.fetch_host`` / ``jax.device_get``) un-taints.

The lattice is two-point (HOST < DEVICE) with one refinement: a tuple or
list literal remembers PER-ELEMENT taint, so the library's fetch-cache
idiom — ``self._cache = (device_array, fetch_host(device_array))`` then
``return self._cache[1]`` — resolves to HOST at the constant-index load
instead of smearing the whole container DEVICE.

Resolution stays conservative the same way callgraph.py is: an unresolved
call contributes nothing (HOST), so every DEVICE verdict is backed by an
actual producer the analyzer can point at.  That under-approximation is
what keeps the four rules built on top — GL019/GL020/GL021/GL022 —
zero-noise enough to run in the default ``--check`` gate with the
empty-by-policy baseline.

Rules (registered in rules.py like the graftrace set):

- **GL019 implicit-host-sync** — interprocedural upgrade of GL001: a
  device value reaching ``bool()/int()/float()/len()/np.*``, an ``if``
  condition, or an f-string in a hot function through a flow the shallow
  pass cannot see (call returns, attribute round trips, containers).
- **GL020 fetch-boundary-bypass** — interprocedural upgrade of GL005: a
  D2H conversion outside ``util.fetch_host`` on a value only deep
  dataflow proves device-resident.  fetch_host counts fetches and bytes;
  a bypass silently corrupts the counters telemetry, accounting, and the
  serve ledger bill from.
- **GL021 unprobed-robustness-boundary** — a retry loop, ``except
  OSError``, or guard.io write call in a guard/fleet/serve-scoped module
  with no graftchaos fault point on its call path, plus drift checks
  against the machine-readable ``guard.chaos.FAULT_POINTS`` registry.
  Chaos coverage becomes a static proof, not a convention.
- **GL022 untyped-error-escape** — a ``raise`` of bare
  ``Exception``/``OSError``/``ValueError`` that can propagate out of a
  serve handler, warden hook, or checkpoint entry point; policy layers
  dispatch on the typed guard errors (analysis stays pure-AST, so the
  check is by name, same contract as GL013).

Pure stdlib (ast only) — same constraint as the rest of analysis/.
"""
from __future__ import annotations

import ast

from magicsoup_tpu.analysis.engine import Context, Finding

RULE_INFO = {
    "GL019": (
        "implicit-host-sync",
        "a device value reaching bool()/int()/float()/len()/np.* "
        "conversion, an `if` condition, or an f-string in a hot-path "
        "function through an interprocedural flow (call returns, "
        "attribute round trips, container packing) the shallow GL001 "
        "pass cannot see — each one blocks the step loop on a hidden "
        "device->host sync",
    ),
    "GL020": (
        "fetch-boundary-bypass",
        "a device->host conversion outside util.fetch_host on a value "
        "only interprocedural dataflow proves device-resident — "
        "fetch_host counts fetches and bytes, so a bypass silently "
        "corrupts the counters that telemetry, accounting, and the "
        "serve ledger all bill from",
    ),
    "GL021": (
        "unprobed-robustness-boundary",
        "a retry loop, `except OSError`, or guard.io write call in a "
        "guard/fleet/serve-scoped module with no graftchaos fault point "
        "on its call path — the chaos campaign can never exercise that "
        "recovery path, so its first real execution is a production "
        "incident; includes drift between probes and the "
        "guard.chaos.FAULT_POINTS registry",
    ),
    "GL022": (
        "untyped-error-escape",
        "a `raise` of bare Exception/OSError/ValueError that can "
        "propagate out of a serve handler, warden hook, or checkpoint "
        "entry point — the policy layers dispatch on the typed guard "
        "errors (CheckpointError, GuardConfigError, ServeError...); an "
        "untyped escape turns a policy decision into a stack trace",
    ),
}

#: conversion call names that force a blocking D2H sync on a device value
_SYNC_BUILTINS = {"bool", "int", "float", "len"}
#: exception names GL022 refuses to let escape a certified entry point
_UNTYPED_RAISES = {"Exception", "BaseException", "OSError", "IOError", "ValueError"}
#: guard.io write entry points (each carries the io.write fault point)
_GUARD_IO_WRITES = {"atomic_write_bytes", "atomic_write_text"}

_FIXPOINT_CAP = 50  # safety valve; the tree converges in a handful


def _flat_targets(tgt: ast.expr) -> list[ast.expr]:
    if isinstance(tgt, (ast.Tuple, ast.List)):
        out = []
        for e in tgt.elts:
            out.extend(_flat_targets(e))
        return out
    if isinstance(tgt, ast.Starred):
        return _flat_targets(tgt.value)
    return [tgt]


_HOST_RETURN_ANNS = {"bool", "int", "float", "str", "bytes", "None"}


def _host_annotated(node) -> bool:
    """Whether a def carries an explicit host-scalar return annotation
    (`-> bool` etc.) — an author-certified host boundary."""
    ann = getattr(node, "returns", None)
    if isinstance(ann, ast.Name):
        return ann.id in _HOST_RETURN_ANNS
    if isinstance(ann, ast.Constant):
        return ann.value is None or str(ann.value) in _HOST_RETURN_ANNS
    return False


class DataflowModel:
    """Fixpoint device-taint facts over one CallGraph.

    After construction:

    - ``returns_device``: FuncKeys whose return value is device-resident
    - ``param_device``: FuncKey -> parameter names that receive device
      values (from annotations or any resolved call site)
    - ``attr_device``: (rel, class, attr) triples stored device values
    - ``iterations``: fixpoint sweeps until convergence (CLI telemetry;
      test_graftlint.py budgets it so propagation can't go quadratic)
    """

    def __init__(self, files: list, graph):
        from magicsoup_tpu.analysis import rules as R

        self._R = R
        self.files = files
        self.graph = graph
        self.iterations = 0
        self.returns_device: set = set()
        self.returns_elems: dict = {}  # FuncKey -> [bool per tuple elt]
        self.param_device: dict = {}
        self.attr_device: set = set()
        self._attr_elems: dict = {}  # (rel, cls, attr) -> [bool per elt]
        self._env: dict = {}  # FuncKey -> final tainted local names
        self._env_elems: dict = {}  # FuncKey -> {name: [bool per elt]}
        self._seed_params()
        self._fixpoint()

    # ------------------------------------------------------------ seeds
    def _seed_params(self) -> None:
        for key, rec in self.graph.functions.items():
            args = getattr(rec.node, "args", None)
            if args is None:
                continue
            seeds = set()
            for a in [*args.posonlyargs, *args.args, *args.kwonlyargs]:
                if a.annotation is not None and self._R.DEVICE_ANN.search(
                    ast.unparse(a.annotation)
                ):
                    seeds.add(a.arg)
            if seeds:
                self.param_device[key] = seeds

    # --------------------------------------------------------- fixpoint
    def _fixpoint(self) -> None:
        changed = True
        while changed and self.iterations < _FIXPOINT_CAP:
            self.iterations += 1
            changed = False
            for key, rec in self.graph.functions.items():
                changed |= self._process(key, rec)

    def _process(self, key, rec) -> bool:
        cls = key[1].rsplit(".", 1)[0] if "." in key[1] else None
        env: set[str] = set(self.param_device.get(key, ()))
        elems: dict[str, list[bool]] = {}
        changed = False
        # two local passes: enough for straight-line propagation inside
        # one body; cross-function flow is the global fixpoint's job
        for _ in range(2):
            for node in ast.walk(rec.node):
                if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                    changed |= self._assign(key, rec, cls, env, elems, node)
                elif isinstance(node, ast.For):
                    # iterating a device array yields device rows
                    if self._expr(rec, cls, env, elems, node.iter):
                        env.update(
                            t.id
                            for t in _flat_targets(node.target)
                            if isinstance(t, ast.Name)
                        )
                elif isinstance(node, ast.Call):
                    # container mutation: lst.append(device) taints lst
                    fn = node.func
                    if (
                        isinstance(fn, ast.Attribute)
                        and fn.attr in ("append", "extend", "add", "insert")
                        and isinstance(fn.value, ast.Name)
                        and any(
                            self._expr(rec, cls, env, elems, a) for a in node.args
                        )
                    ):
                        env.add(fn.value.id)
        # return summary (with per-element precision for tuple returns,
        # so unpacking a mixed device/host result doesn't smear taint
        # onto every target).  An explicit host-scalar return annotation
        # certifies the return host regardless of what the body touches
        # (e.g. identity predicates over tuples that carry device slots).
        if (
            key not in self.returns_device
            and not _host_annotated(rec.node)
            and any(self._expr(rec, cls, env, elems, r) for r in rec.returns)
        ):
            self.returns_device.add(key)
            changed = True
        ret_elems = None
        for r in rec.returns:
            desc = self._elems_of(rec, cls, env, elems, r)
            if desc is None:
                ret_elems = None
                break
            ret_elems = (
                desc if ret_elems is None else self._merge_elems(ret_elems, desc)
            )
        if ret_elems is not None:
            merged = self._merge_elems(self.returns_elems.get(key), ret_elems)
            if merged != self.returns_elems.get(key):
                self.returns_elems[key] = merged
                changed = True
        # call-argument -> callee-parameter propagation
        for node in ast.walk(rec.node):
            if isinstance(node, ast.Call):
                changed |= self._propagate_call(key, rec, cls, env, elems, node)
        self._env[key] = env
        self._env_elems[key] = elems
        return changed

    def _assign(self, key, rec, cls, env, elems, node) -> bool:
        changed = False
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        value = node.value
        if value is None:
            return False
        pairs: list[tuple[ast.expr, ast.expr]] = []
        for tgt in targets:
            if (
                isinstance(tgt, (ast.Tuple, ast.List))
                and isinstance(value, ast.Tuple)
                and len(_flat_targets(tgt)) == len(value.elts)
            ):
                pairs.extend(zip(_flat_targets(tgt), value.elts))
            else:
                pairs.append((tgt, value))
        for tgt, val in pairs:
            dev = self._expr(rec, cls, env, elems, val)
            fetched = isinstance(val, ast.Call) and self._R._is_host_fetch(val.func)
            val_elems = self._elems_of(rec, cls, env, elems, val)
            if isinstance(tgt, (ast.Tuple, ast.List)):
                flat = _flat_targets(tgt)
                if val_elems is not None and len(val_elems) == len(flat):
                    # per-element unpack of a known tuple shape
                    for t, tdev in zip(flat, val_elems):
                        if isinstance(t, ast.Name) and tdev:
                            env.add(t.id)
                else:
                    # unpacking an opaque value: every target inherits
                    # the whole value's taint
                    for t in flat:
                        if isinstance(t, ast.Name):
                            if fetched:
                                env.discard(t.id)
                            elif dev:
                                env.add(t.id)
            elif isinstance(tgt, ast.Name):
                if fetched:
                    env.discard(tgt.id)
                    elems.pop(tgt.id, None)
                elif dev:
                    env.add(tgt.id)
                if val_elems is not None:
                    elems[tgt.id] = self._merge_elems(
                        elems.get(tgt.id), val_elems
                    )
            elif (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
                and cls
            ):
                akey = (rec.file.rel, cls, tgt.attr)
                if dev and not fetched and akey not in self.attr_device:
                    self.attr_device.add(akey)
                    changed = True
                if val_elems is not None:
                    merged = self._merge_elems(
                        self._attr_elems.get(akey), val_elems
                    )
                    if merged != self._attr_elems.get(akey):
                        self._attr_elems[akey] = merged
                        changed = True
            elif isinstance(tgt, ast.Subscript):
                base = tgt.value
                if isinstance(base, ast.Name) and dev:
                    env.add(base.id)
        return changed

    @staticmethod
    def _merge_elems(old, new):
        if old is None:
            return list(new)
        if len(old) != len(new):
            # shape conflict: collapse to a single smeared element
            return [any(old) or any(new)]
        return [a or b for a, b in zip(old, new)]

    def _elems_of(self, rec, cls, env, elems, e):
        """Per-element taint descriptor for tuple/list values, or None."""
        if isinstance(e, (ast.Tuple, ast.List)):
            return [self._expr(rec, cls, env, elems, v) for v in e.elts]
        if isinstance(e, ast.Name):
            got = elems.get(e.id)
            return list(got) if got is not None else None
        if (
            isinstance(e, ast.Attribute)
            and isinstance(e.value, ast.Name)
            and e.value.id == "self"
            and cls
        ):
            got = self._attr_elems.get((rec.file.rel, cls, e.attr))
            return list(got) if got is not None else None
        if isinstance(e, ast.Call):
            tgt = self.graph.resolve(rec.file, cls, e.func, rec.local_types)
            if tgt is not None:
                got = self.returns_elems.get(tgt)
                return list(got) if got is not None else None
        return None

    def _propagate_call(self, key, rec, cls, env, elems, node) -> bool:
        tgt = self.graph.resolve(rec.file, cls, node.func, rec.local_types)
        if tgt is None:
            return False
        tgt_rec = self.graph.functions.get(tgt)
        args_obj = getattr(tgt_rec.node, "args", None)
        if args_obj is None:
            return False
        params = [
            a.arg
            for a in [*args_obj.posonlyargs, *args_obj.args, *args_obj.kwonlyargs]
        ]
        # bound-method call: the explicit args start after self/cls
        offset = (
            1
            if "." in tgt[1]
            and isinstance(node.func, ast.Attribute)
            and params
            and params[0] in ("self", "cls")
            else 0
        )
        changed = False
        got = self.param_device.setdefault(tgt, set())
        for i, a in enumerate(node.args):
            if isinstance(a, ast.Starred):
                continue
            pi = i + offset
            if pi < len(params) and self._expr(rec, cls, env, elems, a):
                if params[pi] not in got:
                    got.add(params[pi])
                    changed = True
        for kw in node.keywords:
            if kw.arg and kw.arg in params and self._expr(
                rec, cls, env, elems, kw.value
            ):
                if kw.arg not in got:
                    got.add(kw.arg)
                    changed = True
        if not got:
            self.param_device.pop(tgt, None)
        return changed

    # -------------------------------------------------------- evaluator
    def _expr(self, rec, cls, env, elems, e) -> bool:
        """Deep `is this expression device-resident` under the current
        global facts.  Superset of rules.expr_is_device: adds resolved
        call returns, attribute-store taint, and per-element containers.
        """
        R = self._R
        if isinstance(e, ast.Name):
            return e.id in env
        if isinstance(e, ast.Attribute):
            if e.attr in R.HOST_META_ATTRS:
                return False
            if e.attr in R.DEVICE_ATTRS:
                return True
            if (
                isinstance(e.value, ast.Name)
                and e.value.id == "self"
                and cls
                and (rec.file.rel, cls, e.attr) in self.attr_device
            ):
                return True
            return self._expr(rec, cls, env, elems, e.value)
        if isinstance(e, ast.Call):
            if R._is_host_fetch(e.func):
                return False
            root = R._root_name(e.func)
            if root in R.JAX_ROOTS:
                return not (
                    isinstance(e.func, ast.Attribute)
                    and e.func.attr in R.JAX_HOST_FNS
                )
            tgt = self.graph.resolve(rec.file, cls, e.func, rec.local_types)
            if tgt is not None:
                return tgt in self.returns_device
            if isinstance(e.func, ast.Attribute) and e.func.attr not in (
                "item",
                "tolist",
            ):
                return self._expr(rec, cls, env, elems, e.func.value)
            return False
        if isinstance(e, ast.Subscript):
            if isinstance(e.slice, ast.Constant) and isinstance(
                e.slice.value, int
            ):
                desc = self._elems_of(rec, cls, env, elems, e.value)
                if desc is not None:
                    i = e.slice.value
                    if -len(desc) <= i < len(desc):
                        return desc[i]
                    return any(desc)
            return self._expr(rec, cls, env, elems, e.value)
        if isinstance(e, ast.BinOp):
            return self._expr(rec, cls, env, elems, e.left) or self._expr(
                rec, cls, env, elems, e.right
            )
        if isinstance(e, ast.UnaryOp):
            return self._expr(rec, cls, env, elems, e.operand)
        if isinstance(e, ast.Compare):
            # identity and membership tests return Python bools, not
            # device scalars (`x is None`, `key in cache`)
            if all(
                isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                for op in e.ops
            ):
                return False
            return self._expr(rec, cls, env, elems, e.left) or any(
                self._expr(rec, cls, env, elems, c) for c in e.comparators
            )
        if isinstance(e, ast.BoolOp):
            return any(self._expr(rec, cls, env, elems, v) for v in e.values)
        if isinstance(e, ast.IfExp):
            return self._expr(rec, cls, env, elems, e.body) or self._expr(
                rec, cls, env, elems, e.orelse
            )
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any(self._expr(rec, cls, env, elems, v) for v in e.elts)
        if isinstance(e, ast.Dict):
            return any(
                self._expr(rec, cls, env, elems, v)
                for v in e.values
                if v is not None
            )
        if isinstance(e, ast.Starred):
            return self._expr(rec, cls, env, elems, e.value)
        if isinstance(e, ast.NamedExpr):
            return self._expr(rec, cls, env, elems, e.value)
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            inner = set(env)
            for gen in e.generators:
                if self._expr(rec, cls, inner, elems, gen.iter):
                    inner.update(
                        t.id
                        for t in _flat_targets(gen.target)
                        if isinstance(t, ast.Name)
                    )
            return self._expr(rec, cls, inner, elems, e.elt)
        if isinstance(e, ast.DictComp):
            return self._expr(rec, cls, env, elems, e.value)
        return False

    # --------------------------------------------------------- queries
    def expr_device(self, key, e: ast.expr) -> bool:
        """Checker entry: deep taint verdict for `e` inside function `key`."""
        rec = self.graph.functions[key]
        cls = key[1].rsplit(".", 1)[0] if "." in key[1] else None
        return self._expr(
            rec, cls, self._env.get(key, set()), self._env_elems.get(key, {}), e
        )

    def d2h_inventory(self) -> list[dict]:
        """Every device->host crossing the analysis can prove: sanctioned
        fetch_host calls plus any conversion on a deep-tainted value.
        This is the ROADMAP item-1 work list — the sites that must move
        on-device (or batch through one fetch) before genomes can."""
        R = self._R
        out = []
        for key in sorted(self.graph.functions):
            rec = self.graph.functions[key]
            cls = key[1].rsplit(".", 1)[0] if "." in key[1] else None
            if key[1].rsplit(".", 1)[-1] in R.HOST_FETCHERS:
                continue  # the boundary's own implementation
            env = self._env.get(key, set())
            elems = self._env_elems.get(key, {})
            for node in ast.walk(rec.node):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                leaf = (
                    fn.attr
                    if isinstance(fn, ast.Attribute)
                    else fn.id
                    if isinstance(fn, ast.Name)
                    else None
                )
                kind = None
                sanctioned = False
                if leaf in R.HOST_FETCHERS:
                    kind, sanctioned = "fetch_host", True
                elif leaf in ("item", "tolist") and isinstance(
                    fn, ast.Attribute
                ) and self._expr(rec, cls, env, elems, fn.value):
                    kind = f".{leaf}()"
                elif (
                    leaf in ("asarray", "array")
                    and R._root_name(fn) in R.NUMPY_ROOTS
                    and node.args
                    and self._expr(rec, cls, env, elems, node.args[0])
                ):
                    kind = f"np.{leaf}"
                elif (
                    isinstance(fn, ast.Name)
                    and leaf in _SYNC_BUILTINS
                    and node.args
                    and self._expr(rec, cls, env, elems, node.args[0])
                ):
                    kind = f"{leaf}()"
                if kind is not None:
                    out.append(
                        {
                            "file": rec.file.rel,
                            "line": node.lineno,
                            "function": rec.qualname,
                            "kind": kind,
                            "sanctioned": sanctioned,
                        }
                    )
        return sorted(
            out, key=lambda d: (d["file"], d["line"], d["kind"])
        )


# ------------------------------------------------------------------ GL019
def _finding(code: str, f, node, message: str, fixit: str) -> Finding:
    return Finding(
        path=f.rel,
        line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0) + 1,
        rule=code,
        name=RULE_INFO[code][0],
        message=message,
        fixit=fixit,
    )


def check_gl019(ctx: Context):
    """Hot functions only — same scope as GL001, deeper taint.  To stay
    an *upgrade* (one finding per defect, not two), forms GL001 already
    covers are reported only when the shallow pass misses them."""
    from magicsoup_tpu.analysis import rules as R

    model = ctx.dataflow
    fix = (
        "keep the value on device, or certify the crossing: fetch ONCE "
        "through magicsoup_tpu.util.fetch_host outside the step loop"
    )
    for key in sorted(ctx.hot):
        rec = ctx.graph.functions[key]
        f = rec.file
        if rec.qualname.rsplit(".", 1)[-1] in R.HOST_FETCHERS:
            continue
        shallow = R.device_tainted_names(rec.node)

        def deep_only(e) -> bool:
            return model.expr_device(key, e) and not R.expr_is_device(
                e, shallow
            )

        for node in ast.walk(rec.node):
            if isinstance(node, ast.Call):
                fn = node.func
                if (
                    isinstance(fn, ast.Name)
                    and fn.id in _SYNC_BUILTINS
                    and node.args
                    and deep_only(node.args[0])
                ):
                    yield _finding(
                        "GL019",
                        f,
                        node,
                        f"`{fn.id}()` in hot function `{rec.qualname}` "
                        "converts a value interprocedural dataflow proves "
                        "device-resident — a hidden blocking sync",
                        fix,
                    )
                elif (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in ("asarray", "array")
                    and R._root_name(fn) in R.NUMPY_ROOTS
                    and node.args
                    and deep_only(node.args[0])
                ):
                    yield _finding(
                        "GL019",
                        f,
                        node,
                        f"`np.{fn.attr}()` in hot function `{rec.qualname}` "
                        "copies a device value to host through a flow the "
                        "shallow pass cannot see",
                        fix,
                    )
                elif (
                    isinstance(fn, ast.Attribute)
                    and fn.attr == "tolist"
                    and deep_only(fn.value)
                ):
                    yield _finding(
                        "GL019",
                        f,
                        node,
                        f"`.tolist()` in hot function `{rec.qualname}` on "
                        "an interprocedurally device-tainted value",
                        fix,
                    )
            elif isinstance(node, ast.If) and deep_only(node.test):
                yield _finding(
                    "GL019",
                    f,
                    node,
                    f"`if` on a device value in hot function "
                    f"`{rec.qualname}` (taint flows in through a call or "
                    "attribute the shallow pass cannot see) — a blocking "
                    "sync every step",
                    "branch with jnp.where / lax.cond, or hoist the "
                    "decision out of the hot loop",
                )
            elif isinstance(node, ast.FormattedValue) and model.expr_device(
                key, node.value
            ):
                yield _finding(
                    "GL019",
                    f,
                    node,
                    f"f-string interpolation of a device value in hot "
                    f"function `{rec.qualname}` — str() materializes the "
                    "buffer on host",
                    fix,
                )


# ------------------------------------------------------------------ GL020
def check_gl020(ctx: Context):
    """Whole tree minus util.py (where fetch_host lives) and minus hot
    functions (GL001/GL019's domain).  Conversions GL005 already flags
    on shallow taint are reported only when just the deep pass sees
    them."""
    from magicsoup_tpu.analysis import rules as R

    model = ctx.dataflow
    fix = (
        "route the crossing through magicsoup_tpu.util.fetch_host — it "
        "is the audited boundary AND the metering point (fetch/bytes "
        "counters feed telemetry, accounting, and the serve ledger)"
    )
    for key in sorted(ctx.graph.functions):
        if key in ctx.hot:
            continue
        rec = ctx.graph.functions[key]
        f = rec.file
        if f.rel.rsplit("/", 1)[-1] == "util.py":
            continue
        if rec.qualname.rsplit(".", 1)[-1] in R.HOST_FETCHERS:
            continue
        shallow = R.device_tainted_names(rec.node)

        def deep_only(e) -> bool:
            return model.expr_device(key, e) and not R.expr_is_device(
                e, shallow
            )

        for node in ast.walk(rec.node):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in ("item", "tolist")
                and deep_only(fn.value)
            ):
                yield _finding(
                    "GL020",
                    f,
                    node,
                    f"`.{fn.attr}()` in `{rec.qualname}` converts a "
                    "device value outside util.fetch_host — the transfer "
                    "is unmetered and unaudited",
                    fix,
                )
            elif (
                isinstance(fn, ast.Name)
                and fn.id in ("int", "float", "bool")
                and node.args
                and deep_only(node.args[0])
            ):
                yield _finding(
                    "GL020",
                    f,
                    node,
                    f"`{fn.id}()` in `{rec.qualname}` syncs a device "
                    "value outside the sanctioned fetch boundary",
                    fix,
                )
            elif (
                isinstance(fn, ast.Attribute)
                and fn.attr in ("asarray", "array")
                and R._root_name(fn) in R.NUMPY_ROOTS
                and node.args
                and deep_only(node.args[0])
            ):
                yield _finding(
                    "GL020",
                    f,
                    node,
                    f"`np.{fn.attr}()` in `{rec.qualname}` is an implicit "
                    "unmetered device->host transfer (interprocedural "
                    "taint)",
                    fix,
                )


# ------------------------------------------------------------------ GL021
def _probe_sites(rec) -> list[tuple[str | None, ast.Call]]:
    """graftchaos probes inside one function: ``chaos.site("x")`` /
    ``_chaos.site(...)`` / ``_chaos_probe(...)`` calls, plus constant
    ``chaos_site=`` keywords and parameter defaults (the guard.io
    pattern, where the probing callable receives its site name)."""
    out: list[tuple[str | None, ast.Call]] = []
    for node in ast.walk(rec.node):
        if isinstance(node, ast.Call):
            fn = node.func
            leaf = (
                fn.attr
                if isinstance(fn, ast.Attribute)
                else fn.id
                if isinstance(fn, ast.Name)
                else None
            )
            is_probe = leaf == "_chaos_probe" or (
                leaf == "site"
                and isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id in ("chaos", "_chaos")
            )
            if is_probe:
                name = None
                if node.args and isinstance(node.args[0], ast.Constant):
                    name = node.args[0].value
                out.append((name, node))
                continue
            for kw in node.keywords:
                if (
                    kw.arg == "chaos_site"
                    and isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, str)
                ):
                    out.append((kw.value.value, node))
    args = getattr(rec.node, "args", None)
    if args is not None:
        names = [*args.posonlyargs, *args.args, *args.kwonlyargs]
        defaults = list(args.defaults)
        # align defaults to the tail of positional params
        pos = [*args.posonlyargs, *args.args]
        for a, d in zip(pos[len(pos) - len(defaults) :], defaults):
            if (
                a.arg == "chaos_site"
                and isinstance(d, ast.Constant)
                and isinstance(d.value, str)
            ):
                out.append((d.value, rec.node))
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if (
                a.arg == "chaos_site"
                and isinstance(d, ast.Constant)
                and isinstance(d.value, str)
            ):
                out.append((d.value, rec.node))
        del names
    return out


def _retries_in_handler(handler: ast.ExceptHandler) -> bool:
    """True when the handler re-attempts the failed operation — a
    `continue`, a backoff sleep/delay, or an attempt counter.  This is
    what separates a RETRY loop (chaos-injectable recovery) from a
    drain/cleanup loop that merely tolerates per-item failures."""
    for sub in ast.walk(handler):
        if isinstance(sub, ast.Continue):
            return True
        if isinstance(sub, ast.AugAssign):
            tgt = sub.target
            name = tgt.id if isinstance(tgt, ast.Name) else (
                tgt.attr if isinstance(tgt, ast.Attribute) else ""
            )
            if "attempt" in name or "retr" in name:
                return True
        if isinstance(sub, ast.Call):
            fn = sub.func
            leaf = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else ""
            )
            if leaf in ("sleep", "delay", "backoff"):
                return True
    return False


_FAULT_CLASSES = {"OSError", "IOError", "Exception", "BaseException"}


def _catches_fault_class(handler: ast.ExceptHandler) -> bool:
    """Whether the handler can see an injected I/O fault at all — a
    `queue.Empty`/`KeyError` drain loop retries, but never on a fault
    the chaos plane could raise, so it is not a chaos boundary."""
    if handler.type is None:
        return True  # bare except catches everything
    names = (
        handler.type.elts
        if isinstance(handler.type, ast.Tuple)
        else [handler.type]
    )
    leaves = {
        n.id if isinstance(n, ast.Name) else (
            n.attr if isinstance(n, ast.Attribute) else None
        )
        for n in names
    }
    return bool(leaves & _FAULT_CLASSES)


def _boundaries(rec) -> list[tuple[str, ast.AST]]:
    """Robustness boundaries inside one function body."""
    out: list[tuple[str, ast.AST]] = []
    for node in ast.walk(rec.node):
        if isinstance(node, (ast.For, ast.While)):
            if any(
                isinstance(sub, ast.Try)
                and any(
                    _retries_in_handler(h) and _catches_fault_class(h)
                    for h in sub.handlers
                )
                for sub in ast.walk(node)
            ):
                out.append(("retry loop", node))
        elif isinstance(node, ast.ExceptHandler) and node.type is not None:
            names = (
                [n for n in node.type.elts]
                if isinstance(node.type, ast.Tuple)
                else [node.type]
            )
            leaves = {
                n.id if isinstance(n, ast.Name) else (
                    n.attr if isinstance(n, ast.Attribute) else None
                )
                for n in names
            }
            # a handler DEDICATED to disk faults is recovery code; an
            # OSError folded into a defensive multi-type tuple (cleanup
            # tolerance) is not a chaos-injectable boundary
            if leaves and leaves <= {"OSError", "IOError"}:
                out.append(("`except OSError`", node))
        elif isinstance(node, ast.Call):
            fn = node.func
            leaf = (
                fn.attr
                if isinstance(fn, ast.Attribute)
                else fn.id
                if isinstance(fn, ast.Name)
                else None
            )
            if leaf in _GUARD_IO_WRITES:
                out.append((f"guard.io `{leaf}` write", node))
    return out


def _parse_registry(chaos_file):
    """(FAULT_POINTS literal, its lineno) from guard/chaos.py's AST —
    the static half of the fault_points() contract."""
    for node in chaos_file.tree.body:
        targets = (
            node.targets
            if isinstance(node, ast.Assign)
            else [node.target]
            if isinstance(node, ast.AnnAssign)
            else []
        )
        if not any(
            isinstance(t, ast.Name) and t.id == "FAULT_POINTS" for t in targets
        ):
            continue
        value = node.value
        if not isinstance(value, ast.Dict):
            continue
        reg = {}
        for k, v in zip(value.keys, value.values):
            if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                continue
            if (
                isinstance(v, ast.Tuple)
                and len(v.elts) == 2
                and all(isinstance(e, ast.Constant) for e in v.elts)
            ):
                reg[k.value] = (v.elts[0].value, v.elts[1].value)
        return reg, node.lineno
    return None, None


def check_gl021(ctx: Context):
    """Chaos coverage as a static proof.  A robustness boundary is
    covered when a graftchaos probe exists in its own function, in a
    transitive callee (the probed primitive it drives), or in a
    transitive caller (the probed driver that owns its retry).  Plus
    registry drift: every constant probe site must appear in
    guard.chaos.FAULT_POINTS with the module/callable that really
    probes it, and vice versa."""
    from magicsoup_tpu.analysis import rules as R

    graph = ctx.graph
    probe_funcs: set = set()
    probes_by_site: dict[str, list] = {}
    for key, rec in graph.functions.items():
        sites = _probe_sites(rec)
        if sites:
            probe_funcs.add(key)
            for name, node in sites:
                if name is not None:
                    probes_by_site.setdefault(name, []).append((key, node))
    # covered = can REACH a probe (reverse closure over callers) or is
    # DRIVEN by probed code (forward closure over calls)
    covered = set(probe_funcs)
    stack = list(probe_funcs)
    callers = graph.callers()
    while stack:
        for c in callers.get(stack.pop(), ()):
            if c not in covered:
                covered.add(c)
                stack.append(c)
    forward = set(probe_funcs)
    stack = list(probe_funcs)
    while stack:
        for c in graph.functions[stack.pop()].calls:
            if c not in forward:
                forward.add(c)
                stack.append(c)
    covered |= forward

    fix = (
        "register a guard.chaos fault point on this path (probe with "
        "`chaos.site(...)` or route the write through guard.io), and "
        "add it to guard.chaos.FAULT_POINTS so the campaign matrix "
        "exercises the failure"
    )
    for key in sorted(graph.functions):
        rec = graph.functions[key]
        f = rec.file
        base = f.rel.rsplit("/", 1)[-1]
        if f.rel.endswith("guard/chaos.py") or base == "chaos.py":
            continue  # the fault plane itself
        if not (
            R._is_guard_scoped(f) or R._is_fleet_scoped(f) or R._is_serve_scoped(f)
        ):
            continue
        if key in covered:
            continue
        for what, node in _boundaries(rec):
            if what.startswith("guard.io") and not any(
                c[1].rsplit(".", 1)[-1] in _GUARD_IO_WRITES
                for c in rec.calls
            ):
                # the guard.io callee did not resolve into this graph
                # (partial-tree run): its in-body probe cannot be seen,
                # so its absence cannot be proven either
                continue
            yield _finding(
                "GL021",
                f,
                node,
                f"{what} in `{rec.qualname}` has no graftchaos fault "
                "point on its call path — the chaos campaign cannot "
                "exercise this recovery code",
                fix,
            )

    chaos_file = next(
        (f for f in ctx.files if f.rel.endswith("guard/chaos.py")), None
    )
    if chaos_file is None:
        return
    registry, reg_line = _parse_registry(chaos_file)
    if registry is None:
        yield _finding(
            "GL021",
            chaos_file,
            chaos_file.tree,
            "guard/chaos.py has no parseable FAULT_POINTS literal — "
            "GL021 cannot certify probe/registry agreement",
            "declare FAULT_POINTS: dict[str, tuple[str, str]] mapping "
            "each site to its probing (module, callable)",
        )
        return
    for site, entries in sorted(probes_by_site.items()):
        if site in registry:
            continue
        key, node = entries[0]
        yield _finding(
            "GL021",
            graph.functions[key].file,
            node,
            f"probe site {site!r} in `{graph.functions[key].qualname}` "
            "is missing from guard.chaos.FAULT_POINTS — analyzer and "
            "runtime plane disagree about what is probed",
            f"add {site!r} to FAULT_POINTS (and SITES) in guard/chaos.py",
        )
    anchor = ast.Module(body=[], type_ignores=[])
    anchor.lineno, anchor.col_offset = reg_line, 0
    for site, (mod, qual) in sorted(registry.items()):
        hits = probes_by_site.get(site, ())
        ok = any(
            graph.functions[k].qualname == qual
            and graph.functions[k].file.module.endswith(
                mod.rsplit("magicsoup_tpu.", 1)[-1]
            )
            for k, _ in hits
        )
        if not ok:
            yield _finding(
                "GL021",
                chaos_file,
                anchor,
                f"FAULT_POINTS entry {site!r} -> {mod}.{qual} has no "
                "matching probe in the tree — the registry drifted from "
                "the code",
                "fix the registry entry (or restore the probe) so "
                "fault_points() and the AST agree",
            )


# ------------------------------------------------------------------ GL022
def _entry_points(ctx: Context) -> dict:
    """Certified entry families -> {FuncKey: human label}."""
    from magicsoup_tpu.analysis import concurrency as C
    from magicsoup_tpu.analysis import rules as R

    entries: dict = {}
    for key, rec in ctx.graph.functions.items():
        qual = rec.qualname
        leaf = qual.rsplit(".", 1)[-1]
        cls = qual.rsplit(".", 1)[0] if "." in qual else None
        if ctx.model is not None and "http-handler" in ctx.model.role_of(key):
            entries.setdefault(key, f"serve handler `{qual}`")
        if leaf.startswith("_cmd_") and R._is_serve_scoped(rec.file):
            entries.setdefault(key, f"serve command `{qual}`")
        if (
            cls
            and "Warden" in cls
            and not leaf.startswith("_")
            and leaf not in C.INIT_NAMES
        ):
            entries.setdefault(key, f"warden hook `{qual}`")
        if (
            rec.file.rel.rsplit("/", 1)[-1] in ("checkpoint.py", "resume.py")
            and "guard" in rec.file.rel.split("/")
            and not leaf.startswith("_")
            and leaf not in C.INIT_NAMES
        ):
            entries.setdefault(key, f"checkpoint entry `{qual}`")
    return entries


_CATCHES = {
    "Exception": {"Exception", "OSError", "IOError", "ValueError"},
    "BaseException": {
        "Exception",
        "BaseException",
        "OSError",
        "IOError",
        "ValueError",
    },
    "OSError": {"OSError", "IOError"},
    "IOError": {"OSError", "IOError"},
    "ValueError": {"ValueError"},
}


def _caught_locally(f, rec, raise_node, exc_name: str) -> bool:
    """True when an enclosing try in the SAME function catches the
    raised type (interprocedural catches are the entry's job — a typed
    error would survive them by design)."""
    parents = f.parents()
    cur = parents.get(raise_node)
    prev = raise_node
    while cur is not None and cur is not rec.node:
        if isinstance(cur, ast.Try) and prev in cur.body:
            for h in cur.handlers:
                names = (
                    [n for n in h.type.elts]
                    if isinstance(h.type, ast.Tuple)
                    else [h.type]
                ) if h.type is not None else []
                for n in names:
                    leaf = (
                        n.attr
                        if isinstance(n, ast.Attribute)
                        else n.id
                        if isinstance(n, ast.Name)
                        else None
                    )
                    if leaf and exc_name in _CATCHES.get(leaf, {leaf}):
                        return True
        prev, cur = cur, parents.get(cur)
    return False


def check_gl022(ctx: Context):
    """Typed-error certification for the three policy surfaces: serve
    handlers, warden hooks, and checkpoint entry points.  Anything
    their call closures can raise must be a typed error (GuardError
    family, ServeError, ...) so the layer above can dispatch on it —
    builtin Exception/OSError/ValueError raises are flagged at the
    raise site, named with the entry they escape from."""
    from magicsoup_tpu.analysis import concurrency as C

    entries = _entry_points(ctx)
    origin: dict = dict(entries)
    stack = list(entries)
    while stack:
        key = stack.pop()
        for callee in ctx.graph.functions[key].calls:
            if callee not in origin:
                origin[callee] = origin[key]
                stack.append(callee)
    fix = (
        "raise a typed error instead (guard.errors.GuardConfigError / "
        "CheckpointError / serve.api.ServeError ...) — or catch and "
        "wrap at the boundary; waive a deliberate builtin with "
        "`# graftlint: disable=GL022`"
    )
    for key in sorted(origin):
        rec = ctx.graph.functions[key]
        if rec.qualname.rsplit(".", 1)[-1] in C.INIT_NAMES:
            continue  # constructor validation is the caller's contract
        f = rec.file
        for node in ast.walk(rec.node):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            target = exc.func if isinstance(exc, ast.Call) else exc
            leaf = (
                target.attr
                if isinstance(target, ast.Attribute)
                else target.id
                if isinstance(target, ast.Name)
                else None
            )
            if leaf not in _UNTYPED_RAISES:
                continue
            if _caught_locally(f, rec, node, leaf):
                continue
            yield _finding(
                "GL022",
                f,
                node,
                f"`raise {leaf}` in `{rec.qualname}` can escape "
                f"{origin[key]} untyped — the policy layer above "
                "dispatches on the typed guard errors and will only see "
                "a stack trace",
                fix,
            )
