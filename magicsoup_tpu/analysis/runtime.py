"""
Runtime half of graftlint: turn the static invariants into test-time
assertions.

- :func:`hot_path_guard` — wraps a hot-path window in
  ``jax.transfer_guard("disallow")`` (implicit host<->device transfers
  raise) AND pins a compilation-count budget for the window, so a PR
  that introduces a per-step retrace or an implicit sync fails the
  suite instead of shipping a 10-1000x slowdown to TPU.
- :func:`compile_count` — process-wide count of traced program variants,
  fed by a ``jax.monitoring`` listener on the jaxpr-trace event.  The
  trace event (unlike backend-compile time) fires for cache MISSES of
  the in-process jit cache regardless of the persistent compilation
  cache's state, so budgets hold on both cold and warm CI runs.
- :func:`persistent_cache_hits` / :func:`persistent_cache_misses` —
  process-wide counts of PERSISTENT compilation-cache outcomes (the
  on-disk cache ``magicsoup_tpu.cache`` configures): a hit means a
  backend compile was skipped by loading a prior process's executable.
  This is the observable the warm-start contract is asserted on — a
  second process stepping the same world shapes must hit, not recompile
  the q-ladder.
- :func:`sanctioned_transfer` — the explicit D2H spelling that stays
  legal under ``transfer_guard("disallow")`` (explicit transfers are
  exempt by JAX's design; the guard exists to catch *implicit* ones).

Caveat for CPU-backed tests: with everything on one host, a
device->host "transfer" is a no-op and the D2H side of the guard cannot
fire — but the implicit HOST->DEVICE side still does (e.g. a Python
scalar silently promoted per step), and the compile budget is fully
backend-independent.  The static rules (GL001/GL005) cover the D2H
direction at review time; on real TPU runs the guard covers both.
"""
from __future__ import annotations

import contextlib
import threading

import jax

_COMPILE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
# record_event (no duration) markers emitted by jax's persistent
# compilation cache on every lookup outcome
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"
_lock = threading.Lock()
_count = 0
_cache_hits = 0
_cache_misses = 0
_installed = False


def _listener(event: str, duration: float, **kwargs) -> None:
    global _count
    if event == _COMPILE_EVENT:
        with _lock:
            _count += 1


def _event_listener(event: str, **kwargs) -> None:
    global _cache_hits, _cache_misses
    if event == _CACHE_HIT_EVENT:
        with _lock:
            _cache_hits += 1
    elif event == _CACHE_MISS_EVENT:
        with _lock:
            _cache_misses += 1


def install() -> None:
    """Register the compile listeners (idempotent; process-global)."""
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    from jax import monitoring

    monitoring.register_event_duration_secs_listener(_listener)
    monitoring.register_event_listener(_event_listener)


def compile_count() -> int:
    """Traced-program variants compiled so far in this process."""
    install()
    with _lock:
        return _count


def persistent_cache_hits() -> int:
    """Backend compiles SKIPPED by loading a persistent-cache entry.

    Only counts lookups after :func:`install` ran — register the
    listener before the first jit execution (e.g. first thing in a
    subprocess) for process-total numbers."""
    install()
    with _lock:
        return _cache_hits


def persistent_cache_misses() -> int:
    """Persistent-cache lookups that fell through to a backend compile."""
    install()
    with _lock:
        return _cache_misses


class GuardStats:
    """Filled in when the guard window closes."""

    def __init__(self) -> None:
        self.compiles: int | None = None


class CompileBudgetExceeded(AssertionError):
    pass


@contextlib.contextmanager
def hot_path_guard(compile_budget: int = 0, transfers: str = "disallow"):
    """Guard a hot-path window: no implicit transfers, at most
    ``compile_budget`` new program compilations.

    Budget choice: warm the functions under test FIRST (run one step of
    every variant the window will use), then wrap the steady-state loop
    with ``compile_budget=0`` — the steady state of a well-formed step
    loop compiles nothing.  A window that legitimately compiles (e.g. a
    capacity regrow) gets exactly that many, pinned, so growth is a
    reviewed decision rather than silent churn.
    """
    install()
    stats = GuardStats()
    start = compile_count()
    with jax.transfer_guard(transfers):
        yield stats
    stats.compiles = compile_count() - start
    if stats.compiles > compile_budget:
        raise CompileBudgetExceeded(
            f"hot-path window compiled {stats.compiles} program(s), "
            f"budget is {compile_budget} — something in the loop is "
            "retracing (new shapes/dtypes/static args?) or was not warmed"
        )


def sanctioned_transfer(arr):
    """Explicit device->host fetch; allowed under transfer guards."""
    return jax.device_get(arr)


# ----------------------------------------------------------------- #
# phenotype-cache counters                                          #
# ----------------------------------------------------------------- #
# process-wide accumulators fed by every PhenotypeCache instance
# (genetics.py) — the observability hook the cache-effectiveness smoke
# and the README's hit-rate guidance read from
_pheno_hits = 0
_pheno_misses = 0
_pheno_evictions = 0
_pheno_pickle_drops = 0


def note_phenotype_cache(
    hits: int = 0, misses: int = 0, evictions: int = 0, pickle_drops: int = 0
) -> None:
    """Accumulate phenotype-cache outcomes (called by the cache itself)."""
    global _pheno_hits, _pheno_misses, _pheno_evictions, _pheno_pickle_drops
    with _lock:
        _pheno_hits += hits
        _pheno_misses += misses
        _pheno_evictions += evictions
        _pheno_pickle_drops += pickle_drops


def phenotype_cache_stats() -> dict[str, int]:
    """Process-total genome->phenotype cache outcomes.

    ``hits`` counts genome lookups served from cached entries (including
    within-batch duplicates after the first occurrence), ``misses``
    counts unique genomes that had to be translated, ``evictions``
    counts LRU drops, ``pickle_drops`` counts entries dropped because a
    cache was pickled (checkpoint/serve handoff) — a restored tenant
    whose first steps miss-storm shows a matching ``pickle_drops`` spike
    here instead of looking like an unexplained cold cache."""
    with _lock:
        return {
            "hits": _pheno_hits,
            "misses": _pheno_misses,
            "evictions": _pheno_evictions,
            "pickle_drops": _pheno_pickle_drops,
        }


# ----------------------------------------------------------------- #
# genome decode counter                                             #
# ----------------------------------------------------------------- #
# fed by GenomeStore's token -> string export paths.  Decoding is the
# sanctioned import/export boundary of the device-resident genome
# store; a decode inside a hot loop (restack, steady-state megastep)
# is host string work the token backend exists to delete, so tests pin
# this counter flat across those windows.
_genome_decode_calls = 0
_genome_decode_rows = 0


def note_genome_decode(rows: int = 0) -> None:
    """Accumulate one token -> string decode of ``rows`` genome rows."""
    global _genome_decode_calls, _genome_decode_rows
    with _lock:
        _genome_decode_calls += 1
        _genome_decode_rows += rows


# ----------------------------------------------------------------- #
# fleet restack / reattach counters                                 #
# ----------------------------------------------------------------- #
# process-wide accumulators fed by the FleetScheduler's restack paths
# and the stepper's flush->reattach boundary — the observability hook
# the serve accounting layer bills restack work through, and the pin
# that the incremental paths actually skip work
_restack_full = 0
_restack_inserts = 0
_restack_skipped = 0
_attach_full = 0
_attach_skipped = 0
# fed by the FleetScheduler's dispatch paths: physical device program
# launches vs. the rung groups they carried (fused dispatch launches
# one program for many groups)
_dispatches = 0
_fused_groups = 0


def note_restack(
    full: int = 0, inserts: int = 0, skipped: int = 0
) -> None:
    """Accumulate fleet restack work (called by the scheduler).

    ``full`` counts whole-group ``stack_worlds`` rebuilds (shape change
    or first stack), ``inserts`` counts single-slot incremental moves
    (re-insert / zero of one changed slot), ``skipped`` counts resident
    worlds an incremental restack left in place untouched."""
    global _restack_full, _restack_inserts, _restack_skipped
    with _lock:
        _restack_full += full
        _restack_inserts += inserts
        _restack_skipped += skipped


# physical integrator program launches, keyed by backend name (the
# ops.backends registry key).  ONE count per device dispatch that ran
# the integrator — a megastep's k fused integrator calls count once,
# and a fused fleet dispatch counts once per distinct backend across
# its groups.  This is the census the batched-pallas acceptance pins
# ("B worlds, ONE kernel") and the serve /metrics
# magicsoup_integrator_dispatches_total{backend=...} family reads.
_integrator_dispatches: dict[str, int] = {}


def note_integrator_dispatch(backend: str, n: int = 1) -> None:
    """Accumulate ``n`` physical integrator launches through ``backend``."""
    with _lock:
        _integrator_dispatches[backend] = (
            _integrator_dispatches.get(backend, 0) + n
        )


def note_dispatch(dispatches: int = 0, fused_groups: int = 0) -> None:
    """Accumulate fleet device dispatches (called by the scheduler).

    ``dispatches`` counts physical device program launches (one per
    rung group, or ONE for a whole fused set), ``fused_groups`` counts
    the rung groups those dispatches carried — the gating fused smoke
    pins ``dispatches == megasteps`` while ``fused_groups`` still sums
    to ``megasteps * n_groups``, which is the amortization the fusion
    planner exists for."""
    global _dispatches, _fused_groups
    with _lock:
        _dispatches += dispatches
        _fused_groups += fused_groups


def note_attach(full: int = 0, skipped: int = 0) -> None:
    """Accumulate flush->reattach outcomes (called by the stepper).

    ``full`` counts full host-replay rebuilds, ``skipped`` counts fast
    reattaches that proved the world untouched since its flush and kept
    the host replay state (and warm-variant bookkeeping) as-is."""
    global _attach_full, _attach_skipped
    with _lock:
        _attach_full += full
        _attach_skipped += skipped


# ----------------------------------------------------------------- #
# unified counter API (telemetry / tests)                           #
# ----------------------------------------------------------------- #
def snapshot() -> dict[str, int]:
    """Every process-global runtime counter as one flat dict.

    This is the read side the telemetry recorder and tests consume:
    one atomic view (single lock acquisition) instead of six separate
    accessor calls that could interleave with concurrent compiles.
    Keys: ``compiles``, ``persistent_cache_hits``,
    ``persistent_cache_misses``, ``phenotype_hits``,
    ``phenotype_misses``, ``phenotype_evictions``, ``restack_full``,
    ``restack_inserts``, ``restack_skipped``, ``attach_full``,
    ``attach_skipped``, ``dispatches``, ``fused_groups``, one
    ``integrator_dispatches_<backend>`` per integrator backend that has
    dispatched — plus the
    chaos/robustness contribution from
    ``guard.chaos.runtime_counters`` (``chaos_fired``, ``degraded``,
    and every ``note_counter`` key, so counted failures ride the same
    telemetry ``counters`` rows as everything else).
    """
    install()
    # lazy imports, and strictly runtime -> chaos / runtime -> metrics:
    # neither guard.chaos nor telemetry.metrics (stdlib-pure) imports
    # this module, so the counter merges cannot cycle
    from magicsoup_tpu.guard import chaos as _chaos
    from magicsoup_tpu.telemetry import metrics as _metrics

    with _lock:
        out = {
            "compiles": _count,
            "persistent_cache_hits": _cache_hits,
            "persistent_cache_misses": _cache_misses,
            "phenotype_hits": _pheno_hits,
            "phenotype_misses": _pheno_misses,
            "phenotype_evictions": _pheno_evictions,
            "phenotype_pickle_drops": _pheno_pickle_drops,
            "restack_full": _restack_full,
            "restack_inserts": _restack_inserts,
            "restack_skipped": _restack_skipped,
            "attach_full": _attach_full,
            "attach_skipped": _attach_skipped,
            "dispatches": _dispatches,
            "fused_groups": _fused_groups,
            "genome_decode_calls": _genome_decode_calls,
            "genome_decode_rows": _genome_decode_rows,
        }
        for name in sorted(_integrator_dispatches):
            out[f"integrator_dispatches_{name}"] = _integrator_dispatches[
                name
            ]
    out.update(_chaos.runtime_counters())
    # graftpulse device-time census (device_time_us/device_dispatches):
    # fed by the stepper/fleet fetch-ready callbacks, billed per-tenant
    # by serve.accounting, scraped via GET /metrics
    out.update(_metrics.device_time_stats())
    return out


def reset_counters() -> None:
    """Zero every counter in :func:`snapshot` (listeners stay installed).

    For test isolation: assert on absolute values after a reset instead
    of diffing raw process totals.  NOT safe inside an open
    :func:`hot_path_guard` window — the guard diffs
    :func:`compile_count` across the window, so zeroing mid-window
    underflows its budget math.
    """
    global _count, _cache_hits, _cache_misses
    global _pheno_hits, _pheno_misses, _pheno_evictions, _pheno_pickle_drops
    global _restack_full, _restack_inserts, _restack_skipped
    global _attach_full, _attach_skipped
    global _dispatches, _fused_groups
    global _genome_decode_calls, _genome_decode_rows
    from magicsoup_tpu.guard import chaos as _chaos
    from magicsoup_tpu.telemetry import metrics as _metrics

    with _lock:
        _count = 0
        _cache_hits = 0
        _cache_misses = 0
        _pheno_hits = 0
        _pheno_misses = 0
        _pheno_evictions = 0
        _pheno_pickle_drops = 0
        _restack_full = 0
        _restack_inserts = 0
        _restack_skipped = 0
        _attach_full = 0
        _attach_skipped = 0
        _dispatches = 0
        _fused_groups = 0
        _genome_decode_calls = 0
        _genome_decode_rows = 0
        _integrator_dispatches.clear()
    _chaos.reset_counters()
    _metrics.reset_device_time()
