"""
graftlint — static analysis + runtime guards for the TPU hot path.

The classes of bug that quietly destroy accelerator throughput (implicit
device->host syncs, jit recompile churn, dtype drift off the BITREPRO.md
float32 contract, hidden nondeterminism) are invisible to normal tests:
the code still computes the right numbers, just 10-1000x slower or
unreproducibly.  This package enforces them mechanically:

- static half: an AST lint pass over the library (`engine`, `callgraph`,
  `rules`) with a CLI (``python -m magicsoup_tpu.analysis --check``)
  wired into ``scripts/test.sh``;
- runtime half (`runtime`): ``hot_path_guard`` wraps hot-path tests in
  ``jax.transfer_guard("disallow")`` plus a compilation-count budget.

Rule codes (see `rules` for details, README.md for the user guide):

- GL001 host-sync-in-hot-path
- GL002 recompile-hazard
- GL003 dtype-discipline
- GL004 nondeterminism
- GL005 blocking-transfer

Suppress a finding on one line with ``# graftlint: disable=GL001`` (or a
comma list, or ``disable=all``); mark extra hot-path roots for the
reachability analysis with ``# graftlint: hot`` on a ``def`` line.
"""
from magicsoup_tpu.analysis.engine import Finding, analyze  # noqa: F401
