"""
Best-effort AST call graph over the linted file set, for hot-path
reachability (rule GL001 needs "functions reachable from the step
dispatches", not just the dispatches themselves).

Resolution is intentionally conservative — an edge is only recorded when
the callee can be pinned to a function in the linted set:

- bare names defined in the same module;
- ``self.meth(...)`` / ``cls.meth(...)`` within the defining class;
- ``from pkg.mod import fn`` then ``fn(...)``;
- ``import pkg.mod as m`` / ``from pkg import mod`` then ``m.fn(...)``;
- ``self._mgr = Ctor(...)`` then ``self._mgr.meth(...)`` — the
  attribute alias is pinned to ``Ctor`` when the constructor resolves
  to a linted class (and dropped again if any other assignment
  disagrees);
- ``x = Ctor(...)`` then ``x.meth(...)`` within one function, and
  ``def f(mgr: Ctor)`` then ``mgr.meth(...)`` through the parameter
  annotation.

Anything dynamic (callbacks, dict dispatch, attribute chains through
objects) is dropped rather than guessed: a too-eager graph would mark
half the library hot and drown real findings in noise.  Nested ``def``s
are folded into their enclosing function — a helper closed over by a hot
function is hot by construction.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field

# The step dispatches the simulation loop actually drives, keyed by file
# basename so the same seeds work on a checkout, an installed tree, or a
# test fixture copy.  Extra roots can be marked in source with a
# `# graftlint: hot` comment on (or directly above) the `def` line.
HOT_SEEDS: dict[str, tuple[str, ...]] = {
    "stepper.py": (
        "PipelinedStepper.step",
        "PipelinedStepper.drain",
    ),
    "world.py": (
        "World.step_many",
        "World.spawn_cells",
        "World.add_cells",
        "World.divide_cells",
        "World.update_cells",
        "World.kill_cells",
        "World.move_cells",
        "World.reposition_cells",
        "World.enzymatic_activity",
        "World.diffuse_molecules",
        "World.degrade_molecules",
        "World.mutate_cells",
        "World.recombinate_cells",
    ),
}

FuncKey = tuple[str, str]  # (file rel path, dotted qualname)


@dataclass
class FunctionRecord:
    """One module- or class-level function, with nested defs folded in."""

    file: object  # engine.SourceFile (duck-typed: .rel, .tree, ...)
    qualname: str
    node: ast.AST
    hot_marked: bool = False
    calls: set[FuncKey] = field(default_factory=set)
    # return-value expressions (for the dataflow engine's summaries)
    returns: list = field(default_factory=list)
    # local name -> (rel, class) pinned via annotation or constructor
    local_types: dict = field(default_factory=dict)


class CallGraph:
    def __init__(self, files: list):
        self.files = list(files)
        self.functions: dict[FuncKey, FunctionRecord] = {}
        self.classes: set[tuple[str, str]] = set()  # (rel, dotted class)
        self._by_module: dict[str, object] = {}
        self._imports: dict[str, dict[str, tuple[str, str | None]]] = {}
        # (rel, cls, attr) -> (class rel, class name); None = conflicting
        self._attr_class: dict[tuple[str, str, str], tuple[str, str] | None] = {}
        self._callers: dict[FuncKey, set[FuncKey]] | None = None
        for f in self.files:
            self._by_module[f.module] = f
            self._index_file(f)
        for rec in self.functions.values():
            self._collect_attr_aliases(rec)
        for rec in self.functions.values():
            self._extract_calls(rec)

    # ------------------------------------------------------------- index
    def _index_file(self, f) -> None:
        imports: dict[str, tuple[str, str | None]] = {}
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    imports[alias] = (a.name, None)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    imports[a.asname or a.name] = (node.module, a.name)
        self._imports[f.rel] = imports

        def visit(body, prefix: str) -> None:
            for node in body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    first = node.decorator_list[0].lineno if node.decorator_list else node.lineno
                    marked = any(
                        ln in f.hot_marks
                        for ln in range(first - 1, node.lineno + 1)
                    )
                    q = prefix + node.name
                    self.functions[(f.rel, q)] = FunctionRecord(
                        file=f, qualname=q, node=node, hot_marked=marked
                    )
                elif isinstance(node, ast.ClassDef):
                    self.classes.add((f.rel, prefix + node.name))
                    visit(node.body, prefix + node.name + ".")

        visit(f.tree.body, "")

    # ----------------------------------------------------------- aliases
    def _collect_attr_aliases(self, rec: FunctionRecord) -> None:
        """Record ``self.X = Ctor(...)`` attribute→class pins for one
        method.  Conflicting pins (two assignments, different classes)
        collapse to None so resolution stays conservative."""
        if "." not in rec.qualname:
            return
        cls = rec.qualname.rsplit(".", 1)[0]
        for node in ast.walk(rec.node):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            pairs = []
            for tgt in targets:
                if (
                    isinstance(tgt, ast.Tuple)
                    and isinstance(node.value, ast.Tuple)
                    and len(tgt.elts) == len(node.value.elts)
                ):
                    pairs.extend(zip(tgt.elts, node.value.elts))
                elif node.value is not None:
                    pairs.append((tgt, node.value))
            for tgt, value in pairs:
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    continue
                pinned = None
                if isinstance(value, ast.Call):
                    pinned = self.resolve_class(rec.file, value.func)
                key = (rec.file.rel, cls, tgt.attr)
                if key in self._attr_class and self._attr_class[key] != pinned:
                    self._attr_class[key] = None
                else:
                    self._attr_class[key] = pinned

    # ------------------------------------------------------------- edges
    def _extract_calls(self, rec: FunctionRecord) -> None:
        cls = rec.qualname.rsplit(".", 1)[0] if "." in rec.qualname else None
        rec.local_types = self._local_types(rec)
        for node in ast.walk(rec.node):
            if isinstance(node, ast.Call):
                tgt = self.resolve(rec.file, cls, node.func, rec.local_types)
                if tgt is not None:
                    rec.calls.add(tgt)
                rec.calls.update(self._getattr_dispatch(rec.file, cls, node))
            elif isinstance(node, ast.Return) and node.value is not None:
                rec.returns.append(node.value)

    def _local_types(self, rec: FunctionRecord) -> dict:
        """Pin local names to linted classes: parameter annotations and
        ``x = Ctor(...)`` assignments.  Reassignment to anything else
        drops the pin."""
        types: dict[str, tuple[str, str] | None] = {}
        node = rec.node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            for a in (
                list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
            ):
                if a.annotation is not None:
                    pinned = self._annotation_class(rec.file, a.annotation)
                    if pinned is not None:
                        types[a.arg] = pinned
        for sub in ast.walk(node):
            if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                continue
            targets = sub.targets if isinstance(sub, ast.Assign) else [sub.target]
            for tgt in targets:
                if not isinstance(tgt, ast.Name):
                    continue
                pinned = None
                if isinstance(sub.value, ast.Call):
                    pinned = self.resolve_class(rec.file, sub.value.func)
                if tgt.id in types and types[tgt.id] != pinned:
                    types[tgt.id] = None
                else:
                    types[tgt.id] = pinned
        return {k: v for k, v in types.items() if v is not None}

    def _annotation_class(self, f, ann: ast.expr) -> tuple[str, str] | None:
        """Resolve a parameter annotation to a linted class.  Handles
        ``Cls``, ``"Cls"`` strings, and ``Cls | None`` unions."""
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            return self._annotation_class(f, ann.left) or self._annotation_class(
                f, ann.right
            )
        if isinstance(ann, (ast.Name, ast.Attribute)):
            return self.resolve_class(f, ann)
        return None

    def resolve_class(self, f, expr: ast.expr) -> tuple[str, str] | None:
        """Resolve a class-reference expression to a linted (rel, name)."""
        imports = self._imports.get(f.rel, {})
        if isinstance(expr, ast.Name):
            if (f.rel, expr.id) in self.classes:
                return (f.rel, expr.id)
            if expr.id in imports:
                mod, name = imports[expr.id]
                if name is not None:
                    return self._module_class(mod, name)
            return None
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            base = expr.value.id
            if base in imports:
                mod, name = imports[base]
                target = mod if name is None else f"{mod}.{name}"
                return self._module_class(target, expr.attr)
        return None

    def _module_class(self, module: str, name: str) -> tuple[str, str] | None:
        tf = self._by_module.get(module)
        if tf is None:
            for m, file in self._by_module.items():
                if module.endswith("." + m) or m.endswith("." + module):
                    tf = file
                    break
        if tf is None:
            return None
        key = (tf.rel, name)
        return key if key in self.classes else None

    def _getattr_dispatch(self, f, cls: str | None, call: ast.Call) -> set[FuncKey]:
        """Edges for ``getattr(self, f"_cmd_{name}")``-style dispatch.

        A constant prefix in the f-string pins the callee set to every
        same-class method sharing that prefix — without this, dynamically
        dispatched handlers have no static callers and the concurrency
        model would misclassify them as unreachable/ambient.
        """
        if not (isinstance(call.func, ast.Name) and call.func.id == "getattr"):
            return set()
        if len(call.args) < 2 or cls is None:
            return set()
        obj, name_expr = call.args[0], call.args[1]
        if not (isinstance(obj, ast.Name) and obj.id in ("self", "cls")):
            return set()
        if not (
            isinstance(name_expr, ast.JoinedStr)
            and name_expr.values
            and isinstance(name_expr.values[0], ast.Constant)
            and isinstance(name_expr.values[0].value, str)
        ):
            return set()
        prefix = f"{cls}." + name_expr.values[0].value
        return {
            key
            for key in self.functions
            if key[0] == f.rel and key[1].startswith(prefix)
        }

    def resolve_ref(self, f, cls: str | None, expr: ast.expr) -> FuncKey | None:
        """Resolve a function *reference* (not a call) to a linted function.

        Handles the forms thread/finalizer registration actually uses:
        ``self._run`` / bare names / imported names, plus
        ``functools.partial(fn, ...)`` which unwraps to its first
        positional argument.
        """
        if isinstance(expr, ast.Call):
            callee = expr.func
            is_partial = (
                isinstance(callee, ast.Name) and callee.id == "partial"
            ) or (
                isinstance(callee, ast.Attribute)
                and callee.attr == "partial"
                and isinstance(callee.value, ast.Name)
                and callee.value.id == "functools"
            )
            if is_partial and expr.args:
                return self.resolve_ref(f, cls, expr.args[0])
            return None
        return self.resolve(f, cls, expr)

    def resolve(
        self,
        f,
        cls: str | None,
        func: ast.expr,
        local_types: dict | None = None,
    ) -> FuncKey | None:
        """Resolve a call target expression to a linted function, or None."""
        imports = self._imports.get(f.rel, {})
        if isinstance(func, ast.Name):
            if (f.rel, func.id) in self.functions:
                return (f.rel, func.id)
            if func.id in imports:
                mod, name = imports[func.id]
                if name is not None:
                    return self._module_func(mod, name)
            return None
        if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
            base = func.value.id
            if base in ("self", "cls") and cls:
                key = (f.rel, f"{cls}.{func.attr}")
                return key if key in self.functions else None
            if base in imports:
                mod, name = imports[base]
                target = mod if name is None else f"{mod}.{name}"
                return self._module_func(target, func.attr)
            if local_types and base in local_types:
                crel, cname = local_types[base]
                key = (crel, f"{cname}.{func.attr}")
                return key if key in self.functions else None
            return None
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Attribute)
            and isinstance(func.value.value, ast.Name)
            and func.value.value.id == "self"
            and cls
        ):
            # ``self._mgr.save(...)`` through a pinned attribute alias
            pinned = self._attr_class.get((f.rel, cls, func.value.attr))
            if pinned is not None:
                crel, cname = pinned
                key = (crel, f"{cname}.{func.attr}")
                return key if key in self.functions else None
        return None

    def _module_func(self, module: str, name: str) -> FuncKey | None:
        tf = self._by_module.get(module)
        if tf is None:
            # linting a subtree (or a fixture dir) yields shorter dotted
            # module names than the import strings — match by suffix
            for m, file in self._by_module.items():
                if module.endswith("." + m) or m.endswith("." + module):
                    tf = file
                    break
        if tf is None:
            return None
        key = (tf.rel, name)
        return key if key in self.functions else None

    # ------------------------------------------------------------ callers
    def callers(self) -> dict[FuncKey, set[FuncKey]]:
        """Reverse edge map (callee -> direct callers), computed once."""
        if self._callers is None:
            rev: dict[FuncKey, set[FuncKey]] = {}
            for key, rec in self.functions.items():
                for callee in rec.calls:
                    rev.setdefault(callee, set()).add(key)
            self._callers = rev
        return self._callers

    # --------------------------------------------------------------- hot
    def hot_functions(self) -> set[FuncKey]:
        """Transitive closure of the step-dispatch seeds + hot marks."""
        seeds = [
            key
            for key, rec in self.functions.items()
            if rec.hot_marked
            or rec.qualname in HOT_SEEDS.get(rec.file.rel.rsplit("/", 1)[-1], ())
        ]
        seen = set(seeds)
        stack = list(seeds)
        while stack:
            for callee in self.functions[stack.pop()].calls:
                if callee not in seen:
                    seen.add(callee)
                    stack.append(callee)
        return seen
