"""
SARIF 2.1.0 subset emitter for graftlint findings.

CI code-scanning surfaces (and most editors) ingest SARIF natively; this
module maps the graftlint finding model onto the minimal valid subset:
one run, one driver, one rule descriptor per GL code, one result per
finding with a physical location and the fix-it as the result message's
second paragraph.  Pure stdlib, no third-party SARIF packages — the
schema subset is small enough that hand-rolling it is less surface than
a dependency (and the container image bakes in nothing SARIF-aware).

Stability contract: the output is deterministic for a given finding list
(rules sorted by code, results in engine order, no timestamps), so the
artifact diffs cleanly between CI runs.
"""
from __future__ import annotations

import json

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def _rule_descriptor(code: str, name: str, desc: str) -> dict:
    return {
        "id": code,
        "name": name,
        "shortDescription": {"text": name},
        "fullDescription": {"text": desc},
        "defaultConfiguration": {"level": "error"},
    }


def _result(finding) -> dict:
    return {
        "ruleId": finding.rule,
        "level": "error",
        "message": {"text": f"{finding.message}\n\nfix-it: {finding.fixit}"},
        "locations": [
            {
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": finding.path,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {
                        "startLine": finding.line,
                        "startColumn": max(1, finding.col),
                    },
                }
            }
        ],
    }


def to_sarif(findings, rule_info: dict) -> dict:
    """Build the SARIF log dict for `findings`.

    `rule_info` is the graftlint RULE_INFO map (code -> (name, desc));
    every known rule is listed in the driver even when it produced no
    results — code-scanning UIs use the rule table to render "passing"
    checks, not just failures.
    """
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftlint",
                        "informationUri": (
                            "https://github.com/mRcSchwering/magic-soup"
                        ),
                        "rules": [
                            _rule_descriptor(code, name, desc)
                            for code, (name, desc) in sorted(rule_info.items())
                        ],
                    }
                },
                "columnKind": "utf16CodeUnits",
                "results": [_result(f) for f in findings],
            }
        ],
    }


def write_sarif(path, findings, rule_info: dict) -> None:
    """Serialize `findings` as a SARIF 2.1.0 log at `path`."""
    log = to_sarif(findings, rule_info)
    with open(path, "w") as fh:
        json.dump(log, fh, indent=2, sort_keys=False)
        fh.write("\n")
