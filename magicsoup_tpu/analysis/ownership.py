"""
graftrace runtime half: thread-ownership assertions that mirror the
static roles of `analysis.concurrency`.

The static model proves what the *source* does; these assertions catch
what the *process* does — a test helper poking ``FleetService._tick``
from the wrong thread, a refactor that moves a flush off the owning
loop — at the exact call site, with the role named in the error.

Zero-cost when disabled: ``MAGICSOUP_DEBUG_OWNERSHIP`` is read once at
import, and with the flag unset ``owned_by`` returns the undecorated
function and ``assert_owner``/``bind`` return immediately.  CI arms the
checks for the whole tier-1 run (scripts/test.sh exports
``MAGICSOUP_DEBUG_OWNERSHIP=1``), so every test doubles as an ownership
probe without taxing production steps.

Binding is per-instance and lazy: the first checked call from any
thread claims the role for that instance; a dead owner thread frees the
role (services restart their loop threads); ``bind()`` force-rebinds at
a sanctioned handoff point (e.g. the top of ``FleetService.run``, which
may execute on a freshly started loop thread after construction touched
the same state from the main thread).
"""
from __future__ import annotations

import functools
import os
import threading

_ENABLED = os.environ.get("MAGICSOUP_DEBUG_OWNERSHIP", "") == "1"
_TABLE = "_graftrace_owners"

__all__ = [
    "OwnershipViolation",
    "assert_owner",
    "bind",
    "enabled",
    "owned_by",
]


class OwnershipViolation(AssertionError):
    """A role-owned attribute or method was touched from a foreign
    thread.  Names the attribute, the expected role, the thread that
    owns the role, and the offending thread."""

    def __init__(self, attribute: str, role: str, owner, offender) -> None:
        self.attribute = attribute
        self.role = role
        self.owner = owner
        self.offender = offender
        super().__init__(
            f"{attribute}: role `{role}` is owned by thread "
            f"{owner.name!r} (ident={owner.ident}) but was entered from "
            f"{offender.name!r} (ident={offender.ident})"
        )


def enabled() -> bool:
    """Whether ownership assertions are armed for this process."""
    return _ENABLED


def _table(obj) -> dict | None:
    table = getattr(obj, _TABLE, None)
    if table is None:
        table = {}
        try:
            object.__setattr__(obj, _TABLE, table)
        except (AttributeError, TypeError):
            return None  # __slots__/frozen instances: nothing to pin to
    return table


def _check(obj, role: str, attribute: str) -> None:
    table = _table(obj)
    if table is None:
        return
    current = threading.current_thread()
    owner = table.get(role)
    if owner is None or owner is current or not owner.is_alive():
        # lazy claim / re-claim after the owning thread exited
        table[role] = current
        return
    raise OwnershipViolation(attribute, role, owner, current)


def bind(obj, role: str, thread=None) -> None:
    """Force-assign `role` on `obj` to `thread` (default: the calling
    thread).  Use at sanctioned handoff points — the top of a loop
    thread's run(), after construction warmed the same state elsewhere."""
    if not _ENABLED:
        return
    table = _table(obj)
    if table is not None:
        table[role] = thread or threading.current_thread()


def assert_owner(obj, role: str, attribute: str | None = None) -> None:
    """Assert the calling thread owns `role` on `obj` (claiming it if
    unclaimed).  Raises :class:`OwnershipViolation` otherwise."""
    if not _ENABLED:
        return
    _check(obj, role, attribute or f"{type(obj).__name__}<{role}>")


def owned_by(role: str):
    """Method decorator: every call must come from the thread owning
    `role` on this instance.  Returns the function untouched when
    ownership checking is disabled, so decorated hot paths cost nothing
    in production."""

    def deco(fn):
        if not _ENABLED:
            return fn

        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            _check(self, role, fn.__qualname__)
            return fn(self, *args, **kwargs)

        return wrapper

    return deco
