"""
graftlint rule engine: file parsing, suppression comments, the baseline,
and the analyze() entry point the CLI and tests share.

Pure stdlib (ast + tokenize) — the static half must run in CI images
without importing jax or the library under analysis.
"""
from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass
from pathlib import Path

# codes only — free-text rationale after the code list is encouraged
SUPPRESS_RE = re.compile(
    r"graftlint:\s*disable=((?:[A-Za-z]+\d*)(?:\s*,\s*[A-Za-z]+\d*)*)"
)
HOT_RE = re.compile(r"graftlint:\s*hot\b")
# thread-ownership declaration for the graftrace concurrency rules:
# `# graftlint: owner=scheduler-loop` on a `def` declares the function a
# role entry point; on an attribute assignment it declares the
# attribute's sanctioned single writer (see analysis/concurrency.py)
OWNER_RE = re.compile(r"graftlint:\s*owner=([A-Za-z0-9_\-]+)")


@dataclass(frozen=True, order=True)
class Finding:
    path: str
    line: int
    col: int
    rule: str  # "GL001"
    name: str  # "host-sync-in-hot-path"
    message: str
    fixit: str

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"({self.name}) {self.message}\n    fix-it: {self.fixit}"
        )

    @property
    def key(self) -> str:
        """Baseline key: stable across line-number drift."""
        return f"{self.path}::{self.rule}"


class SourceFile:
    """One parsed module: AST + suppression/hot comment maps."""

    def __init__(self, path: Path, rel: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = path.read_text()
        self.tree = ast.parse(self.text, filename=str(path))
        self.module = self.rel[:-3].replace("/", ".")
        self.suppressions: dict[int, set[str]] = {}
        self.hot_marks: set[int] = set()
        self.owners: dict[int, str] = {}
        self._parents: dict[ast.AST, ast.AST] | None = None
        lines = self.text.splitlines()
        for tok in tokenize.generate_tokens(io.StringIO(self.text).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = SUPPRESS_RE.search(tok.string)
            if m:
                codes = {
                    c.strip().upper()
                    for c in m.group(1).split(",")
                    if c.strip()
                }
                line = tok.start[0]
                self.suppressions.setdefault(line, set()).update(codes)
                # a comment-only line suppresses the line BELOW it too
                # (trailing comments don't fit next to long expressions)
                if lines[line - 1].lstrip().startswith("#"):
                    self.suppressions.setdefault(line + 1, set()).update(codes)
            m = OWNER_RE.search(tok.string)
            if m:
                line = tok.start[0]
                self.owners[line] = m.group(1)
                if lines[line - 1].lstrip().startswith("#"):
                    self.owners.setdefault(line + 1, m.group(1))
            if HOT_RE.search(tok.string):
                self.hot_marks.add(tok.start[0])
        self._share_across_decorated_headers()

    def _share_across_decorated_headers(self) -> None:
        """Findings on decorated defs are reported at the decorator line;
        waivers and owner= declarations written on the `def` line must
        still match.  Union both maps across each decorated definition's
        header lines (every decorator line plus the def line)."""
        for node in ast.walk(self.tree):
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            if not node.decorator_list:
                continue
            header = {d.lineno for d in node.decorator_list} | {node.lineno}
            codes: set[str] = set()
            for ln in header:
                codes |= self.suppressions.get(ln, set())
            if codes:
                for ln in header:
                    self.suppressions.setdefault(ln, set()).update(codes)
            owner = next(
                (self.owners[ln] for ln in sorted(header) if ln in self.owners),
                None,
            )
            if owner is not None:
                for ln in header:
                    self.owners.setdefault(ln, owner)

    def suppressed(self, line: int, rule: str) -> bool:
        codes = self.suppressions.get(line, ())
        return rule in codes or "ALL" in codes

    def parents(self) -> dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents


@dataclass
class Context:
    """Everything a rule checker gets to see."""

    files: list[SourceFile]
    graph: object  # callgraph.CallGraph
    hot: set  # set[FuncKey]
    model: object | None = None  # concurrency.ThreadModel
    dataflow: object | None = None  # dataflow.DataflowModel


def default_target() -> Path:
    """The library source dir (the `magicsoup_tpu` package itself)."""
    return Path(__file__).resolve().parents[1]


def iter_python_files(paths) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            out.append(p)
    return out


def load_files(paths, exclude_analysis: bool = True) -> list[SourceFile]:
    files = []
    seen = set()
    for path in iter_python_files(paths):
        resolved = path.resolve()
        if resolved in seen:
            continue
        seen.add(resolved)
        if exclude_analysis and "analysis" in resolved.parts:
            continue  # the linter does not lint itself
        rel = os.path.relpath(resolved)
        files.append(SourceFile(resolved, rel))
    return files


def build_context(paths, timings: dict | None = None) -> Context:
    """Parse `paths` and build every shared model (call graph, thread
    roles, device dataflow).  `timings`, when given, is filled with
    per-pass wall seconds — the CLI's `--check` telemetry."""
    import time

    from magicsoup_tpu.analysis.callgraph import CallGraph
    from magicsoup_tpu.analysis.concurrency import ThreadModel
    from magicsoup_tpu.analysis.dataflow import DataflowModel

    marks = timings if timings is not None else {}
    t0 = time.perf_counter()
    files = load_files(paths)
    t1 = time.perf_counter()
    marks["parse"] = t1 - t0
    graph = CallGraph(files)
    t2 = time.perf_counter()
    marks["callgraph"] = t2 - t1
    model = ThreadModel(files, graph)
    t3 = time.perf_counter()
    marks["threadmodel"] = t3 - t2
    dataflow = DataflowModel(files, graph)
    marks["dataflow"] = time.perf_counter() - t3
    return Context(
        files=files,
        graph=graph,
        hot=graph.hot_functions(),
        model=model,
        dataflow=dataflow,
    )


def analyze(
    paths,
    rules: list[str] | None = None,
    ctx: Context | None = None,
    timings: dict | None = None,
) -> list[Finding]:
    """Run the (optionally filtered) rule set over `paths`.

    Returns suppression-filtered findings sorted by location.  Baseline
    subtraction is separate (see apply_baseline) so callers can report
    both totals.
    """
    import time

    from magicsoup_tpu.analysis import rules as rules_mod

    if ctx is None:
        ctx = build_context(paths, timings=timings)

    t0 = time.perf_counter()
    by_rel = {f.rel: f for f in ctx.files}
    findings: list[Finding] = []
    for code, checker in rules_mod.checkers(rules).items():
        for finding in checker(ctx):
            src = by_rel.get(finding.path)
            if src is not None and src.suppressed(finding.line, finding.rule):
                continue
            findings.append(finding)
    if timings is not None:
        timings["rules"] = time.perf_counter() - t0
    return sorted(set(findings))


def default_baseline_path() -> Path:
    return Path(__file__).resolve().parent / "baseline.json"


def load_baseline(path: Path | None = None) -> dict[str, int]:
    """Baseline: map of `path::RULE` -> tolerated finding count.  The
    shipped baseline is EMPTY by policy — pre-existing findings are fixed
    or annotated inline where the next reader sees them; the file exists
    so a downstream fork can stage a large cleanup incrementally."""
    path = path or default_baseline_path()
    if not Path(path).exists():
        return {}
    data = json.loads(Path(path).read_text() or "{}")
    # "_"-prefixed keys are policy/comment entries, not budgets
    return {
        str(k): int(v) for k, v in data.items() if not str(k).startswith("_")
    }


def apply_baseline(
    findings: list[Finding], baseline: dict[str, int]
) -> list[Finding]:
    """Drop up to the baselined count of findings per `path::RULE` key."""
    budget = dict(baseline)
    fresh = []
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
        else:
            fresh.append(f)
    return fresh
