"""
The graftlint rule set.  Each checker takes an engine.Context and yields
Findings; registration at the bottom.

| code  | name                 | protects                                   |
|-------|----------------------|--------------------------------------------|
| GL001 | host-sync-in-hot-path| step-loop latency (no blocking D2H syncs)  |
| GL002 | recompile-hazard     | compile-time amortization (no per-step jit)|
| GL003 | dtype-discipline     | BITREPRO.md float32 contract               |
| GL004 | nondeterminism       | seeded reproducibility                     |
| GL005 | blocking-transfer    | the single audited D2H boundary            |
| GL006 | missing-donation     | steady-state HBM (step buffers donated)    |
| GL007 | tolist-in-hot-loop   | batch host conversion (no per-item tolist) |
| GL008 | host-callback-in-jit | no host round trips inside jitted bodies   |
| GL009 | missing-sharding     | explicit placement in mesh-aware modules   |
| GL010 | non-atomic-save      | crash-safe state persistence (guard.io)    |
| GL011 | traced-assert        | invariants that actually fire (no traced   |
|       |                      | `assert` inside jitted bodies)             |
| GL012 | shared-prng-key      | per-world randomness in fleet modules (no  |
|       |                      | one key consumed across the world axis)    |
| GL013 | swallowed-guard-error| typed guard errors reach their policy layer|
|       |                      | (no broad `except` without re-raise in     |
|       |                      | guard/fleet-scoped modules)                |
| GL014 | blocking-call-in-    | serve-loop liveness (no unbounded sleeps / |
|       | serve-loop           | waits inside serve-scoped scheduler loops) |
| GL015 | cross-thread-write   | single-writer discipline (no attribute     |
|       |                      | written from two thread roles lock-free)   |
| GL016 | lock-order-inversion | deadlock freedom (one global acquisition   |
|       |                      | order for every lock pair)                 |
| GL017 | queue-bypass         | the serve command-queue contract (handler  |
|       |                      | threads never mutate fleet state directly) |
| GL018 | raw-io-in-guard-path | the guard.io write boundary (no direct     |
|       |                      | `open(...,"wb")`/`os.replace` in guard/    |
|       |                      | fleet/serve-scoped modules — raw writes    |
|       |                      | bypass atomicity AND the chaos fault plane)|
| GL019 | implicit-host-sync   | step-loop latency across call boundaries   |
|       |                      | (syncs the shallow GL001 pass cannot see:  |
|       |                      | taint through returns/attrs/containers)    |
| GL020 | fetch-boundary-bypass| the metered util.fetch_host boundary (D2H  |
|       |                      | conversions that corrupt the fetch/bytes   |
|       |                      | counters telemetry and accounting bill)    |
| GL021 | unprobed-robustness- | chaos coverage as a static proof (every    |
|       | boundary             | retry/except-OSError boundary in guarded   |
|       |                      | subsystems reachable by a fault point, and |
|       |                      | FAULT_POINTS registry/probe agreement)     |
| GL022 | untyped-error-escape | typed errors at certified entries (no bare |
|       |                      | ValueError/OSError escaping serve handlers,|
|       |                      | warden hooks, or checkpoint paths)         |
| GL023 | host-genome-in-hot-  | device-resident genomes (no host genome    |
|       | path                 | list access or per-cell string mutation    |
|       |                      | engine calls in stepper/fleet/serve hot    |
|       |                      | functions — tokens stay on device)         |
| GL024 | per-group-dispatch-  | the fused-dispatch contract (no device     |
|       | loop                 | dispatch call inside a `for ... group/     |
|       |                      | sibling` loop in fleet/serve-scoped        |
|       |                      | modules — dispatches route through the     |
|       |                      | fusion planner, or carry a waiver)         |
| GL025 | bare-clock-in-hot-   | the graftpulse measurement plane (no bare  |
|       | path                 | `time.time()`/`perf_counter()` readings in |
|       |                      | stepper/fleet/serve hot functions unless   |
|       |                      | the measurement routes into the recorder   |
|       |                      | span API or the metrics registry)          |
| GL026 | integrator-backend-  | the integrator backend plane (no direct    |
|       | bypass               | `integrate_signals`/`integrate_signals_    |
|       |                      | pallas` calls in stepper/fleet/serve hot   |
|       |                      | functions — the kernel choice routes       |
|       |                      | through ops.backends.integrate)            |

GL015-GL017 are built on the graftrace thread-role model; see
analysis/concurrency.py for the model and analysis/ownership.py for the
matching runtime assertions.

The device-taint analysis in THIS module is a deliberately shallow
intra-procedural pass: a name is "device" when it is a parameter
annotated with a device type, is assigned from a jax/jnp call, or flows
through arithmetic / indexing / method calls on device values; fetching
through the sanctioned boundary (util.fetch_host, jax.device_get)
un-taints.  Shallow means under-approximate — precision here buys a
zero-noise default, which is what keeps the lint gate tolerable in CI.
GL019-GL022 layer the graftflow INTERPROCEDURAL taint fixpoint on top
(analysis/dataflow.py): call/return summaries, self-attribute facts, and
per-element tuple tracking catch what the shallow pass cannot, deduped
so each site is reported by exactly one rule.
"""
from __future__ import annotations

import ast
import re

from magicsoup_tpu.analysis import concurrency, dataflow
from magicsoup_tpu.analysis.engine import Context, Finding

JAX_ROOTS = {"jax", "jnp", "lax"}
NUMPY_ROOTS = {"np", "numpy"}
# device-resident attributes of the library's own classes
DEVICE_ATTRS = {
    "_state",
    "_molecule_map",
    "_cell_molecules",
    "_positions_dev",
    "_mol_idx_dev",
    "_kill_below_dev",
    "_divide_above_dev",
    "_divide_cost_dev",
}
# metadata attributes that never touch device buffers
HOST_META_ATTRS = {
    "shape",
    "ndim",
    "dtype",
    "size",
    "nbytes",
    "itemsize",
    "sharding",
    "is_fully_addressable",
    "is_deleted",
    "weak_type",
}
# the sanctioned boundary: fetching through these returns HOST data
HOST_FETCHERS = {"fetch_host", "_fetch_host", "device_get", "sanctioned_transfer"}
# jax.* calls that return host metadata, not device buffers
JAX_HOST_FNS = {
    "devices",
    "local_devices",
    "device_count",
    "local_device_count",
    "process_index",
    "process_count",
    "default_backend",
    "eval_shape",
}
DEVICE_ANN = re.compile(r"\bArray\b|\bDeviceState\b|\bCellParams\b")

RULE_INFO = {
    "GL001": (
        "host-sync-in-hot-path",
        "blocking device->host sync inside a function reachable from the "
        "step dispatches",
    ),
    "GL002": (
        "recompile-hazard",
        "jit/pmap wrapper constructed per call, or unhashable static "
        "argument — every occurrence retriggers trace+compile",
    ),
    "GL003": (
        "dtype-discipline",
        "float64 / bare-Python-float array construction outside "
        "ops/detmath.py (BITREPRO.md float32 contract)",
    ),
    "GL004": (
        "nondeterminism",
        "wall-clock or unseeded randomness in library code",
    ),
    "GL005": (
        "blocking-transfer",
        "device->host transfer outside the sanctioned util.fetch_host "
        "boundary",
    ),
    "GL006": (
        "missing-donation",
        "jit over a DeviceState/CellParams argument without "
        "donate_argnums — the program returns the successor buffers, so "
        "an undonated input keeps TWO copies of the tensors live in HBM",
    ),
    "GL007": (
        "tolist-in-hot-loop",
        "per-item `.tolist()` inside a loop in a hot-path function — "
        "each call crosses the C/Python boundary per element; convert "
        "the whole array ONCE before the loop and slice host lists",
    ),
    "GL008": (
        "host-callback-in-jit",
        "io_callback/pure_callback/jax.debug host work inside a jitted "
        "body — a host round trip compiled into the device program; "
        "telemetry must ride the packed output record instead",
    ),
    "GL009": (
        "missing-sharding",
        "hot-path `jax.device_put` / jnp array construction without an "
        "explicit device/sharding inside a mesh-aware module — the "
        "array lands on the default device uncommitted, and a sharded "
        "jit silently re-replicates it across the mesh on EVERY "
        "dispatch (the silent-replication footgun)",
    ),
    "GL010": (
        "non-atomic-save",
        "state pickled straight into its destination file — a crash "
        "mid-write destroys BOTH the old snapshot and the new one; "
        "persistence must go through guard.io's "
        "write-temp->fsync->os.replace protocol",
    ),
    "GL011": (
        "traced-assert",
        "bare `assert` inside a jitted body — a condition on traced "
        "values silently vanishes at trace time (tracers are truthy), "
        "and a condition on Python values bakes into the compiled "
        "program as a per-shape recompile hazard",
    ),
    "GL012": (
        "shared-prng-key",
        "a `jax.random.*` draw in a fleet module consuming a key that "
        "is not per-world — one unsplit key broadcast across the world "
        "axis gives every world of the batch the SAME random stream, "
        "silently correlating trajectories that are documented "
        "independent",
    ),
    "GL013": (
        "swallowed-guard-error",
        "broad `except Exception:`/`except BaseException:` without a "
        "re-raise in a guard/fleet-scoped module — the typed guard "
        "errors (CheckpointError, SentinelTripped, WatchdogTimeout) "
        "exist so the policy layer can react; a blanket handler that "
        "logs-and-continues turns a refused checkpoint or a tripped "
        "sentinel into silent corruption",
    ),
    "GL014": (
        "blocking-call-in-serve-loop",
        "an unbounded blocking call (`time.sleep`, `.result()` with no "
        "timeout, `.get()` with no timeout) inside a loop in a "
        "serve-scoped module — the serving loop is the single writer "
        "for every tenant, so one unbounded wait stalls all of them "
        "and turns a transient hiccup into a fleet-wide outage",
    ),
    "GL018": (
        "raw-io-in-guard-path",
        "a direct write-mode `open()` or `os.replace`/`os.rename` in a "
        "guard/fleet/serve-scoped module — raw file writes bypass "
        "guard.io's write-temp->fsync->replace protocol (so a crash "
        "tears the file) AND the graftchaos fault plane (so the chaos "
        "campaign cannot reach the failure path at all); append-mode "
        "streams are exempt",
    ),
    "GL023": (
        "host-genome-in-hot-path",
        "a host genome list access (`.cell_genomes` / `._genomes`) or a "
        "per-cell string mutation engine call inside a stepper-, "
        "fleet-, or serve-scoped hot function — genomes are "
        "device-resident packed token arrays; decoding them (or running "
        "the host string engine) on the hot path reintroduces the "
        "per-cell host work the token backend exists to delete",
    ),
    "GL024": (
        "per-group-dispatch-loop",
        "a device dispatch call inside a `for`-loop over rung groups / "
        "sibling groups in a fleet- or serve-scoped module — each "
        "iteration pays a full program launch (+ its own D2H fetch), "
        "which is exactly the R-dispatches-per-megastep cost the "
        "cross-rung fusion planner deletes; route the loop through "
        "FleetScheduler._plan_fusion (one batched program per fused "
        "set) or waive a deliberate per-group path",
    ),
    "GL025": (
        "bare-clock-in-hot-path",
        "a bare `time.time()` / `time.perf_counter()` / "
        "`time.monotonic()` reading inside a stepper-, fleet-, or "
        "serve-scoped hot function whose measurement never routes into "
        "the telemetry plane — timings taken on the hot path and kept "
        "in local state are invisible to the recorder spans, the "
        "graftpulse metrics registry, and therefore to `/metrics`; "
        "route the reading through the recorder span API "
        "(TelemetryRecorder.note) or the metrics registry (observe / "
        "note_device_time), or waive a deliberate local timing",
    ),
    "GL026": (
        "integrator-backend-bypass",
        "a direct `integrate_signals` / `integrate_signals_pallas` / "
        "`_integrate_signals_jit` call inside a stepper-, fleet-, or "
        "serve-scoped hot function — the integrator implementation is "
        "selected by the backend registry (ops.backends: capability "
        "flags, env/constructor resolution, the dispatch census), and a "
        "hot-path call that names a kernel directly pins one "
        "implementation, skips the capability checks, and invisibly "
        "forks the selection logic the `World(integrator=...)` plane "
        "exists to centralize; route through ops.backends.integrate "
        "with the resolved backend name",
    ),
}
# the graftrace concurrency rules keep their metadata next to their
# model (analysis/concurrency.py) — merge so the CLI/docs see one table
RULE_INFO.update(concurrency.RULE_INFO)
# ...and the graftflow dataflow rules next to theirs (analysis/dataflow.py)
RULE_INFO.update(dataflow.RULE_INFO)


def _root_name(node: ast.expr) -> str | None:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _attr_chain(node: ast.expr) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _is_host_fetch(func: ast.expr) -> bool:
    if isinstance(func, ast.Name):
        return func.id in HOST_FETCHERS
    if isinstance(func, ast.Attribute):
        return func.attr in HOST_FETCHERS
    return False


def _finding(code: str, f, node: ast.AST, message: str, fixit: str) -> Finding:
    return Finding(
        path=f.rel,
        line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0) + 1,
        rule=code,
        name=RULE_INFO[code][0],
        message=message,
        fixit=fixit,
    )


# --------------------------------------------------------------- taint
def device_tainted_names(fn_node: ast.AST) -> set[str]:
    tainted: set[str] = set()
    args = getattr(fn_node, "args", None)
    if args is not None:
        for a in [*args.posonlyargs, *args.args, *args.kwonlyargs]:
            if a.annotation is not None and DEVICE_ANN.search(
                ast.unparse(a.annotation)
            ):
                tainted.add(a.arg)
    # two fixed passes: enough for straight-line propagation without a
    # full dataflow framework
    for _ in range(2):
        for node in ast.walk(fn_node):
            value = None
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                value, targets = node.value, [node.target]
            elif isinstance(node, ast.AugAssign):
                value, targets = node.value, [node.target]
            if value is None:
                continue
            names = [
                t.id
                for tgt in targets
                for t in (
                    tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) else [tgt]
                )
                if isinstance(t, ast.Name)
            ]
            if isinstance(value, ast.Call) and _is_host_fetch(value.func):
                tainted.difference_update(names)
            elif expr_is_device(value, tainted):
                tainted.update(names)
    return tainted


def expr_is_device(e: ast.expr, tainted: set[str]) -> bool:
    if isinstance(e, ast.Name):
        return e.id in tainted
    if isinstance(e, ast.Attribute):
        if e.attr in HOST_META_ATTRS:
            return False
        if e.attr in DEVICE_ATTRS:
            return True
        return expr_is_device(e.value, tainted)
    if isinstance(e, ast.Call):
        if _is_host_fetch(e.func):
            return False
        root = _root_name(e.func)
        if root in JAX_ROOTS:
            return not (
                isinstance(e.func, ast.Attribute) and e.func.attr in JAX_HOST_FNS
            )
        if isinstance(e.func, ast.Attribute) and e.func.attr not in (
            "item",
            "tolist",
        ):
            # method call on a device value returns a device value
            return expr_is_device(e.func.value, tainted)
        return False
    if isinstance(e, ast.BinOp):
        return expr_is_device(e.left, tainted) or expr_is_device(e.right, tainted)
    if isinstance(e, ast.UnaryOp):
        return expr_is_device(e.operand, tainted)
    if isinstance(e, ast.Subscript):
        return expr_is_device(e.value, tainted)
    if isinstance(e, ast.Compare):
        # identity tests (`x is None`) read the reference, not the buffer
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
            return False
        return expr_is_device(e.left, tainted) or any(
            expr_is_device(c, tainted) for c in e.comparators
        )
    if isinstance(e, ast.BoolOp):
        return any(expr_is_device(v, tainted) for v in e.values)
    if isinstance(e, ast.IfExp):
        return expr_is_device(e.body, tainted) or expr_is_device(e.orelse, tainted)
    if isinstance(e, (ast.Tuple, ast.List)):
        return any(expr_is_device(v, tainted) for v in e.elts)
    return False


# --------------------------------------------------------------- GL001
def check_gl001(ctx: Context):
    fix_fetch = (
        "keep the value on device, or fetch ONCE through "
        "magicsoup_tpu.util.fetch_host outside the step loop"
    )
    for key in sorted(ctx.hot):
        rec = ctx.graph.functions[key]
        f = rec.file
        tainted = device_tainted_names(rec.node)
        for node in ast.walk(rec.node):
            if isinstance(node, ast.Call):
                fn = node.func
                # .item() is unconditional (it is a sync by definition);
                # .tolist() only on device-tainted receivers — host numpy
                # .tolist() is idiomatic in the pure-python fallbacks
                if isinstance(fn, ast.Attribute) and (
                    fn.attr == "item"
                    or (
                        fn.attr == "tolist"
                        and expr_is_device(fn.value, tainted)
                    )
                ):
                    yield _finding(
                        "GL001",
                        f,
                        node,
                        f"`.{fn.attr}()` in hot function `{rec.qualname}` "
                        "blocks the step loop on a device->host transfer",
                        fix_fetch,
                    )
                elif (
                    isinstance(fn, ast.Name)
                    and fn.id in ("float", "int", "bool")
                    and node.args
                    and expr_is_device(node.args[0], tainted)
                ):
                    yield _finding(
                        "GL001",
                        f,
                        node,
                        f"`{fn.id}()` on a device value in hot function "
                        f"`{rec.qualname}` forces a blocking sync",
                        fix_fetch,
                    )
                elif (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in ("asarray", "array")
                    and _root_name(fn) in NUMPY_ROOTS
                    and node.args
                    and expr_is_device(node.args[0], tainted)
                ):
                    yield _finding(
                        "GL001",
                        f,
                        node,
                        f"`np.{fn.attr}()` on a device value in hot function "
                        f"`{rec.qualname}` forces a blocking sync",
                        fix_fetch,
                    )
            elif isinstance(node, ast.If) and expr_is_device(node.test, tainted):
                yield _finding(
                    "GL001",
                    f,
                    node,
                    f"`if` on a device value in hot function `{rec.qualname}` "
                    "synchronizes every step (ConcretizationTypeError under "
                    "jit; a blocking D2H when eager)",
                    "branch with jnp.where / lax.cond, or hoist the decision "
                    "out of the hot loop",
                )


# --------------------------------------------------------------- GL002
_JIT_NAMES = {"jit", "pmap", "shard_map"}


def _is_jit_ctor(func: ast.expr) -> bool:
    if isinstance(func, ast.Name):
        return func.id in _JIT_NAMES
    if isinstance(func, ast.Attribute):
        return func.attr in _JIT_NAMES and (
            _root_name(func) in JAX_ROOTS or _root_name(func) is None
        )
    return False


def _memo_decorated(fn_node: ast.AST) -> bool:
    """True when the enclosing builder is itself memoized
    (``functools.lru_cache`` / ``functools.cache``) — the decorator IS
    the once-per-static-configuration guard, same contract as an
    explicit cache dict."""
    for dec in getattr(fn_node, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        chain = _attr_chain(target)
        if chain.rsplit(".", 1)[-1] in ("lru_cache", "cache"):
            return True
    return False


def _cache_guarded(f, node: ast.AST) -> bool:
    """The sanctioned memoized-jit idiom: the wrapper is built under an
    ``if key not in cache:`` guard or stored into a cache subscript, so
    it is constructed once per static configuration, not per call."""
    parents = f.parents()
    cur = node
    while cur is not None:
        if isinstance(cur, ast.If):
            for sub in ast.walk(cur.test):
                if isinstance(sub, ast.Compare) and any(
                    isinstance(op, (ast.NotIn, ast.In)) for op in sub.ops
                ):
                    return True
        if isinstance(cur, ast.Assign) and any(
            isinstance(t, ast.Subscript) for t in cur.targets
        ):
            return True
        cur = parents.get(cur)
    return False


def _enclosing_function(f, node: ast.AST):
    parents = f.parents()
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = parents.get(cur)
    return None


def _static_argnames(fn_node: ast.AST) -> set[str]:
    """Static-arg names declared by a @jit / @partial(jax.jit, ...)
    decorator on `fn_node` (string literals only)."""
    out: set[str] = set()
    for dec in getattr(fn_node, "decorator_list", []):
        if not isinstance(dec, ast.Call):
            continue
        # direct jax.jit(...) or partial(jax.jit, static_argnames=...)
        if not _is_jit_ctor(dec.func) and not any(
            _is_jit_ctor(a) for a in dec.args
        ):
            continue
        for kw in dec.keywords:
            if kw.arg == "static_argnames":
                for sub in ast.walk(kw.value):
                    if isinstance(sub, ast.Constant) and isinstance(
                        sub.value, str
                    ):
                        out.add(sub.value)
    return out


_UNHASHABLE = (ast.List, ast.Set, ast.Dict, ast.ListComp, ast.SetComp, ast.DictComp)


def check_gl002(ctx: Context):
    # index statically-declared jit functions for the call-site check
    static_by_key: dict = {}
    for key, rec in ctx.graph.functions.items():
        names = _static_argnames(rec.node)
        if names:
            static_by_key[key] = names

    for f in ctx.files:
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_jit_ctor(node.func):
                enclosing = _enclosing_function(f, node)
                if enclosing is None:
                    continue  # module-scope jit compiles once
                if _cache_guarded(f, node) or _memo_decorated(enclosing):
                    continue
                yield _finding(
                    "GL002",
                    f,
                    node,
                    f"jit/pmap wrapper constructed inside "
                    f"`{enclosing.name}()` — a fresh wrapper per call "
                    "restarts trace+compile every step",
                    "hoist the jit to module scope, or memoize it in a "
                    "module-level cache keyed by its static configuration",
                )
                continue
            # call-site check: unhashable value passed to a declared
            # static argument of a jitted function in the linted set
            cls = None
            enclosing = _enclosing_function(f, node)
            if enclosing is not None:
                parents = f.parents()
                cur = parents.get(enclosing)
                while cur is not None:
                    if isinstance(cur, ast.ClassDef):
                        cls = cur.name
                        break
                    cur = parents.get(cur)
            target = ctx.graph.resolve(f, cls, node.func)
            if target is None or target not in static_by_key:
                continue
            statics = static_by_key[target]
            for kw in node.keywords:
                if kw.arg in statics and isinstance(kw.value, _UNHASHABLE):
                    yield _finding(
                        "GL002",
                        f,
                        node,
                        f"unhashable `{kw.arg}={ast.unparse(kw.value)}` "
                        f"passed to static argument of jitted "
                        f"`{target[1]}` — jit static args must be hashable "
                        "(and every new value recompiles)",
                        "pass a tuple / frozen value, and make sure its "
                        "cardinality is bounded",
                    )


# --------------------------------------------------------------- GL003
def check_gl003(ctx: Context):
    fix = (
        "stay in float32 (BITREPRO.md contract); deterministic f64 "
        "accumulation belongs in ops/detmath.py — annotate sanctioned "
        "sites with `# graftlint: disable=GL003`"
    )
    for f in ctx.files:
        if f.rel.rsplit("/", 1)[-1] == "detmath.py":
            continue  # THE sanctioned f64 module
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Attribute) and node.attr == "float64":
                root = _root_name(node)
                if root in JAX_ROOTS | NUMPY_ROOTS:
                    yield _finding(
                        "GL003",
                        f,
                        node,
                        f"`{_attr_chain(node)}` outside ops/detmath.py",
                        fix,
                    )
            elif (
                isinstance(node, ast.keyword)
                and node.arg == "dtype"
                and isinstance(node.value, ast.Constant)
                and node.value.value == "float64"
            ):
                yield _finding(
                    "GL003", f, node.value, 'dtype="float64" string literal', fix
                )
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("array", "asarray")
                and _root_name(node.func) in JAX_ROOTS
                and not any(kw.arg == "dtype" for kw in node.keywords)
                and any(
                    isinstance(a, ast.Constant) and isinstance(a.value, float)
                    for a in ast.walk(node)
                    if isinstance(a, ast.Constant)
                )
            ):
                yield _finding(
                    "GL003",
                    f,
                    node,
                    "bare Python float in jnp.array(...) without an explicit "
                    "dtype — weak typing drifts to f64 under x64",
                    "pass dtype=jnp.float32 explicitly",
                )


# --------------------------------------------------------------- GL004
_NP_RANDOM_OK = {"default_rng", "Generator", "PCG64", "SeedSequence", "Philox"}


def check_gl004(ctx: Context):
    for f in ctx.files:
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if chain in ("time.time", "time.time_ns"):
                yield _finding(
                    "GL004",
                    f,
                    node,
                    f"`{chain}()` in library code — wall clock breaks seeded "
                    "reproducibility",
                    "thread an explicit seed / step counter through instead; "
                    "annotate telemetry-only sites with "
                    "`# graftlint: disable=GL004`",
                )
            elif chain.startswith("random.") and chain.split(".")[1] not in (
                "Random",
            ):
                yield _finding(
                    "GL004",
                    f,
                    node,
                    f"`{chain}()` uses process-global (or OS-entropy) "
                    "randomness",
                    "use a seeded random.Random(seed) instance plumbed from "
                    "the caller",
                )
            elif (
                chain.startswith(("np.random.", "numpy.random."))
                and chain.rsplit(".", 1)[-1] not in _NP_RANDOM_OK
            ):
                yield _finding(
                    "GL004",
                    f,
                    node,
                    f"`{chain}()` mutates numpy's process-global RNG",
                    "use np.random.default_rng(seed) plumbed from the caller",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "PRNGKey"
            ):
                bad_seed = not node.args or any(
                    isinstance(sub, ast.Call)
                    and _attr_chain(sub.func).split(".")[0] in ("time", "random")
                    for a in node.args
                    for sub in ast.walk(a)
                )
                if bad_seed:
                    yield _finding(
                        "GL004",
                        f,
                        node,
                        "unseeded (or clock-seeded) jax.random.PRNGKey",
                        "derive keys from one experiment-level seed via "
                        "jax.random.split / fold_in",
                    )


# --------------------------------------------------------------- GL005
def check_gl005(ctx: Context):
    fix = (
        "route the fetch through magicsoup_tpu.util.fetch_host — the one "
        "audited device->host point (explicit jax.device_get, allowed "
        "under transfer guards)"
    )
    for f in ctx.files:
        if f.rel.rsplit("/", 1)[-1] == "util.py":
            continue  # fetch_host lives here: the sanctioned boundary
        for key, rec in ctx.graph.functions.items():
            if rec.file is not f or key in ctx.hot:
                continue  # hot functions are GL001's domain
            tainted = device_tainted_names(rec.node)
            for node in ast.walk(rec.node):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if (
                    isinstance(fn, ast.Attribute)
                    and fn.attr == "device_get"
                    and _root_name(fn) in JAX_ROOTS
                ) or (isinstance(fn, ast.Name) and fn.id == "device_get"):
                    yield _finding(
                        "GL005",
                        f,
                        node,
                        f"`jax.device_get` in `{rec.qualname}` bypasses the "
                        "sanctioned boundary",
                        fix,
                    )
                elif (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in ("asarray", "array")
                    and _root_name(fn) in NUMPY_ROOTS
                    and node.args
                    and expr_is_device(node.args[0], tainted)
                ):
                    yield _finding(
                        "GL005",
                        f,
                        node,
                        f"`np.{fn.attr}()` on a device value in "
                        f"`{rec.qualname}` is an implicit blocking transfer",
                        fix,
                    )


# --------------------------------------------------------------- GL006
def _jit_wrapper_kwargs(call: ast.Call) -> dict | None:
    """Keyword args of a jit-wrapper construction — ``jax.jit(...)``
    directly or ``(functools.)partial(jax.jit, ...)`` — else None."""
    if _is_jit_ctor(call.func) or any(_is_jit_ctor(a) for a in call.args):
        return {kw.arg: kw.value for kw in call.keywords if kw.arg}
    return None


def _jit_wrapped_defs(ctx: Context, f) -> list[tuple]:
    """(wrapped function def, node to report, wrapper kwargs) for every
    jit-wrapped function in the file — the decorator spellings
    (``@jax.jit``, ``@partial(jax.jit, ...)``) and the assignment
    spelling (``name = partial(jax.jit, ...)(fn)``).  Shared by GL006
    (donation) and GL008 (host callbacks) so "what counts as a jitted
    body" cannot drift between the rules."""
    fns_by_name = {
        rec.qualname: rec.node
        for rec in ctx.graph.functions.values()
        if rec.file is f
    }
    wrappers: list[tuple] = []
    for node in ast.walk(f.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    kwargs = _jit_wrapper_kwargs(dec)
                    if kwargs is not None:
                        wrappers.append((node, dec, kwargs))
                elif _is_jit_ctor(dec):  # bare @jax.jit
                    wrappers.append((node, dec, {}))
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Call
        ):
            # partial(jax.jit, ...)(fn) as an expression
            kwargs = _jit_wrapper_kwargs(node.func)
            if (
                kwargs is not None
                and len(node.args) == 1
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in fns_by_name
            ):
                wrappers.append((fns_by_name[node.args[0].id], node, kwargs))
    return wrappers


def check_gl006(ctx: Context):
    """Step-level jits over a ``DeviceState`` (or a ``CellParams``
    pytree — the phenotype scatter path) must donate it: the program
    consumes the buffers and returns their successors, so without
    ``donate_argnums`` XLA keeps BOTH generations of every tensor live
    (the exact double-buffering the stepper exists to avoid).  Covers
    the decorator spellings (``@jax.jit``, ``@partial(jax.jit, ...)``)
    and the assignment spelling (``name = partial(jax.jit, ...)(fn)``)."""
    fix = (
        "add donate_argnums covering the DeviceState/CellParams "
        "parameter (its successor is returned, so the buffer can be "
        "reused in place); annotate intentionally double-buffered "
        "programs with `# graftlint: disable=GL006`"
    )
    for f in ctx.files:
        for fn_node, where, kwargs in _jit_wrapped_defs(ctx, f):
            args = getattr(fn_node, "args", None)
            if args is None:
                continue
            pos = [*args.posonlyargs, *args.args]
            state_idxs = [
                i
                for i, a in enumerate(pos)
                if a.annotation is not None
                and re.search(
                    r"\bDeviceState\b|\bCellParams\b",
                    ast.unparse(a.annotation),
                )
            ]
            if not state_idxs:
                continue
            if kwargs.get("donate_argnames") is not None:
                continue  # name-based donation: assume it covers the state
            donated: set[int] = set()
            dval = kwargs.get("donate_argnums")
            if dval is not None:
                for sub in ast.walk(dval):
                    if isinstance(sub, ast.Constant) and isinstance(
                        sub.value, int
                    ):
                        donated.add(sub.value)
            missing = [i for i in state_idxs if i not in donated]
            if missing:
                yield _finding(
                    "GL006",
                    f,
                    where,
                    f"jit over `{fn_node.name}` leaves its device-pytree "
                    f"argument (position {missing[0]}) undonated — "
                    "steady-state HBM holds two copies of its tensors",
                    fix,
                )


# --------------------------------------------------------------- GL007
def check_gl007(ctx: Context):
    """Per-item ``.tolist()`` inside a loop in a hot function: every
    call crosses the C/Python boundary and allocates a fresh list for
    ONE row, so a batch of n items pays n round-trips.  The fast idiom
    (genetics.translate_genomes) converts the whole array once before
    the loop and slices host lists inside it.  GL001 already covers the
    device-tainted case (a blocking D2H per iteration); this rule keeps
    the host-numpy case out of the hot paths too."""
    fix = (
        "hoist the conversion: call `.tolist()` ONCE on the full array "
        "before the loop and index the resulting host list per item; "
        "waive a deliberate per-item conversion with "
        "`# graftlint: disable=GL007`"
    )
    for key in sorted(ctx.hot):
        rec = ctx.graph.functions[key]
        f = rec.file
        seen: set[int] = set()  # nested loops walk the same calls twice
        for loop in ast.walk(rec.node):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if id(node) in seen:
                    continue
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "tolist"
                ):
                    seen.add(id(node))
                    yield _finding(
                        "GL007",
                        f,
                        node,
                        f"`.tolist()` inside a loop in hot function "
                        f"`{rec.qualname}` converts per item — n "
                        "iterations pay n C/Python round-trips",
                        fix,
                    )


# --------------------------------------------------------------- GL008
_HOST_CALLBACK_LEAVES = {"io_callback", "pure_callback"}
_DEBUG_LEAVES = {"print", "callback", "breakpoint"}


def check_gl008(ctx: Context):
    """Telemetry must stay off the device: a host callback
    (``io_callback`` / ``pure_callback`` / ``host_callback`` /
    ``jax.debug.print|callback|breakpoint``) inside a jit-wrapped body
    compiles a host round trip into the device program — paid on EVERY
    execution, exactly the per-step sync the pipelined stepper exists
    to avoid.  The sanctioned design packs metrics into the step's
    output record on device (stepper._step_body's telemetry lanes) and
    times phases host-side around the dispatch
    (telemetry.TelemetryRecorder)."""
    fix = (
        "compute the metric on device and pack it into the step output "
        "record (it rides the existing fetch for free); host-side spans "
        "belong in TelemetryRecorder AROUND the dispatch, not inside "
        "the jitted body; waive a deliberate debugging callback with "
        "`# graftlint: disable=GL008`"
    )
    for f in ctx.files:
        seen: set[int] = set()
        for fn_node, _where, _kwargs in _jit_wrapped_defs(ctx, f):
            for node in ast.walk(fn_node):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                chain = _attr_chain(node.func)
                if not chain:
                    continue
                leaf = chain.rsplit(".", 1)[-1]
                if (
                    leaf in _HOST_CALLBACK_LEAVES
                    or "host_callback" in chain.split(".")
                    or (
                        "debug" in chain.split(".")
                        and leaf in _DEBUG_LEAVES
                    )
                ):
                    seen.add(id(node))
                    yield _finding(
                        "GL008",
                        f,
                        node,
                        f"host callback `{chain}` inside jitted body "
                        f"`{fn_node.name}` compiles a host round trip "
                        "into the device program",
                        fix,
                    )


# --------------------------------------------------------------- GL009
# a module is mesh-aware when it imports sharding machinery at the TOP
# level (jax.sharding / shard_map / magicsoup_tpu.parallel).  Lazy
# in-function imports (world.py's tiled fallback) deliberately do not
# count: those modules place buffers through the mesh-aware ones.
_MESH_IMPORT_ROOTS = (
    "jax.sharding",
    "jax.experimental.shard_map",
    "magicsoup_tpu.parallel",
)
# jnp constructors that materialize NEW buffers and accept `device=`
# (zeros_like & co. inherit their prototype's sharding and are exempt)
_PLACEMENT_CTORS = {
    "asarray",
    "array",
    "zeros",
    "ones",
    "full",
    "empty",
    "arange",
}


def _is_mesh_aware(f) -> bool:
    for node in f.tree.body:
        if isinstance(node, ast.Import):
            if any(
                alias.name.startswith(_MESH_IMPORT_ROOTS)
                for alias in node.names
            ):
                return True
        elif isinstance(node, ast.ImportFrom) and node.module:
            if node.module.startswith(_MESH_IMPORT_ROOTS):
                return True
    return False


def check_gl009(ctx: Context):
    """Placement must be explicit in mesh-aware modules: a bare
    ``jax.device_put(x)`` or ``jnp.asarray/zeros/...`` WITHOUT a
    device/sharding lands the buffer on the default device
    uncommitted, so a sharded jit re-replicates it across the mesh on
    every dispatch — silently, because GSPMD treats an unplaced input
    as "replicate however you like".  Jitted bodies are exempt (inside
    a trace, intermediates are placed by GSPMD / sharding constraints,
    not ``device=``); so are non-mesh-aware modules, where there is
    only one device to land on."""
    fix = (
        "pass the placement explicitly — `device=sharding` on the jnp "
        "constructor or a second argument to `jax.device_put` (use "
        "tiled.replicated_sharding/cell_sharding/map_sharding, or the "
        "stepper's `_dev()` helper); waive a deliberate single-device "
        "fallback branch with `# graftlint: disable=GL009`"
    )
    mesh_ids = {id(f) for f in ctx.files if _is_mesh_aware(f)}
    jit_ids_by_file: dict[int, set[int]] = {}
    for key in sorted(ctx.hot):
        rec = ctx.graph.functions[key]
        f = rec.file
        if id(f) not in mesh_ids:
            continue
        if id(f) not in jit_ids_by_file:
            jit_ids_by_file[id(f)] = {
                id(n)
                for fn_node, _w, _k in _jit_wrapped_defs(ctx, f)
                for n in ast.walk(fn_node)
            }
        jit_ids = jit_ids_by_file[id(f)]
        if id(rec.node) in jit_ids:
            continue  # traced body: GSPMD places intermediates
        for node in ast.walk(rec.node):
            if (
                not isinstance(node, ast.Call)
                or id(node) in jit_ids
            ):
                continue
            chain = _attr_chain(node.func)
            if not chain:
                continue
            root = chain.split(".", 1)[0]
            leaf = chain.rsplit(".", 1)[-1]
            if root not in JAX_ROOTS:
                continue
            kwnames = {kw.arg for kw in node.keywords}
            if "device" in kwnames:
                continue
            if leaf == "device_put" and len(node.args) < 2:
                yield _finding(
                    "GL009",
                    f,
                    node,
                    f"`{chain}()` without a placement in hot function "
                    f"`{rec.qualname}` of a mesh-aware module — the "
                    "buffer is uncommitted and a sharded jit "
                    "re-replicates it every dispatch",
                    fix,
                )
            elif leaf in _PLACEMENT_CTORS:
                yield _finding(
                    "GL009",
                    f,
                    node,
                    f"`{chain}()` without `device=` in hot function "
                    f"`{rec.qualname}` of a mesh-aware module — the "
                    "array lands on the default device instead of its "
                    "mesh sharding",
                    fix,
                )


_PICKLE_DUMP = {"pickle.dump", "cloudpickle.dump", "dill.dump"}
_PICKLE_DUMPS = {"pickle.dumps", "cloudpickle.dumps", "dill.dumps"}


def check_gl010(ctx: Context):
    """State persistence must be crash-safe: ``pickle.dump(obj, fh)``
    (or ``fh.write(pickle.dumps(obj))``) straight into the destination
    file truncates the previous snapshot the moment the file opens, so
    a crash mid-write destroys both the old bytes and the new — the
    exact failure guard.io's write-temp -> fsync -> ``os.replace``
    protocol exists to close.  Passing ``pickle.dumps`` bytes to
    ``guard.io.atomic_write_bytes`` (or any non-``.write`` consumer) is
    the sanctioned form and is not flagged; the guard package itself —
    the one place allowed to own raw file protocol — is exempt."""
    fix = (
        "serialize to bytes and hand them to "
        "guard.io.atomic_write_bytes(path, pickle.dumps(obj)) — or use "
        "guard.write_checkpoint for a verified, versioned snapshot; "
        "waive a deliberate raw write (e.g. a fault injector) with "
        "`# graftlint: disable=GL010`"
    )
    for f in ctx.files:
        if "guard" in f.path.parts:
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if chain in _PICKLE_DUMP and len(node.args) >= 2:
                yield _finding(
                    "GL010",
                    f,
                    node,
                    f"`{chain}()` writes state directly into its "
                    "destination file — a crash mid-write destroys the "
                    "previous snapshot along with the new one",
                    fix,
                )
            elif (
                chain.endswith(".write")
                and chain not in _PICKLE_DUMP
                and node.args
                and isinstance(node.args[0], ast.Call)
                and _attr_chain(node.args[0].func) in _PICKLE_DUMPS
            ):
                yield _finding(
                    "GL010",
                    f,
                    node,
                    f"`{chain}({_attr_chain(node.args[0].func)}(...))` "
                    "writes pickled state non-atomically — a partial "
                    "write leaves a truncated pickle where the previous "
                    "snapshot was",
                    fix,
                )


# --------------------------------------------------------------- GL011
def check_gl011(ctx: Context):
    """Invariants inside a jitted body must use machinery that can
    actually fire: a bare ``assert`` on traced values evaluates the
    TRACER's truthiness at trace time — always true, so the check
    silently vanishes from the compiled program — and an ``assert`` on
    Python-level values bakes the outcome into the traced program,
    turning a data-dependent check into a per-shape recompile hazard.
    The sanctioned designs are the graftcheck invariant lanes (compute
    the flag on device, pack it into the step record, police it on the
    host replay — ``check.invariants``) or ``jax.experimental.checkify``
    for a hard functional assert."""
    fix = (
        "compute the condition on device and pack it into the step "
        "output record as an invariant lane (check.invariants; the host "
        "replay polices it via sentinel_policy), or use "
        "jax.experimental.checkify for a hard assert; waive a "
        "deliberate trace-time shape check with "
        "`# graftlint: disable=GL011`"
    )
    for f in ctx.files:
        seen: set[int] = set()
        for fn_node, _where, _kwargs in _jit_wrapped_defs(ctx, f):
            for node in ast.walk(fn_node):
                if not isinstance(node, ast.Assert) or id(node) in seen:
                    continue
                seen.add(id(node))
                yield _finding(
                    "GL011",
                    f,
                    node,
                    f"bare `assert` inside jitted body `{fn_node.name}` "
                    "— on traced values it silently vanishes at trace "
                    "time; on Python values it is a recompile hazard",
                    fix,
                )


# --------------------------------------------------------------- GL012
#: key-plumbing forms, never draws — exempt from the per-world check
_KEY_PLUMBING = {
    "PRNGKey",
    "key",
    "split",
    "fold_in",
    "wrap_key_data",
    "key_data",
    "clone",
}
#: first-arg forms that ARE per-world: a subscripted key array
#: (``keys[w]``) or a fresh derivation from the world lane
_PER_WORLD_DERIVES = {"split", "fold_in"}


def _is_fleet_scoped(f) -> bool:
    """A file is fleet-scoped when it lives under a ``fleet`` package or
    imports one — the modules whose code runs under the stacked world
    axis, where a non-per-world key is a correctness hazard rather than
    a style choice."""
    if "fleet" in f.path.parts:
        return True
    for node in ast.walk(f.tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and "fleet" in node.module.split("."):
                return True
            if any(a.name == "fleet" for a in node.names):
                return True
        elif isinstance(node, ast.Import):
            if any("fleet" in a.name.split(".") for a in node.names):
                return True
    return False


def check_gl012(ctx: Context):
    """Randomness under the fleet's stacked world axis must be
    per-world: every ``jax.random.*`` draw in a fleet-scoped module has
    to consume a key indexed out of a per-world key array (``keys[w]``)
    or freshly derived from one (``split`` / ``fold_in``).  A bare key
    name — one unsplit key reused across the batch — broadcasts the
    SAME stream to every world, so B "independent" trajectories share
    their mutation draws, spawn positions, and recombination points.
    The solo stepper's single-key discipline is exactly the bug here:
    stacking it without splitting correlates the fleet."""
    fix = (
        "index a per-world key array (`keys[w]`) or derive the lane key "
        "with jax.random.fold_in(key, world_index) / jax.random.split "
        "before drawing; waive a deliberately shared stream (e.g. a "
        "common environment shock) with `# graftlint: disable=GL012`"
    )
    for f in ctx.files:
        if not _is_fleet_scoped(f):
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if not chain.startswith("jax.random."):
                continue
            leaf = chain.rsplit(".", 1)[-1]
            if leaf in _KEY_PLUMBING:
                continue
            if not node.args:
                yield _finding(
                    "GL012",
                    f,
                    node,
                    f"`{chain}()` without a key argument in a fleet "
                    "module — there is no per-world stream at all",
                    fix,
                )
                continue
            k = node.args[0]
            per_world = isinstance(k, ast.Subscript) or (
                isinstance(k, ast.Call)
                and _attr_chain(k.func).rsplit(".", 1)[-1]
                in _PER_WORLD_DERIVES
            )
            if not per_world:
                yield _finding(
                    "GL012",
                    f,
                    node,
                    f"`{chain}()` consumes a key shared across the "
                    "world axis — every world of the fleet draws the "
                    "same stream",
                    fix,
                )


#: broad handler types GL013 flags — anything these catch includes the
#: whole typed guard hierarchy (GuardError is a RuntimeError)
_BROAD_EXC = {"Exception", "BaseException"}


def _is_guard_scoped(f) -> bool:
    """A file is guard-scoped when it lives under a ``guard`` package
    or imports one — the modules that handle the typed guard errors
    (and every fleet-scoped module, which sits above them)."""
    if "guard" in f.path.parts:
        return True
    if _is_fleet_scoped(f):
        return True
    for node in ast.walk(f.tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and "guard" in node.module.split("."):
                return True
            if any(a.name == "guard" for a in node.names):
                return True
        elif isinstance(node, ast.Import):
            if any("guard" in a.name.split(".") for a in node.names):
                return True
    return False


def check_gl013(ctx: Context):
    """Typed guard errors must reach their policy layer.  The guard
    hierarchy (``CheckpointError``, ``SentinelTripped``,
    ``WatchdogTimeout``, ...) exists so callers can REACT — restore a
    checkpoint, quarantine a world, kill a wedged fetch.  A broad
    ``except Exception:`` (or ``BaseException:``, or a bare
    ``except:``) in a guard/fleet-scoped module that never re-raises
    swallows all of them indistinguishably from a transient hiccup:
    the run continues on corrupt state and the fault surfaces far from
    its cause.  A handler whose body contains any ``raise`` passes —
    wrapping into a typed error or re-raising after cleanup is exactly
    the sanctioned shape."""
    fix = (
        "catch the specific errors the block can actually handle, or "
        "re-raise (`raise` / `raise TypedError(...) from exc`) after "
        "cleanup; waive a handler that deliberately delivers the error "
        "elsewhere (e.g. future.set_exception) with "
        "`# graftlint: disable=GL013`"
    )
    for f in ctx.files:
        if not _is_guard_scoped(f):
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            excs = (
                list(node.type.elts)
                if isinstance(node.type, ast.Tuple)
                else [node.type]
            )
            broad = any(
                e is None or _attr_chain(e).rsplit(".", 1)[-1] in _BROAD_EXC
                for e in excs
            )
            if not broad:
                continue
            if any(
                isinstance(n, ast.Raise)
                for stmt in node.body
                for n in ast.walk(stmt)
            ):
                continue
            what = (
                "bare `except:`"
                if node.type is None
                else f"`except {ast.unparse(node.type)}:`"
            )
            yield _finding(
                "GL013",
                f,
                node,
                f"{what} without re-raise in a guard-scoped module "
                "swallows the typed guard errors (CheckpointError, "
                "SentinelTripped, WatchdogTimeout) the policy layer "
                "needs to see",
                fix,
            )


def _is_serve_scoped(f) -> bool:
    """A file is serve-scoped when it lives under a ``serve`` package
    or imports one — the modules that run (or ride on) the service's
    single-writer scheduler loop, where an unbounded wait blocks every
    tenant at once."""
    if "serve" in f.path.parts:
        return True
    for node in ast.walk(f.tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and "serve" in node.module.split("."):
                return True
            if any(a.name == "serve" for a in node.names):
                return True
        elif isinstance(node, ast.Import):
            if any("serve" in a.name.split(".") for a in node.names):
                return True
    return False


def check_gl014(ctx: Context):
    """Serve loops must stay live.  The serving layer runs ONE
    scheduler thread for every tenant; any unbounded blocking call
    inside one of its loops — ``time.sleep`` pacing, a ``.result()``
    with no timeout on a future, a ``queue.get()`` with no timeout —
    parks the whole fleet behind a single wait that nothing can
    interrupt (a wedged transfer then looks identical to a busy
    service, and SIGTERM drain deadlines silently slip).  The
    sanctioned shapes are the non-blocking drain
    (``get_nowait()``/``Event.wait(timeout=...)``) and
    timeout-bounded waits whose expiry surfaces as a typed error.
    Scope: ``while`` loops in serve-scoped modules — request handlers
    and one-shot commands may block (their caller holds the timeout).
    """
    fix = (
        "bound the wait (`q.get(timeout=...)`, `fut.result(timeout=...)`)"
        " or drain without blocking (`get_nowait()` + `Event.wait(t)`); "
        "waive a deliberately blocking wait with "
        "`# graftlint: disable=GL014`"
    )
    for f in ctx.files:
        if not _is_serve_scoped(f):
            continue
        for loop in ast.walk(f.tree):
            if not isinstance(loop, ast.While):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                kwargs = {k.arg for k in node.keywords}
                chain = _attr_chain(node.func)
                if chain == "time.sleep":
                    yield _finding(
                        "GL014",
                        f,
                        node,
                        "`time.sleep()` inside a serve-loop `while` — "
                        "sleep pacing blocks every tenant and ignores "
                        "wake/stop events",
                        fix,
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "result"
                    and not node.args
                    and "timeout" not in kwargs
                ):
                    yield _finding(
                        "GL014",
                        f,
                        node,
                        f"`{chain}()` without a timeout inside a "
                        "serve-loop `while` — an unfinished future "
                        "wedges the single scheduler thread forever",
                        fix,
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and not node.args
                    and "timeout" not in kwargs
                    and not any(
                        k.arg == "block"
                        and isinstance(k.value, ast.Constant)
                        and k.value.value is False
                        for k in node.keywords
                    )
                ):
                    yield _finding(
                        "GL014",
                        f,
                        node,
                        f"`{chain}()` without a timeout inside a "
                        "serve-loop `while` — an empty queue blocks "
                        "the loop with no way to observe stop/wake",
                        fix,
                    )


# --------------------------------------------------------------- GL018
#: open() modes that can MODIFY the target ("w"/"x" truncate or create,
#: "+" allows in-place writes); plain reads and append-only streams
#: (JSONL telemetry sinks) are legitimately raw
_WRITE_MODE = re.compile(r"[wx+]")


def _open_write_mode(node: ast.Call) -> str | None:
    """The string-literal mode of an ``open()`` call when it can write,
    else None (reads, appends, or a dynamic mode expression)."""
    mode = node.args[1] if len(node.args) >= 2 else None
    if mode is None:
        for kw in node.keywords:
            if kw.arg == "mode":
                mode = kw.value
    if (
        isinstance(mode, ast.Constant)
        and isinstance(mode.value, str)
        and _WRITE_MODE.search(mode.value)
    ):
        return mode.value
    return None


def check_gl018(ctx: Context):
    """Writes in the robustness stack must go through ``guard.io``.
    A raw ``open(path, "wb")`` (or ``os.replace`` of a hand-built temp
    file) in a guard/fleet/serve-scoped module bypasses two contracts
    at once: the write-temp -> fsync -> ``os.replace`` atomicity that
    keeps a crash from tearing the file, and the graftchaos
    ``io.write`` fault point — so the chaos campaign can never reach
    the code's failure path, which means its recovery behavior is
    unproven by construction.  ``guard/io.py`` itself (the one module
    that owns the raw protocol) is exempt; append-mode streams and
    reads are not flagged."""
    fix = (
        "route the write through guard.io.atomic_write_bytes / "
        "atomic_write_text (pass chaos_site= to join the fault plane); "
        "waive a deliberate raw write (e.g. a fault injector) with "
        "`# graftlint: disable=GL018`"
    )
    for f in ctx.files:
        if f.path.parts[-2:] == ("guard", "io.py"):
            continue
        if not (_is_guard_scoped(f) or _is_serve_scoped(f)):
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if chain == "open":
                mode = _open_write_mode(node)
                if mode is not None:
                    yield _finding(
                        "GL018",
                        f,
                        node,
                        f"`open(..., {mode!r})` in a guard-path module "
                        "writes raw — it bypasses guard.io's atomic "
                        "protocol and the chaos fault plane",
                        fix,
                    )
            elif chain in ("os.replace", "os.rename"):
                yield _finding(
                    "GL018",
                    f,
                    node,
                    f"`{chain}()` in a guard-path module finishes a "
                    "hand-rolled write protocol — use guard.io, which "
                    "already fsyncs, replaces atomically, and carries "
                    "the chaos fault point",
                    fix,
                )


# --------------------------------------------------------------- GL023
def _is_stepper_scoped(f) -> bool:
    """A file is stepper-scoped when it IS the stepper module or imports
    it — code that runs on (or rides along) the fused megastep's
    dispatch/replay loop, where per-cell host work serializes the
    pipeline."""
    if f.path.stem == "stepper":
        return True
    for node in ast.walk(f.tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and "stepper" in node.module.split("."):
                return True
            if any(a.name == "stepper" for a in node.names):
                return True
        elif isinstance(node, ast.Import):
            if any("stepper" in a.name.split(".") for a in node.names):
                return True
    return False


#: attribute names that resolve to a host genome string list; loading
#: one in a hot function decodes the device token store (or walks the
#: legacy list) cell by cell
_GENOME_LIST_ATTRS = {"cell_genomes", "genomes", "_genomes", "_genomes_list"}
#: the host string mutation engine's entry points — per-cell Python
#: string work; hot paths use the token kernels instead
_HOST_MUTATION_ENGINES = {"point_mutations", "recombinations"}


def check_gl023(ctx: Context):
    """Host genome work must not ride the hot path.  Genomes live on
    device as packed token arrays; the string side (``.cell_genomes``,
    the host mutation engine) is an import/export boundary.  In a hot
    function of a stepper-, fleet-, or serve-scoped module, a genome
    list load or a host-engine mutation call is per-cell host string
    work on the step loop's critical path — the exact cost the token
    backend deleted.  String-backend fallback sites waive with
    ``# graftlint: disable=GL023``."""
    fix = (
        "keep genomes on device: use the GenomeStore token arrays and "
        "the jitted mutation kernels (magicsoup_tpu.genomes); decode "
        "through .cell_genomes only at the import/export boundary, or "
        "waive a deliberate string-backend fallback with "
        "`# graftlint: disable=GL023`"
    )
    for key in sorted(ctx.hot):
        rec = ctx.graph.functions[key]
        f = rec.file
        if not (
            _is_stepper_scoped(f)
            or _is_fleet_scoped(f)
            or _is_serve_scoped(f)
        ):
            continue
        for node in ast.walk(rec.node):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.ctx, ast.Load)
                and node.attr in _GENOME_LIST_ATTRS
            ):
                yield _finding(
                    "GL023",
                    f,
                    node,
                    f"`.{node.attr}` in hot function `{rec.qualname}` "
                    "loads the host genome string list — decoding the "
                    "device token store per cell on the hot path",
                    fix,
                )
            elif isinstance(node, ast.Call):
                leaf = _attr_chain(node.func).rsplit(".", 1)[-1]
                if leaf in _HOST_MUTATION_ENGINES:
                    yield _finding(
                        "GL023",
                        f,
                        node,
                        f"`{leaf}()` in hot function `{rec.qualname}` "
                        "runs the host string mutation engine per cell "
                        "— use the jitted token kernels "
                        "(point_mutations_tokens / "
                        "recombinations_tokens)",
                        fix,
                    )


# --------------------------------------------------------------- GL024
#: device dispatch entry points: the per-rung and fused fleet programs
#: plus the scheduler's `_dispatch_*` wrappers (the `_dispatch_` prefix
#: is the scheduler's dispatch-path naming convention; the commit/retry
#: helpers deliberately do not share it)
_DISPATCH_LEAVES = {"fleet_step", "fused_fleet_step"}
#: loop-name fragments that identify iteration over rung/sibling groups
_GROUP_LOOP_NAMES = ("group", "sibling")


def _loop_target_names(target) -> list[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        return [n for e in target.elts for n in _loop_target_names(e)]
    return []


def check_gl024(ctx: Context):
    """Device dispatches must not loop over rung groups.  A ``for``
    loop whose target or iterable names groups/siblings and whose body
    launches a device program (``fleet_step`` / ``fused_fleet_step`` /
    a scheduler ``_dispatch_*`` method) pays one program launch AND one
    physical fetch per iteration — the R-dispatches-per-megastep cost
    on the serve critical path that the cross-rung fusion planner
    exists to delete.  Loops over the PLANNER's output (an iterable
    whose expression mentions ``plan``, e.g. ``self._plan_fusion(...)``)
    are the sanctioned route and exempt; a deliberate per-group path
    waives with ``# graftlint: disable=GL024``."""
    fix = (
        "route the dispatch through the fusion planner "
        "(FleetScheduler._plan_fusion partitions the live groups; one "
        "fused set dispatches as ONE batched program), or waive a "
        "deliberate per-group dispatch with `# graftlint: disable=GL024`"
    )
    for f in ctx.files:
        if not (_is_fleet_scoped(f) or _is_serve_scoped(f)):
            continue
        for loop in ast.walk(f.tree):
            if not isinstance(loop, ast.For):
                continue
            names = " ".join(_loop_target_names(loop.target)).lower()
            iter_chain = _attr_chain(
                loop.iter.func
                if isinstance(loop.iter, ast.Call)
                else loop.iter
            ).lower()
            if not any(
                frag in names or frag in iter_chain
                for frag in _GROUP_LOOP_NAMES
            ):
                continue
            if "plan" in iter_chain:
                continue  # planner-routed: the sanctioned dispatch loop
            for node in loop.body:
                for call in ast.walk(node):
                    if not isinstance(call, ast.Call):
                        continue
                    leaf = _attr_chain(call.func).rsplit(".", 1)[-1]
                    if leaf in _DISPATCH_LEAVES or (
                        leaf.startswith("_dispatch_")
                        and leaf != "_dispatch_with_retry"
                    ):
                        yield _finding(
                            "GL024",
                            f,
                            call,
                            f"`{leaf}` dispatches a device program "
                            "inside a per-group loop — R rung groups "
                            "pay R launches + R fetches per megastep "
                            "instead of one fused program",
                            fix,
                        )


# --------------------------------------------------------------- GL025
#: attribute chains that read a wall/monotonic clock; covers the repo's
#: idioms (`time.perf_counter()`, `import time as _time`, and the bare
#: from-import forms). `time.sleep` et al. are not readings.
_BARE_CLOCK_CHAINS = {
    "time.time",
    "time.monotonic",
    "time.monotonic_ns",
    "time.perf_counter",
    "time.perf_counter_ns",
    "_time.time",
    "_time.monotonic",
    "_time.perf_counter",
    "perf_counter",
    "perf_counter_ns",
    "monotonic",
    "monotonic_ns",
}
#: call leaves that carry a measurement into the telemetry plane: the
#: recorder span API (`note`, `span`), the graftpulse registry
#: (`observe`, plus the device census `note_device_time` and the
#: commit-to-fetch-ready bracket constructor `_device_ready`), and the
#: dispatch-row drain (`take_dispatch`).  A hot function containing one
#: of these is routing its clock readings, not hoarding them.
_CLOCK_ROUTING_LEAVES = {
    "note",
    "span",
    "observe",
    "note_device_time",
    "_device_ready",
    "take_dispatch",
}


def _routes_clock_readings(fn_node: ast.AST) -> bool:
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Call):
            leaf = _attr_chain(node.func).rsplit(".", 1)[-1]
            if leaf in _CLOCK_ROUTING_LEAVES:
                return True
    return False


def check_gl025(ctx: Context):
    """Clock readings on the hot path must feed the telemetry plane.
    A bare ``time.time()`` / ``perf_counter()`` / ``monotonic()`` in a
    stepper-, fleet-, or serve-scoped hot function is a measurement the
    operator can never see: it costs a syscall on the step loop's
    critical path and then dies in a local, bypassing the recorder
    spans and the graftpulse registry that ``/metrics`` exposes.  A
    function that also calls the span/registry route
    (:data:`_CLOCK_ROUTING_LEAVES`, nested closures included) is
    exempt — its readings land in telemetry.  Deliberate local timings
    (e.g. a deadline check) waive with
    ``# graftlint: disable=GL025``."""
    fix = (
        "route the measurement into the telemetry plane: bracket the "
        "reading with TelemetryRecorder.note(phase, dt) or feed a "
        "registry histogram/census (MetricsRegistry.observe, "
        "telemetry.metrics.note_device_time), or waive a deliberate "
        "local timing with `# graftlint: disable=GL025`"
    )
    for key in sorted(ctx.hot):
        rec = ctx.graph.functions[key]
        f = rec.file
        if not (
            _is_stepper_scoped(f)
            or _is_fleet_scoped(f)
            or _is_serve_scoped(f)
        ):
            continue
        if _routes_clock_readings(rec.node):
            continue
        for node in ast.walk(rec.node):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if chain in _BARE_CLOCK_CHAINS:
                yield _finding(
                    "GL025",
                    f,
                    node,
                    f"`{chain}()` in hot function `{rec.qualname}` takes "
                    "a clock reading that never reaches the telemetry "
                    "plane — invisible to recorder spans and /metrics",
                    fix,
                )


# --------------------------------------------------------------- GL026
#: the integrator entry points a hot function must not name directly —
#: the registry (`ops.backends.integrate`) is the one selection path
_INTEGRATOR_LEAVES = {
    "integrate_signals",
    "integrate_signals_pallas",
    "_integrate_signals_jit",
}


def check_gl026(ctx: Context):
    """The integrator backend registry is the ONE selection path on the
    hot path.  A stepper-, fleet-, or serve-scoped hot function that
    calls ``integrate_signals`` / ``integrate_signals_pallas`` /
    ``_integrate_signals_jit`` directly has hard-wired a kernel choice:
    it bypasses the capability flags (det-able, mesh-able) the registry
    enforces, the ``World(integrator=...)``/env resolution the operator
    controls, and the per-backend dispatch census ``/metrics`` exposes.
    Route through :func:`magicsoup_tpu.ops.backends.integrate` with the
    resolved backend name (a jit-static string); a deliberate direct
    call waives with ``# graftlint: disable=GL026``."""
    fix = (
        "route the call through the backend registry: "
        "ops.backends.integrate(integrator, X, params) with the "
        "resolved backend name threaded as a static argument "
        "(World.integrator / ops.backends.resolve), or waive a "
        "deliberate direct kernel call with "
        "`# graftlint: disable=GL026`"
    )
    for key in sorted(ctx.hot):
        rec = ctx.graph.functions[key]
        f = rec.file
        if not (
            _is_stepper_scoped(f)
            or _is_fleet_scoped(f)
            or _is_serve_scoped(f)
        ):
            continue
        for node in ast.walk(rec.node):
            if not isinstance(node, ast.Call):
                continue
            leaf = _attr_chain(node.func).rsplit(".", 1)[-1]
            if leaf in _INTEGRATOR_LEAVES:
                yield _finding(
                    "GL026",
                    f,
                    node,
                    f"`{leaf}()` in hot function `{rec.qualname}` "
                    "names an integrator kernel directly — bypassing "
                    "the backend registry's capability flags, "
                    "selection plane, and dispatch census",
                    fix,
                )


CHECKERS = {
    "GL001": check_gl001,
    "GL002": check_gl002,
    "GL003": check_gl003,
    "GL004": check_gl004,
    "GL005": check_gl005,
    "GL006": check_gl006,
    "GL007": check_gl007,
    "GL008": check_gl008,
    "GL009": check_gl009,
    "GL010": check_gl010,
    "GL011": check_gl011,
    "GL012": check_gl012,
    "GL013": check_gl013,
    "GL014": check_gl014,
    "GL015": concurrency.check_gl015,
    "GL016": concurrency.check_gl016,
    "GL017": concurrency.check_gl017,
    "GL018": check_gl018,
    "GL019": dataflow.check_gl019,
    "GL020": dataflow.check_gl020,
    "GL021": dataflow.check_gl021,
    "GL022": dataflow.check_gl022,
    "GL023": check_gl023,
    "GL024": check_gl024,
    "GL025": check_gl025,
    "GL026": check_gl026,
}


def checkers(only: list[str] | None = None):
    if not only:
        return dict(CHECKERS)
    wanted = {c.strip().upper() for c in only}
    unknown = wanted - CHECKERS.keys()
    if unknown:
        raise SystemExit(f"graftlint: unknown rule(s): {', '.join(sorted(unknown))}")
    return {c: fn for c, fn in CHECKERS.items() if c in wanted}
