"""
Codon machinery and genome -> proteome translation.

Parity reference: `python/magicsoup/genetics.py:18-178`.  Same defaults
(start codons TTG/GTG/ATG, stop codons TGA/TAG/TAA, 2 domain-type codons +
3 one-codon scalar tokens + 1 two-codon vector token => 21-nt domains) and
the same token-map construction: all 2-codon sequences not containing a
start codon are shuffled and fractions assigned to the three domain types.

TPU-first deltas:
- explicit ``seed`` — the reference draws its genotype->phenotype mapping
  from the global `random` module and is unreproducible across instances
  (SURVEY.md §2 quirks); here the shuffle is driven by a private
  ``random.Random(seed)``.
- translation is engine-backed (C++/OpenMP or pure-Python fallback,
  :mod:`magicsoup_tpu.native`) and primarily returns *flat numpy index
  buffers* that feed the jitted cell-parameter assembly directly; the
  reference's nested-list format is still available through
  :meth:`Genetics.translate_genomes` for interpretation APIs.
"""
import random
import warnings
from collections import OrderedDict
from dataclasses import dataclass, field

import numpy as np

from magicsoup_tpu.constants import CODON_SIZE, ProteinSpecType
from magicsoup_tpu.native import (
    TranslationTables,
    pack_dense,
    translate_genomes_flat,
)
from magicsoup_tpu.util import codons


def _get_n(p: float, s: int, name: str) -> int:
    n = int(p * s)
    if n == 0 and p > 0.0:
        warnings.warn(
            f"There will be no {name}."
            f" Increase dom_type_size to accomodate low probabilities of having {name}."
        )
    return n


class Genetics:
    """
    Class holding logic about transcribing and translating nucleotide
    sequences.

    Arguments:
        start_codons: Codons which start a coding sequence.
        stop_codons: Codons which stop a coding sequence.
        p_catal_dom: Chance of encountering a catalytic domain in a random
            nucleotide sequence.
        p_transp_dom: Chance of encountering a transporter domain in a random
            nucleotide sequence.
        p_reg_dom: Chance of encountering a regulatory domain in a random
            nucleotide sequence.
        n_dom_type_codons: Number of codons encoding the domain type.
        seed: Seed for the token-map shuffle (genotype->phenotype mapping).

    A CDS starts at every start codon and ends with the first in-frame stop
    codon; un-stopped CDSs are discarded; both strands are considered.  Each
    CDS is one protein; every matched domain-type sequence inside it adds a
    domain (see `docs/mechanics.md:22-28` of the reference).
    """

    def __init__(
        self,
        start_codons: tuple[str, ...] = ("TTG", "GTG", "ATG"),
        stop_codons: tuple[str, ...] = ("TGA", "TAG", "TAA"),
        p_catal_dom: float = 0.01,
        p_transp_dom: float = 0.01,
        p_reg_dom: float = 0.01,
        n_dom_type_codons: int = 2,
        seed: int | None = None,
    ):
        if any(len(d) != CODON_SIZE for d in start_codons):
            raise ValueError(f"Not all start codons are of length {CODON_SIZE}")
        if any(len(d) != CODON_SIZE for d in stop_codons):
            raise ValueError(f"Not all stop codons are of length {CODON_SIZE}")
        overlap = set(start_codons) & set(stop_codons)
        if len(overlap) > 0:
            raise ValueError(
                "Overlapping start and stop codons:"
                f" {','.join(str(d) for d in overlap)}"
            )
        if p_catal_dom + p_transp_dom + p_reg_dom > 1.0:
            raise ValueError(
                "p_catal_dom, p_transp_dom, p_reg_dom together must not be greater 1.0"
            )

        self.seed = seed
        self.start_codons = list(start_codons)
        self.stop_codons = list(stop_codons)

        # domain structure: type codons + 3 x 1-codon + 1 x 2-codon tokens;
        # a domain can end on the CDS-terminating stop codon, so the minimum
        # CDS size equals dom_size
        self.dom_size = (n_dom_type_codons + 5) * CODON_SIZE
        self.dom_type_size = n_dom_type_codons * CODON_SIZE

        # type sequences containing a start codon are excluded (they would
        # open nested CDSs wherever a domain occurs)
        rng = random.Random(seed)
        sets = codons(n=n_dom_type_codons, excl_codons=self.start_codons)
        rng.shuffle(sets)
        n = len(sets)

        n_catal_doms = _get_n(p=p_catal_dom, s=n, name="catalytic domains")
        n_transp_doms = _get_n(p=p_transp_dom, s=n, name="transporter domains")
        n_reg_doms = _get_n(p=p_reg_dom, s=n, name="allosteric domains")

        # 1=catalytic, 2=transporter, 3=regulatory
        self.domain_types: dict[int, list[str]] = {}
        self.domain_types[1] = sets[:n_catal_doms]
        del sets[:n_catal_doms]
        self.domain_types[2] = sets[:n_transp_doms]
        del sets[:n_transp_doms]
        self.domain_types[3] = sets[:n_reg_doms]
        del sets[:n_reg_doms]

        self.domain_map = {d: k for k, v in self.domain_types.items() for d in v}

        # premature stop codons cannot appear inside a CDS
        self.one_codon_map = {d: i + 1 for i, d in enumerate(self._get_single_codons())}

        # the second codon of a 2-codon token may be the CDS-final stop codon
        self.two_codon_map = {d: i + 1 for i, d in enumerate(self._get_double_codons())}

        # inverse maps for genome generation (factories)
        self.idx_2_one_codon = {v: k for k, v in self.one_codon_map.items()}
        self.idx_2_two_codon = {v: k for k, v in self.two_codon_map.items()}

        # integer lookup tables for the genome engine
        self.tables = TranslationTables(
            start_codons=self.start_codons,
            stop_codons=self.stop_codons,
            domain_map=self.domain_map,
            one_codon_map=self.one_codon_map,
            two_codon_map=self.two_codon_map,
            dom_size=self.dom_size,
            dom_type_size=self.dom_type_size,
        )

    def translate_genomes_flat(
        self, genomes: list[str]
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """
        Translate genomes into flat index buffers:
        ``(prot_counts (g,), prots (P,4), doms (D,7))`` with protein rows
        ``[cds_start, cds_end, is_fwd, n_doms]`` and domain rows
        ``[dom_type, i0, i1, i2, i3, start, end]``.  This is the hot path
        feeding :meth:`magicsoup_tpu.kinetics.Kinetics.set_cell_params`.
        """
        return translate_genomes_flat(genomes, self.tables)

    def translate_tokens_flat(
        self, tokens, lengths
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """
        Token-input translation path: host token rows (``(b, G)`` int8 in
        the ``TCGA`` -> ``0..3`` code of :mod:`magicsoup_tpu.genomes`)
        plus per-row lengths, translated through the same flat-buffer
        engine as :meth:`translate_genomes_flat`.  The decode is the
        string import/export boundary — device-resident paths only reach
        it for phenotype-cache MISSES, so steady state translates from
        tokens without per-cell string bookkeeping.
        """
        from magicsoup_tpu.genomes import decode_tokens

        return translate_genomes_flat(
            decode_tokens(tokens, lengths), self.tables
        )

    def translate_genomes(self, genomes: list[str]) -> list[list[ProteinSpecType]]:
        """
        Translate all genomes into proteomes.

        Returns a list (per genome) of lists (proteins) where each protein is
        a tuple ``(domains, cds_start, cds_end, is_fwd)`` and each domain is
        ``((dom_type, i0, i1, i2, i3), start, end)`` — the reference's nested
        format (`genetics.py:124-168`), built from the engine's flat buffers.
        """
        if len(genomes) < 1:
            return []
        prot_counts, prots, doms = self.translate_genomes_flat(genomes)
        # batched host conversion: ONE .tolist() per buffer plus numpy
        # cumsum offsets, instead of a per-protein/per-domain .tolist()
        # in the loop (the per-item form is what graftlint GL007 flags)
        prot_rows = prots.tolist()
        dom_rows = doms.tolist()
        prot_offs = np.concatenate([[0], np.cumsum(prot_counts)]).tolist()
        dom_offs = np.concatenate(
            [[0], np.cumsum(prots[:, 3])] if len(prots) else [[0]]
        ).tolist()
        out: list[list[ProteinSpecType]] = []
        for gi in range(len(genomes)):
            proteome: list[ProteinSpecType] = []
            for pi in range(prot_offs[gi], prot_offs[gi + 1]):
                cds_start, cds_end, is_fwd, n_doms = prot_rows[pi]
                d0 = dom_offs[pi]
                dom_specs = [
                    ((dt, i0, i1, i2, i3), start, end)
                    for dt, i0, i1, i2, i3, start, end in dom_rows[
                        d0 : d0 + n_doms
                    ]
                ]
                proteome.append((dom_specs, cds_start, cds_end, bool(is_fwd)))
            out.append(proteome)
        return out

    def _get_single_codons(self) -> list[str]:
        seqs = codons(n=1)
        return [d for d in seqs if d not in self.stop_codons]

    def _get_double_codons(self) -> list[str]:
        seqs = codons(n=2)
        return [d for d in seqs if d[:CODON_SIZE] not in self.stop_codons]


@dataclass
class PhenotypeEntry:
    """One cached genome phenotype: the flat translation buffers plus the
    packed dense token row per assembly rung it has been packed at."""

    n_prots: int
    max_doms: int  # max domains over this genome's proteins (0 if none)
    prots: np.ndarray  # (n_prots, 4) i32 [cds_start, cds_end, is_fwd, n_doms]
    doms: np.ndarray  # (sum n_doms, 7) i32
    # (p_cap, d_cap) -> (p_cap, d_cap, 5) i16 dense token row
    dense: dict = field(default_factory=dict)


class PhenotypeCache:
    """
    Content-addressed genome -> phenotype cache, LRU-bounded.

    Entries are keyed by the genome STRING and hold the flat translation
    buffers plus packed dense token rows per assembly rung, so a batch
    with repeated genomes (spawn bursts from shared seeds, division
    daughters, mutation no-ops) translates and packs each unique genome
    once, and a genome seen in an earlier step skips both entirely.

    Byte-identity contract: cached rows come from the same
    ``pack_dense`` call a cold path would make and are never mutated, so
    cached and uncached parameter assembly are BIT-identical (pinned by
    tests/fast/test_kinetics.py).

    ``maxsize <= 0`` disables cross-call caching: lookups still dedupe
    within the batch, but nothing is retained.  Counters (``hits`` /
    ``misses`` / ``evictions``) count per genome occurrence and also
    accumulate into the process-wide
    :func:`magicsoup_tpu.analysis.runtime.phenotype_cache_stats`.
    """

    def __init__(self, genetics: Genetics, maxsize: int = 16384):
        self.genetics = genetics
        self.maxsize = int(maxsize)
        self._entries: OrderedDict[str, PhenotypeEntry] = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def clear(self) -> None:
        """Drop all entries (counters are kept)."""
        self._entries.clear()

    def __getstate__(self) -> dict:
        """Pickle WITHOUT the entries (cached rows would bloat saves) —
        but record how many were dropped, so the restoring process's
        :func:`~magicsoup_tpu.analysis.runtime.phenotype_cache_stats`
        shows a ``pickle_drops`` spike explaining the first-step miss
        storm instead of silently presenting a cold cache."""
        state = self.__dict__.copy()
        state["_entries"] = OrderedDict()
        state["_pickle_dropped"] = len(self._entries)
        return state

    def __setstate__(self, state: dict) -> None:
        dropped = state.pop("_pickle_dropped", 0)
        self.__dict__.update(state)
        if dropped:
            _note_phenotype_cache(pickle_drops=int(dropped))

    def _translate_misses(self, genomes: list[str]) -> list[PhenotypeEntry]:
        """Translate a batch of cache misses in ONE engine call and
        build their entries (shared by the string- and token-key paths)."""
        pc, prots, doms = self.genetics.translate_genomes_flat(genomes)
        dom_counts = (
            prots[:, 3] if len(prots) else np.zeros(0, dtype=np.int32)
        )
        p_offs = np.concatenate([[0], np.cumsum(pc)])
        d_offs = np.concatenate([[0], np.cumsum(dom_counts)])
        out: list[PhenotypeEntry] = []
        for i in range(len(genomes)):
            p0, p1 = int(p_offs[i]), int(p_offs[i + 1])
            d0, d1 = int(d_offs[p0]), int(d_offs[p1])
            out.append(
                PhenotypeEntry(
                    n_prots=p1 - p0,
                    max_doms=(
                        int(dom_counts[p0:p1].max()) if p1 > p0 else 0
                    ),
                    prots=np.ascontiguousarray(prots[p0:p1]),
                    doms=np.ascontiguousarray(doms[d0:d1]),
                )
            )
        return out

    # graftlint: hot
    def lookup(self, genomes: list[str]) -> list[PhenotypeEntry]:
        """Entries for ``genomes`` (one per input, duplicates aliased);
        unique misses are translated in ONE engine batch."""
        unique: list[str] = []
        seen: set[str] = set()
        for g in genomes:
            if g not in seen:
                seen.add(g)
                unique.append(g)
        entries: dict[str, PhenotypeEntry] = {}
        misses: list[str] = []
        for g in unique:
            e = self._entries.get(g)
            if e is None:
                misses.append(g)
            else:
                self._entries.move_to_end(g)
                entries[g] = e
        if misses:
            for g, e in zip(misses, self._translate_misses(misses)):
                entries[g] = e
                self._store(g, e)
        n_hits = len(genomes) - len(misses)
        self.hits += n_hits
        self.misses += len(misses)
        _note_phenotype_cache(hits=n_hits, misses=len(misses))
        return [entries[g] for g in genomes]

    # graftlint: hot
    def lookup_tokens(
        self, tokens, lengths, idxs=None, hashes=None
    ) -> list[PhenotypeEntry]:
        """Token-path lookup: entries keyed by token-row CONTENT HASHES
        (:func:`magicsoup_tpu.genomes.token_hashes`) instead of genome
        strings.  Only cache MISSES decode their rows (the one string
        boundary on this path); hits never materialize a string, so a
        device-resident world's steady state translates straight from
        token arrays.  ``idxs`` selects rows (all by default); pass
        precomputed ``hashes`` to skip rehashing."""
        from magicsoup_tpu.genomes import decode_tokens, token_hashes

        tokens = np.asarray(tokens)
        lengths = np.asarray(lengths)
        idxs = list(range(len(lengths))) if idxs is None else list(idxs)
        if hashes is None:
            hashes = token_hashes(tokens, lengths, idxs)
        entries: dict[bytes, PhenotypeEntry] = {}
        miss_keys: list[bytes] = []
        miss_rows: list[int] = []
        seen: set[bytes] = set()
        for i, h in zip(idxs, hashes):
            if h in seen:
                continue
            seen.add(h)
            e = self._entries.get(h)
            if e is None:
                miss_keys.append(h)
                miss_rows.append(i)
            else:
                self._entries.move_to_end(h)
                entries[h] = e
        if miss_keys:
            from magicsoup_tpu.genomes import _note_decode

            genomes = decode_tokens(
                tokens[miss_rows], lengths[miss_rows]
            )
            _note_decode(len(miss_rows))
            for h, e in zip(miss_keys, self._translate_misses(genomes)):
                entries[h] = e
                self._store(h, e)
        n_hits = len(hashes) - len(miss_keys)
        self.hits += n_hits
        self.misses += len(miss_keys)
        _note_phenotype_cache(hits=n_hits, misses=len(miss_keys))
        return [entries[h] for h in hashes]

    # graftlint: hot
    def dense_rows(
        self, entries: list[PhenotypeEntry], p_cap: int, d_cap: int
    ) -> np.ndarray:
        """Stack the entries' dense token rows at rung ``(p_cap, d_cap)``
        into one (b, p_cap, d_cap, 5) i16 batch; rows not yet packed at
        this rung are packed in ONE engine batch and memoized on their
        entries."""
        key = (int(p_cap), int(d_cap))
        missing: list[PhenotypeEntry] = []
        seen: set[int] = set()
        for e in entries:
            if key not in e.dense and id(e) not in seen:
                seen.add(id(e))
                missing.append(e)
        if missing:
            pc = np.fromiter(
                (e.n_prots for e in missing), dtype=np.int32,
                count=len(missing),
            )
            prots = np.concatenate([e.prots for e in missing])
            doms = np.concatenate([e.doms for e in missing])
            dense = pack_dense(pc, prots, doms, key[0], key[1])
            for i, e in enumerate(missing):
                e.dense[key] = dense[i]
        if not entries:
            return np.zeros((0, key[0], key[1], 5), dtype=np.int16)
        return np.stack([e.dense[key] for e in entries])

    def _store(self, genome: str, entry: PhenotypeEntry) -> None:
        if self.maxsize <= 0:
            return
        self._entries[genome] = entry
        evicted = 0
        while len(self._entries) > self.maxsize:
            self._entries.popitem(last=False)
            evicted += 1
        if evicted:
            self.evictions += evicted
            _note_phenotype_cache(evictions=evicted)


def _note_phenotype_cache(**kwargs) -> None:
    """Forward counters to the runtime metrics layer (imported lazily —
    :mod:`magicsoup_tpu.analysis.runtime` pulls in jax, which this
    host-only module otherwise never needs)."""
    from magicsoup_tpu.analysis.runtime import note_phenotype_cache

    note_phenotype_cache(**kwargs)
