"""
Codon machinery and genome -> proteome translation.

Parity reference: `python/magicsoup/genetics.py:18-178`.  Same defaults
(start codons TTG/GTG/ATG, stop codons TGA/TAG/TAA, 2 domain-type codons +
3 one-codon scalar tokens + 1 two-codon vector token => 21-nt domains) and
the same token-map construction: all 2-codon sequences not containing a
start codon are shuffled and fractions assigned to the three domain types.

TPU-first deltas:
- explicit ``seed`` — the reference draws its genotype->phenotype mapping
  from the global `random` module and is unreproducible across instances
  (SURVEY.md §2 quirks); here the shuffle is driven by a private
  ``random.Random(seed)``.
- translation is engine-backed (C++/OpenMP or pure-Python fallback,
  :mod:`magicsoup_tpu.native`) and primarily returns *flat numpy index
  buffers* that feed the jitted cell-parameter assembly directly; the
  reference's nested-list format is still available through
  :meth:`Genetics.translate_genomes` for interpretation APIs.
"""
import random
import warnings

import numpy as np

from magicsoup_tpu.constants import CODON_SIZE, ProteinSpecType
from magicsoup_tpu.native import TranslationTables, translate_genomes_flat
from magicsoup_tpu.util import codons


def _get_n(p: float, s: int, name: str) -> int:
    n = int(p * s)
    if n == 0 and p > 0.0:
        warnings.warn(
            f"There will be no {name}."
            f" Increase dom_type_size to accomodate low probabilities of having {name}."
        )
    return n


class Genetics:
    """
    Class holding logic about transcribing and translating nucleotide
    sequences.

    Arguments:
        start_codons: Codons which start a coding sequence.
        stop_codons: Codons which stop a coding sequence.
        p_catal_dom: Chance of encountering a catalytic domain in a random
            nucleotide sequence.
        p_transp_dom: Chance of encountering a transporter domain in a random
            nucleotide sequence.
        p_reg_dom: Chance of encountering a regulatory domain in a random
            nucleotide sequence.
        n_dom_type_codons: Number of codons encoding the domain type.
        seed: Seed for the token-map shuffle (genotype->phenotype mapping).

    A CDS starts at every start codon and ends with the first in-frame stop
    codon; un-stopped CDSs are discarded; both strands are considered.  Each
    CDS is one protein; every matched domain-type sequence inside it adds a
    domain (see `docs/mechanics.md:22-28` of the reference).
    """

    def __init__(
        self,
        start_codons: tuple[str, ...] = ("TTG", "GTG", "ATG"),
        stop_codons: tuple[str, ...] = ("TGA", "TAG", "TAA"),
        p_catal_dom: float = 0.01,
        p_transp_dom: float = 0.01,
        p_reg_dom: float = 0.01,
        n_dom_type_codons: int = 2,
        seed: int | None = None,
    ):
        if any(len(d) != CODON_SIZE for d in start_codons):
            raise ValueError(f"Not all start codons are of length {CODON_SIZE}")
        if any(len(d) != CODON_SIZE for d in stop_codons):
            raise ValueError(f"Not all stop codons are of length {CODON_SIZE}")
        overlap = set(start_codons) & set(stop_codons)
        if len(overlap) > 0:
            raise ValueError(
                "Overlapping start and stop codons:"
                f" {','.join(str(d) for d in overlap)}"
            )
        if p_catal_dom + p_transp_dom + p_reg_dom > 1.0:
            raise ValueError(
                "p_catal_dom, p_transp_dom, p_reg_dom together must not be greater 1.0"
            )

        self.seed = seed
        self.start_codons = list(start_codons)
        self.stop_codons = list(stop_codons)

        # domain structure: type codons + 3 x 1-codon + 1 x 2-codon tokens;
        # a domain can end on the CDS-terminating stop codon, so the minimum
        # CDS size equals dom_size
        self.dom_size = (n_dom_type_codons + 5) * CODON_SIZE
        self.dom_type_size = n_dom_type_codons * CODON_SIZE

        # type sequences containing a start codon are excluded (they would
        # open nested CDSs wherever a domain occurs)
        rng = random.Random(seed)
        sets = codons(n=n_dom_type_codons, excl_codons=self.start_codons)
        rng.shuffle(sets)
        n = len(sets)

        n_catal_doms = _get_n(p=p_catal_dom, s=n, name="catalytic domains")
        n_transp_doms = _get_n(p=p_transp_dom, s=n, name="transporter domains")
        n_reg_doms = _get_n(p=p_reg_dom, s=n, name="allosteric domains")

        # 1=catalytic, 2=transporter, 3=regulatory
        self.domain_types: dict[int, list[str]] = {}
        self.domain_types[1] = sets[:n_catal_doms]
        del sets[:n_catal_doms]
        self.domain_types[2] = sets[:n_transp_doms]
        del sets[:n_transp_doms]
        self.domain_types[3] = sets[:n_reg_doms]
        del sets[:n_reg_doms]

        self.domain_map = {d: k for k, v in self.domain_types.items() for d in v}

        # premature stop codons cannot appear inside a CDS
        self.one_codon_map = {d: i + 1 for i, d in enumerate(self._get_single_codons())}

        # the second codon of a 2-codon token may be the CDS-final stop codon
        self.two_codon_map = {d: i + 1 for i, d in enumerate(self._get_double_codons())}

        # inverse maps for genome generation (factories)
        self.idx_2_one_codon = {v: k for k, v in self.one_codon_map.items()}
        self.idx_2_two_codon = {v: k for k, v in self.two_codon_map.items()}

        # integer lookup tables for the genome engine
        self.tables = TranslationTables(
            start_codons=self.start_codons,
            stop_codons=self.stop_codons,
            domain_map=self.domain_map,
            one_codon_map=self.one_codon_map,
            two_codon_map=self.two_codon_map,
            dom_size=self.dom_size,
            dom_type_size=self.dom_type_size,
        )

    def translate_genomes_flat(
        self, genomes: list[str]
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """
        Translate genomes into flat index buffers:
        ``(prot_counts (g,), prots (P,4), doms (D,7))`` with protein rows
        ``[cds_start, cds_end, is_fwd, n_doms]`` and domain rows
        ``[dom_type, i0, i1, i2, i3, start, end]``.  This is the hot path
        feeding :meth:`magicsoup_tpu.kinetics.Kinetics.set_cell_params`.
        """
        return translate_genomes_flat(genomes, self.tables)

    def translate_genomes(self, genomes: list[str]) -> list[list[ProteinSpecType]]:
        """
        Translate all genomes into proteomes.

        Returns a list (per genome) of lists (proteins) where each protein is
        a tuple ``(domains, cds_start, cds_end, is_fwd)`` and each domain is
        ``((dom_type, i0, i1, i2, i3), start, end)`` — the reference's nested
        format (`genetics.py:124-168`), built from the engine's flat buffers.
        """
        if len(genomes) < 1:
            return []
        prot_counts, prots, doms = self.translate_genomes_flat(genomes)
        out: list[list[ProteinSpecType]] = []
        pi = 0
        di = 0
        for count in prot_counts.tolist():
            proteome: list[ProteinSpecType] = []
            for _ in range(count):
                cds_start, cds_end, is_fwd, n_doms = prots[pi].tolist()
                dom_specs = [
                    (
                        (int(dt), int(i0), int(i1), int(i2), int(i3)),
                        int(start),
                        int(end),
                    )
                    for dt, i0, i1, i2, i3, start, end in doms[di : di + n_doms].tolist()
                ]
                proteome.append((dom_specs, cds_start, cds_end, bool(is_fwd)))
                pi += 1
                di += n_doms
            out.append(proteome)
        return out

    def _get_single_codons(self) -> list[str]:
        seqs = codons(n=1)
        return [d for d in seqs if d not in self.stop_codons]

    def _get_double_codons(self) -> list[str]:
        seqs = codons(n=2)
        return [d for d in seqs if d[:CODON_SIZE] not in self.stop_codons]
