"""
Protein kinetics: random genotype->phenotype token maps, the per-cell
parameter tensors, and the signal integrator.

Parity reference: `python/magicsoup/kinetics.py:292-992`.  Same state
semantics — 9 tensors over (c cells, p proteins, s = 2 * n_molecules
signals): ``Ke, Kmf, Kmb, Vmax`` (c,p) f32, ``Kmr`` (c,p,s) f32,
``N, Nf, Nb, A`` (c,p,s) i16 — and the same token->parameter sampling
distributions (Km/Vmax lognormal with rejection, signs 50/50, hill
1..5 at 52/26/13/6/3%, uniformly-mapped reaction/transport/effector
vectors, token 0 = empty).

TPU-first deltas:
- all tensors are jnp arrays at slot capacity; cells are rows, dead slots
  are all-zero and inert (SURVEY.md §7 design delta 1)
- parameter assembly consumes the genome engine's flat buffers through a
  vectorized scatter + one jitted XLA program
  (:mod:`magicsoup_tpu.ops.params`) instead of nested Python loops
- ``integrate_signals`` is the jitted kernel in
  :mod:`magicsoup_tpu.ops.integrate`
- sampling is driven by an explicit seed (the reference draws from the
  global `random` module and cannot be reproduced across instances)
"""
import math
import random

import jax
import jax.numpy as jnp
import numpy as np

from magicsoup_tpu.constants import ProteinSpecType
from magicsoup_tpu.util import fetch_host
from magicsoup_tpu.containers import Chemistry, Molecule, Protein
from magicsoup_tpu.ops.integrate import (
    INT_PARAM_DTYPE,
    CellParams,
    integrate_signals,
)
from magicsoup_tpu.native import pack_dense
from magicsoup_tpu.ops.params import (
    IDX_BLOCK as _IDX_BLOCK,
    RUNG_D_MIN,
    RUNG_P_MIN,
    TokenTables,
    assemble_params,
    assemble_params_retained,
    assemble_params_scan,
    assemble_params_scan_retained,
    copy_params,
    pad_idxs,
    pad_pow2,
    permute_params,
    rung_pow2,
    unset_params,
)


def _grow_params(params: CellParams, *, cp: tuple, cps: tuple) -> CellParams:
    """Pad every parameter tensor up to the target capacities.  Module
    level + static targets so the compiled pad program is shared across
    instances — a fleet admitting a world through the same capacity step
    its peers took must hit a warm cache, not recompile per lane."""

    def g(o: jax.Array, tgt: tuple) -> jax.Array:
        return jnp.pad(o, [(0, t - d) for t, d in zip(tgt, o.shape)])

    return CellParams(
        Ke=g(params.Ke, cp),
        Kmf=g(params.Kmf, cp),
        Kmb=g(params.Kmb, cp),
        Kmr=g(params.Kmr, cps),
        Vmax=g(params.Vmax, cp),
        N=g(params.N, cps),
        Nf=g(params.Nf, cps),
        Nb=g(params.Nb, cps),
        A=g(params.A, cps),
    )


# capacity regrow runs once per capacity step (capacity never shrinks),
# not once per simulation step — graftlint: disable=GL002
_grow_params_jit = jax.jit(_grow_params, static_argnames=("cp", "cps"))


def _gather_ranges(starts: np.ndarray, counts: np.ndarray) -> np.ndarray:
    """Concatenated ``arange(start, start + count)`` runs — the
    vectorized flat-buffer row gather of the rung-grouped assembly (no
    per-cell Python loop)."""
    counts = np.asarray(counts, dtype=np.int64)
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    ends = np.cumsum(counts)
    starts = np.asarray(starts, dtype=np.int64)
    return np.repeat(starts - (ends - counts), counts) + np.arange(total)


def _token_rng(rng: random.Random) -> np.random.Generator:
    """Derive a numpy Generator for vectorized table sampling from the
    instance's seeded ``random.Random``."""
    return np.random.default_rng(rng.randrange(2**63))


class _HillMapFact:
    """Token -> 1,2,3,4,5 with chances 52/26/13/6/3% respectively"""

    _HILL_P = np.array([16.0, 8.0, 4.0, 2.0, 1.0]) / 31.0  # hill = 1..5

    def __init__(self, rng: random.Random, max_token: int, zero_value: int = 0):
        drawn = _token_rng(rng).choice(
            np.arange(1, 6), size=max_token, p=self._HILL_P
        )
        self.numbers = np.concatenate([[zero_value], drawn]).astype(np.int32)

    def __call__(self, t: np.ndarray) -> np.ndarray:
        return self.numbers[t]

    def inverse(self) -> dict[int, list[int]]:
        out = {}
        for v in (1, 3, 5):
            out[v] = np.argwhere(self.numbers == v).flatten().tolist()
        return out


class _LogNormWeightMapFact:
    """Token -> float sampled from a range-rejected log-normal distribution"""

    def __init__(
        self,
        rng: random.Random,
        max_token: int,
        weight_range: tuple[float, float],
        zero_value: float = math.nan,
    ):
        lo, hi = sorted(weight_range)
        mu = (math.log(lo) + math.log(hi)) / 2.0
        sig = math.log(hi) - math.log(lo)
        nprng = _token_rng(rng)
        # vectorized rejection: redraw the whole remainder until full
        # (the acceptance rate is ~2/3, so this converges in a few rounds)
        vals = np.empty(max_token, dtype=np.float64)  # graftlint: disable=GL003 host token-table precompute, downcast before device
        n_ok = 0
        while n_ok < max_token:
            draw = np.exp(nprng.normal(mu, sig, size=max_token - n_ok))
            draw = draw[(draw >= lo) & (draw <= hi)]
            vals[n_ok : n_ok + len(draw)] = draw
            n_ok += len(draw)
        self.weights = np.concatenate([[zero_value], vals]).astype(np.float32)

    def __call__(self, t: np.ndarray) -> np.ndarray:
        return self.weights[t]

    def inverse(self) -> dict[float, list[int]]:
        out: dict[float, list[int]] = {}
        for i in range(1, len(self.weights)):
            out.setdefault(float(self.weights[i]), []).append(i)
        return out


class _SignMapFact:
    """Token -> +1 or -1 with 50% probability each"""

    def __init__(self, rng: random.Random, max_token: int, zero_value: int = 0):
        drawn = np.where(_token_rng(rng).random(max_token) < 0.5, 1, -1)
        self.signs = np.concatenate([[zero_value], drawn]).astype(np.int32)

    def __call__(self, t: np.ndarray) -> np.ndarray:
        return self.signs[t]

    def inverse(self) -> dict[bool, list[int]]:
        return {
            True: np.argwhere(self.signs == 1).flatten().tolist(),
            False: np.argwhere(self.signs == -1).flatten().tolist(),
        }


class _VectorMapFact:
    """Token -> one of a list of vectors, each mapped with equal frequency"""

    def __init__(
        self,
        rng: random.Random,
        max_token: int,
        n_signals: int,
        vectors: list[list[int]],
        zero_value: int = 0,
    ):
        M = np.full((max_token + 1, n_signals), zero_value, dtype=np.int32)
        if len(vectors) == 0:
            self.M = M
            return

        V = np.asarray(vectors, dtype=np.int32)
        if V.ndim != 2 or V.shape[1] != n_signals:
            raise ValueError(
                f"every vector must have one entry per signal ({n_signals})"
            )
        if len(V) > max_token:
            raise ValueError(
                f"{len(V)} vectors cannot all get a token: only"
                f" {max_token} tokens are available"
            )
        if (V == 0).all(axis=1).any():
            raise ValueError("all-zero vectors cannot be mapped to tokens")

        M[1:] = V[_token_rng(rng).integers(0, len(V), size=max_token)]
        self.M = M

    def __call__(self, t: np.ndarray) -> np.ndarray:
        return self.M[t]


class _ReactionMapFact(_VectorMapFact):
    """Token -> signed stoichiometry vector of one reaction over 2n signals"""

    def __init__(
        self,
        rng: random.Random,
        molmap: dict[Molecule, int],
        reactions: list[tuple[list[Molecule], list[Molecule]]],
        max_token: int,
        zero_value: int = 0,
    ):
        n_signals = 2 * len(molmap)
        vectors = [[0] * n_signals for _ in range(len(reactions))]
        for ri, (lft, rgt) in enumerate(reactions):
            for mol in lft:
                vectors[ri][molmap[mol]] -= 1
            for mol in rgt:
                vectors[ri][molmap[mol]] += 1
        super().__init__(
            rng=rng,
            vectors=vectors,
            n_signals=n_signals,
            max_token=max_token,
            zero_value=zero_value,
        )

    def inverse(
        self,
        molmap: dict[Molecule, int],
        reactions: list[tuple[list[Molecule], list[Molecule]]],
        n_signals: int,
    ) -> dict[tuple[tuple[Molecule, ...], tuple[Molecule, ...]], list[int]]:
        react_map = {}
        for subs, prods in reactions:
            t = np.zeros(n_signals, dtype=np.int32)
            for sub in subs:
                t[molmap[sub]] -= 1
            for prod in prods:
                t[molmap[prod]] += 1
            idxs = np.argwhere((self.M == t).all(axis=1)).flatten().tolist()
            react_map[(tuple(subs), tuple(prods))] = idxs
        return react_map


class _TransporterMapFact(_VectorMapFact):
    """Token -> transport vector (-1 intracellular, +1 extracellular)"""

    def __init__(
        self,
        rng: random.Random,
        n_molecules: int,
        max_token: int,
        zero_value: int = 0,
    ):
        n_signals = 2 * n_molecules
        vectors = [[0] * n_signals for _ in range(n_molecules)]
        for mi in range(n_molecules):
            vectors[mi][mi] = -1
            vectors[mi][mi + n_molecules] = 1
        super().__init__(
            rng=rng,
            vectors=vectors,
            n_signals=n_signals,
            max_token=max_token,
            zero_value=zero_value,
        )

    def inverse(self, molecules: list[Molecule]) -> dict[Molecule, list[int]]:
        return {
            mol: np.argwhere(self.M[:, mi] != 0).flatten().tolist()
            for mi, mol in enumerate(molecules)
        }


class _RegulatoryMapFact(_VectorMapFact):
    """Token -> one-hot effector vector over 2n signals"""

    def __init__(
        self,
        rng: random.Random,
        n_molecules: int,
        max_token: int,
        zero_value: int = 0,
    ):
        n_signals = 2 * n_molecules
        vectors = [[0] * n_signals for _ in range(n_signals)]
        for mi in range(n_signals):
            vectors[mi][mi] = 1
        super().__init__(
            rng=rng,
            vectors=vectors,
            n_signals=n_signals,
            max_token=max_token,
            zero_value=zero_value,
        )

    def inverse(
        self, molecules: list[Molecule]
    ) -> dict[tuple[Molecule, bool], list[int]]:
        n = len(molecules)
        reg_map = {}
        for mi, mol in enumerate(molecules):
            reg_map[(mol, False)] = np.argwhere(self.M[:, mi] != 0).flatten().tolist()
            reg_map[(mol, True)] = (
                np.argwhere(self.M[:, mi + n] != 0).flatten().tolist()
            )
        return reg_map


class Kinetics:
    """
    Class holding the cell parameter tensors and the logic simulating
    protein work.  Usually instantiated by :class:`World` — access it on
    ``world.kinetics``.

    Parameters:
        chemistry: Simulation :class:`Chemistry`.
        abs_temp: Absolute temperature (K); influences reaction equilibria.
        km_range: Range for sampled Michaelis-Menten constants (mM).
        vmax_range: Range for sampled maximum velocities (mM/s).
        scalar_enc_size: Number of tokens encoding scalars (Vmax, Km, sign);
            ``max(genetics.one_codon_map.values())``.
        vector_enc_size: Number of tokens encoding vectors (reactions,
            molecules); ``max(genetics.two_codon_map.values())``.
        seed: Seed for the token->parameter sampling.

    Cells are slot rows, proteins are ordered as translated; signals are
    all intracellular molecules (chemistry order) then all extracellular
    ones.  Dead/empty slots hold all-zero rows and do not react.
    """

    def __init__(
        self,
        chemistry: Chemistry,
        abs_temp: float = 310.0,
        km_range: tuple[float, float] = (1e-2, 100.0),
        vmax_range: tuple[float, float] = (1e-3, 100.0),
        scalar_enc_size: int = 64 - 3,
        vector_enc_size: int = 4096 - 3 * 64,
        seed: int | None = None,
    ):
        self.abs_temp = abs_temp
        self.seed = seed
        self.chemistry = chemistry
        self.mol_names = [d.name for d in chemistry.molecules]
        self.n_molecules = len(chemistry.molecules)
        self.n_signals = 2 * self.n_molecules
        mol_energies = np.array(
            [d.energy for d in chemistry.molecules] * 2, dtype=np.float32
        )

        # sampling order follows the reference so distributions match
        rng = random.Random(seed)
        mol_2_mi = {d: i for i, d in enumerate(chemistry.molecules)}
        self.km_map = _LogNormWeightMapFact(
            rng=rng, max_token=scalar_enc_size, weight_range=km_range
        )
        self.vmax_map = _LogNormWeightMapFact(
            rng=rng, max_token=scalar_enc_size, weight_range=vmax_range
        )
        self.sign_map = _SignMapFact(rng=rng, max_token=scalar_enc_size)
        self.hill_map = _HillMapFact(rng=rng, max_token=scalar_enc_size)
        self.reaction_map = _ReactionMapFact(
            rng=rng,
            molmap=mol_2_mi,
            reactions=chemistry.reactions,
            max_token=vector_enc_size,
        )
        self.transport_map = _TransporterMapFact(
            rng=rng, n_molecules=self.n_molecules, max_token=vector_enc_size
        )
        self.effector_map = _RegulatoryMapFact(
            rng=rng, n_molecules=self.n_molecules, max_token=vector_enc_size
        )

        # inverse maps for genome generation (factories)
        self.km_2_idxs = self.km_map.inverse()
        self.vmax_2_idxs = self.vmax_map.inverse()
        self.sign_2_idxs = self.sign_map.inverse()
        self.hill_2_idxs = self.hill_map.inverse()
        self.trnsp_2_idxs = self.transport_map.inverse(molecules=chemistry.molecules)
        self.regul_2_idxs = self.effector_map.inverse(molecules=chemistry.molecules)
        self.catal_2_idxs = self.reaction_map.inverse(
            molmap=mol_2_mi, reactions=chemistry.reactions, n_signals=self.n_signals
        )

        # device-side token tables consumed by the jitted assembly
        self.tables = TokenTables(
            km_weights=jnp.asarray(self.km_map.weights),
            vmax_weights=jnp.asarray(self.vmax_map.weights),
            signs=jnp.asarray(self.sign_map.signs),
            hills=jnp.asarray(self.hill_map.numbers),
            reactions=jnp.asarray(self.reaction_map.M),
            transports=jnp.asarray(self.transport_map.M),
            effectors=jnp.asarray(self.effector_map.M),
            mol_energies=jnp.asarray(mol_energies),
        )
        self._abs_temp_arr = jnp.asarray(abs_temp, dtype=jnp.float32)

        self.max_cells = 0
        self.max_proteins = 0
        self.max_doms = 1
        # optional NamedSharding for the cell axis (set by a mesh-placed
        # World); parameter tensors are then allocated sharded and every
        # jitted update runs SPMD
        self.cell_sharding = None
        self.params = self._alloc(0, 0)

    # ------------------------------------------------------------------ #
    # capacity management                                                #
    # ------------------------------------------------------------------ #

    def _alloc(self, c: int, p: int) -> CellParams:
        s = self.n_signals

        def _zeros(*shape, dtype):
            if self.cell_sharding is not None:
                # allocate sharded directly — materializing unsharded first
                # would peak device-0 HBM at the full unsharded size
                return jnp.zeros(shape, dtype=dtype, device=self.cell_sharding)
            return jnp.zeros(shape, dtype=dtype)

        f32 = lambda *shape: _zeros(*shape, dtype=jnp.float32)  # noqa: E731
        i16 = lambda *shape: _zeros(*shape, dtype=INT_PARAM_DTYPE)  # noqa: E731
        return CellParams(
            Ke=f32(c, p),
            Kmf=f32(c, p),
            Kmb=f32(c, p),
            Kmr=f32(c, p, s),
            Vmax=f32(c, p),
            N=i16(c, p, s),
            Nf=i16(c, p, s),
            Nb=i16(c, p, s),
            A=i16(c, p, s),
        )

    def _resize(self, c: int, p: int):
        old = self.params
        if self.max_cells == 0 or self.max_proteins == 0:
            self.params = self._alloc(c, p)
            self.max_cells = c
            self.max_proteins = p
            return
        # grow-only (ensure_capacity never shrinks): one fused pad
        # program instead of 9 eager slice/scatter pairs — growth used to
        # cost seconds of eager compiles per pow2 step.  Donation would
        # be useless — the padded outputs are strictly larger than the
        # inputs, so no buffer can be reused.
        s = self.n_signals
        if self.cell_sharding is None:
            # module-level jit: the pad program is shared across
            # Kinetics instances (zero-compile fleet admission)
            self.params = _grow_params_jit(old, cp=(c, p), cps=(c, p, s))
        else:
            # sharded resize is per-mesh and rare; keep the out_shardings
            # bound locally — graftlint: disable=GL002
            fn = jax.jit(
                _grow_params,
                static_argnames=("cp", "cps"),
                out_shardings=CellParams(*([self.cell_sharding] * 9)),
            )
            self.params = fn(old, cp=(c, p), cps=(c, p, s))
        self.max_cells = c
        self.max_proteins = p

    def ensure_capacity(self, n_cells: int | None = None, n_proteins: int | None = None):
        """Grow slot capacity (cells and/or proteins); never shrinks."""
        c = max(self.max_cells, n_cells or 0)
        p = max(self.max_proteins, n_proteins or 0)
        if c != self.max_cells or p != self.max_proteins:
            self._resize(c, p)

    def increase_max_cells(self, by_n: int):
        """Increase the cell dimension of all parameter tensors"""
        self.ensure_capacity(n_cells=self.max_cells + by_n)

    def increase_max_proteins(self, max_n: int):
        """Ensure at least ``max_n`` rows in the protein dimension"""
        self.ensure_capacity(n_proteins=max_n)

    # ------------------------------------------------------------------ #
    # parameter assembly                                                 #
    # ------------------------------------------------------------------ #

    def ensure_token_capacity(
        self, prot_counts: np.ndarray, prots: np.ndarray
    ) -> None:
        """Grow the protein/domain capacities (grow-only, pow2) to cover
        a translated batch — call for EVERY batch of one dispatch before
        densifying ANY of them, so no batch's growth invalidates another
        already-built dense tensor."""
        max_prots = int(prot_counts.max()) if len(prot_counts) else 0
        max_doms = int(prots[:, 3].max()) if len(prots) else 1
        self.ensure_token_limits(max_prots, max_doms)

    def ensure_token_limits(self, max_prots: int, max_doms: int) -> None:
        """Scalar form of :meth:`ensure_token_capacity` for callers that
        already know the batch maxima (the phenotype-cache path)."""
        if max_prots > self.max_proteins:
            self.ensure_capacity(n_proteins=pad_pow2(max_prots, minimum=1))
        # grow-only domain capacity: a per-batch capacity would recompile
        # `compute_cell_params` for every distinct batch shape
        self.max_doms = max(
            self.max_doms, pad_pow2(max(max_doms, 1), minimum=1)
        )

    def build_dense_tokens(
        self,
        prot_counts: np.ndarray,
        prots: np.ndarray,
        doms: np.ndarray,
    ) -> np.ndarray:
        """Flat genome-engine buffers -> the dense (b, p, d, 5) token
        tensor at the CURRENT protein/domain capacities, growing them
        first if the batch needs more — the one implementation of the
        capacity rule, shared by the normal set path and the pipelined
        stepper's in-program spawn and riding pushes."""
        self.ensure_token_capacity(prot_counts, prots)
        return pack_dense(
            prot_counts, prots, doms, self.max_proteins, self.max_doms
        )

    # graftlint: hot
    def set_cell_params_flat(
        self,
        cell_idxs: np.ndarray | list[int],
        prot_counts: np.ndarray,
        prots: np.ndarray,
        doms: np.ndarray,
    ):
        """
        Translate flat genome-engine buffers into kinetic parameters and
        write them to the given cell slots — the hot path of
        spawn/update/mutate (reference: kinetics.py:521-625 + the Python
        loop it replaces at kinetics.py:920-970).

        Cells are grouped by their assembly rung — the pow2 of their own
        (protein count, max domains/protein), floored at
        (RUNG_P_MIN, RUNG_D_MIN) and clamped to the capacities — and each
        group is packed and assembled at ITS rung instead of the
        worst-case capacities.  At benchmark scale (1 kb genomes) ~96% of
        cells fit the (32, 4) rung while capacities sit at (64, 16): a
        ~7x cut in assembly compute volume, bit-identical to full-width
        assembly (see ops/params._assemble_rows).
        """
        cell_idxs = np.asarray(cell_idxs, dtype=np.int32)
        b = len(cell_idxs)
        if b == 0:
            return
        prot_counts = np.asarray(prot_counts, dtype=np.int32)
        prots = np.asarray(prots, dtype=np.int32).reshape(-1, 4)
        doms = np.asarray(doms, dtype=np.int32).reshape(-1, 7)
        self.ensure_token_capacity(prot_counts, prots)

        # duplicate target slots: the old chunk loop made the LAST row
        # win across chunks while XLA leaves within-dispatch duplicate
        # scatter order unspecified — pin last-wins by dropping earlier
        # duplicates BEFORE grouping (groups reorder the scatter)
        if len(np.unique(cell_idxs)) != b:
            _, keep = np.unique(cell_idxs[::-1], return_index=True)
            keep = np.sort(b - 1 - keep)
            prot_offs = np.concatenate([[0], np.cumsum(prot_counts)])
            pidx = _gather_ranges(prot_offs[keep], prot_counts[keep])
            dom_offs = np.concatenate([[0], np.cumsum(prots[:, 3])])
            didx = _gather_ranges(dom_offs[pidx], prots[pidx, 3])
            cell_idxs = cell_idxs[keep]
            prot_counts = prot_counts[keep]
            prots = prots[pidx]
            doms = doms[didx]
            b = len(cell_idxs)

        # per-cell rung: pow2 of (n_prots, max doms over its proteins)
        dmax = np.zeros(b, dtype=np.int64)
        if len(prots):
            prot_cell = np.repeat(
                np.arange(b, dtype=np.int64), prot_counts
            )
            np.maximum.at(dmax, prot_cell, prots[:, 3].astype(np.int64))

        prot_offs = np.concatenate([[0], np.cumsum(prot_counts)])
        dom_offs = np.concatenate([[0], np.cumsum(prots[:, 3])])
        for sel, p_r, d_r in self._rung_groups(prot_counts, dmax):
            pidx = _gather_ranges(prot_offs[sel], prot_counts[sel])
            g_prots = prots[pidx]
            didx = _gather_ranges(dom_offs[pidx], g_prots[:, 3])
            dense = pack_dense(
                prot_counts[sel], g_prots, doms[didx], p_r, d_r
            )
            self.scatter_dense(cell_idxs[sel], dense)

    def _rung_groups(
        self, counts: np.ndarray, dmax: np.ndarray
    ) -> list[tuple[np.ndarray, int, int]]:
        """Group cells by assembly rung -> ``[(sel, p_rung, d_rung)]``.

        Minority rungs would each trace+compile their own assembly
        variant for a handful of rows, so groups smaller than the
        256-row scatter floor fold into the (sticky, already-compiled)
        full-capacity program — the variant count stays bounded while
        the dominant rung (~96% of cells at benchmark scale) keeps the
        ~7x volume cut."""
        p_rung = rung_pow2(counts, RUNG_P_MIN, self.max_proteins)
        d_rung = rung_pow2(dmax, RUNG_D_MIN, self.max_doms)
        key = p_rung * (self.max_doms + 1) + d_rung
        uniq, n_per = np.unique(key, return_counts=True)
        if len(uniq) > 1:
            small = np.isin(key, uniq[n_per < _IDX_BLOCK])
            if small.any():
                p_rung = np.where(small, self.max_proteins, p_rung)
                d_rung = np.where(small, self.max_doms, d_rung)
                key = p_rung * (self.max_doms + 1) + d_rung
        return [
            (
                sel := np.nonzero(key == k)[0],
                int(p_rung[sel[0]]),
                int(d_rung[sel[0]]),
            )
            for k in np.unique(key)
        ]

    # graftlint: hot
    def set_cell_params_cached(self, cell_idxs, entries, cache):
        """Write parameters for cells whose phenotypes come from a
        :class:`magicsoup_tpu.genetics.PhenotypeCache` — the same rung
        grouping as :meth:`set_cell_params_flat`, with each group's dense
        token rows served (and memoized per rung) by the cache instead of
        re-packed.  ``entries`` is one cache entry per cell (duplicates
        aliased); callers pre-dedupe duplicate slots."""
        cell_idxs = np.asarray(cell_idxs, dtype=np.int32)
        b = len(cell_idxs)
        if b == 0:
            return
        counts = np.fromiter(
            (e.n_prots for e in entries), dtype=np.int64, count=b
        )
        dmax = np.fromiter(
            (e.max_doms for e in entries), dtype=np.int64, count=b
        )
        self.ensure_token_limits(int(counts.max()), int(dmax.max()))
        for sel, p_r, d_r in self._rung_groups(counts, dmax):
            rows = cache.dense_rows([entries[i] for i in sel], p_r, d_r)
            self.scatter_dense(cell_idxs[sel], rows)

    # graftlint: hot
    def scatter_dense(self, cell_idxs: np.ndarray, dense: np.ndarray):
        """Dispatch one packed token batch: pad rows to the shared pow2
        floor, then run the fused assemble+scatter program.

        ``self.params`` is DONATED on accelerator backends so steady-state
        assembly holds one params copy instead of double-buffering the
        pytree per dispatch; XLA:CPU (jax 0.4.37) reuses donated buffers
        while in-flight consumers still read them, so CPU keeps the
        retained twins (same gate as the stepper's dispatch donation,
        asserted by tests/fast/test_kinetics.py's donation contract test).
        Batches spanning multiple assembly chunks fold into ONE
        ``lax.scan`` program carrying the params through the chunks — a
        10k-cell spawn is a handful of dispatches, not dozens."""
        cell_idxs = np.asarray(cell_idxs, dtype=np.int32)
        b = len(cell_idxs)
        if b == 0:
            return
        p_r, d_r = int(dense.shape[1]), int(dense.shape[2])
        # token batch and row-index batch pad to the SAME length (they
        # feed one scatter); the shared 256-row floor keeps the typical
        # mutate/update batch at one compiled variant (IDX_BLOCK)
        b_pad = pad_pow2(b, minimum=_IDX_BLOCK)
        dense_pad = np.zeros((b_pad, p_r, d_r, 5), dtype=np.int16)
        dense_pad[:b] = dense
        idxs = pad_idxs(cell_idxs, oob=self.max_cells)
        # Bound the per-dispatch rows: the assembly program materializes
        # several (b, p, d, s) temps, and one giant batch (the initial
        # 40k-cell spawn pads to 65536 rows = ~1.9 GB PER temp at
        # benchmark capacities) OOMs the device at buffer assignment.
        chunk = self._assembly_chunk(p_r, d_r)
        donate = self._donate_param_buffers()
        if b_pad <= chunk:
            fn = assemble_params if donate else assemble_params_retained
            self.params = fn(
                self.params,
                jnp.asarray(dense_pad),
                self.tables,
                self._abs_temp_arr,
                jnp.asarray(idxs),
            )
        else:
            # pow2 rows / pow2 chunk -> exact reshape; scan over chunks
            n_chunks = b_pad // chunk
            fn = (
                assemble_params_scan
                if donate
                else assemble_params_scan_retained
            )
            self.params = fn(
                self.params,
                jnp.asarray(
                    dense_pad.reshape(n_chunks, chunk, p_r, d_r, 5)
                ),
                self.tables,
                self._abs_temp_arr,
                jnp.asarray(idxs.reshape(n_chunks, chunk)),
            )

    def _donate_param_buffers(self) -> bool:
        """Donation gate for the params scatter: XLA:CPU (jax 0.4.37)
        hands donated buffers to new writers while in-flight consumers
        still read them (~50% corrupted rows under the async dispatch
        queue — same root cause as the stepper gate, PR 2), so donate
        only on accelerator backends."""
        return jax.default_backend() != "cpu"

    def _assembly_chunk(self, p_cap: int, d_cap: int) -> int:
        """Largest pow2 batch whose (b, p, d, s) i32 assembly temps stay
        ~<= 256 MB each at the given rung — big batches stream through
        the scan in chunks of one compiled shape instead of OOMing
        buffer assignment."""
        per_row = max(p_cap * d_cap * self.n_signals, 1)
        chunk = 1 << max((2**26 // per_row).bit_length() - 1, 0)
        return max(_IDX_BLOCK, chunk)

    def set_cell_params(
        self,
        cell_idxs: list[int],
        proteomes: list[list[ProteinSpecType]],
    ):
        """
        Set cell parameters from nested proteome specifications (the
        reference's API shape, `kinetics.py:521-538`).  ``proteomes`` come
        from :meth:`Genetics.translate_genomes`.
        """
        prot_counts = np.array([len(p) for p in proteomes], dtype=np.int32)
        prot_rows = []
        dom_rows = []
        for proteome in proteomes:
            for doms, cds_start, cds_end, is_fwd in proteome:
                prot_rows.append([cds_start, cds_end, int(is_fwd), len(doms)])
                for (dt, i0, i1, i2, i3), start, end in doms:
                    dom_rows.append([dt, i0, i1, i2, i3, start, end])
        prots = np.array(prot_rows, dtype=np.int32).reshape(-1, 4)
        doms_arr = np.array(dom_rows, dtype=np.int32).reshape(-1, 7)
        self.set_cell_params_flat(cell_idxs, prot_counts, prots, doms_arr)

    def unset_cell_params(self, cell_idxs: np.ndarray | list[int]):
        """Zero the parameter rows of the given cell slots"""
        cell_idxs = np.asarray(cell_idxs, dtype=np.int32)
        if len(cell_idxs) == 0:
            return
        idxs = pad_idxs(cell_idxs, oob=self.max_cells)
        self.params = unset_params(self.params, jnp.asarray(idxs))

    def copy_cell_params(
        self, from_idxs: np.ndarray | list[int], to_idxs: np.ndarray | list[int]
    ):
        """Copy parameter rows between cell slots (same-length index lists)"""
        from_idxs = np.asarray(from_idxs, dtype=np.int32)
        to_idxs = np.asarray(to_idxs, dtype=np.int32)
        if len(from_idxs) == 0:
            return
        f = pad_idxs(from_idxs, oob=self.max_cells)
        t = pad_idxs(to_idxs, oob=self.max_cells)
        self.params = copy_params(self.params, jnp.asarray(f), jnp.asarray(t))

    def remove_cell_params(self, keep: np.ndarray):
        """
        Compact cell slots down to the kept ones, preserving order — the
        kept rows move to the front, freed rows are zeroed.  ``keep`` is a
        bool array over all slots.
        """
        keep = np.asarray(keep, dtype=bool)
        perm = np.concatenate([np.nonzero(keep)[0], np.nonzero(~keep)[0]])
        n_keep = int(keep.sum())
        self.permute_cells(perm.astype(np.int32), n_keep)

    def permute_cells(self, perm: np.ndarray, n_keep: int):
        """Gather slot rows by a full-capacity permutation; zero the tail"""
        self.params = permute_params(
            self.params, jnp.asarray(perm, dtype=jnp.int32), jnp.asarray(n_keep)
        )

    # ------------------------------------------------------------------ #
    # integration                                                        #
    # ------------------------------------------------------------------ #

    def __getstate__(self) -> dict:
        state = self.__dict__.copy()
        # shardings are bound to live devices; restored instances are
        # unsharded until a mesh-placed World re-sets cell_sharding
        state["cell_sharding"] = None
        state["params"] = CellParams(*(fetch_host(t) for t in self.params))
        state["tables"] = TokenTables(*(fetch_host(t) for t in self.tables))
        state["_abs_temp_arr"] = fetch_host(self._abs_temp_arr)
        return state

    def __setstate__(self, state: dict):
        self.__dict__.update(state)
        # compat defaults for pickles from before these attributes existed
        self.__dict__.setdefault("max_doms", 1)
        self.__dict__.setdefault("cell_sharding", None)
        # cast to the canonical dtypes so worlds pickled with i32 integer
        # tensors share compiled programs with fresh ones; saturating like
        # the assembly's narrow(), not wrapping.  Host-side on purpose:
        # restore must stay transfer-only (the fleet warden's heal path
        # pins zero compiles through rollback + re-admission)
        def narrow(t) -> jax.Array:
            arr = np.clip(np.asarray(t), -32768, 32767)
            return jnp.asarray(arr.astype(INT_PARAM_DTYPE))

        raw = state["params"]
        restored = CellParams(*(jnp.asarray(t) for t in raw))
        self.params = restored._replace(
            N=narrow(raw.N),
            Nf=narrow(raw.Nf),
            Nb=narrow(raw.Nb),
            A=narrow(raw.A),
        )
        self.tables = TokenTables(*(jnp.asarray(t) for t in state["tables"]))
        self._abs_temp_arr = jnp.asarray(state["_abs_temp_arr"])

    def integrate_signals(self, X: jnp.ndarray) -> jnp.ndarray:
        """
        Simulate protein work for one time step.  ``X`` is (c, s) over all
        cell slots (intracellular signals first, extracellular second);
        returns the updated signals.
        """
        return integrate_signals(jnp.asarray(X, dtype=jnp.float32), self.params)

    # ------------------------------------------------------------------ #
    # interpretation                                                     #
    # ------------------------------------------------------------------ #

    def get_proteome(self, proteome: list[ProteinSpecType]) -> list[Protein]:
        """
        Interpret one index-level proteome as human-readable
        :class:`Protein` objects (replaces the reference's native dict
        builder, `rust/kinetics.rs:101-202`).
        """
        out = []
        for dom_specs, cds_start, cds_end, is_fwd in proteome:
            domains = []
            for (dt, i0, i1, i2, i3), start, end in dom_specs:
                dct = self._domain_dict(dt, i0, i1, i2, i3, start, end)
                if dct is not None:
                    domains.append(dct)
            out.append(
                Protein.from_dict(
                    {
                        "domains": domains,
                        "cds_start": cds_start,
                        "cds_end": cds_end,
                        "is_fwd": is_fwd,
                    }
                )
            )
        return out

    def _domain_dict(
        self, dt: int, i0: int, i1: int, i2: int, i3: int, start: int, end: int
    ) -> dict | None:
        mols = self.mol_names
        n_mols = self.n_molecules
        km = float(self.km_map.weights[i1])
        sign = int(self.sign_map.signs[i2])
        if dt == 1:
            vmax = float(self.vmax_map.weights[i0])
            react = self.reaction_map.M[i3]
            lfts: list[str] = []
            rgts: list[str] = []
            for mol_i, n in enumerate(react[:n_mols].tolist()):
                signed_n = n * sign
                if signed_n > 0:
                    rgts.extend([mols[mol_i]] * abs(n))
                elif signed_n < 0:
                    lfts.extend([mols[mol_i]] * abs(n))
            spec = {
                "reaction": (lfts, rgts),
                "km": km,
                "vmax": vmax,
                "start": start,
                "end": end,
            }
            return {"type": "C", "spec": spec}
        if dt == 2:
            vmax = float(self.vmax_map.weights[i0])
            trnspt = self.transport_map.M[i3]
            nz = np.nonzero(trnspt)[0]
            if len(nz) == 0:
                raise ValueError("No transporter molecule identified")
            mol_i = int(nz[0])
            signed_n = int(trnspt[mol_i]) * sign
            spec = {
                "molecule": mols[mol_i % n_mols],
                "km": km,
                "vmax": vmax,
                "is_exporter": signed_n < 0,
                "start": start,
                "end": end,
            }
            return {"type": "T", "spec": spec}
        if dt == 3:
            hill = int(self.hill_map.numbers[i0])
            eff = self.effector_map.M[i3]
            nz = np.nonzero(eff)[0]
            if len(nz) == 0:
                raise ValueError("No effector molecule identified")
            i = int(nz[0])
            signed_n = int(eff[i]) * sign
            is_trns = i >= n_mols
            spec = {
                "effector": mols[i % n_mols],
                "km": km,
                "hill": hill,
                "is_inhibiting": signed_n < 0,
                "is_transmembrane": is_trns,
                "start": start,
                "end": end,
            }
            return {"type": "R", "spec": spec}
        return None
