"""
FleetScheduler: admit/retire worlds dynamically, pack same-rung worlds
into shared compiled variants, and step the whole fleet with ONE
dispatch + ONE fetch per group per megastep.

Grouping
    Worlds are bucketed by **capacity rung** — the tuple of every
    shape/static that feeds the compiled fleet program (state and
    constant leaf shapes, spawn/push blocks, megastep ``k``, division
    budget cap, det/pallas flags).  Each rung owns a list of sibling
    groups with a FIXED power-of-two slot count; admitting a world into
    a rung with a free slot changes NO program shape, so a warm rung
    admits with **zero new compiles** (pinned via ``analysis.runtime``
    compile counters in tests/fast/test_fleet.py).

    Padded-slot admission (default, ``grow="pad"``): when every sibling
    group is full, the rung opens ANOTHER block-sized group whose
    pre-padded dead slots hold zero worlds.  Token capacities are
    unified per RUNG (grow-only), so the new group's program shapes
    equal its siblings' — its stack/step/extract/insert dispatches all
    hit the already-compiled programs and admission past a full group
    stays pure data movement.  The legacy ``grow="double"`` mode keeps
    the old behavior (a full group doubles its slot count — a new shape,
    one recompile for the whole rung) as the reference path the
    padded-admission bit-identity pin compares against.

Stepping
    ``step()`` runs every lane's solo ``_prepare_dispatch`` (all host
    decisions — spawn batches, push rides, compaction, growth — are the
    UNCHANGED solo code paths), re-buckets lanes whose rung changed,
    unifies token capacities across each group (grow-only, so solo
    trajectories are preserved — capacity invariance is pinned by the
    kinetics tests), stacks the planned batches, and dispatches one
    fleet program per group.  All member lanes share one physical fetch
    of the batched ``(B, k, record)`` output; each lane replays its own
    slice through the unchanged solo replay.

Cross-rung fusion
    A mixed fleet with R capacity rungs pays R dispatches + R physical
    fetches per megastep on the per-rung path.  The fusion planner
    (``fusion="rung"|"fleet"|"auto"``) collapses that to ONE batched
    program + ONE physical fetch for a whole fused set of rungs: each
    rung still runs its own program body at NATIVE shapes inside the
    one jit (bit-identity is structural — no state is ever padded, so
    shape-sensitive PRNG consumption is untouched), and only the packed
    step records are padded to a fleet-wide grow-only ``(k_env,
    rec_env)`` envelope and concatenated into one fetch buffer.
    ``auto`` fuses only rungs whose padded records stay under the
    ``fusion_waste`` slot-waste budget and falls back to per-rung
    dispatch otherwise.  Warm admission into an existing envelope
    compiles nothing (the fused signature is shape-stable); an envelope
    bump is exactly one counted recompile for the whole fleet (pinned
    in tests/fast/test_fleet.py via ``runtime.compile_count``).
"""
from __future__ import annotations

import threading
from concurrent.futures import TimeoutError as _FuturesTimeout
from typing import Any, NamedTuple

import jax
import numpy as np

from magicsoup_tpu.fleet.batch import (
    extract_world,
    fleet_step,
    fused_fleet_step,
    insert_world,
    lane_consts,
    stack_worlds,
    zeros_world_like,
)
from magicsoup_tpu.analysis import runtime as _runtime
from magicsoup_tpu.fleet.lanes import FleetLane
from magicsoup_tpu.guard import chaos as _chaos
from magicsoup_tpu.stepper import _LazyFetch, crop_fused_record, record_length
from magicsoup_tpu.telemetry import metrics as _metrics

__all__ = ["FleetScheduler"]

_OOB_ROW = np.iinfo(np.int32).max


def _device_ready(t_dispatched: float, lanes):
    """graftpulse device-time bracket for a SHARED fleet dispatch: the
    fetch-ready callback closes the commit-to-fetch-ready span once —
    one process census entry per physical launch (conservation is per
    physical dispatch) — and notes the full span as the ``"device"``
    phase on every rider lane's recorder, mirroring the solo stepper's
    ``_device_ready``.  Fires on the fetch worker thread before any
    lane's ``result()`` returns, so ``drain()`` implies a settled
    census."""
    import time as _time

    recorders = tuple(lane.telemetry for lane in lanes)

    def _ready():
        dt = _time.perf_counter() - t_dispatched
        _metrics.note_device_time(dt)
        for rec in recorders:
            rec.note("device", dt)

    return _ready


class _SharedFetch:
    """ONE physical D2H fetch of a group's batched step record, shared
    by every member lane — the whole fleet pays a single transfer per
    megastep (the fetch-census test pins this).

    The fetch is watchdogged like the solo path (``guard.watchdog``):
    a wedged transfer or dead fetch worker dumps diagnostics with the
    fleet context and raises a typed
    :class:`~magicsoup_tpu.guard.errors.WatchdogTimeout` instead of
    hanging every member lane.  Note the 3.10 trap this guards against:
    a bare worker Future raises ``concurrent.futures.TimeoutError``,
    which is NOT the builtin ``TimeoutError`` there — catching only the
    builtin would let fleet fetch timeouts sail past as untyped errors.
    """

    def __init__(self, fut, *, timeout=None, context=None):
        self._fut = fut
        self._value = None
        self._lock = threading.Lock()
        self._timeout = timeout
        self._context = dict(context or {})

    def done(self) -> bool:
        return self._value is not None or self._fut.done()

    def result(self, timeout=None):
        with self._lock:
            if self._value is None:
                budget = timeout if timeout is not None else self._timeout
                try:
                    self._value = np.asarray(
                        self._fut.result(timeout=budget)
                    )
                except (TimeoutError, _FuturesTimeout) as exc:
                    from magicsoup_tpu.guard.errors import WatchdogTimeout
                    from magicsoup_tpu.guard.watchdog import dump_diagnostics

                    dump_diagnostics(
                        "fleet step-record fetch timed out",
                        {
                            "phase": "fleet-fetch",
                            "timeout_s": budget,
                            **self._context,
                        },
                    )
                    raise WatchdogTimeout(
                        f"fleet step-record fetch exceeded {budget:.0f}s "
                        "(wedged transfer or dead fetch worker); "
                        "diagnostics dumped to stderr",
                        phase="fleet-fetch",
                        seconds=budget,
                    ) from exc
                self._fut = None  # drop the device buffer reference
            return self._value


class _SliceFetch:
    """A lane's view of the shared fetch: ``result()`` is that world's
    ``(k, record)`` slice of the batched record."""

    __slots__ = ("_shared", "_slot")

    def __init__(self, shared: _SharedFetch, slot: int):
        self._shared = shared
        self._slot = slot

    def done(self) -> bool:
        return self._shared.done()

    def result(self, timeout=None):
        return self._shared.result(timeout=timeout)[self._slot]


class _FusedSliceFetch:
    """A lane's view of a cross-rung FUSED fetch: ``result()`` crops the
    lane's native ``(k, record)`` megastep record back out of its
    world-row of the envelope-padded fused buffer (the pad columns are
    zeros the replay must never see)."""

    __slots__ = ("_shared", "_row", "_k", "_length")

    def __init__(self, shared: _SharedFetch, row: int, k: int, length: int):
        self._shared = shared
        self._row = row
        self._k = k
        self._length = length

    def done(self) -> bool:
        return self._shared.done()

    def result(self, timeout=None):
        return crop_fused_record(
            self._shared.result(timeout=timeout)[self._row],
            self._k,
            self._length,
        )


class _GroupInputs(NamedTuple):
    """One rung group's device inputs, densified and ready to dispatch —
    the shared product of the per-rung and fused dispatch paths."""

    first: FleetLane  # members[0]: statics / fetcher / retry source
    lane_plans: dict  # slot -> _DispatchPlan
    B: int  # slot count (padded group size)
    cap: int  # cell capacity (q of every member dispatch)
    maxp: int
    maxd: int
    k: int  # megastep (records per world per dispatch)
    length: int  # native record length of this rung
    statics: tuple  # (det, max_div, n_rounds, k, integrator)
    rest: tuple  # (consts, spawn_dense, spawn_valid, push_dense,
    #              push_rows, div_budget, do_compact)


def _rung_key(lane: FleetLane) -> tuple:
    """Everything that feeds the compiled fleet program's shape/static
    signature.  Token capacities are deliberately EXCLUDED — they are
    unified per group (grow-only), so worlds whose kinetics grew at
    different times still share one program."""
    state_sig = tuple(
        (tuple(l.shape), str(l.dtype))
        for l in jax.tree_util.tree_leaves(lane._state)
    )
    # constant shapes EXCLUDING tables: table leaves are token-capacity
    # shaped and may be regrown; they are checked at stack time instead
    c = lane_consts(lane)
    const_sig = tuple(
        (tuple(l.shape), str(l.dtype))
        for l in jax.tree_util.tree_leaves(c._replace(tables=()))
    )
    return (
        state_sig,
        const_sig,
        lane.spawn_block,
        lane.push_block,
        lane.megastep,
        lane.max_divisions,
        lane.n_rounds,
        bool(lane.world.deterministic),
        str(lane.world.integrator),
    )


class _FleetGroup:
    """One capacity rung's stacked program state."""

    def __init__(self, key: tuple, block: int):
        self.key = key
        self.slots: list[FleetLane | None] = [None] * block
        self.fstate = None
        self.fparams = None
        self.consts = None
        self.consts_ids: tuple | None = None
        self.maxp = 0
        self.maxd = 0
        self.dirty = True  # restack needed before next dispatch
        # shape the current fstate/fparams were stacked at — while it
        # matches, a dirty group restacks INCREMENTALLY (only changed
        # slots move) instead of rebuilding the whole stack
        self.stacked_shape: tuple | None = None
        # freshly vacated slots whose stack slices still hold live data
        # (zeroed by the next restack)
        self.stale: set[int] = set()
        self.warm: set[tuple] = set()
        self.empty_spawn: dict[tuple, Any] = {}
        self.empty_push: dict[tuple, Any] = {}
        self.budget_cache: dict[tuple, Any] = {}
        self.compact_cache: dict[tuple, Any] = {}

    def members(self) -> list[tuple[int, FleetLane]]:
        return [
            (i, lane) for i, lane in enumerate(self.slots) if lane is not None
        ]


class FleetScheduler:
    """Run B independent worlds as one compiled program per capacity
    rung.  ``admit`` wraps a :class:`~magicsoup_tpu.World` in a
    :class:`FleetLane`; ``step`` advances every admitted world by its
    ``megastep`` with one dispatch and one fetch per group.

    Parameters:
        block: Slot count of a group (power of two).  Spare slots are
            what make admission free — pre-padded dead slots admit with
            pure data movement.
        grow: ``"pad"`` (default) opens a sibling block-sized group when
            a rung is full (same program shapes, zero new compiles);
            ``"double"`` keeps the legacy behavior of doubling the one
            group's slot count (a new shape — recompiles the rung).
        fusion: Cross-rung dispatch fusion.  ``"rung"`` (default) keeps
            the one-dispatch-one-fetch-PER-GROUP contract; ``"fleet"``
            fuses every live group into ONE batched program + ONE
            physical fetch per megastep; ``"auto"`` fuses greedily but
            only while every fused member's padded step records stay
            under the ``fusion_waste`` budget, falling back to per-rung
            dispatch for outliers.
        fusion_waste: Slot-waste budget for ``fusion="auto"``: the
            largest tolerated fraction of a member's fetched record
            envelope that is padding (``1 - (k*L)/(k_env*rec_env)``).
    """

    def __init__(
        self,
        *,
        block: int = 4,
        grow: str = "pad",
        fusion: str = "rung",
        fusion_waste: float = 0.5,
    ):
        if block < 1:
            raise ValueError("block must be >= 1")
        if grow not in ("pad", "double"):
            raise ValueError('grow must be "pad" or "double"')
        if fusion not in ("rung", "fleet", "auto"):
            raise ValueError('fusion must be "rung", "fleet" or "auto"')
        if not 0.0 <= float(fusion_waste) < 1.0:
            raise ValueError("fusion_waste must be in [0, 1)")
        self.fusion = fusion
        self.fusion_waste = float(fusion_waste)
        self.block = 1 << (int(block) - 1).bit_length()  # round up to pow2
        self.grow = grow
        self.lanes: list[FleetLane] = []
        # rung key -> sibling groups (one per key in "double" mode)
        self._groups: dict[tuple, list[_FleetGroup]] = {}
        # rung key -> grow-only (max_proteins, max_doms) unified across
        # sibling groups so they share program shapes; remembered past
        # group teardown so a re-created rung re-hits warm programs
        self._rung_caps: dict[tuple, tuple[int, int]] = {}
        # cross-rung fusion state: the GROW-ONLY record envelope every
        # fused fetch buffer is padded to (monotone max, so membership
        # churn between known configurations re-hits warm signatures
        # instead of bouncing shapes), and the warm fused-program
        # signatures (per-rung shape tuples + envelope)
        self._env_k = 0
        self._env_rec = 0
        self._fused_warm: set[tuple] = set()
        self._warden = None  # bound by fleet.warden.FleetWarden

    # ------------------------------------------------------------ #
    # membership                                                   #
    # ------------------------------------------------------------ #

    def admit(self, world, **stepper_kwargs) -> FleetLane:
        """Wrap ``world`` in a :class:`FleetLane` and join the fleet.
        Placement into a rung group happens at the next ``step()``."""
        if getattr(world, "_mesh", None) is not None:
            raise ValueError(
                "fleet worlds must be single-device; shard the WORLD axis "
                "instead (magicsoup_tpu.fleet.sharding)"
            )
        lane = FleetLane(world, **stepper_kwargs)
        lane._fleet = self
        # the warden re-admits healed worlds with the SAME kwargs —
        # keep them (restore_stepper refuses config drift anyway)
        lane._admit_kwargs = dict(stepper_kwargs)
        self.lanes.append(lane)
        if self._warden is not None:
            self._warden._on_admit(lane)
        return lane

    def retire(self, lane: FleetLane) -> FleetLane:
        """Remove ``lane`` from the fleet (its slot is restacked to
        zeros) and return it as a standalone stepper — ``lane.step()``
        works solo afterwards, no state is lost."""
        if lane._fleet is not self:
            raise ValueError("lane is not managed by this scheduler")
        if lane._fleet_resident:
            self._checkout(lane)
        if lane._fleet_slot is not None:
            group, slot = lane._fleet_slot
            group.slots[slot] = None
            group.stale.add(slot)  # slice still holds the lane's data
            group.dirty = True
            group.consts_ids = None
            lane._fleet_slot = None
            if not group.members():
                self._drop_group(group)
        self.lanes.remove(lane)
        lane._fleet = None
        if self._warden is not None:
            self._warden._on_retire(lane)
        return lane

    def readmit(self, lane: FleetLane) -> FleetLane:
        """Re-join a previously :meth:`retire`-d lane WITHOUT rebuilding
        it: the lane object keeps all of its pipeline state (host replay
        lists, RNG schedule, telemetry, stats), so a retire/readmit round
        trip — the serve layer's budget pause — is invisible to the
        world's trajectory.  Placement happens at the next ``step()``."""
        if not isinstance(lane, FleetLane):
            raise TypeError("readmit() takes the FleetLane retire() returned")
        if lane._fleet is not None:
            raise ValueError("lane is already managed by a scheduler")
        lane._fleet = self
        self.lanes.append(lane)
        if self._warden is not None:
            self._warden._on_admit(lane)
        return lane

    def _drop_group(self, group: _FleetGroup) -> None:
        siblings = self._groups.get(group.key)
        if siblings and group in siblings:
            siblings.remove(group)
            if not siblings:
                self._groups.pop(group.key, None)

    # ------------------------------------------------------------ #
    # stepping                                                     #
    # ------------------------------------------------------------ #

    def step(self) -> None:
        """One fleet megastep: every world advances ``megastep`` fused
        steps.  One dispatch + one fetch per rung group — or per FUSED
        SET of groups when the fusion planner merges rungs (one for the
        whole fleet under ``fusion="fleet"``)."""
        if self._warden is not None:
            # evict tripped worlds / heal cooled-down ones / cadence
            # saves BEFORE any plan is prepared: membership must be
            # settled when the groups stack
            self._warden.before_step()
        plans = {}
        for lane in list(self.lanes):
            plans[id(lane)] = lane._prepare_dispatch()
        self._place()
        live = [
            group
            for siblings in list(self._groups.values())
            for group in list(siblings)
            if group.members()
        ]
        for fused_set in self._plan_fusion(live):
            if len(fused_set) == 1:
                self._dispatch_group(fused_set[0], plans)
            else:
                self._dispatch_fused(fused_set, plans)

    def _plan_fusion(self, groups: list[_FleetGroup]) -> list[list]:
        """Partition the live groups into fused dispatch sets.

        ``"rung"`` returns singletons (the legacy per-group contract).
        ``"fleet"`` returns one set.  ``"auto"`` packs greedily, largest
        record footprint first, admitting a group into a set only while
        EVERY member's padded-record waste — measured against the
        grow-only envelope the merged set would fetch under — stays
        within ``fusion_waste``.  Every dispatch below routes through
        this planner (graftlint GL024 pins that no per-group dispatch
        loop bypasses it)."""
        if self.fusion == "rung" or len(groups) <= 1:
            return [[g] for g in groups]
        if self.fusion == "fleet":
            return [list(groups)]
        geo = {}
        for g in groups:
            _, first = g.members()[0]
            geo[id(g)] = (
                first.megastep,
                record_length(
                    first._cap, first.max_divisions, first.spawn_block
                ),
            )
        order = sorted(
            range(len(groups)),
            key=lambda i: (-geo[id(groups[i])][0] * geo[id(groups[i])][1], i),
        )
        sets: list[list] = []
        for i in order:
            g = groups[i]
            placed = False
            for s in sets:
                cand = s + [g]
                k_env = max(
                    self._env_k, max(geo[id(x)][0] for x in cand)
                )
                rec_env = max(
                    self._env_rec, max(geo[id(x)][1] for x in cand)
                )
                envelope = k_env * rec_env
                if all(
                    geo[id(x)][0] * geo[id(x)][1]
                    >= (1.0 - self.fusion_waste) * envelope
                    for x in cand
                ):
                    s.append(g)
                    placed = True
                    break
            if not placed:
                sets.append([g])
        return sets

    def drain(self) -> None:
        """Block until every lane's dispatched steps are replayed."""
        for lane in self.lanes:
            lane.drain()

    def flush(self) -> None:
        """Drain + sync every lane's ``World`` (checks all lanes out of
        the stacks; they are re-admitted at the next ``step``)."""
        for lane in self.lanes:
            lane.flush()

    # ------------------------------------------------------------ #
    # placement                                                    #
    # ------------------------------------------------------------ #

    def _place(self) -> None:
        for lane in self.lanes:
            key = _rung_key(lane)
            if lane._fleet_slot is not None:
                group, slot = lane._fleet_slot
                if group.key == key:
                    continue
                # rung changed (capacity growth, flag flip): leave the
                # old group — its stack restacks without this lane
                if lane._fleet_resident:
                    self._checkout(lane)
                group.slots[slot] = None
                group.stale.add(slot)
                group.dirty = True
                group.consts_ids = None
                lane._fleet_slot = None
                if not group.members():
                    self._drop_group(group)
            self._assign(lane, key)

    def _assign(self, lane: FleetLane, key: tuple) -> None:
        siblings = self._groups.setdefault(key, [])
        group = next((g for g in siblings if None in g.slots), None)
        if group is None:
            if self.grow == "pad" or not siblings:
                # padded-slot admission: the rung opens ANOTHER
                # block-sized group.  Its shapes equal its siblings'
                # (token caps are rung-unified, grow-only), so every
                # program it needs is already compiled — admission past
                # a full group stays pure data movement
                group = _FleetGroup(key, self.block)
                if siblings:
                    # the sibling already ran these variants — the new
                    # group's dispatches are warm, not cold
                    group.warm |= siblings[0].warm
                rp, rd = self._rung_caps.get(key, (0, 0))
                group.maxp, group.maxd = rp, rd
                siblings.append(group)
            else:
                # the legacy admission cliff (grow="double"): the rung's
                # one group doubles its slot count — new shapes, one
                # recompile for the whole rung
                group = siblings[0]
                group.slots.extend([None] * len(group.slots))
                group.dirty = True
                group.warm.clear()
                group.empty_spawn.clear()
                group.empty_push.clear()
                group.budget_cache.clear()
                group.compact_cache.clear()
        slot = group.slots.index(None)
        group.slots[slot] = lane
        group.stale.discard(slot)  # occupied again; insert overwrites it
        lane._fleet_slot = (group, slot)
        lane._fleet_resident = False
        group.consts_ids = None  # membership changed -> restack consts

    # ------------------------------------------------------------ #
    # checkout / restack                                           #
    # ------------------------------------------------------------ #

    def _checkout(self, lane: FleetLane) -> None:
        group, slot = lane._fleet_slot
        lane._state = extract_world(group.fstate, slot)
        lane.kin.params = extract_world(group.fparams, slot)
        lane._fleet_resident = False

    def _restack(self, group: _FleetGroup) -> None:
        """Rebuild or patch the group's stacked state/params.

        While the stacked SHAPE is unchanged (slot count and token caps),
        a dirty group restacks incrementally: resident lanes' slices in
        the old stack are still the truth and are skipped outright; only
        changed slots move (non-resident members are inserted, freshly
        vacated slots are zeroed).  A membership change therefore costs
        one ``insert_world`` per CHANGED slot instead of a serial
        checkout + full ``stack_worlds`` over every member — the skip is
        counted in the ``analysis.runtime`` restack counters so serve
        accounting sees restack work.  A shape change (token-cap growth,
        legacy slot doubling) or the first stack takes the full-rebuild
        path.  Either way every program involved is shape-stable, so a
        warm rung's restack never compiles."""
        members = group.members()
        shape = (len(group.slots), group.maxp, group.maxd)
        if group.fstate is not None and group.stacked_shape == shape:
            zs = zp = None
            inserts = skipped = 0
            for slot, lane in members:
                if lane._fleet_resident:
                    skipped += 1
                    continue
                lane.kin.ensure_token_limits(group.maxp, group.maxd)
                group.fstate = insert_world(group.fstate, slot, lane._state)
                group.fparams = insert_world(
                    group.fparams, slot, lane.kin.params
                )
                lane._fleet_resident = True
                inserts += 1
            for slot in sorted(group.stale):
                if group.slots[slot] is not None:
                    continue
                if zs is None:
                    _, first = members[0]
                    zs = zeros_world_like(first._state)
                    zp = zeros_world_like(first.kin.params)
                group.fstate = insert_world(group.fstate, slot, zs)
                group.fparams = insert_world(group.fparams, slot, zp)
                inserts += 1
            group.stale.clear()
            group.dirty = False
            _runtime.note_restack(inserts=inserts, skipped=skipped)
            return
        # full rebuild: residents' truth lives in the old stack — pull
        # it back first
        for _, lane in members:
            if lane._fleet_resident:
                self._checkout(lane)
        for _, lane in members:
            lane.kin.ensure_token_limits(group.maxp, group.maxd)
        _, first = members[0]
        zs = zeros_world_like(first._state)
        zp = zeros_world_like(first.kin.params)
        group.fstate = stack_worlds(
            [l._state if l is not None else zs for l in group.slots]
        )
        group.fparams = stack_worlds(
            [l.kin.params if l is not None else zp for l in group.slots]
        )
        for _, lane in members:
            lane._fleet_resident = True
        group.stale.clear()
        group.dirty = False
        group.stacked_shape = shape
        _runtime.note_restack(full=1)
        # warm the checkout AND re-admit programs for this shape NOW:
        # a later admission/checkout must not be the first extract or
        # insert at these shapes (results discarded — pure programs)
        insert_world(group.fstate, 0, extract_world(group.fstate, 0))
        insert_world(group.fparams, 0, extract_world(group.fparams, 0))

    def _ensure_stacked(self, group: _FleetGroup) -> None:
        members = group.members()
        maxp = max(l.kin.max_proteins for _, l in members)
        maxd = max(l.kin.max_doms for _, l in members)
        # unify token caps across the whole RUNG, not just this group:
        # sibling groups must share program shapes so a padded admission
        # into a fresh block stays zero-compile
        rp, rd = self._rung_caps.get(group.key, (0, 0))
        maxp, maxd = max(maxp, rp), max(maxd, rd)
        if (maxp, maxd) != (rp, rd):
            self._rung_caps[group.key] = (maxp, maxd)
        if maxp > group.maxp or maxd > group.maxd:
            # token capacities are grow-only and growth is trajectory
            # invariant; the params shapes change, so restack
            group.maxp, group.maxd = max(group.maxp, maxp), max(
                group.maxd, maxd
            )
            group.dirty = True
        if group.dirty:
            self._restack(group)
        else:
            for slot, lane in members:
                if not lane._fleet_resident:
                    lane.kin.ensure_token_limits(group.maxp, group.maxd)
                    group.fstate = insert_world(group.fstate, slot, lane._state)
                    group.fparams = insert_world(
                        group.fparams, slot, lane.kin.params
                    )
                    lane._fleet_resident = True
        ids = tuple(
            (id(lane), id(lane.kin.tables)) if lane is not None else None
            for lane in group.slots
        )
        if group.consts is None or ids != group.consts_ids:
            _, first = members[0]
            zc = zeros_world_like(lane_consts(first))
            group.consts = stack_worlds(
                [
                    lane_consts(l) if l is not None else zc
                    for l in group.slots
                ]
            )
            group.consts_ids = ids

    # ------------------------------------------------------------ #
    # batched dispatch                                             #
    # ------------------------------------------------------------ #

    def _prepare_group_inputs(
        self, group: _FleetGroup, plans: dict
    ) -> _GroupInputs:
        """Densify one (already stacked) group's device inputs — the
        shared front half of the per-rung and fused dispatch paths."""
        import time as _time

        members = group.members()
        _, first = members[0]
        B = len(group.slots)
        cap = first._cap
        sb, pb = first.spawn_block, first.push_block
        maxp, maxd = group.maxp, group.maxd

        # ---- stacked spawn/push uploads (one H2D each, cached when
        # every lane is idle on that input — mirrors the solo
        # _empty_spawn/_empty_push caching) ----
        lane_plans = {slot: plans[id(l)] for slot, l in members}
        if any(p.spawn_entries is not None for p in lane_plans.values()):
            dense_pad = np.zeros((B, sb, maxp, maxd, 5), dtype=np.int16)
            valid_pad = np.zeros((B, sb), dtype=bool)
            for slot, lane in members:
                plan = lane_plans[slot]
                if plan.spawn_entries is None:
                    continue
                dense = lane.world.phenotypes.dense_rows(
                    plan.spawn_entries, maxp, maxd
                )
                dense_pad[slot, : len(plan.spawn)] = dense
                valid_pad[slot, : len(plan.spawn)] = True
                lane.telemetry.note(
                    "spawn", _time.perf_counter() - plan.t_spawn0
                )
            spawn_dense = jax.device_put(dense_pad)
            spawn_valid = jax.device_put(valid_pad)
        else:
            ck = (B, sb, maxp, maxd)
            if ck not in group.empty_spawn:
                group.empty_spawn[ck] = (
                    jax.device_put(
                        np.zeros((B, sb, maxp, maxd, 5), dtype=np.int16)
                    ),
                    jax.device_put(np.zeros((B, sb), dtype=bool)),
                )
            spawn_dense, spawn_valid = group.empty_spawn[ck]
        if any(p.ride is not None for p in lane_plans.values()):
            push_pad = np.zeros((B, pb, maxp, maxd, 5), dtype=np.int16)
            rows_pad = np.full((B, pb), _OOB_ROW, dtype=np.int32)
            for slot, lane in members:
                plan = lane_plans[slot]
                if plan.ride is None:
                    continue
                entries, rows = plan.ride
                with lane.telemetry.span("push"):
                    dense = lane.world.phenotypes.dense_rows(
                        entries, maxp, maxd
                    )
                    push_pad[slot, : len(rows)] = dense
                    # same OOB padding value the solo densify uses
                    rows_pad[slot] = cap
                    rows_pad[slot, : len(rows)] = rows
            push_dense = jax.device_put(push_pad)
            push_rows = jax.device_put(rows_pad)
        else:
            ck = (B, pb, maxp, maxd)
            if ck not in group.empty_push:
                group.empty_push[ck] = (
                    jax.device_put(
                        np.zeros((B, pb, maxp, maxd, 5), dtype=np.int16)
                    ),
                    jax.device_put(np.full((B, pb), _OOB_ROW, dtype=np.int32)),
                )
            push_dense, push_rows = group.empty_push[ck]
        for slot, lane in members:
            lane.telemetry.note(
                "param_assembly",
                _time.perf_counter() - lane_plans[slot].t_asm0,
            )

        budgets = tuple(
            lane_plans[i].div_budget if l is not None else 0
            for i, l in enumerate(group.slots)
        )
        dev_budget = group.budget_cache.get(budgets)
        if dev_budget is None:
            if len(group.budget_cache) > 256:
                group.budget_cache.clear()
            dev_budget = jax.device_put(np.asarray(budgets, dtype=np.int32))
            group.budget_cache[budgets] = dev_budget
        compacts = tuple(
            bool(lane_plans[i].compact) if l is not None else False
            for i, l in enumerate(group.slots)
        )
        do_compact = group.compact_cache.get(compacts)
        if do_compact is None:
            if len(group.compact_cache) > 256:
                group.compact_cache.clear()
            do_compact = jax.device_put(np.asarray(compacts, dtype=bool))
            group.compact_cache[compacts] = do_compact

        return _GroupInputs(
            first=first,
            lane_plans=lane_plans,
            B=B,
            cap=cap,
            maxp=maxp,
            maxd=maxd,
            k=first.megastep,
            length=record_length(cap, first.max_divisions, sb),
            statics=(
                bool(first.world.deterministic),
                first.max_divisions,
                first.n_rounds,
                first.megastep,
                str(first.world.integrator),
            ),
            rest=(
                group.consts,
                spawn_dense,
                spawn_valid,
                push_dense,
                push_rows,
                dev_budget,
                do_compact,
            ),
        )

    @staticmethod
    def _chaos_dispatch_site() -> None:
        """Fire the armed graftchaos ``dispatch`` fault (if any) BEFORE
        any donated buffer is touched, so a retried fleet dispatch
        re-sends bit-identical inputs — same contract as the solo
        ``PipelinedStepper.step`` probe."""
        fault = _chaos.site("dispatch")
        if fault is not None:
            from magicsoup_tpu.guard.errors import TransientDispatchError

            raise TransientDispatchError(
                "injected fault: UNAVAILABLE: chaos dispatch fault "
                f"#{fault.index}"
            )

    # graftlint: hot
    def _dispatch_group(self, group: _FleetGroup, plans: dict) -> None:
        import time as _time

        self._ensure_stacked(group)
        gi = self._prepare_group_inputs(group, plans)
        first = gi.first
        det, max_div, n_rounds, k, integrator = gi.statics

        vkey = (gi.B, gi.cap, gi.maxp, gi.maxd)
        cold = vkey not in group.warm
        t_dispatch0 = _time.perf_counter()

        def _go():
            self._chaos_dispatch_site()
            return fleet_step(
                group.fstate,
                group.fparams,
                *gi.rest,
                det=det,
                max_div=max_div,
                n_rounds=n_rounds,
                k=k,
                integrator=integrator,
            )

        group.fstate, group.fparams, fouts = first._dispatch_with_retry(_go)
        t_dispatched = _time.perf_counter()
        group.warm.add(vkey)
        _runtime.note_dispatch(dispatches=1, fused_groups=1)
        # integrator census: the one batched program carried every
        # world's integrator calls through this backend — ONE count
        _runtime.note_integrator_dispatch(integrator)

        # one fetch for the whole group; lanes replay their slices.
        # graftpulse device-time bracket: ONE census entry per physical
        # dispatch; every member lane's recorder gets the full span (the
        # shared program ran FOR each of them — same cost model as the
        # shared `dispatches` counter)
        ready = _device_ready(
            t_dispatched, [lane for _, lane in group.members()]
        )
        fut = (
            first._fetcher.submit(fouts, on_ready=ready)
            if first._fetcher is not None
            else _LazyFetch(fouts, on_ready=ready)
        )
        shared = _SharedFetch(
            fut,
            timeout=first._fetch_timeout,
            context={
                "B": gi.B,
                "k": gi.k,
                "slots": [slot for slot, _ in group.members()],
            },
        )
        for slot, lane in group.members():
            lane._fused_tags = {}
            lane._commit_dispatch(
                gi.lane_plans[slot],
                _SliceFetch(shared, slot),
                q=gi.cap,
                cold=cold,
                t_dispatch0=t_dispatch0,
                t_dispatched=t_dispatched,
                extra_row={"fleet_slot": slot, "fleet_size": gi.B},
            )

    # graftlint: hot
    def _dispatch_fused(self, fused_set: list, plans: dict) -> None:
        """ONE batched program + ONE physical fetch for a whole fused
        set of rung groups.  Every rung keeps its native shapes inside
        the one jit (bit-identity is structural); only the packed step
        records are padded to the grow-only ``(k_env, rec_env)``
        envelope and concatenated, so the fleet's records come back in
        a single ``(sum B_r, k_env, rec_env)`` buffer each lane crops
        its native view out of."""
        import time as _time

        prepped = []
        for group in fused_set:
            self._ensure_stacked(group)
            prepped.append(self._prepare_group_inputs(group, plans))

        # grow-only envelope: monotone max, so membership churn between
        # known configurations re-hits warm signatures.  A bump here is
        # exactly one counted recompile (the fused program's).
        self._env_k = max(self._env_k, max(p.k for p in prepped))
        self._env_rec = max(self._env_rec, max(p.length for p in prepped))
        k_env, rec_env = self._env_k, self._env_rec
        statics = tuple(p.statics for p in prepped)
        sig = (
            tuple((p.B, p.cap, p.maxp, p.maxd, p.statics) for p in prepped),
            k_env,
            rec_env,
        )
        cold = sig not in self._fused_warm
        states = tuple(g.fstate for g in fused_set)
        params = tuple(g.fparams for g in fused_set)
        rest = tuple(p.rest for p in prepped)
        first = prepped[0].first
        t_dispatch0 = _time.perf_counter()

        def _go():
            self._chaos_dispatch_site()
            return fused_fleet_step(
                states,
                params,
                rest,
                statics=statics,
                k_env=k_env,
                rec_env=rec_env,
            )

        new_states, new_params, fouts = first._dispatch_with_retry(_go)
        t_dispatched = _time.perf_counter()
        self._fused_warm.add(sig)
        _runtime.note_dispatch(dispatches=1, fused_groups=len(fused_set))
        # integrator census: ONE count per distinct backend the fused
        # program ran (a mixed-backend set is still one launch, but the
        # per-backend attribution stays truthful)
        for name in sorted({s[4] for s in statics}):
            _runtime.note_integrator_dispatch(name)
        # NOTE: group.warm is deliberately untouched — it tracks the
        # PER-RUNG program's warmth, which a fused dispatch neither
        # exercises nor compiles
        for group, fs, fp in zip(fused_set, new_states, new_params):
            group.fstate, group.fparams = fs, fp

        # ONE physical fetch for the whole fused set; each lane crops
        # its native (k, record) view out of its world-row.  Device
        # time: one census entry for the one fused launch, the full
        # span noted on every rider lane's recorder
        ready = _device_ready(
            t_dispatched,
            [lane for group in fused_set for _, lane in group.members()],
        )
        fut = (
            first._fetcher.submit(fouts, on_ready=ready)
            if first._fetcher is not None
            else _LazyFetch(fouts, on_ready=ready)
        )
        shared = _SharedFetch(
            fut,
            timeout=first._fetch_timeout,
            context={
                "fused_groups": len(fused_set),
                "worlds": sum(p.B for p in prepped),
                "envelope": [k_env, rec_env],
            },
        )
        row_base = 0
        fused_tags = {
            "fused_groups": len(fused_set),
            "envelope": [k_env, rec_env],
        }
        for group, p in zip(fused_set, prepped):
            for slot, lane in group.members():
                lane._fused_tags = dict(fused_tags)
                lane._commit_dispatch(
                    p.lane_plans[slot],
                    _FusedSliceFetch(
                        shared, row_base + slot, p.k, p.length
                    ),
                    q=p.cap,
                    cold=cold,
                    t_dispatch0=t_dispatch0,
                    t_dispatched=t_dispatched,
                    extra_row={
                        "fleet_slot": slot,
                        "fleet_size": p.B,
                        **fused_tags,
                    },
                )
            row_base += p.B
