"""
graftfleet — multi-world batch axis: B independent worlds as ONE
compiled program.

The production shape for "millions of users" is not one giant world but
thousands of independent ones (sessions, replicates, sweeps) packed
onto shared hardware.  This subsystem stacks same-rung worlds on a
leading world axis and steps them with a single dispatch and a single
host fetch per megastep:

    fleet = FleetScheduler(block=4)
    a = fleet.admit(world_a, mol_name="atp", ...)   # solo stepper kwargs
    b = fleet.admit(world_b, mol_name="atp", ...)
    fleet.step()          # ONE dispatch + ONE fetch for the whole rung
    fleet.flush()         # sync every World
    solo = fleet.retire(b)  # b continues as a standalone stepper

Contracts (all pinned in tests/fast/test_fleet.py and the gating fleet
smoke):

- **bit-identity**: in det mode every world in a fleet computes exactly
  what it would compute alone — a B=1 fleet matches the solo
  ``PipelinedStepper`` at any megastep K.
- **one fetch per megastep per fleet group**: member lanes share one
  physical D2H transfer of the batched ``(B, k, record)`` step record.
- **zero-compile admission**: admitting a world into a rung whose group
  has a free slot and a warm program compiles nothing.

Submodules: :mod:`~magicsoup_tpu.fleet.batch` (the stacked device
program), :mod:`~magicsoup_tpu.fleet.lanes` (per-world steppers),
:mod:`~magicsoup_tpu.fleet.scheduler` (admission/rungs/dispatch),
:mod:`~magicsoup_tpu.fleet.sharding` (world-axis mesh placement),
:mod:`~magicsoup_tpu.fleet.persist` (batch-aware guard checkpoints),
:mod:`~magicsoup_tpu.fleet.warden` (per-world fault isolation,
rolling checkpoint streams, and self-healing).
"""
from magicsoup_tpu.fleet.lanes import FleetLane
from magicsoup_tpu.fleet.persist import (
    FLEET_FORMAT,
    restore_fleet,
    restore_world,
    save_fleet,
    snapshot_fleet,
)
from magicsoup_tpu.fleet.scheduler import FleetScheduler
from magicsoup_tpu.fleet.warden import (
    WARDEN_POLICIES,
    FleetWarden,
    WardenStatus,
)

__all__ = [
    "FLEET_FORMAT",
    "WARDEN_POLICIES",
    "FleetLane",
    "FleetScheduler",
    "FleetWarden",
    "WardenStatus",
    "restore_fleet",
    "restore_world",
    "save_fleet",
    "snapshot_fleet",
]
