"""
Fleet lanes: one :class:`~magicsoup_tpu.stepper.PipelinedStepper` per
admitted world, with its device state RESIDENT in the group's stacked
arrays instead of its own.

A lane keeps the full solo host machinery — spawn/push queues, replay,
growth and compaction decisions, telemetry, guard hooks — untouched.
Only the device boundary changes: the scheduler runs the lane's
``_prepare_dispatch`` (host half), stacks the planned batches of every
lane in the group, dispatches ONE fleet program, and hands the lane its
slice of the shared fetch via ``_commit_dispatch``.  Because every host
decision is the solo code path, a lane's trajectory is bit-identical to
running the same world alone (pinned in tests/fast/test_fleet.py).

Checkout protocol: while resident, ``lane._state`` / ``lane.kin.params``
are STALE — the truth lives in the group stack.  Every operation that
touches them host-side (flush, consistency audit, standalone push
programs) first checks the lane out (extracts its slice); the scheduler
re-admits checked-out lanes before the next group dispatch.
"""
from __future__ import annotations

from magicsoup_tpu.stepper import PipelinedStepper

__all__ = ["FleetLane"]


class FleetLane(PipelinedStepper):
    """A :class:`PipelinedStepper` whose device state is a slot of a
    fleet group's stacked arrays.  Constructed by
    :meth:`~magicsoup_tpu.fleet.scheduler.FleetScheduler.admit`; after
    :meth:`~magicsoup_tpu.fleet.scheduler.FleetScheduler.retire` it is a
    plain standalone stepper again."""

    def __init__(self, world, **kwargs):
        # set before super().__init__ — the constructor's _attach path
        # must see a detached lane
        self._fleet = None
        self._fleet_slot = None  # (group, slot index) while a member
        self._fleet_resident = False  # device truth lives in the stack
        # fused-dispatch context of the lane's LAST dispatch (set by the
        # scheduler's _dispatch_fused, cleared by _dispatch_group) —
        # rides every guard row so a sentinel/invariant trip names the
        # fused set it fired under
        self._fused_tags: dict = {}
        super().__init__(world, **kwargs)

    # ------------------------------------------------------------ #
    # checkout boundary                                            #
    # ------------------------------------------------------------ #

    def _checkout(self) -> None:
        """Pull this lane's current slice out of the group stack so
        ``self._state`` / ``self.kin.params`` are the device truth
        again.  No-op when detached or already checked out."""
        if self._fleet is not None and self._fleet_resident:
            self._fleet._checkout(self)

    def flush(self) -> None:
        self._checkout()
        super().flush()

    def check_consistency(self) -> None:
        self._checkout()
        super().check_consistency()

    def _grow_tokens(self, n_prots: int, n_doms: int) -> None:
        # the resize pads kin.params in place — while resident that is a
        # STALE copy; pull the truth out of the stack first so the
        # padded tensor is the one the scheduler restacks
        if n_prots > self.kin.max_proteins or n_doms > self.kin.max_doms:
            self._checkout()
        super()._grow_tokens(n_prots, n_doms)

    def _apply_push_now(self, genomes, rows, seq) -> None:
        # standalone push programs scatter into kin.params directly —
        # that buffer must be the truth, not a stale pre-stack copy
        self._checkout()
        super()._apply_push_now(genomes, rows, seq)

    def step(self) -> None:
        if self._fleet is not None:
            raise RuntimeError(
                "lane is managed by a FleetScheduler — drive it with "
                "scheduler.step(), or retire() it for solo stepping"
            )
        super().step()

    # ------------------------------------------------------------ #
    # per-world guard routing                                      #
    # ------------------------------------------------------------ #

    def _guard_row_extra(self) -> dict:
        if self._fleet_slot is not None:
            group, slot = self._fleet_slot
            return {
                "fleet_slot": slot,
                "fleet_size": len(group.slots),
                **self._fused_tags,
            }
        return {}

    def _handle_sentinel(self, out) -> None:
        # with a warden attached, a trip is a WORLD-level event: record
        # it and let the scheduler evict/heal at the next step boundary
        # instead of raising through the shared commit loop (which
        # would take down the other B-1 worlds)
        w = self._fleet._warden if self._fleet is not None else None
        if w is not None and w.manages(self):
            w.report(self, "sentinel", out)
        else:
            super()._handle_sentinel(out)

    def _handle_invariant(self, out) -> None:
        w = self._fleet._warden if self._fleet is not None else None
        if w is not None and w.manages(self):
            w.report(self, "invariant", out)
        else:
            super()._handle_invariant(out)
