"""
graftwarden: per-world fault isolation and self-healing for the fleet.

graftguard's sentinel lanes and graftcheck's invariant lanes are packed
PER WORLD SLOT in the fleet step record (the scanned solo body computes
them per member — zero extra D2H, no device-program change), and every
lane already decodes its own slice during replay.  What was missing is
world-level POLICY: a solo stepper's ``sentinel_policy`` either warns
or raises THROUGH the scheduler's shared commit loop, so one tenant's
NaN took down all B worlds.  :class:`FleetWarden` closes that gap
(ROADMAP item 3, "one tenant's NaN must never take down the fleet"):

- ``warn`` — per-world telemetry ``sentinel``/``invariant`` rows tagged
  ``fleet_slot``; nothing raises, trips are counted per lane.
- ``quarantine`` — the poisoned world is EVICTED from its rung group at
  the next ``scheduler.step()`` (its slot restacks to zeros — pure data
  movement, no new shapes) and parked as a standalone stepper.  The
  other B-1 worlds keep stepping, their det-mode trajectories
  BIT-identical to an unpoisoned run of the same schedule (pinned in
  tests/fast/test_fleet_warden.py).
- ``heal`` — quarantine, then auto-rollback from the world's rolling
  per-world checkpoint stream and re-admit through the existing
  zero-compile warm-rung path, under a bounded restart budget with
  exponential backoff that circuit-breaks to parked after
  ``max_restarts`` trips.

The stream half is ROADMAP gap 3b: each world gets its own
:class:`~magicsoup_tpu.guard.CheckpointManager` cadence (prefix-scoped
files sharing one directory, atomic verified MSCK writes), so data loss
is bounded PER TENANT instead of per fleet.  A cadence save is a lane
flush, which is itself part of the deterministic schedule — compare
warden-armed runs against baselines running the SAME cadence.

Failure-latency note: with pipeline lag L and megastep K, a poison
lands in the record of the dispatch that integrated it and is decoded
up to L dispatches later; eviction happens at the next ``step()`` after
the replay that tripped.  The quarantine window is therefore
O((L+1) * K) steps — the healthy worlds never see any of it.

Cross-rung fusion note: under ``FleetScheduler(fusion="fleet"|"auto")``
several rung groups share one fused launch and one envelope fetch, but
each lane still replays its NATIVE ``(k, record)`` slice (cropped out
of the shared buffer before replay), so the per-slot sentinel /
invariant flag views, the trip counters, and the eviction contract are
unchanged — and warden telemetry rows inherit the lane's
``fused_groups`` / ``envelope`` dispatch context, so a trip can be
correlated with the fused launch that carried it.
"""
from __future__ import annotations

import warnings
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

from magicsoup_tpu.guard import chaos as _chaos
from magicsoup_tpu.guard.backoff import BackoffPolicy
from magicsoup_tpu.guard.checkpoint import CheckpointManager
from magicsoup_tpu.guard.errors import CheckpointError, GuardConfigError

__all__ = ["WARDEN_POLICIES", "FleetWarden", "WardenStatus"]

WARDEN_POLICIES = ("warn", "quarantine", "heal")


@dataclass
class WardenStatus:
    """Typed per-world health status (:meth:`FleetWarden.statuses`).

    ``status`` is one of ``active`` (stepping in the fleet), ``tripped``
    (flagged, eviction pending at the next scheduler step), ``cooldown``
    (evicted, heal scheduled at ``cooldown_until``), ``parked``
    (evicted for good: quarantine policy, no loadable checkpoint, or
    the circuit breaker — see ``reason``), ``suspended`` (checked out by
    the serve layer's budget pause — :meth:`FleetWarden.suspend` — and
    re-joinable via :meth:`FleetWarden.resume`), ``retired`` (the caller
    retired it manually; the warden no longer tracks it)."""

    label: int
    status: str
    trips: int
    restarts: int
    last_flags: int
    cooldown_until: int | None = None
    reason: str | None = None
    # graceful-degradation accounting: cadence saves that failed and
    # were SKIPPED (the run kept stepping), and whether the stream is
    # currently in its degraded state (consecutive failures > 0)
    save_skips: int = 0
    save_degraded: bool = False


@dataclass
class _WorldRecord:
    """Warden-side bookkeeping for one admitted world."""

    label: int
    lane: Any
    kwargs: dict
    stream: CheckpointManager | None = None
    status: str = "active"
    trips: int = 0
    restarts: int = 0
    last_flags: int = 0
    last_kind: str = ""
    cooldown_until: int | None = None
    reason: str | None = None
    save_skips: int = 0
    save_degraded: bool = False
    extra: dict = field(default_factory=dict)


class FleetWarden:
    """World-level health policy for a
    :class:`~magicsoup_tpu.fleet.FleetScheduler`.

    Attaching a warden re-routes every member lane's sentinel/invariant
    trip handling (the per-slot flag words of the shared fleet fetch)
    away from the solo ``sentinel_policy`` machinery — trips NEVER
    raise through the scheduler's commit loop; they mark the single
    affected world and the policy runs at the next ``scheduler.step()``
    boundary.

    Parameters:
        scheduler: The fleet to guard; ``scheduler._warden`` is bound
            here and every current and future lane is tracked.
        policy: ``warn`` | ``quarantine`` | ``heal`` (see module docs).
        checkpoint_dir: Directory for the per-world rolling checkpoint
            streams (``world-<label>-<step>.msck``; several streams
            share the directory via prefix scoping).  Required for
            ``heal``.
        cadence: Save each ACTIVE world's stream every ``cadence``
            scheduler steps (a lane flush — part of the det schedule).
            ``0`` disables cadence saves.  ``heal`` requires ``>= 1``.
        keep: Rolling retention per world stream.
        max_restarts: Heal budget per world; the breaker parks the
            world when a trip arrives with the budget exhausted.
        backoff_base: Cooldown before the n-th heal is
            ``backoff_base * 2**n`` scheduler steps (the shared
            :class:`~magicsoup_tpu.guard.backoff.BackoffPolicy` ladder).
        audit_on_heal: Run the graftcheck deep audit on the restored
            world before re-admission (an audit failure walks back is
            NOT attempted — the world parks with the typed reason).
        max_save_failures: The graceful-degradation budget for cadence
            checkpoint saves: a failed save (ENOSPC, EIO — the atomic
            protocol guarantees no torn file) is SKIPPED with a warning
            + counter and retried next cadence; only this many
            CONSECUTIVE failures raise the typed
            :class:`CheckpointError` (``check="degraded"``).  A later
            successful save resets the ladder and clears the degraded
            state.
    """

    def __init__(
        self,
        scheduler,
        *,
        policy: str = "warn",
        checkpoint_dir=None,
        cadence: int = 0,
        keep: int = 3,
        max_restarts: int = 3,
        backoff_base: int = 1,
        audit_on_heal: bool = False,
        max_save_failures: int = 5,
    ):
        if policy not in WARDEN_POLICIES:
            raise GuardConfigError(
                f"warden policy must be one of {WARDEN_POLICIES}, "
                f"got {policy!r}",
                variable="policy",
                value=str(policy),
            )
        if cadence < 0:
            raise GuardConfigError(
                "cadence must be >= 0",
                variable="cadence",
                value=str(cadence),
            )
        if policy == "heal":
            if checkpoint_dir is None:
                raise GuardConfigError(
                    "policy='heal' needs checkpoint_dir: healing rolls "
                    "back from the per-world stream",
                    variable="checkpoint_dir",
                    value="None",
                )
            if cadence < 1:
                raise GuardConfigError(
                    "policy='heal' needs cadence >= 1: a stream nobody "
                    "writes to cannot heal anything",
                    variable="cadence",
                    value=str(cadence),
                )
        if getattr(scheduler, "_warden", None) is not None:
            raise GuardConfigError(
                "scheduler already has a FleetWarden attached",
                variable="scheduler",
                value=repr(scheduler._warden),
            )
        if max_save_failures < 1:
            raise GuardConfigError(
                "max_save_failures must be >= 1",
                variable="max_save_failures",
                value=str(max_save_failures),
            )
        self.scheduler = scheduler
        self.policy = policy
        self.cadence = int(cadence)
        self.keep = int(keep)
        self.max_restarts = int(max_restarts)
        self.backoff_base = int(backoff_base)
        self.audit_on_heal = bool(audit_on_heal)
        self.max_save_failures = int(max_save_failures)
        # restart-cooldown ladder: delay(n) = backoff_base * 2**(n-1),
        # the exact schedule the old inline `backoff_base << restarts`
        # produced, now shared with guard.retry and the serve edge
        self._restart_backoff = BackoffPolicy(
            base=float(backoff_base), factor=2.0
        )
        self._dir = Path(checkpoint_dir) if checkpoint_dir is not None else None
        self._records: list[_WorldRecord] = []
        self._by_lane: dict[int, _WorldRecord] = {}
        self._next_label = 0
        self._steps = 0  # scheduler.step() calls seen (cadence clock)
        self._adopting: _WorldRecord | None = None
        self._evicting = None
        scheduler._warden = self
        for lane in scheduler.lanes:
            self._on_admit(lane)

    # ------------------------------------------------------------ #
    # membership tracking (called by the scheduler)                #
    # ------------------------------------------------------------ #

    def _on_admit(self, lane) -> None:
        if self._adopting is not None:
            # heal re-admission: the new lane IS the old world
            rec = self._adopting
            rec.lane = lane
        else:
            rec = _WorldRecord(
                label=self._next_label,
                lane=lane,
                kwargs=dict(getattr(lane, "_admit_kwargs", {})),
            )
            self._next_label += 1
            if self._dir is not None:
                rec.stream = CheckpointManager(
                    self._dir,
                    keep=self.keep,
                    prefix=f"world-{rec.label:03d}",
                )
            self._records.append(rec)
        self._by_lane[id(lane)] = rec

    def _on_retire(self, lane) -> None:
        rec = self._by_lane.pop(id(lane), None)
        if rec is None or lane is self._evicting:
            return  # unknown lane, or our own eviction (status set there)
        rec.status = "retired"
        rec.lane = None

    def manages(self, lane) -> bool:
        """Whether ``lane``'s trips are routed through this warden."""
        return id(lane) in self._by_lane

    # ------------------------------------------------------------ #
    # serve support (tenant lifecycle — magicsoup_tpu.serve)       #
    # ------------------------------------------------------------ #

    def suspend(self, lane):
        """Retire ``lane`` from the scheduler while KEEPING its warden
        record (label, rolling stream, trip/restart counts) — the serve
        layer's budget pause.  Returns the lane (a standalone stepper
        again); :meth:`resume` re-joins the SAME lane object, so the
        round trip is invisible to the world's trajectory."""
        rec = self._by_lane.get(id(lane))
        if rec is None:
            raise KeyError("warden does not track this lane")
        self._evicting = lane
        try:
            self.scheduler.retire(lane)
        finally:
            self._evicting = None
        rec.status = "suspended"
        return lane

    def resume(self, lane):
        """Re-join a lane parked by :meth:`suspend` — same object, no
        state rebuild (``scheduler.readmit``)."""
        rec = next(
            (
                r
                for r in self._records
                if r.lane is lane and r.status == "suspended"
            ),
            None,
        )
        if rec is None:
            raise KeyError("lane is not suspended by this warden")
        self._adopting = rec
        try:
            self.scheduler.readmit(lane)
        finally:
            self._adopting = None
        rec.status = "active"
        return lane

    def adopt(self, world, *, label: int, **stepper_kwargs):
        """Admit ``world`` under a FORCED label — service restart
        recovery: a tenant restored from its rolling stream must keep
        appending to the same ``world-<label>`` prefix.  Creates (or
        reuses) the record for ``label`` and bumps the label allocator
        past it so later admissions never collide."""
        label = int(label)
        rec = next((r for r in self._records if r.label == label), None)
        if rec is None:
            rec = _WorldRecord(
                label=label, lane=None, kwargs=dict(stepper_kwargs)
            )
            if self._dir is not None:
                rec.stream = CheckpointManager(
                    self._dir,
                    keep=self.keep,
                    prefix=f"world-{rec.label:03d}",
                )
            self._records.append(rec)
        self._next_label = max(self._next_label, label + 1)
        rec.kwargs = dict(stepper_kwargs)
        self._adopting = rec
        try:
            lane = self.scheduler.admit(world, **stepper_kwargs)
        finally:
            self._adopting = None
        rec.status = "active"
        return lane

    def reserve_label(self, label: int) -> None:
        """Keep ``label`` (and everything below it) out of the
        allocator's future assignments WITHOUT creating a record —
        service recovery reserves the labels of registered tenants it
        could NOT restore, so a later admission never reuses a lost
        tenant's ``world-<label>`` stream prefix (rolling retention on
        a reused prefix would rotate the lost tenant's surviving
        checkpoints out of existence)."""
        self._next_label = max(self._next_label, int(label) + 1)

    def label_of(self, lane) -> int:
        """The stable world label behind ``lane`` (stream prefix id)."""
        rec = self._by_lane.get(id(lane))
        if rec is None:
            raise KeyError("warden does not track this lane")
        return rec.label

    def stream_of(self, lane_or_label):
        """The per-world rolling checkpoint stream (by lane object or
        integer label); ``None`` when the warden has no checkpoint_dir."""
        for rec in self._records:
            if rec.lane is lane_or_label or rec.label == lane_or_label:
                return rec.stream
        raise KeyError(f"warden does not track {lane_or_label!r}")

    # ------------------------------------------------------------ #
    # trip intake (called from FleetLane replay — never raises)    #
    # ------------------------------------------------------------ #

    def report(self, lane, kind: str, out) -> None:
        """Record one tripped flag word for ``lane`` — the per-world
        reaction to the per-slot sentinel/invariant lanes.  Emits the
        same telemetry row the solo handler would (plus ``fleet_slot``
        / ``world`` tags) and, under quarantine/heal, marks the world
        for eviction at the next scheduler step.  NEVER raises: the
        whole point is that one world's poison must not unwind the
        shared commit loop under the other B-1 worlds."""
        rec = self._by_lane.get(id(lane))
        if rec is None:
            return
        step = lane.stats["replayed"]
        if kind == "sentinel":
            from magicsoup_tpu.guard.sentinel import decode_health

            flags_int = int(out.health)
            flags = decode_health(out.health)
            lane.stats["sentinel_trips"] += 1
            row = {
                "type": "sentinel",
                "step": step,
                "flags": flags_int,
                "n_bad_cells": (
                    int(out.bad_cells.sum())
                    if out.bad_cells is not None
                    else 0
                ),
            }
        else:
            from magicsoup_tpu.check.invariants import decode_invariants

            flags_int = int(out.invariants)
            flags = decode_invariants(out.invariants)
            lane.stats["invariant_trips"] += 1
            row = {
                "type": "invariant",
                "step": step,
                "flags": flags_int,
                "mass_drift": float(out.mass_drift),
            }
        row.update(flags)
        row["policy"] = f"warden-{self.policy}"
        row["world"] = rec.label
        row.update(lane._guard_row_extra())
        if lane.telemetry.attached:
            lane.telemetry.emit(row)
        rec.trips += 1
        rec.last_flags = flags_int
        rec.last_kind = kind
        if self.policy != "warn" and rec.status == "active":
            rec.status = "tripped"

    # ------------------------------------------------------------ #
    # policy (called by the scheduler at the top of step())        #
    # ------------------------------------------------------------ #

    def pending_policy(self) -> bool:
        """Whether a policy action (eviction of a tripped world, heal of
        a cooled-down one) is waiting for the next step boundary.  The
        serve loop checks this when NO tenant is runnable: a sole
        tripped tenant must still reach its terminal state even though
        ``scheduler.step()`` (the usual :meth:`before_step` driver)
        never runs."""
        return any(
            rec.status in ("tripped", "cooldown") for rec in self._records
        )

    def before_step(self) -> None:
        """One warden tick: evict tripped worlds, heal cooled-down
        ones, run cadence saves.  Runs BEFORE the scheduler prepares
        any dispatch, so membership is settled for this step."""
        step = self._steps
        for rec in self._records:
            if rec.status == "tripped":
                self._evict(rec, step)
        for rec in self._records:
            if (
                rec.status == "cooldown"
                and rec.cooldown_until is not None
                and step >= rec.cooldown_until
            ):
                self._heal(rec, step)
        if self.cadence:
            from magicsoup_tpu.guard.resume import save_run

            for rec in self._records:
                if (
                    rec.status == "active"
                    and rec.stream is not None
                    and step % self.cadence == 0
                ):
                    try:
                        save_run(
                            rec.stream,
                            rec.lane.world,
                            rec.lane,
                            step=step,
                            meta={"world": rec.label},
                        )
                    except OSError as exc:
                        self._save_failed(rec, step, exc)
                    else:
                        self._save_recovered(rec, step)
        self._steps += 1

    # ------------------------------------------------------------ #
    # cadence-save graceful degradation                            #
    # ------------------------------------------------------------ #

    def _save_failed(self, rec: _WorldRecord, step: int, exc: OSError) -> None:
        """One cadence save failed: the run does NOT die.  The skip is
        counted (record + chaos registry + telemetry row), warned once
        per degradation episode, and retried next cadence; only
        ``max_save_failures`` CONSECUTIVE failures escalate to the
        typed error — at that point data loss is unbounded and silence
        would be lying."""
        rec.save_skips += 1
        consecutive = (
            rec.stream.consecutive_save_failures if rec.stream else 1
        )
        subsystem = f"warden.checkpoint.world-{rec.label:03d}"
        _chaos.note_degraded(subsystem, f"{type(exc).__name__}: {exc}")
        _chaos.note_counter("warden_save_skips")
        self._emit(
            rec,
            rec.lane,
            "save_degraded",
            step,
            error=f"{type(exc).__name__}: {exc}",
            save_skips=rec.save_skips,
            consecutive=consecutive,
        )
        if not rec.save_degraded:
            rec.save_degraded = True
            warnings.warn(
                f"cadence checkpoint save for world {rec.label} failed "
                f"({exc}); skipped and counted — retrying next cadence "
                f"(typed error after {self.max_save_failures} consecutive "
                "failures)"
            )
        if consecutive >= self.max_save_failures:
            raise CheckpointError(
                f"cadence checkpoint stream for world {rec.label} is "
                f"degraded: {consecutive} consecutive save failures "
                f"(last: {exc}) exhausted the budget of "
                f"{self.max_save_failures}",
                check="degraded",
                path=rec.stream.directory if rec.stream else None,
            ) from exc

    def _save_recovered(self, rec: _WorldRecord, step: int) -> None:
        if not rec.save_degraded:
            return
        rec.save_degraded = False
        subsystem = f"warden.checkpoint.world-{rec.label:03d}"
        _chaos.clear_degraded(subsystem)
        self._emit(
            rec, rec.lane, "save_recovered", step, save_skips=rec.save_skips
        )

    def _evict(self, rec: _WorldRecord, step: int) -> None:
        lane = rec.lane
        self._evicting = lane
        try:
            self.scheduler.retire(lane)
        finally:
            self._evicting = None
        if (
            self.policy == "heal"
            and rec.stream is not None
            and rec.restarts < self.max_restarts
        ):
            rec.status = "cooldown"
            rec.cooldown_until = step + int(
                self._restart_backoff.delay(rec.restarts + 1)
            )
            self._emit(
                rec,
                lane,
                "quarantine",
                step,
                cooldown_until=rec.cooldown_until,
            )
        else:
            rec.status = "parked"
            rec.cooldown_until = None
            if self.policy == "heal" and rec.restarts >= self.max_restarts:
                rec.reason = (
                    f"circuit breaker: {rec.restarts} restarts exhausted "
                    f"the budget of {self.max_restarts}"
                )
                self._emit(rec, lane, "quarantine", step)
                self._emit(rec, lane, "circuit_break", step)
            else:
                rec.reason = f"quarantined on {rec.last_kind} trip"
                self._emit(rec, lane, "quarantine", step)

    def _heal(self, rec: _WorldRecord, step: int) -> None:
        from magicsoup_tpu.check import AuditFailed
        from magicsoup_tpu.guard.resume import restore_run, restore_stepper

        old_lane = rec.lane
        try:
            world, aux, meta = restore_run(
                rec.stream, audit=self.audit_on_heal
            )
        except (CheckpointError, AuditFailed) as exc:
            rec.status = "parked"
            rec.cooldown_until = None
            rec.reason = f"heal failed: {exc}"
            self._emit(rec, old_lane, "heal_failed", step, error=str(exc))
            return
        self._adopting = rec
        try:
            lane = self.scheduler.admit(world, **rec.kwargs)
        finally:
            self._adopting = None
        restore_stepper(lane, aux)
        rec.status = "active"
        rec.restarts += 1
        rec.cooldown_until = None
        rec.reason = None
        # the fresh lane starts with an unattached recorder; fall back
        # to the parked lane's so the heal event lands in the same
        # stream as the quarantine it resolves
        emit_lane = lane if lane.telemetry.attached else old_lane
        self._emit(
            rec,
            emit_lane,
            "heal",
            step,
            restarts=rec.restarts,
            checkpoint_step=meta.get("step"),
        )

    def _emit(self, rec, lane, event: str, step: int, **extra) -> None:
        if lane is None or not lane.telemetry.attached:
            return
        lane.telemetry.emit(
            {
                "type": "warden",
                "event": event,
                "step": step,
                "world": rec.label,
                "policy": self.policy,
                **extra,
            }
        )

    # ------------------------------------------------------------ #
    # inspection                                                   #
    # ------------------------------------------------------------ #

    def statuses(self) -> list[WardenStatus]:
        """Typed status of every world the warden has ever tracked."""
        return [
            WardenStatus(
                label=rec.label,
                status=rec.status,
                trips=rec.trips,
                restarts=rec.restarts,
                last_flags=rec.last_flags,
                cooldown_until=rec.cooldown_until,
                reason=rec.reason,
                save_skips=rec.save_skips,
                save_degraded=rec.save_degraded,
            )
            for rec in self._records
        ]

    def status_of(self, lane_or_label) -> WardenStatus:
        """Status for one world, by lane object or integer label."""
        for rec in self._records:
            if (
                rec.lane is lane_or_label
                or rec.label == lane_or_label
            ):
                return WardenStatus(
                    label=rec.label,
                    status=rec.status,
                    trips=rec.trips,
                    restarts=rec.restarts,
                    last_flags=rec.last_flags,
                    cooldown_until=rec.cooldown_until,
                    reason=rec.reason,
                    save_skips=rec.save_skips,
                    save_degraded=rec.save_degraded,
                )
        raise KeyError(f"warden does not track {lane_or_label!r}")

    def parked(self) -> list:
        """The evicted-for-good lanes (standalone steppers again, state
        intact as of eviction) — inspect, flush, or re-``admit`` them
        manually."""
        return [
            rec.lane
            for rec in self._records
            if rec.status == "parked" and rec.lane is not None
        ]
