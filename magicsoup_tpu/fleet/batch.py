"""
graftfleet device program: B independent worlds, ONE compiled program.

The fleet stacks every per-world input of the fused megastep
(:func:`magicsoup_tpu.stepper._megastep`) on a leading **world axis**
and runs a ``lax.scan`` over that axis — each scan iteration steps one
world's slice through the exact solo step body, so a world inside a
fleet computes bit-for-bit what it would compute alone (the det-mode
bit-identity tests pin this).  One dispatch advances all B worlds by
``k`` fused steps; the batched ``(B, k, record)`` output is fetched
ONCE per megastep for the whole fleet and sliced per world on the host
(the one-fetch-per-megastep-per-fleet contract).

Compaction inside the fleet is a TRACED per-world decision, not the
solo path's static variant flag: every world computes both the
compacted and uncompacted next state and selects per leaf with its
``do_compact`` lane (same op sequence as the solo static-compact
branch, so the selected values are bitwise identical — only record
header word 3, the post-step row count, needs a select; word 4 is a
permutation-invariant alive count).  Paying the sort every step buys
the property that makes dynamic admission cheap: a fleet group has
exactly ONE compiled variant per shape, so admitting a world into a
warm capacity rung compiles nothing.

Inactive slots (retired, or not yet admitted) hold all-zero state and
parameters.  A zero slot is an exact no-op through every phase: the
``alive`` mask is all-False (no chemistry/kill/divide writes land), the
spawn-valid lane is all-False, push rows are zero-padded scatters into
dead rows, and the zero PRNG key is a valid key that is never consumed
into live state.
"""
from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from magicsoup_tpu.ops.params import compact_rows, permute_params
from magicsoup_tpu.stepper import DeviceState, _donate_step_buffers, _megastep

__all__ = [
    "FleetConsts",
    "extract_world",
    "fleet_step",
    "fleet_step_program",
    "fused_fleet_step",
    "fused_step_program",
    "insert_world",
    "lane_consts",
    "stack_worlds",
    "zeros_world_like",
]


class FleetConsts(NamedTuple):
    """Per-world constant inputs of the fused step, in stacking order.

    One leading world axis over everything the solo dispatch passes as
    loose positional constants — keeping them in one pytree lets the
    scheduler restack membership changes with a single (warm) program.
    """

    kernels: Any
    perm_factors: Any
    degrad_factors: Any
    mol_idx: Any
    kill_below: Any
    divide_above: Any
    divide_cost: Any
    tables: Any
    abs_temp: Any


def lane_consts(stepper) -> FleetConsts:
    """One lane's per-world constants (unstacked) in fleet order."""
    return FleetConsts(
        kernels=stepper._kernels_dev,
        perm_factors=stepper._perm_dev,
        degrad_factors=stepper._degrad_dev,
        mol_idx=stepper._mol_idx_dev,
        kill_below=stepper._kill_below_dev,
        divide_above=stepper._divide_above_dev,
        divide_cost=stepper._divide_cost_dev,
        tables=stepper._tables(),
        abs_temp=stepper._abs_temp_dev,
    )


def fleet_step_program(
    fstate: DeviceState,
    fparams: Any,
    consts: FleetConsts,
    spawn_dense: jax.Array,
    spawn_valid: jax.Array,
    push_dense: jax.Array,
    push_rows: jax.Array,
    div_budget: jax.Array,
    do_compact: jax.Array,
    *,
    det: bool,
    max_div: int,
    n_rounds: int,
    k: int,
    integrator: str,
) -> tuple[DeviceState, Any, jax.Array]:
    """The raw (unjitted) fleet program: scan the solo megastep over the
    world axis, then apply each world's traced maybe-compact.

    Every argument carries a leading world axis; ``div_budget`` is
    ``(B,)`` i32 and ``do_compact`` ``(B,)`` bool.  Returns the stacked
    next state/params and the ``(B, k, record)`` packed step records.
    """
    cap = fstate.cm.shape[1]
    rows = jnp.arange(cap, dtype=jnp.int32)

    def body(_, wxs):
        state, params, c, sd, sv, pd, pr, db, do = wxs
        state, params, outs = _megastep.__wrapped__(
            state,
            params,
            c.kernels,
            c.perm_factors,
            c.degrad_factors,
            c.mol_idx,
            c.kill_below,
            c.divide_above,
            c.divide_cost,
            db,
            sd,
            sv,
            pd,
            pr,
            c.tables,
            c.abs_temp,
            det=det,
            max_div=max_div,
            n_rounds=n_rounds,
            compact=False,
            q=cap,
            integrator=integrator,
            k=k,
            mesh=None,
        )
        # traced per-world maybe-compact: the solo static-compact
        # branch's exact op sequence, computed unconditionally and
        # selected per leaf — so the selected values are bitwise what
        # the solo compact variant produces
        perm = jnp.argsort(~state.alive, stable=True).astype(jnp.int32)
        n_keep = state.alive.sum(dtype=jnp.int32)
        cm2 = compact_rows(state.cm, perm, n_keep)
        pos2 = compact_rows(state.pos, perm, n_keep)
        params2 = permute_params(params, perm, n_keep)
        alive2 = rows < n_keep

        def sel(a, b):
            return jnp.where(do, a, b)

        state = DeviceState(
            mm=state.mm,
            cm=sel(cm2, state.cm),
            pos=sel(pos2, state.pos),
            occ=state.occ,
            alive=sel(alive2, state.alive),
            n_rows=sel(n_keep, state.n_rows),
            key=state.key,
        )
        params = jax.tree_util.tree_map(sel, params2, params)
        # record fixup: only header word 3 (post-step row count) of the
        # final record depends on the compact decision — word 4 (alive
        # count) is permutation-invariant and needs no select
        outs = outs.at[-1, 3].set(jnp.where(do, n_keep, outs[-1, 3]))
        return _, (state, params, outs)

    _, (fstate, fparams, fouts) = jax.lax.scan(
        body,
        0,
        (
            fstate,
            fparams,
            consts,
            spawn_dense,
            spawn_valid,
            push_dense,
            push_rows,
            div_budget,
            do_compact,
        ),
    )
    return fstate, fparams, fouts


_STATICS = ("det", "max_div", "n_rounds", "k", "integrator")

_fleet_step_donated = functools.partial(
    jax.jit, static_argnames=_STATICS, donate_argnums=(0, 1)
)(fleet_step_program)

_fleet_step_retained = functools.partial(  # graftlint: disable=GL006 CPU twin of the fleet step; donation races XLA:CPU async execution
    jax.jit, static_argnames=_STATICS
)(fleet_step_program)


def fleet_step(*args, **statics):
    """Dispatch one fleet megastep through the backend-appropriate jit
    twin (donated on accelerators, retained on XLA:CPU — same split as
    the solo ``_megastep``/``_megastep_retained`` pair)."""
    fn = _fleet_step_donated if _donate_step_buffers() else _fleet_step_retained
    return fn(*args, **statics)


# ------------------------------------------------------------------ #
# cross-rung fused dispatch                                          #
# ------------------------------------------------------------------ #


def fused_step_program(states, params, rest, *, statics, k_env, rec_env):
    """The raw (unjitted) CROSS-RUNG fused program: one device launch
    advancing EVERY rung group of a fleet by one megastep.

    Each rung runs :func:`fleet_step_program` — the exact per-group
    body, at its NATIVE shapes and statics — inside one jit, so every
    world's arithmetic (including its PRNG consumption, which is
    shape-sensitive under threefry counter pairing) is bitwise what the
    per-rung dispatch computes.  The capacity envelope applies ONLY to
    the packed step records: each rung's ``(B_r, k_r, L_r)`` output is
    zero-padded to the grow-only ``(k_env, rec_env)`` envelope and the
    rungs are concatenated on the world axis, so the whole fleet's
    records come back in ONE ``(sum B_r, k_env, rec_env)`` buffer = ONE
    physical D2H fetch per megastep (the host crops each lane's native
    ``(k_r, L_r)`` view back out — ``stepper.crop_fused_record``).

    ``states`` / ``params`` are tuples of per-rung stacked pytrees (in
    planner order; donated as one buffer set), ``rest`` is a matching
    tuple of per-rung ``(consts, spawn_dense, spawn_valid, push_dense,
    push_rows, div_budget, do_compact)`` — NOT donated, because the
    consts and the cached empty spawn/push uploads are reused across
    megasteps.  ``statics`` is a hashable tuple of per-rung
    ``(det, max_div, n_rounds, k, integrator)`` tuples.
    """
    new_states, new_params, outs = [], [], []
    for i, (det, max_div, n_rounds, k, integrator) in enumerate(statics):
        consts, sd, sv, pd, pr, db, do = rest[i]
        fs, fp, fo = fleet_step_program(
            states[i],
            params[i],
            consts,
            sd,
            sv,
            pd,
            pr,
            db,
            do,
            det=det,
            max_div=max_div,
            n_rounds=n_rounds,
            k=k,
            integrator=integrator,
        )
        fo = jnp.pad(
            fo,
            ((0, 0), (0, k_env - fo.shape[1]), (0, rec_env - fo.shape[2])),
        )
        new_states.append(fs)
        new_params.append(fp)
        outs.append(fo)
    return tuple(new_states), tuple(new_params), jnp.concatenate(outs, axis=0)


_FUSED_STATICS = ("statics", "k_env", "rec_env")

_fused_step_donated = functools.partial(
    jax.jit, static_argnames=_FUSED_STATICS, donate_argnums=(0, 1)
)(fused_step_program)

_fused_step_retained = functools.partial(  # graftlint: disable=GL006 CPU twin of the fused step; donation races XLA:CPU async execution
    jax.jit, static_argnames=_FUSED_STATICS
)(fused_step_program)


def fused_fleet_step(states, params, rest, **statics):
    """Dispatch one fused fleet megastep (every rung group in ONE
    program launch) through the backend-appropriate jit twin — same
    donated/retained split as :func:`fleet_step`."""
    fn = _fused_step_donated if _donate_step_buffers() else _fused_step_retained
    return fn(states, params, rest, **statics)


# ------------------------------------------------------------------ #
# world-axis stacking helpers                                        #
# ------------------------------------------------------------------ #
# All three are jitted with ARRAY slot indices so the compiled program
# is shared across slots: a python-int index would bake into the jaxpr
# and give every slot its own compile, defeating the zero-compile
# admission contract.


@jax.jit
def _stack(*trees):
    return jax.tree_util.tree_map(lambda *ts: jnp.stack(ts), *trees)


def stack_worlds(trees):
    """Stack per-world pytrees into one batched pytree (leading B axis)."""
    return _stack(*trees)


@jax.jit
def _extract(tree, idx):
    return jax.tree_util.tree_map(
        lambda t: jax.lax.dynamic_index_in_dim(t, idx, axis=0, keepdims=False),
        tree,
    )


def extract_world(tree, slot: int):
    """One world's slice out of a batched pytree (checkout path)."""
    return _extract(tree, jnp.asarray(slot, jnp.int32))


@jax.jit
def _insert(tree, sub, idx):
    return jax.tree_util.tree_map(
        lambda t, s: jax.lax.dynamic_update_slice_in_dim(
            t, s[None], idx, axis=0
        ),
        tree,
        sub,
    )


def insert_world(tree, slot: int, sub):
    """Write one world's pytree into slot ``slot`` of a batched pytree."""
    return _insert(tree, sub, jnp.asarray(slot, jnp.int32))


def zeros_world_like(tree):
    """All-zero single-world pytree — the inactive-slot filler (an exact
    no-op through every step phase; see module docstring)."""
    return jax.tree_util.tree_map(jnp.zeros_like, tree)
