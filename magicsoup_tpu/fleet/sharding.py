"""
World-axis sharding: data-parallel fleets over a device mesh.

Worlds are independent, so the fleet's world axis shards with NO
collectives: a 1D ``P("world")`` mesh gives every device its own
contiguous block of worlds, each block stepped by the same local scan
the single-device fleet program runs (``shard_map`` over
:func:`magicsoup_tpu.fleet.batch.fleet_step_program`).  This composes
with, but is distinct from, the cell/row sharding of
:mod:`magicsoup_tpu.parallel.tiled` — a fleet world must itself be
single-device (the scheduler enforces it); scale-out for fleets is MORE
WORLDS PER MESH, not bigger worlds.

In det mode the sharded fleet step is bit-identical to the unsharded
one (pinned in tests/fast/test_fleet.py): no cross-world reduction
exists anywhere in the program, so placement cannot reorder any float
work.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

try:  # pragma: no cover - version-dependent import
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map
from jax.experimental import enable_x64 as _enable_x64
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from magicsoup_tpu.fleet.batch import _donate_step_buffers, fleet_step_program

__all__ = [
    "WORLD_AXIS",
    "make_world_mesh",
    "shard_fleet",
    "sharded_fleet_step",
]

WORLD_AXIS = "world"


def make_world_mesh(n_devices: int | None = None) -> Mesh:
    """1D device mesh over the fleet's world axis."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (WORLD_AXIS,))


def world_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis ``P("world")`` placement for every stacked leaf."""
    return NamedSharding(mesh, P(WORLD_AXIS))


def shard_fleet(tree, mesh: Mesh):
    """Place a stacked fleet pytree world-sharded on ``mesh`` (the
    leading axis of every leaf must be divisible by the device count)."""
    sh = world_sharding(mesh)
    return jax.tree_util.tree_map(lambda t: jax.device_put(t, sh), tree)


@functools.lru_cache(maxsize=None)
def _build(mesh: Mesh, det, max_div, n_rounds, k, integrator, donate):
    spec = P(WORLD_AXIS)

    def body(*args):
        state, params, outs = fleet_step_program(
            *args,
            det=det,
            max_div=max_div,
            n_rounds=n_rounds,
            k=k,
            integrator=integrator,
        )
        # the x64 tracing scope below widens the packed record's counter
        # lanes to i64; values are identical (int arithmetic is exact),
        # so pin the wire dtype back to the solo record's
        return state, params, outs.astype(jnp.int32)

    mapped = shard_map(body, mesh=mesh, in_specs=spec, out_specs=spec)
    if donate:
        fn = jax.jit(mapped, donate_argnums=(0, 1))
    else:
        fn = jax.jit(mapped)  # graftlint: disable=GL006 CPU twin of the sharded fleet step; donation races XLA:CPU async execution

    @functools.wraps(fn)
    def call(*args):
        # trace AND lower inside the x64 scope: shard_map re-canonicalizes
        # body avals at lowering time, so det mode's f64 reduction trees
        # (detmath.sum_axis) produce inconsistent IR unless the scope is
        # still open — plain jit only canonicalizes literals (see the
        # traced_zeros32 notes); shard_map verifies the whole module
        with _enable_x64(True):
            return fn(*args)

    return call


def sharded_fleet_step(
    mesh: Mesh,
    *,
    det: bool,
    max_div: int,
    n_rounds: int,
    k: int,
    integrator: str = "xla-fast",
):
    """A jitted world-sharded fleet step for ``mesh`` with the given
    statics — same signature as the positional part of
    :func:`magicsoup_tpu.fleet.batch.fleet_step` (9 stacked inputs,
    world axis divisible by the mesh's device count).  Compiled
    programs are cached per (mesh, statics)."""
    return _build(
        mesh,
        bool(det),
        int(max_div),
        int(n_rounds),
        int(k),
        str(integrator),
        _donate_step_buffers(),
    )
