"""
Batch-aware guard checkpointing for fleets.

Two restore shapes, both riding the verified ``.msck`` container and
the single-run snapshot format of :mod:`magicsoup_tpu.guard.resume`:

- **whole fleet, atomically**: :func:`save_fleet` flushes every lane
  (one drain boundary for the whole fleet) and writes ONE checkpoint
  file nesting one run payload per world — a crash mid-save never
  leaves a half-fleet on disk (``guard.io.atomic_write_bytes``), and
  the chaos smoke SIGKILLs through it (``performance/smoke.py
  --chaos``).
- **one world out of a running fleet**: :func:`restore_world` extracts
  a single world's run payload and restores it as a standalone
  :class:`~magicsoup_tpu.World` + stepper aux — bit-identical to a solo
  checkpoint of that world (pinned in tests/fast/test_fleet_guard.py),
  because a lane's snapshot IS a solo ``snapshot_run`` (the flush
  checks the lane out of the stack first).
"""
from __future__ import annotations

from magicsoup_tpu.guard.checkpoint import (
    CheckpointManager,
    read_checkpoint,
    write_checkpoint,
)
from magicsoup_tpu.guard.errors import CheckpointError
from magicsoup_tpu.guard.resume import (
    restore_run_payload,
    restore_stepper,
    snapshot_run,
)

__all__ = [
    "FLEET_FORMAT",
    "restore_fleet",
    "restore_world",
    "save_fleet",
    "snapshot_fleet",
]

FLEET_FORMAT = "magicsoup_tpu.fleet.run/1"


def snapshot_fleet(scheduler) -> dict:
    """Flush every lane (checking them out of the group stacks) and
    capture one single-run payload per world."""
    runs = [snapshot_run(lane.world, lane) for lane in scheduler.lanes]
    return {"format": FLEET_FORMAT, "runs": runs}


def save_fleet(target, scheduler, *, step: int = 0, meta: dict | None = None):
    """Atomically write the whole fleet as ONE verified checkpoint.

    ``target`` is a :class:`~magicsoup_tpu.guard.CheckpointManager`
    (step-indexed rolling retention) or a path to a single ``.msck``
    file.  Returns the written path."""
    payload = snapshot_fleet(scheduler)
    meta = {
        **(meta or {}),
        "format": FLEET_FORMAT,
        "worlds": len(payload["runs"]),
    }
    if isinstance(target, CheckpointManager):
        return target.save(payload, step=step, meta=meta)
    return write_checkpoint(target, payload, meta=meta)


def _load(source) -> tuple[dict, dict]:
    if isinstance(source, CheckpointManager):
        payload, meta, _path = source.load_latest()
    else:
        payload, meta = read_checkpoint(source)
    if not isinstance(payload, dict) or payload.get("format") != FLEET_FORMAT:
        raise CheckpointError(
            f"checkpoint payload is not a {FLEET_FORMAT} fleet snapshot "
            f"(got {type(payload).__name__}"
            + (
                f" with format={payload.get('format')!r})"
                if isinstance(payload, dict)
                else ")"
            ),
            check="format",
        )
    return payload, meta


def restore_world(
    source, index: int = 0, *, audit: bool = False, genome_backend=None
) -> tuple:
    """Restore ONE world out of a fleet checkpoint as a standalone run;
    returns ``(world, stepper_aux, meta)`` exactly like
    :func:`magicsoup_tpu.guard.restore_run` — construct a stepper with
    the same kwargs and hand both to ``guard.restore_stepper`` (or keep
    driving it with the classic API).  ``genome_backend`` converts the
    restored world's genome storage (schema-1 string checkpoints resume
    on the token path with ``genome_backend="token"``)."""
    payload, meta = _load(source)
    runs = payload["runs"]
    if not -len(runs) <= index < len(runs):
        raise CheckpointError(
            f"fleet checkpoint holds {len(runs)} worlds; index {index} "
            "is out of range",
            check="index",
        )
    world, aux = restore_run_payload(
        runs[index], audit=audit, genome_backend=genome_backend
    )
    return world, aux, meta


def restore_fleet(
    source,
    scheduler,
    stepper_kwargs,
    *,
    audit: bool = False,
    genome_backend=None,
) -> tuple[list, dict]:
    """Rebuild every world of a fleet checkpoint into ``scheduler``.

    ``stepper_kwargs`` is the ctor kwargs dict each lane was originally
    built with (or a callable ``index -> kwargs`` when lanes differ) —
    the same same-kwargs contract as ``guard.restore_stepper``, which
    refuses on any trajectory-determining mismatch.  Returns the list
    of admitted lanes (in checkpoint order) and the checkpoint meta."""
    payload, meta = _load(source)
    lanes = []
    for i, run in enumerate(payload["runs"]):
        world, aux = restore_run_payload(
            run, audit=audit, genome_backend=genome_backend
        )
        kwargs = (
            stepper_kwargs(i)
            if callable(stepper_kwargs)
            else dict(stepper_kwargs)
        )
        lane = scheduler.admit(world, **kwargs)
        restore_stepper(lane, aux)
        lanes.append(lane)
    return lanes, meta
