"""
Bit-reproducibility check: CPU vs accelerator (the BASELINE.json north
star — "bit-reproducible vs CPU").

Runs the canonical benchmark workload (`performance/workload.py`) for N
seeded steps once on the CPU backend and once on whatever accelerator JAX
finds, hashing every piece of simulation state after every step, and
reports the first divergence (step + tensor).

All simulation randomness is host-side (numpy / python / C++ engine) and
seeded, so the two runs execute identical event sequences; any divergence
comes from device float semantics — reduction order, exp/log
implementations, FMA contraction.  Divergence at step k poisons selection
at step k+1, so only the FIRST divergent (step, tensor) is meaningful.

Usage:
    python scripts/bitrepro.py                     # parent: run + compare
    python scripts/bitrepro.py --child cpu         # internal
    python scripts/bitrepro.py --steps 20 --n-cells 500 --map-size 64

Exit code 0 = bit-identical, 1 = diverged, 2 = runner error.
Results are recorded in BITREPRO.md.
"""
import argparse
import hashlib
import json
import os
import subprocess
import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(_REPO))
sys.path.insert(0, str(_REPO / "performance"))


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--n-cells", type=int, default=500)
    ap.add_argument("--map-size", type=int, default=64)
    ap.add_argument("--genome-size", type=int, default=300)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--child", choices=["cpu", "accel"], default=None)
    ap.add_argument("--dump-step", type=int, default=None,
                    help="child: also save raw state arrays after this step")
    ap.add_argument("--dump-path", type=str, default=None)
    return ap


def _digest(arr) -> str:
    import numpy as np

    return hashlib.sha256(np.ascontiguousarray(arr).tobytes()).hexdigest()[:16]


def state_digests(world) -> dict[str, str]:
    """Hash every piece of simulation state, device and host"""
    import numpy as np

    n = world.n_cells
    out = {
        "molecule_map": _digest(np.asarray(world._molecule_map)),
        "cell_molecules": _digest(np.asarray(world._cell_molecules)[:n]),
        "positions": _digest(world.cell_positions),
        "lifetimes": _digest(world.cell_lifetimes),
        "divisions": _digest(world.cell_divisions),
        "genomes": hashlib.sha256(
            "\n".join(world.cell_genomes).encode()
        ).hexdigest()[:16],
    }
    for name in ("Ke", "Kmf", "Kmb", "Kmr", "Vmax", "N", "Nf", "Nb", "A"):
        t = getattr(world.kinetics.params, name)
        out[f"params.{name}"] = _digest(np.asarray(t)[:n])
    return out


def child_main(args: argparse.Namespace) -> None:
    import random

    import magicsoup_tpu as ms
    from magicsoup_tpu.examples.wood_ljungdahl import CHEMISTRY
    from workload import sim_step

    import jax

    rng = random.Random(args.seed)
    world = ms.World(chemistry=CHEMISTRY, map_size=args.map_size, seed=args.seed)
    atp = CHEMISTRY.molname_2_idx["ATP"]
    print(json.dumps({"platform": jax.default_backend()}))
    for step in range(args.steps):
        sim_step(
            world,
            rng,
            n_cells=args.n_cells,
            genome_size=args.genome_size,
            atp_idx=atp,
            sync=True,
        )
        print(json.dumps({"step": step, "n_cells": world.n_cells} | state_digests(world)))
        if args.dump_step == step and args.dump_path:
            import numpy as np

            n = world.n_cells
            arrays = {
                "molecule_map": np.asarray(world._molecule_map),
                "cell_molecules": np.asarray(world._cell_molecules)[:n],
            }
            for name in ("Ke", "Kmf", "Kmb", "Kmr", "Vmax", "N", "Nf", "Nb", "A"):
                arrays[f"params.{name}"] = np.asarray(
                    getattr(world.kinetics.params, name)
                )[:n]
            np.savez(args.dump_path, **arrays)


def _run_child(
    args: argparse.Namespace, platform: str, dump: tuple[int, str] | None = None
) -> list[dict]:
    env = dict(os.environ)
    # the deterministic numeric mode (fixed-order reductions, integer
    # powers, polynomial exp, software division) is what makes the two
    # backends comparable at all — see BITREPRO.md
    env["MAGICSOUP_TPU_DETERMINISTIC"] = "1"
    # forbid FMA contraction / excess precision: the deterministic math in
    # ops/detmath.py fixes operation ORDER, but XLA may still fuse a
    # mul+add into an FMA on one backend and not the other
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_allow_excess_precision=false"
    ).strip()
    # BOTH children must compile fresh: cache-loaded XLA:CPU AOT
    # executables can differ numerically from fresh compiles (observed on
    # this box — the loader even warns when the cached machine features
    # don't match the host), so a persistent compile cache leaking in via
    # the environment would compare a stale binary against a fresh one
    # and report a spurious divergence
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    if platform == "cpu":
        # strip any PJRT shim and pin the CPU backend
        env["PYTHONPATH"] = ""
        env["JAX_PLATFORMS"] = "cpu"
    cmd = [
        sys.executable, str(Path(__file__).resolve()), "--child", platform,
        "--steps", str(args.steps), "--n-cells", str(args.n_cells),
        "--map-size", str(args.map_size), "--genome-size", str(args.genome_size),
        "--seed", str(args.seed),
    ]
    if dump is not None:
        cmd += ["--dump-step", str(dump[0]), "--dump-path", dump[1]]
    res = subprocess.run(cmd, capture_output=True, text=True, env=env, timeout=3600)
    if res.returncode != 0:
        sys.stderr.write(res.stderr[-3000:])
        raise RuntimeError(f"{platform} child failed (rc={res.returncode})")
    return [json.loads(line) for line in res.stdout.splitlines() if line.strip()]


def _divergence_magnitudes(args: argparse.Namespace, step: int) -> dict:
    """Re-run both children dumping raw state at the first divergent step
    and quantify how far apart the tensors actually are (max abs/rel diff
    and max ULP distance) — a hash mismatch alone cannot distinguish an
    ULP-level transcendental difference from a real bug."""
    import tempfile

    import numpy as np

    out: dict[str, dict] = {}
    with tempfile.TemporaryDirectory() as td:
        cpu_npz = str(Path(td) / "cpu.npz")
        acc_npz = str(Path(td) / "acc.npz")
        _run_child(args, "cpu", dump=(step, cpu_npz))
        _run_child(args, "accel", dump=(step, acc_npz))
        a = np.load(cpu_npz)
        b = np.load(acc_npz)
        for key in a.files:
            x, y = a[key], b[key]
            if x.shape != y.shape:
                out[key] = {"shape_mismatch": [list(x.shape), list(y.shape)]}
                continue
            if not np.array_equal(x, y):
                dx = np.abs(x.astype(np.float64) - y.astype(np.float64))
                denom = np.maximum(np.abs(x).astype(np.float64), 1e-30)
                ulp = 0
                if x.dtype == np.float32:
                    ulp = int(
                        np.max(
                            np.abs(
                                x.view(np.int32).astype(np.int64)
                                - y.view(np.int32).astype(np.int64)
                            )
                        )
                    )
                out[key] = {
                    "n_diff": int((dx > 0).sum()),
                    "max_abs": float(dx.max()),
                    "max_rel": float((dx / denom).max()),
                    "max_ulp": ulp,
                }
    return out


def main() -> None:
    args = _build_parser().parse_args()
    if args.child is not None:
        child_main(args)
        return

    try:
        cpu_rows = _run_child(args, "cpu")
        acc_rows = _run_child(args, "accel")
    except RuntimeError as err:
        print(json.dumps({"result": "error", "error": str(err)}))
        sys.exit(2)

    cpu_platform = cpu_rows.pop(0)["platform"]
    acc_platform = acc_rows.pop(0)["platform"]
    header = f"{cpu_platform} vs {acc_platform}"
    if acc_platform == cpu_platform:
        header += " (no accelerator found: self-check)"

    for cpu_row, acc_row in zip(cpu_rows, acc_rows):
        step = cpu_row["step"]
        diff = [
            k
            for k in cpu_row
            if k not in ("step",) and cpu_row[k] != acc_row.get(k)
        ]
        if diff:
            try:
                magnitudes = _divergence_magnitudes(args, step)
            except Exception as err:  # noqa: BLE001
                magnitudes = {"error": str(err)[:500]}
            print(
                json.dumps(
                    {
                        "result": "diverged",
                        "backends": header,
                        "first_divergence_step": step,
                        "tensors": diff,
                        "magnitudes": magnitudes,
                        "steps_checked": len(cpu_rows),
                    }
                )
            )
            sys.exit(1)
    print(
        json.dumps(
            {
                "result": "bit-identical",
                "backends": header,
                "steps_checked": len(cpu_rows),
                "final_n_cells": cpu_rows[-1]["n_cells"],
            }
        )
    )


if __name__ == "__main__":
    main()
